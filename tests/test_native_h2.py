"""Native h2/gRPC data plane (VERDICT r4 #5): the engine owns h2 framing,
HPACK and flow control; grpc unary requests ride the EV_REQUEST fast path
and the native-echo registry. Reference semantics:
/root/reference/src/brpc/policy/http2_rpc_protocol.cpp + details/hpack.cpp.

Covered here:
- Python grpc client (Python transport) -> native listener: the engine
  sniffs the h2 preface, decodes HPACK, dispatches to the Python service,
  encodes the h2 response.
- Python grpc client over the NATIVE lane (dp_connect_grpc): the client
  h2 framing happens in C++ too (sync = engine-parked dp_call_sync).
- Window-parked responses (payload >> the client's 65535 initial window).
- Error mapping (unknown method -> UNIMPLEMENTED -> ENOMETHOD).
- Stream multiplexing (concurrent sync calls share one h2 conn).
- Non-grpc h2 on a native listener detaches to the Python h2 stack with
  the raw bytes replayed (dashboard-over-h2 still works).
- The C++ grpc load generator end to end (native client + native server
  h2, Python service).
"""

import threading

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (Channel, ChannelOptions, Controller, Server,
                          ServerOptions, Service, Stub, errors)
from brpc_tpu.rpc.channel import RpcError

try:
    from brpc_tpu.rpc.native_transport import (bench_echo_native,
                                               dataplane_available)
    HAVE_ENGINE = dataplane_available()
except Exception:
    HAVE_ENGINE = False

pytestmark = pytest.mark.skipif(not HAVE_ENGINE,
                                reason="native engine unavailable")

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = ECHO_DESC

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture
def native_server():
    srv = Server(ServerOptions(native_dataplane=True, usercode_inline=True))
    srv.add_service(EchoImpl())
    srv.start("127.0.0.1:0")
    yield srv
    srv.stop()
    srv.join()


def _stub(server, **opts):
    opts.setdefault("protocol", "grpc")
    opts.setdefault("timeout_ms", 10000)
    ch = Channel(ChannelOptions(**opts))
    ch.init(str(server.listen_endpoint()))
    return Stub(ch, ECHO_DESC)


class TestNativeH2Server:
    def test_py_grpc_client_echo(self, native_server):
        stub = _stub(native_server)
        r = stub.Echo(echo_pb2.EchoRequest(message="hello", payload=b"p"))
        assert r.message == "hello" and r.payload == b"p"

    def test_window_parked_response(self, native_server):
        # 200KB >> the Python client's 65535 initial stream window: the
        # engine parks DATA and drains on WINDOW_UPDATE (h2_pump)
        stub = _stub(native_server)
        big = bytes(range(256)) * 800
        r = stub.Echo(echo_pb2.EchoRequest(message="big", payload=big))
        assert r.payload == big

    def test_unserved_service_maps_to_unimplemented(self):
        # a native server WITHOUT EchoService: grpc UNIMPLEMENTED comes
        # back and reverse-maps to ENOMETHOD (grpc_protocol.GRPC_TO_BRPC)
        srv = Server(ServerOptions(native_dataplane=True,
                                   usercode_inline=True))
        srv.start("127.0.0.1:0")
        try:
            stub = _stub(srv)
            with pytest.raises(RpcError) as ei:
                stub.Echo(echo_pb2.EchoRequest(message="x"))
            assert ei.value.error_code in (errors.ENOSERVICE,
                                           errors.ENOMETHOD)
        finally:
            srv.stop()
            srv.join()

    def test_multiplexed_concurrent_sync_calls(self, native_server):
        stub = _stub(native_server, native_transport=True)
        outs, errs = [], []

        def worker(i):
            try:
                r = stub.Echo(echo_pb2.EchoRequest(message=f"m{i}"))
                outs.append(r.message)
            except BaseException as e:  # noqa: BLE001 - collected
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
        assert sorted(outs) == [f"m{i}" for i in range(8)]

    def test_native_client_lane(self, native_server):
        # dp_connect_grpc: the CLIENT h2 framing is C++ too
        stub = _stub(native_server, native_transport=True)
        r = stub.Echo(echo_pb2.EchoRequest(message="native", payload=b"zz"))
        assert r.message == "native" and r.payload == b"zz"

    def test_native_client_big_request_and_response(self, native_server):
        stub = _stub(native_server, native_transport=True)
        big = b"\xa5" * 300000
        r = stub.Echo(echo_pb2.EchoRequest(message="b", payload=big))
        assert r.payload == big

    def test_cpp_loadgen_grpc(self, native_server):
        host, port = str(native_server.listen_endpoint()).rsplit(":", 1)
        res = bench_echo_native(host, int(port), conns=2, depth=4,
                                payload=16, duration_ms=400, grpc=True)
        assert res is not None and res["qps"] > 100, res

    def test_non_grpc_h2_detaches_to_python(self, native_server):
        # an h2 GET (no grpc content-type) must reach the Python h2 stack
        # (builtin dashboard) — the engine replays the sniffed bytes
        import socket

        from brpc_tpu.policy import h2 as _h2
        from brpc_tpu.policy.hpack import HpackEncoder

        host, port = str(native_server.listen_endpoint()).rsplit(":", 1)
        s = socket.create_connection((host, int(port)))
        s.settimeout(10)
        enc = HpackEncoder()
        block = enc.encode([(":method", "GET"), (":scheme", "http"),
                            (":path", "/status"), (":authority", "x")])
        s.sendall(b"PRI * HTTP/2.0\r\n\r\nSM\r\n\r\n"
                  + _h2.pack_settings([])
                  + _h2.pack_frame(_h2.HEADERS,
                                   _h2.FLAG_END_HEADERS | _h2.FLAG_END_STREAM,
                                   1, block))
        buf = b""
        while b"grpc" not in buf and len(buf) < 200:
            chunk = s.recv(65536)
            if not chunk:
                break
            buf += chunk
        s.close()
        # the Python h2 stack answered (its SETTINGS frame + a HEADERS
        # with :status 200 somewhere in the stream)
        assert len(buf) > 9, "no h2 reply after detach"

    def test_grpc_and_trpc_share_the_port(self, native_server):
        # the same native listener serves trpc_std AND grpc
        grpc_stub = _stub(native_server)
        std_stub = _stub(native_server, protocol="trpc_std",
                         native_transport=True)
        r1 = grpc_stub.Echo(echo_pb2.EchoRequest(message="g"))
        r2 = std_stub.Echo(echo_pb2.EchoRequest(message="t"))
        assert (r1.message, r2.message) == ("g", "t")


class TestNativeGrpcEcho:
    def test_native_echo_service_grpc(self):
        # C++ end to end: native echo registry answers grpc in-engine
        srv = Server(ServerOptions(native_dataplane=True))
        srv.add_service(EchoImpl())
        srv.start("127.0.0.1:0")
        try:
            srv.register_native_echo("EchoService", "Echo")
            stub = _stub(srv)
            r = stub.Echo(echo_pb2.EchoRequest(message="cpp",
                                               payload=b"123"))
            assert r.message == "cpp" and r.payload == b"123"
        finally:
            srv.stop()
            srv.join()
