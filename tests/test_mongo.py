"""Mongo wire protocol + BSON codec tests (VERDICT r1 #10; reference
policy/mongo_protocol.cpp). Pattern: real loopback server running a
MongoService fake-mongod, client over Channel — no mocks (SURVEY §4)."""

import datetime

import pytest

from brpc_tpu.policy import bson
from brpc_tpu.policy.mongo_protocol import (
    MongoRequest,
    MongoResponse,
    MongoService,
    mongo_method,
    pack_msg,
    unpack_msg_body,
)
from brpc_tpu.rpc import Channel, ChannelOptions, RpcError, Server, ServerOptions


class TestBson:
    def test_roundtrip_all_types(self):
        oid = bson.ObjectId()
        now = datetime.datetime(2026, 7, 30, 12, 0,
                                tzinfo=datetime.timezone.utc)
        doc = {
            "d": 2.5, "s": "héllo", "sub": {"a": 1}, "arr": [1, "two", None],
            "bin": b"\x00\xff", "oid": oid, "flag": True, "ts": now,
            "nil": None, "i32": -5, "i64": 1 << 40,
        }
        assert bson.decode(bson.encode(doc)) == doc

    def test_objectid_uniqueness(self):
        assert bson.ObjectId() != bson.ObjectId()
        fixed = bson.ObjectId(b"\x01" * 12)
        assert bson.decode(bson.encode({"x": fixed}))["x"] == fixed

    def test_malformed_rejected(self):
        good = bson.encode({"a": 1})
        with pytest.raises(bson.BsonError):
            bson.decode(good[:-2])
        with pytest.raises(bson.BsonError):
            bson.decode(b"\x03\x00\x00\x00")
        bad_type = bytearray(good)
        bad_type[4] = 0x7F  # unknown element type
        with pytest.raises(bson.BsonError):
            bson.decode(bytes(bad_type))

    def test_opmsg_roundtrip(self):
        raw = pack_msg(7, 0, {"ping": 1})
        assert unpack_msg_body(raw[16:]) == {"ping": 1}


@pytest.fixture()
def mongod():
    svc = MongoService()
    store = {}

    def insert(doc):
        for d in doc.get("documents", []):
            store[str(d.get("_id"))] = d
        return {"ok": 1.0, "n": len(doc.get("documents", []))}

    def find(doc):
        batch = [d for d in store.values()
                 if all(d.get(k) == v for k, v in
                        doc.get("filter", {}).items())]
        return {"ok": 1.0,
                "cursor": {"id": 0, "ns": f"t.{doc['find']}",
                           "firstBatch": batch}}

    svc.add_command_handler("insert", insert)
    svc.add_command_handler("find", find)
    server = Server(ServerOptions(mongo_service=svc))
    server.start("127.0.0.1:0")
    yield server
    server.stop()
    server.join(timeout=2)


def _call(channel, doc) -> MongoResponse:
    return channel.call_method(mongo_method(), MongoRequest(doc))


class TestMongoClientServer:
    def test_ping_hello(self, mongod):
        ch = Channel(ChannelOptions(protocol="mongo", timeout_ms=5000))
        ch.init(str(mongod.listen_endpoint()))
        assert _call(ch, {"ping": 1, "$db": "admin"}).ok
        hello = _call(ch, {"hello": 1})
        assert hello.document["isWritablePrimary"] is True

    def test_insert_find(self, mongod):
        ch = Channel(ChannelOptions(protocol="mongo", timeout_ms=5000))
        ch.init(str(mongod.listen_endpoint()))
        oid = bson.ObjectId()
        r = _call(ch, {"insert": "users", "$db": "t", "documents": [
            {"_id": oid, "name": "ada", "age": 36},
            {"_id": bson.ObjectId(), "name": "bob", "age": 41},
        ]})
        assert r.ok and r.document["n"] == 2
        found = _call(ch, {"find": "users", "$db": "t",
                           "filter": {"name": "ada"}})
        batch = found.document["cursor"]["firstBatch"]
        assert len(batch) == 1 and batch[0]["_id"] == oid

    def test_unknown_command(self, mongod):
        ch = Channel(ChannelOptions(protocol="mongo", timeout_ms=5000))
        ch.init(str(mongod.listen_endpoint()))
        r = _call(ch, {"frobnicate": 1})
        assert not r.ok and r.document["code"] == 59

    def test_pipelined_commands(self, mongod):
        """requestID/responseTo correlation: many in-flight commands on one
        connection complete correctly."""
        import threading

        ch = Channel(ChannelOptions(protocol="mongo", timeout_ms=5000))
        ch.init(str(mongod.listen_endpoint()))
        errs = []

        def worker(i):
            try:
                for _ in range(20):
                    assert _call(ch, {"ping": 1}).ok
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs

    def test_timeout_on_dead_server(self):
        server = Server(ServerOptions(mongo_service=MongoService()))
        server.start("127.0.0.1:0")
        addr = str(server.listen_endpoint())
        ch = Channel(ChannelOptions(protocol="mongo", timeout_ms=1500,
                                    max_retry=0))
        ch.init(addr)
        assert _call(ch, {"ping": 1}).ok
        server.stop()
        server.join(timeout=2)
        with pytest.raises(RpcError):
            _call(ch, {"ping": 1})
