"""The record -> replay -> diff loop (PR 7 tentpole).

* v2 dump format — begin-at-dispatch/commit-at-settle records carrying
  arrival timestamps, trace ids and the server span's settled phase
  timeline; v1 files still load; rotation and truncated tails tolerated;
* both dispatch paths sample — the generic pipeline over TCP and the
  fast path (exercised against a fake dataplane, since the native engine
  is absent in CI);
* the /dump builtin view and ``rpc_view --dump`` renderer;
* the diff engine — which PHASE moved, gated on relative AND absolute
  thresholds so clean replays stay quiet;
* rpc_replay's open-loop pacing and trace tagging;
* the deterministic end-to-end over tpu://: record a scenario, replay it
  at 2x through the full client stack, and trace_diff localizes an
  injected handler delay to ``execute_us`` on the right method — and
  flags nothing on a clean replay;
* OTLP span export and the stitched /rpcz trace tree.
"""

import json
import os
import struct
import time

import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.proto import echo_pb2, rpc_meta_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    Service,
    Stub,
)
from brpc_tpu.trace import diff as _diff
from brpc_tpu.trace import span as _span
from brpc_tpu.trace.rpc_dump import (
    MAGIC_V2,
    RpcDumper,
    RpcDumpLoader,
    pack_record,
)

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def traced():
    """Span + dump sampling wide open, span DB clean."""
    from brpc_tpu.metrics.collector import global_collector

    _flags.set_flag("rpcz_sample_ratio", "1.0")
    _flags.set_flag("collector_max_samples_per_second", "0")
    global_collector()._deny_until = 0.0
    _span.reset_for_test()
    yield
    _flags.set_flag("collector_max_samples_per_second", "1000")
    _flags.set_flag("rpc_dump_ratio", "0.0")


def _mk_meta(service="EchoService", method="Echo", trace_id=0, span_id=0,
             log_id=0, timeout_ms=0):
    meta = rpc_meta_pb2.RpcMeta()
    meta.request.service_name = service
    meta.request.method_name = method
    meta.request.trace_id = trace_id
    meta.request.span_id = span_id
    meta.request.log_id = log_id
    meta.request.timeout_ms = timeout_ms
    return meta


def _mk_span(phases, latency_us=1000.0, trace_id=1, span_id=2):
    sp = _span.Span(trace_id, span_id, 0, _span.KIND_SERVER, "S", "M")
    for k, v in phases.items():
        sp.add_phase(k, v)
    sp.end_mono_us = sp.start_mono_us + latency_us  # settle without _db_add
    return sp


def _wait(predicate, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        if predicate():
            return True
        time.sleep(0.01)
    return predicate()


# ------------------------------------------------------------------ v2 format
class TestV2Format:
    def test_begin_commit_roundtrip(self, tmp_path):
        dumper = RpcDumper(str(tmp_path))
        meta = _mk_meta(trace_id=0xabc, span_id=0xdef, log_id=7,
                        timeout_ms=250)
        pending = dumper.begin(meta, b"wire-bytes")
        assert pending["ts_us"] > 0
        sp = _mk_span({"parse_us": 12.0, "execute_us": 345.6},
                      latency_us=1234.5)
        dumper.commit(pending, sp, error_code=0)
        dumper.close()

        recs = list(RpcDumpLoader(str(tmp_path)))
        assert len(recs) == 1
        rec = recs[0]
        assert rec.version == 2
        assert rec.info["service"] == "EchoService"
        assert rec.info["method"] == "Echo"
        assert rec.info["timeout_ms"] == 250
        assert rec.info["priority"] == 0
        assert rec.info["phases"]["execute_us"] == pytest.approx(345.6)
        assert rec.info["latency_us"] == pytest.approx(1234.5)
        assert rec.trace_id == 0xabc and rec.span_id == 0xdef
        assert rec.ts_us > 0
        assert rec.method_key == "EchoService.Echo"
        # v1-era consumers unpack records as (meta, body) tuples
        m, b = rec
        assert m.request.log_id == 7 and b == b"wire-bytes"

    def test_v1_files_still_load(self, tmp_path):
        p = tmp_path / "requests.0.dump"
        with open(p, "wb") as f:
            f.write(pack_record(_mk_meta(method="Old"), b"v1-body"))
        recs = list(RpcDumpLoader(str(p)))
        assert len(recs) == 1
        assert recs[0].version == 1
        assert recs[0].info == {}
        assert recs[0].ts_us == 0.0
        meta, body = recs[0]
        assert meta.request.method_name == "Old" and body == b"v1-body"

    def test_mixed_version_directory(self, tmp_path):
        with open(tmp_path / "requests.0.dump", "wb") as f:
            f.write(pack_record(_mk_meta(), b"old"))
        dumper = RpcDumper(str(tmp_path))
        # the dumper's own files start at index 0 too — point it elsewhere
        dumper._file_index = 1
        dumper.sample(_mk_meta(), b"new")
        dumper.close()
        recs = list(RpcDumpLoader(str(tmp_path)))
        assert sorted(r.version for r in recs) == [1, 2]

    def test_rotation_at_max_file_bytes(self, tmp_path):
        from brpc_tpu.trace import rpc_dump as _dump

        rot0 = _dump.g_dump_rotations.get_value()
        dumper = RpcDumper(str(tmp_path), max_file_bytes=200)
        for i in range(6):
            dumper.sample(_mk_meta(log_id=i), b"x" * 64)
        dumper.close()
        files = sorted(f for f in os.listdir(tmp_path)
                       if f.endswith(".dump"))
        assert len(files) > 1
        assert _dump.g_dump_rotations.get_value() - rot0 == len(files) - 1
        for f in files:  # every rolled file carries the v2 magic
            assert (tmp_path / f).read_bytes().startswith(MAGIC_V2)
        recs = list(RpcDumpLoader(str(tmp_path)))
        assert sorted(r.meta.request.log_id for r in recs) == list(range(6))

    def test_truncated_tail_v2(self, tmp_path):
        dumper = RpcDumper(str(tmp_path))
        for i in range(3):
            dumper.sample(_mk_meta(log_id=i), b"payload")
        dumper.close()
        p = tmp_path / "requests.0.dump"
        data = p.read_bytes()
        p.write_bytes(data[:-5])  # crash mid-write of the last record
        recs = list(RpcDumpLoader(str(p)))
        assert [r.meta.request.log_id for r in recs] == [0, 1]

    def test_truncated_tail_v1(self, tmp_path):
        p = tmp_path / "requests.0.dump"
        rec = pack_record(_mk_meta(), b"bb")
        with open(p, "wb") as f:
            f.write(rec + rec + struct.pack("!II", 100, 100) + b"short")
        assert len(list(RpcDumpLoader(str(p)))) == 2

    def test_rate_cap_token_bucket(self, tmp_path, traced):
        from brpc_tpu.trace import rpc_dump as _dump

        dumper = RpcDumper(str(tmp_path))
        _flags.set_flag("rpc_dump_ratio", "1.0")
        _flags.set_flag("rpc_dump_max_per_sec", "1")
        try:
            skip0 = _dump.g_dump_skipped.get_value()
            assert dumper.ask_to_be_sampled()  # first token is pre-filled
            assert not dumper.ask_to_be_sampled()  # bucket drained
            assert _dump.g_dump_skipped.get_value() == skip0 + 1
            _flags.set_flag("rpc_dump_max_per_sec", "0")
            assert dumper.ask_to_be_sampled()  # cap off: ratio rules again
        finally:
            _flags.set_flag("rpc_dump_max_per_sec", "0")
            _flags.set_flag("rpc_dump_ratio", "0.0")


# -------------------------------------------------------------- /dump builtin
class _Http:
    def __init__(self, path="/dump", query=None):
        self.path = path
        self.query = query or {}

    def header(self, k, default=""):
        return default


class TestDumpBuiltin:
    def test_view_without_dumper(self):
        from brpc_tpu.builtin.services import dump_service

        status, _ctype, body = dump_service(None, _Http())
        assert status == 200
        assert "no dumper" in body

    def test_view_with_traffic(self, tmp_path, traced):
        from brpc_tpu.builtin.services import dump_service
        from brpc_tpu.policy.http_protocol import http_fetch

        _flags.set_flag("rpc_dump_ratio", "1.0")
        server = (Server(ServerOptions(rpc_dump_dir=str(tmp_path)))
                  .add_service(EchoImpl()).start("127.0.0.1:0"))
        try:
            stub = Stub(Channel().init(str(server.listen_endpoint())), ECHO)
            for i in range(3):
                stub.Echo(echo_pb2.EchoRequest(message=f"d{i}"))
            assert _wait(lambda: server.rpc_dumper.sampled_count >= 3)

            status, _ctype, body = dump_service(
                server, _Http(query={"format": "json"}))
            assert status == 200
            doc = json.loads(body)
            assert doc["rpc_dump_ratio"] == 1.0
            assert doc["dumper"]["per_method"]["EchoService.Echo"] == 3
            assert doc["dumper"]["files"], "dump files listed"

            # and over the server's own HTTP surface
            resp = http_fetch(str(server.listen_endpoint()), "GET", "/dump")
            assert resp.status == 200
            assert b"EchoService.Echo: 3" in resp.body
        finally:
            _flags.set_flag("rpc_dump_ratio", "0.0")
            server.stop()
            server.join(timeout=2)


# ------------------------------------------------------------ dispatch paths
class TestDispatchPathsSample:
    def test_slow_path_records_phases(self, tmp_path, traced):
        _flags.set_flag("rpc_dump_ratio", "1.0")
        server = (Server(ServerOptions(rpc_dump_dir=str(tmp_path)))
                  .add_service(EchoImpl()).start("127.0.0.1:0"))
        try:
            stub = Stub(Channel().init(str(server.listen_endpoint())), ECHO)
            for i in range(3):
                stub.Echo(echo_pb2.EchoRequest(message=f"p{i}"))
            assert _wait(lambda: server.rpc_dumper.sampled_count >= 3)
            server.rpc_dumper.close()
        finally:
            _flags.set_flag("rpc_dump_ratio", "0.0")
            server.stop()
            server.join(timeout=2)
        recs = list(RpcDumpLoader(str(tmp_path)))
        assert len(recs) == 3
        for rec in recs:
            # committed at settle: the full server phase timeline is in
            assert "execute_us" in rec.info["phases"]
            assert "parse_us" in rec.info["phases"]
            assert rec.info["latency_us"] > 0
            assert rec.trace_id != 0  # client tracing was on

    def test_fast_path_records_phases(self, tmp_path, traced):
        """fast_process_request against a fake dataplane: dump sampling
        rides the fast path natively (no slow-lane replay) and the record
        still carries the settled phases."""
        from brpc_tpu.rpc import server_processing as sp_mod

        class _FakeDp:
            def __init__(self):
                self.responses = []

            def respond(self, conn, cid, attempt, code, err, payload,
                        attachment, q, compress_type=0):
                self.responses.append((conn, cid, code, payload))

        class _FakeSock:
            def __init__(self, dp):
                self._dp = dp
                self.conn_id = 17
                self.peer_str = "fake:0"
                self.remote = "fake:0"

        _flags.set_flag("rpc_dump_ratio", "1.0")
        server = (Server(ServerOptions(rpc_dump_dir=str(tmp_path)))
                  .add_service(EchoImpl()).start("127.0.0.1:0"))
        try:
            dp = _FakeDp()
            body = echo_pb2.EchoRequest(message="fast").SerializeToString()
            item = (server, _FakeSock(dp), "EchoService", "Echo",
                    99, 1, 0, 5, 0xfeed, 0xbeef, 0, body)
            sp_mod.fast_process_request(item)
            assert dp.responses and dp.responses[0][2] == 0
            server.rpc_dumper.close()
        finally:
            _flags.set_flag("rpc_dump_ratio", "0.0")
            server.stop()
            server.join(timeout=2)
        recs = list(RpcDumpLoader(str(tmp_path)))
        assert len(recs) == 1
        rec = recs[0]
        assert rec.trace_id == 0xfeed
        assert rec.meta.request.span_id == 0xbeef
        assert rec.meta.correlation_id == 99
        assert "execute_us" in rec.info["phases"]
        # raw body survives the round trip for replay
        req = echo_pb2.EchoRequest()
        req.ParseFromString(rec.body)
        assert req.message == "fast"


# ------------------------------------------------------------------ the diff
def _profile(method, n, **phase_us):
    prof = _diff.MethodProfile(method)
    for _ in range(n):
        prof.add(dict(phase_us), sum(phase_us.values()))
    return prof


class TestDiffEngine:
    def test_percentile_nearest_rank(self):
        assert _diff.percentile([], 0.99) == 0.0
        assert _diff.percentile([5.0], 0.5) == 5.0
        vals = list(range(1, 101))
        assert _diff.percentile(vals, 0.99) == 99
        assert _diff.percentile(vals, 1.0) == 100

    def test_flags_the_moved_phase(self):
        base = {"S.M": _profile("S.M", 5, execute_us=1000.0, parse_us=50.0)}
        new = {"S.M": _profile("S.M", 5, execute_us=40000.0, parse_us=50.0)}
        regs = _diff.diff_profiles(base, new)
        assert len(regs) == 1
        r = regs[0]
        assert r.method == "S.M" and r.phase == "execute_us"
        assert "execute p99" in r.describe()
        assert "on S.M" in r.describe()
        assert r.to_dict()["summary"] == r.describe()

    def test_identical_runs_stay_quiet(self):
        base = {"S.M": _profile("S.M", 5, execute_us=1000.0)}
        new = {"S.M": _profile("S.M", 5, execute_us=1000.0)}
        assert _diff.diff_profiles(base, new) == []

    def test_absolute_floor_gates_jitter(self):
        # +150% but only +1.5ms: under the 2ms floor, not a page
        base = {"S.M": _profile("S.M", 5, execute_us=1000.0)}
        new = {"S.M": _profile("S.M", 5, execute_us=2500.0)}
        assert _diff.diff_profiles(base, new) == []
        assert _diff.diff_profiles(base, new, min_delta_us=500.0)

    def test_relative_floor_gates_wide_phases(self):
        # +20ms but only +20%: under the 30% threshold
        base = {"S.M": _profile("S.M", 5, execute_us=100000.0)}
        new = {"S.M": _profile("S.M", 5, execute_us=120000.0)}
        assert _diff.diff_profiles(base, new) == []
        assert _diff.diff_profiles(base, new, threshold=0.1)

    def test_min_samples_and_missing_methods(self):
        base = {"S.M": _profile("S.M", 2, execute_us=100.0)}
        new = {"S.M": _profile("S.M", 2, execute_us=90000.0),
               "S.Other": _profile("S.Other", 9, execute_us=90000.0)}
        assert _diff.diff_profiles(base, new) == []  # n too small / no base

    def test_render_report_marks_regressions(self):
        base = {"S.M": _profile("S.M", 5, execute_us=1000.0)}
        new = {"S.M": _profile("S.M", 5, execute_us=40000.0)}
        regs = _diff.diff_profiles(base, new)
        out = _diff.render_report(base, new, regs)
        assert "<-- REGRESSED" in out
        assert "1 phase regression(s):" in out
        clean = _diff.render_report(base, base, [])
        assert "no phase regressions" in clean

    def test_profiles_from_dump_skips_v1(self, tmp_path):
        with open(tmp_path / "requests.0.dump", "wb") as f:
            f.write(pack_record(_mk_meta(), b"old"))
        dumper = RpcDumper(str(tmp_path))
        dumper._file_index = 1
        dumper.commit(dumper.begin(_mk_meta(), b"new"),
                      _mk_span({"execute_us": 42.0}))
        dumper.close()
        profs = _diff.profiles_from_dump(str(tmp_path))
        assert profs["EchoService.Echo"].count == 1


class TestTraceDiffCLI:
    @staticmethod
    def _spans_json(path, execute_us):
        doc = {"spans": [
            {"kind": "server", "service": "S", "method": "M",
             "phases": {"execute_us": execute_us}, "latency_us": execute_us}
            for _ in range(5)]}
        path.write_text(json.dumps(doc))
        return str(path)

    def test_exit_codes_and_json(self, tmp_path, capsys):
        from tools import trace_diff

        base = self._spans_json(tmp_path / "base.json", 1000.0)
        same = self._spans_json(tmp_path / "same.json", 1100.0)
        bad = self._spans_json(tmp_path / "bad.json", 50000.0)

        assert trace_diff.main([base, same]) == 0
        assert "no phase regressions" in capsys.readouterr().out

        assert trace_diff.main([base, bad, "--json"]) == 1
        doc = json.loads(capsys.readouterr().out)
        assert doc["regressions"][0]["phase"] == "execute_us"
        assert doc["methods_compared"] == ["S.M"]

        assert trace_diff.main([base, str(tmp_path / "nope.json")]) == 2
        assert trace_diff.main([base, same, "--percentile", "0"]) == 2


# -------------------------------------------------------------------- replay
class TestReplayPacing:
    def test_items_sorted_by_arrival_not_commit(self, tmp_path):
        from tools.rpc_replay import load_items

        dumper = RpcDumper(str(tmp_path))
        # commit order 3,1,2 — arrival stamps say 1,2,3
        for log_id, ts in ((3, 3000.0), (1, 1000.0), (2, 2000.0)):
            pending = dumper.begin(_mk_meta(log_id=log_id), b"x")
            pending["ts_us"] = ts * 1000.0  # 1ms apart
            dumper.commit(pending)
        dumper.close()
        items, skipped = load_items(str(tmp_path))
        assert skipped == 0
        assert [i.md.service_name for i in items] == ["EchoService"] * 3
        assert [round(i.offset_s, 3) for i in items] == [0.0, 1.0, 2.0]

    def test_replay_tags_recorded_trace_ids(self, tmp_path, traced):
        from tools import rpc_replay

        _flags.set_flag("rpc_dump_ratio", "1.0")
        server = (Server(ServerOptions(rpc_dump_dir=str(tmp_path)))
                  .add_service(EchoImpl()).start("127.0.0.1:0"))
        try:
            stub = Stub(Channel().init(str(server.listen_endpoint())), ECHO)
            for i in range(3):
                stub.Echo(echo_pb2.EchoRequest(message=f"r{i}"))
            assert _wait(lambda: server.rpc_dumper.sampled_count >= 3)
            server.rpc_dumper.close()
        finally:
            server.stop()
            server.join(timeout=2)
        _flags.set_flag("rpc_dump_ratio", "0.0")
        recorded = {rec.trace_id for rec in RpcDumpLoader(str(tmp_path))}
        assert len(recorded) == 3

        _span.reset_for_test()
        server2 = Server().add_service(EchoImpl()).start("127.0.0.1:0")
        try:
            rc = rpc_replay.main([
                "--dump", str(tmp_path),
                "--server", str(server2.listen_endpoint()),
                "--report-interval", "0"])
            assert rc == 0
            assert _wait(lambda: len([s for s in _span.recent_spans(50)
                                      if s.kind == _span.KIND_SERVER]) >= 3)
        finally:
            server2.stop()
            server2.join(timeout=2)
        spans = _span.recent_spans(50)
        # replayed server spans land under the SAME trace ids as recorded
        srv = [s for s in spans if s.kind == _span.KIND_SERVER]
        assert {s.trace_id for s in srv} == recorded
        # the replay client spans carry the replay annotation and hang
        # under the recorded client span
        cli = [s for s in spans if s.kind == _span.KIND_CLIENT]
        assert cli and all(
            any("replay pass=1" in t for _, t in s.annotations)
            for s in cli)
        assert all(s.parent_span_id for s in cli)


# --------------------------------------------------- the deterministic loop
class TestRecordReplayDiffE2E:
    def _record(self, dump_dir, n=8):
        _flags.set_flag("rpc_dump_ratio", "1.0")
        server = (Server(ServerOptions(rpc_dump_dir=str(dump_dir)))
                  .add_service(EchoImpl()).start("tpu://127.0.0.1:0/0"))
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=10000))
            ch.init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO)
            for i in range(n):
                stub.Echo(echo_pb2.EchoRequest(message=f"rec{i}"))
            assert _wait(lambda: server.rpc_dumper.sampled_count >= n)
            server.rpc_dumper.close()
        finally:
            _flags.set_flag("rpc_dump_ratio", "0.0")
            server.stop()
            server.join(timeout=2)

    def _replay_2x(self, dump_dir, server):
        from tools import rpc_replay

        rc = rpc_replay.main([
            "--dump", str(dump_dir),
            "--server", str(server.listen_endpoint()),
            "--rate-mult", "2", "--timeout-ms", "10000",
            "--report-interval", "0"])
        assert rc == 0

    def _server_profiles(self, n):
        assert _wait(lambda: len([s for s in _span.recent_spans(100)
                                  if s.kind == _span.KIND_SERVER]) >= n)
        return _diff.profiles_from_spans(
            [s.to_dict() for s in _span.recent_spans(100)], "server")

    # p50 with a 10ms floor: immune to single-sample scheduler hiccups on
    # a loaded CI box, while the injected 30ms stall (shifting the whole
    # distribution) still clears the floor 3x over
    _GATES = dict(q=0.5, min_delta_us=10_000.0)

    def test_diff_localizes_injected_fault_over_tpu(self, tmp_path, traced):
        """Record over tpu://, replay at 2x through the full client stack:
        a clean replay diffs quiet; with rpc.handler.delay armed the diff
        names execute_us on the faulted method — and nothing else."""
        self._record(tmp_path, n=8)
        base = _diff.profiles_from_dump(str(tmp_path))
        assert base["EchoService.Echo"].count == 8

        server = (Server().add_service(EchoImpl())
                  .start("tpu://127.0.0.1:0/0"))
        try:
            # clean replay: no regression may be flagged
            _span.reset_for_test()
            self._replay_2x(tmp_path, server)
            clean = self._server_profiles(8)
            assert _diff.diff_profiles(base, clean, **self._GATES) == []

            # faulted replay: 30ms handler stall on Echo only
            _span.reset_for_test()
            _flags.set_flag("fault_injection_enabled", "true")
            fault.arm("rpc.handler.delay", mode="always",
                      match={"method": "Echo"}, delay_ms=30)
            try:
                self._replay_2x(tmp_path, server)
            finally:
                fault.disarm("rpc.handler.delay")
                _flags.set_flag("fault_injection_enabled", "false")
            faulted = self._server_profiles(8)
            regs = _diff.diff_profiles(base, faulted, **self._GATES)
            assert regs, "injected 30ms stall must be flagged"
            assert regs[0].method == "EchoService.Echo"
            assert regs[0].phase == "execute_us"
            assert regs[0].new_us - regs[0].base_us > 20000
            assert all(r.phase == "execute_us" for r in regs)
        finally:
            server.stop()
            server.join(timeout=2)


# ----------------------------------------------------------- rpc_view --dump
class TestRpcViewDump:
    def test_renders_dump_summary(self, tmp_path, capsys):
        from tools import rpc_view

        with open(tmp_path / "requests.9.dump", "wb") as f:
            f.write(pack_record(_mk_meta(method="Legacy"), b"v1"))
        dumper = RpcDumper(str(tmp_path))
        for _ in range(2):
            dumper.commit(dumper.begin(_mk_meta(), b"bodybytes"),
                          _mk_span({"execute_us": 10.0}))
        dumper.close()

        assert rpc_view.main(["--dump", str(tmp_path)]) == 0
        out = capsys.readouterr().out
        assert "records: 3 (v1/v2; 2 with phase timelines)" in out
        assert "EchoService.Echo" in out and "EchoService.Legacy" in out

    def test_requires_server_or_dump(self, capsys):
        from tools import rpc_view

        with pytest.raises(SystemExit):
            rpc_view.main([])
        assert "server is required" in capsys.readouterr().err

    def test_missing_path_fails_cleanly(self, tmp_path, capsys):
        from tools import rpc_view

        assert rpc_view.main(["--dump", str(tmp_path / "nope")]) == 1


# --------------------------------------------------------------- OTLP export
class TestOtlpExport:
    def test_span_to_otlp_shape(self):
        from brpc_tpu.trace import export as _export

        sp = _mk_span({"execute_us": 99.5}, latency_us=500.0,
                      trace_id=0x1234, span_id=0x5678)
        sp.parent_span_id = 0x42
        sp.error_code = 7
        d = _export.span_to_otlp(sp)
        assert d["traceId"] == f"{0x1234:032x}"
        assert d["spanId"] == f"{0x5678:016x}"
        assert d["parentSpanId"] == f"{0x42:016x}"
        assert d["kind"] == 2  # server
        assert d["status"]["code"] == 2
        phases = {a["key"]: a["value"] for a in d["attributes"]
                  if a["key"].startswith("phase.")}
        assert phases["phase.execute_us"]["doubleValue"] == 99.5
        assert int(d["endTimeUnixNano"]) - int(d["startTimeUnixNano"]) \
            == 500_000

    def test_export_hook_writes_json_lines(self, tmp_path, traced):
        from brpc_tpu.trace import export as _export

        path = tmp_path / "spans.jsonl"
        _export.reset_for_test()
        _flags.set_flag("span_export_path", str(path))
        try:
            n0 = _export.g_spans_exported.get_value()
            sp = _span.Span(0xaa, 0xbb, 0, _span.KIND_CLIENT, "S", "M")
            sp.add_phase("send_us", 5.0)
            sp.end()  # Span.end drives the export hook
            assert _export.g_spans_exported.get_value() == n0 + 1
        finally:
            _flags.set_flag("span_export_path", "")
            _export.reset_for_test()
        lines = path.read_text().splitlines()
        assert len(lines) == 1
        doc = json.loads(lines[0])
        span = doc["resourceSpans"][0]["scopeSpans"][0]["spans"][0]
        assert span["traceId"] == f"{0xaa:032x}"
        assert span["kind"] == 3  # client

    def test_export_off_by_default(self, traced):
        from brpc_tpu.trace import export as _export

        n0 = _export.g_spans_exported.get_value()
        _span.Span(1, 2, 0, _span.KIND_CLIENT, "S", "M").end()
        assert _export.g_spans_exported.get_value() == n0


# ------------------------------------------------------------- stitched tree
class TestStitchedTree:
    def test_build_span_tree_nests_by_parent(self):
        spans = [
            {"span_id": "aa", "parent_span_id": "00", "kind": "client",
             "start_us": 1.0},
            {"span_id": "bb", "parent_span_id": "aa", "kind": "server",
             "start_us": 2.0},
            {"span_id": "cc", "parent_span_id": "bb", "kind": "client",
             "start_us": 3.0},
        ]
        tree = _span.build_span_tree(spans)
        assert len(tree) == 1
        assert tree[0]["kind"] == "client"
        assert tree[0]["children"][0]["kind"] == "server"
        assert tree[0]["children"][0]["children"][0]["span_id"] == "cc"

    def test_trace_to_dict_carries_tree(self, traced):
        tid = 0x777
        cli = _span.Span(tid, 0x1, 0, _span.KIND_CLIENT, "S", "M")
        srv = _span.Span(tid, 0x2, 0x1, _span.KIND_SERVER, "S", "M")
        srv.end()
        cli.end()
        doc = _span.trace_to_dict(tid)
        assert doc["trace_id"] == f"{tid:016x}"
        assert len(doc["spans"]) == 2
        assert len(doc["tree"]) == 1
        assert doc["tree"][0]["children"][0]["kind"] == "server"

    def test_merge_trace_docs_dedups_across_processes(self):
        cli = {"span_id": "aa", "parent_span_id": "00", "kind": "client",
               "start_us": 1.0}
        srv = {"span_id": "bb", "parent_span_id": "aa", "kind": "server",
               "start_us": 2.0}
        merged = _span.merge_trace_docs([
            {"trace_id": "t1", "spans": [cli]},
            {"trace_id": "t1", "spans": [dict(cli), srv]},  # overlap
        ])
        assert merged["trace_id"] == "t1"
        assert len(merged["spans"]) == 2
        assert merged["tree"][0]["children"][0]["span_id"] == "bb"
