"""Adaptive request batching (brpc_tpu/batch/): queue mechanics (flush on
size vs deadline vs poll boundary), padding/bucketing, per-item error
isolation, backpressure ELIMIT, and a CPU-only end-to-end batched echo
through a real Server + Channel."""

import threading
import time

import pytest

from brpc_tpu.batch import (
    BatchContext,
    BatchPolicy,
    batched_method,
    flush_poll_batch,
    make_batched,
)
from brpc_tpu.batch import metrics as bmetrics
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    Service,
    Stub,
    errors,
)
from brpc_tpu.rpc.controller import Controller

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


def _drive(bm, n, results, start=0):
    """Admit n requests through the dispatch-path contract; done callbacks
    collect (index, response)."""
    cntls = []
    for i in range(start, start + n):
        c = Controller()
        cntls.append(c)

        def done(resp=None, _i=i, _c=c):
            results.append((_i, resp, _c.error_code))

        ret = bm(c, f"req{i}", done)
        assert ret is None  # async per the dispatch contract
        if c.failed():      # dispatcher would send the error itself
            results.append((i, None, c.error_code))
    return cntls


def _wait(pred, timeout=5.0):
    deadline = time.time() + timeout
    while time.time() < deadline:
        if pred():
            return True
        time.sleep(0.005)
    return False


class TestPolicy:
    def test_default_buckets_pow2(self):
        p = BatchPolicy(max_batch_size=32)
        assert p.bucket_shapes == (1, 2, 4, 8, 16, 32)
        assert p.bucket_for(1) == 1
        assert p.bucket_for(3) == 4
        assert p.bucket_for(17) == 32
        assert p.bucket_for(99) == 32  # capped at the largest bucket

    def test_custom_buckets_cover_max(self):
        p = BatchPolicy(max_batch_size=24, bucket_shapes=(4, 8))
        # the largest bucket must carry a full batch
        assert p.bucket_shapes == (4, 8, 24)
        assert p.bucket_for(9) == 24

    def test_max_queue_at_least_batch(self):
        p = BatchPolicy(max_batch_size=64, max_queue=8)
        assert p.max_queue == 64


class TestContext:
    def _items(self, payloads):
        class _Item:
            def __init__(self, req):
                self.request = req
                self.cntl = Controller()
                self.done = lambda resp=None: None
                self.enqueue_us = 0
                self.settled = False
        return [_Item(p) for p in payloads]

    def test_stack_pads_to_bucket(self):
        import numpy as np

        ctx = BatchContext(self._items([[1.0, 2.0], [3.0, 4.0]]), 4, "size")
        out = ctx.stack([it.request for it in ctx.items])
        assert out.shape == (4, 2)
        assert out[1].tolist() == [3.0, 4.0]
        assert np.all(out[2:] == 0)

    def test_stack_isolates_ragged_row(self):
        ctx = BatchContext(
            self._items([[1.0, 2.0], [1.0, 2.0, 3.0], [5.0, 6.0]]),
            4, "size")
        out = ctx.stack([it.request for it in ctx.items])
        assert out.shape == (4, 2)
        assert ctx.failed(1) and not ctx.failed(0) and not ctx.failed(2)
        assert ctx._errors[1][0] == errors.EREQUEST


class TestQueueFlush:
    def test_flush_on_size(self):
        batches, results = [], []
        bm = make_batched(
            "t.size", lambda b: batches.append(b.size) or ["ok"] * b.size,
            max_batch_size=4, max_delay_us=500000, flush_on_poll_batch=False)
        _drive(bm, 4, results)
        # size trigger fires immediately — nowhere near the 500ms deadline
        assert _wait(lambda: len(results) == 4, 3.0), results
        assert batches == [4]
        assert bm.queue.depth() == 0

    def test_flush_on_deadline(self):
        batches, results = [], []
        bm = make_batched(
            "t.dl", lambda b: batches.append(b.size) or ["ok"] * b.size,
            max_batch_size=64, max_delay_us=30000, flush_on_poll_batch=False)
        t0 = time.perf_counter()
        _drive(bm, 3, results)
        assert bm.queue.depth() == 3  # parked: size cap far away
        assert _wait(lambda: len(results) == 3, 5.0), results
        assert time.perf_counter() - t0 >= 0.025  # waited for the deadline
        assert batches == [3]

    def test_flush_on_poll_boundary(self):
        from brpc_tpu.rpc import input_messenger

        batches, results = [], []
        bm = make_batched(
            "t.poll", lambda b: batches.append(b.size) or ["ok"] * b.size,
            max_batch_size=64, max_delay_us=500000)
        _drive(bm, 5, results)
        assert bm.queue.depth() == 5
        # registering installed the messenger hook; the dispatcher calls it
        # after every cut loop
        assert input_messenger.poll_batch_hook is flush_poll_batch
        flush_poll_batch()
        assert _wait(lambda: len(results) == 5, 3.0), results
        assert batches == [5]
        flush_poll_batch()  # idle boundary: no-op
        assert batches == [5]

    def test_bucket_padding_recorded(self):
        seen = []
        bm = make_batched(
            "t.bucket",
            lambda b: seen.append((b.size, b.bucket)) or ["ok"] * b.size,
            max_batch_size=8, max_delay_us=5000, flush_on_poll_batch=False)
        results = []
        _drive(bm, 3, results)
        assert _wait(lambda: len(results) == 3, 3.0)
        assert seen == [(3, 4)]  # 3 live items padded to the 4-bucket

    def test_pad_waste_recorded_per_bucket(self):
        """Every flush records bucket - size into the per-bucket pad-waste
        recorder, surfaced on /vars as g_batch_pad_waste_<bucket> — the
        signal for tuning bucket_shapes against real traffic."""
        from brpc_tpu.metrics.variable import get_exposed

        bm = make_batched(
            "t.waste", lambda b: ["ok"] * b.size,
            max_batch_size=8, max_delay_us=5000, flush_on_poll_batch=False)
        results = []
        _drive(bm, 3, results)           # size 3 -> bucket 4 -> waste 1
        assert _wait(lambda: len(results) == 3, 3.0)
        waste_sum, waste_count = bmetrics.pad_waste_buckets()[4].get_value()
        assert waste_count >= 1 and waste_sum >= 1
        var = get_exposed("g_batch_pad_waste_4")
        assert var is not None
        rendered = var.describe()
        assert "count=" in rendered, rendered


class TestIsolation:
    def test_one_bad_request_fails_alone(self):
        def vec(batch):
            if any(r == "req1" for r in batch.requests):
                raise ValueError("poisoned")
            return [r.upper() for r in batch.requests]

        results = []
        bm = make_batched("t.iso", vec, max_batch_size=4, max_delay_us=0,
                          flush_on_poll_batch=False)
        _drive(bm, 4, results)
        bm.queue.flush()
        assert _wait(lambda: len(results) == 4, 5.0), results
        by_idx = {i: (resp, code) for i, resp, code in results}
        assert by_idx[1] == (None, errors.EINTERNAL)
        for i in (0, 2, 3):  # survivors re-ran as singletons
            assert by_idx[i] == (f"REQ{i}", 0)

    def test_fail_marks_single_item(self):
        def vec(batch):
            out = []
            for i, r in enumerate(batch.requests):
                if r.endswith("2"):
                    batch.fail(i, errors.EREQUEST, "bad tensor")
                    out.append(None)
                else:
                    out.append(r)
            return out

        results = []
        bm = make_batched("t.fail", vec, max_batch_size=4, max_delay_us=0,
                          flush_on_poll_batch=False)
        _drive(bm, 4, results)
        bm.queue.flush()
        assert _wait(lambda: len(results) == 4, 3.0), results
        by_idx = {i: (resp, code) for i, resp, code in results}
        assert by_idx[2] == (None, errors.EREQUEST)
        assert all(by_idx[i][1] == 0 for i in (0, 1, 3))

    def test_short_response_list_is_internal_error(self):
        results = []
        bm = make_batched("t.short", lambda b: [b.requests[0]],
                          max_batch_size=2, max_delay_us=0,
                          flush_on_poll_batch=False)
        _drive(bm, 2, results)
        bm.queue.flush()
        assert _wait(lambda: len(results) == 2, 3.0)
        by_idx = {i: code for i, _, code in results}
        assert by_idx[0] == 0 and by_idx[1] == errors.EINTERNAL


class TestBackpressure:
    def test_elimit_past_outstanding_cap(self):
        gate = threading.Event()

        def vec(batch):
            gate.wait(10)
            return ["ok"] * batch.size

        results = []
        bm = make_batched("t.bp", vec, max_batch_size=2, max_delay_us=0,
                          max_queue=4, flush_on_poll_batch=False)
        try:
            cntls = _drive(bm, 7, results)
            codes = [c.error_code for c in cntls]
            assert codes.count(errors.ELIMIT) == 3
            assert codes.count(0) == 4
            assert bm.queue.rejected == 3
        finally:
            gate.set()
        assert _wait(lambda: len(results) == 7, 5.0), results
        # slots free once batches settle: admission works again
        c = Controller()
        bm(c, "late", lambda resp=None: None)
        assert c.error_code == 0
        bm.queue.flush()

    def test_limiter_spec_admission(self):
        gate = threading.Event()

        def vec(batch):
            gate.wait(10)
            return ["ok"] * batch.size

        bm = make_batched("t.lim", vec, max_batch_size=8, max_delay_us=0,
                          flush_on_poll_batch=False, limiter="constant:2")
        results = []
        try:
            cntls = _drive(bm, 4, results)
            codes = [c.error_code for c in cntls]
            assert codes == [0, 0, errors.ELIMIT, errors.ELIMIT]
        finally:
            gate.set()
        bm.queue.flush()
        assert _wait(lambda: len(results) == 4, 5.0)


class TestObservability:
    def test_vars_exposed_and_recorded(self):
        from brpc_tpu.metrics import dump_exposed

        before = bmetrics.batch_size_recorder.get_value()[1]
        results = []
        bm = make_batched("t.vars", lambda b: ["ok"] * b.size,
                          max_batch_size=2, max_delay_us=0,
                          flush_on_poll_batch=False)
        _drive(bm, 2, results)
        assert _wait(lambda: len(results) == 2, 3.0)
        snapshot = dump_exposed()
        assert "g_batch_size" in snapshot
        assert "g_batch_queue_delay_us" in snapshot
        assert bmetrics.batch_size_recorder.get_value()[1] == before + 1

    def test_span_annotation(self):
        notes = []

        class _Span:
            def annotate(self, text):
                notes.append(text)

        results = []
        bm = make_batched("t.span", lambda b: ["ok"] * b.size,
                          max_batch_size=2, max_delay_us=0,
                          flush_on_poll_batch=False)
        c = Controller()
        c.span = _Span()
        bm(c, "x", lambda resp=None: results.append(resp))
        bm(Controller(), "y", lambda resp=None: results.append(resp))
        assert _wait(lambda: len(results) == 2, 3.0)
        assert len(notes) == 1
        assert "size=2" in notes[0] and "reason=size" in notes[0]
        assert "queue=t.span" in notes[0]


# ---------------------------------------------------------------- end to end
class BatchedEchoService(Service):
    """EchoService whose Echo is vectorized through @batched_method —
    DESCRIPTOR-driven wiring: Service.__init__'s getattr() binds the
    descriptor, which builds the per-instance BatchQueue."""

    DESCRIPTOR = ECHO_DESC

    def __init__(self):
        self.batch_sizes = []
        self.gate = None
        super().__init__()

    @batched_method(max_batch_size=8, max_delay_us=40000,
                    flush_on_poll_batch=False, max_queue=8)
    def Echo(self, batch):
        if self.gate is not None:
            self.gate.wait(10)
        self.batch_sizes.append(batch.size)
        out = []
        for i, req in enumerate(batch.requests):
            if req.message == "poison":
                batch.fail(i, errors.EREQUEST, "poisoned request")
                out.append(None)
            else:
                out.append(echo_pb2.EchoResponse(message=req.message.upper(),
                                                 payload=req.payload))
        return out


@pytest.fixture()
def batched_echo_server():
    impl = BatchedEchoService()
    server = Server().add_service(impl).start("127.0.0.1:0")
    yield server, impl
    if impl.gate is not None:
        impl.gate.set()
    server.stop()
    server.join(timeout=2)


def _async_burst(stub, messages, timeout=15.0):
    """Fire all messages without waiting, then collect (message, resp,
    code) per call."""
    ev = threading.Event()
    out = []
    lock = threading.Lock()

    def mk(msg):
        def done(cntl):
            with lock:
                out.append((msg, getattr(cntl, "_response", None),
                            cntl.error_code))
                if len(out) == len(messages):
                    ev.set()
        return done

    for m in messages:
        stub.Echo(echo_pb2.EchoRequest(message=m), done=mk(m))
    assert ev.wait(timeout), f"only {len(out)}/{len(messages)} completed"
    return out


class TestEndToEnd:
    def test_batched_echo_coalesces(self, batched_echo_server):
        server, impl = batched_echo_server
        ch = Channel(ChannelOptions(timeout_ms=15000)).init(
            str(server.listen_endpoint()))
        stub = Stub(ch, ECHO_DESC)
        msgs = [f"m{i}" for i in range(8)]
        out = _async_burst(stub, msgs)
        by_msg = {m: (r, c) for m, r, c in out}
        for m in msgs:
            resp, code = by_msg[m]
            assert code == 0 and resp.message == m.upper()
        assert sum(impl.batch_sizes) == 8
        # a pipelined burst against a 40ms deadline must coalesce
        assert max(impl.batch_sizes) >= 2, impl.batch_sizes

    def test_batched_echo_sync_call(self, batched_echo_server):
        server, impl = batched_echo_server
        ch = Channel(ChannelOptions(timeout_ms=15000)).init(
            str(server.listen_endpoint()))
        stub = Stub(ch, ECHO_DESC)
        resp = stub.Echo(echo_pb2.EchoRequest(message="solo"))
        assert resp.message == "SOLO"
        assert impl.batch_sizes and impl.batch_sizes[-1] == 1

    def test_poisoned_request_fails_alone_e2e(self, batched_echo_server):
        server, impl = batched_echo_server
        ch = Channel(ChannelOptions(timeout_ms=15000)).init(
            str(server.listen_endpoint()))
        stub = Stub(ch, ECHO_DESC)
        out = _async_burst(stub, ["a", "poison", "b", "c"])
        codes = {m: c for m, _, c in out}
        assert codes["poison"] == errors.EREQUEST
        assert codes["a"] == 0 and codes["b"] == 0 and codes["c"] == 0
        resps = {m: r for m, r, _ in out}
        assert resps["a"].message == "A"

    def test_backpressure_elimit_e2e(self, batched_echo_server):
        server, impl = batched_echo_server
        impl.gate = threading.Event()
        ch = Channel(ChannelOptions(timeout_ms=20000)).init(
            str(server.listen_endpoint()))
        stub = Stub(ch, ECHO_DESC)
        # max_queue=8: a 12-call burst must shed at least the overflow
        # while the handler is gated; the rest complete after release
        ev = threading.Event()
        out = []
        lock = threading.Lock()

        def done(cntl):
            with lock:
                out.append(cntl.error_code)
                if len(out) == 12:
                    ev.set()

        for i in range(12):
            stub.Echo(echo_pb2.EchoRequest(message=f"q{i}"), done=done)
        # the burst lands while the gate is closed; give the overflow time
        # to be rejected, then open the gate for the admitted calls
        time.sleep(0.3)
        impl.gate.set()
        assert ev.wait(20), f"only {len(out)}/12 completed"
        rejected = sum(1 for c in out if c == errors.ELIMIT)
        succeeded = sum(1 for c in out if c == 0)
        assert rejected >= 1, out
        assert succeeded >= 8, out
        assert rejected + succeeded == 12, out
