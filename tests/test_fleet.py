"""Fleet observer plane (ISSUE 20): op-correct cross-server merge of
scraped /vars, member liveness under injected + real failures, the SLO
engine's multi-window error-budget burn, the /fleet and /slo builtins,
and the 2-real-server acceptance path (cluster Adder exactness + the
slo_burn watch rule flipping firing -> ok on a seeded latency spike)."""

import json
import time

import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.fleet import (
    FleetObserver,
    SloEngine,
    SloObjective,
    global_observer,
    global_slo,
    set_global_observer,
)
from brpc_tpu.metrics import clear_registry
from brpc_tpu.metrics.reducer import Adder
from brpc_tpu.metrics.series import global_series
from brpc_tpu.metrics.status import PassiveStatus
from brpc_tpu.metrics.variable import get_exposed
from brpc_tpu.metrics.watch import STATE_FIRING, STATE_OK, global_watch


@pytest.fixture(autouse=True)
def _clean_state():
    clear_registry()
    global_series().clear()
    yield
    global_slo().clear()
    set_global_observer(None)
    fault.disarm_all()
    clear_registry()
    global_series().clear()


@pytest.fixture()
def fault_enabled():
    _flags.set_flag("fault_injection_enabled", True)
    yield
    fault.disarm_all()
    _flags.set_flag("fault_injection_enabled", False)


class _Http:
    """Minimal HttpMessage stand-in for invoking builtin handlers."""

    def __init__(self, path, query=None, headers=None):
        self.path = path
        self.query = query or {}
        self.headers = headers or {}

    def header(self, name, default=""):
        return self.headers.get(name, default)


def _doc(vars_map, series=None, rules=None, engines=None):
    """One fake member's scrape surface, keyed by endpoint path."""
    return {
        "/vars?series=json": {"workers": 0, "series": series or {},
                              "vars": vars_map},
        "/serving?format=json": {"engines": engines or []},
        "/watch?format=json": {"rules": rules or []},
    }


def _stub_fetch(cluster):
    """cluster: {addr: _doc(...)}. Missing addr/path -> ConnectionError."""
    def fetch(addr, path):
        member = cluster.get(addr)
        if member is None:
            raise ConnectionError(f"no route to {addr}")
        doc = member.get(path)
        if doc is None:
            raise ConnectionError(f"{addr}{path} -> HTTP 404")
        return doc
    return fetch


# ----------------------------------------------------------------- seeds
class TestObserverSeeds:
    def test_list_scheme_and_plain_and_list(self):
        for seeds in ("list://a:1,b:2", "a:1,b:2", ["a:1", "b:2"]):
            obs = FleetObserver(seeds, fetch=_stub_fetch({}))
            try:
                assert obs.member_addrs() == ["a:1", "b:2"]
            finally:
                obs.hide_all()

    def test_naming_service_reconsulted_each_round(self):
        class _Node:
            def __init__(self, ep):
                self.ep = ep

        class _Naming:
            def __init__(self):
                self.addrs = ["a:1"]

            def get_servers(self):
                return [_Node(a) for a in self.addrs]

        ns = _Naming()
        obs = FleetObserver(ns, fetch=_stub_fetch(
            {"a:1": _doc({}), "b:2": _doc({})}))
        try:
            obs.scrape_once()
            assert [m.addr for m in obs.members()] == ["a:1"]
            ns.addrs = ["a:1", "b:2"]   # the autoscaler hook: new member
            assert obs.member_addrs() == ["a:1", "b:2"]
            obs.scrape_once()
            assert [m.addr for m in obs.members()] == ["a:1", "b:2"]
        finally:
            obs.hide_all()


# ----------------------------------------------------------------- merge
class TestObserverMerge:
    def test_adder_sum_is_exact(self):
        obs = FleetObserver("a:1,b:2", fetch=_stub_fetch({
            "a:1": _doc({"g_reqs": ["sum", "counter", 2]}),
            "b:2": _doc({"g_reqs": ["sum", "counter", 3]}),
        }))
        try:
            assert obs.scrape_once() == 2
            assert obs.cluster_value("g_reqs") == 5
            var = get_exposed("cluster_g_reqs")
            assert var is not None and var.get_value() == 5
            assert var.prometheus_type == "counter"
            assert "sum" in var.prometheus_help
        finally:
            obs.hide_all()

    def test_latency_merges_qps_weighted_and_p99_takes_max(self):
        obs = FleetObserver("a:1,b:2", fetch=_stub_fetch({
            "a:1": _doc({"m_latency": ["wavg_qps", "gauge", 100.0],
                         "m_qps": ["sum", "gauge", 1.0],
                         "m_latency_p99": ["max", "gauge", 400.0]}),
            "b:2": _doc({"m_latency": ["wavg_qps", "gauge", 300.0],
                         "m_qps": ["sum", "gauge", 3.0],
                         "m_latency_p99": ["max", "gauge", 900.0]}),
        }))
        try:
            obs.scrape_once()
            # (100*1 + 300*3) / 4 — the busy member dominates the mean
            assert obs.cluster_value("m_latency") == pytest.approx(250.0)
            assert obs.cluster_value("m_qps") == pytest.approx(4.0)
            # conservative percentile bound: max, never an average
            assert obs.cluster_value("m_latency_p99") == 900.0
        finally:
            obs.hide_all()

    def test_derived_families_never_reingested(self):
        # an observer scraping an observer (or itself) must not feed
        # cluster_*/g_slo_* aggregates back into the merge
        obs = FleetObserver("a:1", fetch=_stub_fetch({
            "a:1": _doc({"g_x": ["sum", "counter", 1],
                         "cluster_g_x": ["sum", "counter", 99],
                         "g_slo_echo_burn": ["avg", "gauge", 5.0]}),
        }))
        try:
            obs.scrape_once()
            member = obs.members()[0]
            assert "g_x" in member.vars
            assert "cluster_g_x" not in member.vars
            assert "g_slo_echo_burn" not in member.vars
            assert get_exposed("cluster_cluster_g_x") is None
        finally:
            obs.hide_all()

    def test_malformed_records_skipped(self):
        obs = FleetObserver("a:1", fetch=_stub_fetch({
            "a:1": _doc({"ok": ["sum", "counter", 1],
                         "bad_arity": ["sum", "counter"],
                         "bad_value": ["sum", "counter", "nope"],
                         "bad_bool": ["sum", "counter", True]}),
        }))
        try:
            obs.scrape_once()
            assert set(obs.members()[0].vars) == {"ok"}
        finally:
            obs.hide_all()

    def test_merged_series_elementwise(self):
        obs = FleetObserver("a:1,b:2", fetch=_stub_fetch({
            "a:1": _doc({"g_q": ["sum", "gauge", 3.0]},
                        series={"g_q": {"second": [1.0, 2.0, 3.0],
                                        "count": 3}}),
            "b:2": _doc({"g_q": ["sum", "gauge", 30.0]},
                        series={"g_q": {"second": [10.0, 20.0, 30.0],
                                        "count": 2}}),
        }))
        try:
            obs.scrape_once()
            doc = obs.merged_series("g_q")
            assert doc["second"] == [11.0, 22.0, 33.0]
            assert doc["count"] == 3
            assert doc["op"] == "sum"
            assert obs.merged_series("no_such_var") is None
        finally:
            obs.hide_all()

    def test_serving_union_and_firing(self):
        obs = FleetObserver("a:1,b:2", fetch=_stub_fetch({
            "a:1": _doc({}, engines=[
                {"kv": {"shard_map": {"7": "0", "9": "1"}}}]),
            "b:2": _doc({}, rules=[
                {"name": "kv_pressure", "state": "firing"},
                {"name": "quiet", "state": "ok"}]),
        }))
        try:
            obs.scrape_once()
            assert obs.serving_shard_union() == {
                "a:1/7": "0", "a:1/9": "1"}
            assert obs.firing_rules() == {"b:2": ["kv_pressure"]}
        finally:
            obs.hide_all()


# ----------------------------------------------------------------- chaos
class TestObserverChaos:
    def test_member_death_degrades_and_recovers(self, fault_enabled):
        docs = {
            "a:1": _doc({"g_n": ["sum", "counter", 10]}),
            "b:2": _doc({"g_n": ["sum", "counter", 7]}),
        }
        obs = FleetObserver("a:1,b:2", fetch=_stub_fetch(docs))
        try:
            assert obs.scrape_once() == 2
            assert obs.cluster_value("g_n") == 17
            # kill only member b mid-scrape via the fault point
            fault.arm("fleet.scrape.fail", mode="always",
                      match={"member": "b:2"})
            assert obs.scrape_once() == 1   # no crash, a still answers
            a, b = obs.members()
            assert a.live() and not b.live()
            assert b.stale()
            assert b.consecutive_failures == 1
            assert "fleet.scrape.fail" in b.last_error
            # cluster_* degrades gracefully to the live subset
            assert obs.cluster_value("g_n") == 10
            assert get_exposed("cluster_fleet_members_live").get_value() == 1
            # recovery: disarm -> next scrape folds b back in
            fault.disarm("fleet.scrape.fail")
            assert obs.scrape_once() == 2
            assert all(m.live() for m in obs.members())
            assert obs.cluster_value("g_n") == 17
        finally:
            obs.hide_all()

    def test_all_members_dead_returns_zero_not_crash(self, fault_enabled):
        fault.arm("fleet.scrape.fail", mode="always")
        obs = FleetObserver("a:1,b:2", fetch=_stub_fetch({
            "a:1": _doc({}), "b:2": _doc({})}))
        try:
            assert obs.scrape_once() == 0
            assert obs.live_members() == []
            assert obs.cluster_value("anything") == 0
        finally:
            obs.hide_all()

    def test_fetch_exception_marks_member_not_live(self):
        # a plain network error (no fault framework) takes the same path
        obs = FleetObserver("a:1,gone:9", fetch=_stub_fetch(
            {"a:1": _doc({"g_n": ["sum", "counter", 4]})}))
        try:
            assert obs.scrape_once() == 1
            gone = [m for m in obs.members() if m.addr == "gone:9"][0]
            assert not gone.live() and gone.scrapes_failed == 1
            assert obs.cluster_value("g_n") == 4
        finally:
            obs.hide_all()


# --------------------------------------------------------------- builtins
class TestFleetBuiltin:
    def test_no_observer_message(self):
        from brpc_tpu.builtin.services import fleet_service

        status, _, body = fleet_service(None, _Http("/fleet"))
        assert status == 200 and "no fleet observer" in body

    def test_member_table_and_json(self):
        from brpc_tpu.builtin.services import fleet_service

        obs = FleetObserver("a:1,b:2", fetch=_stub_fetch({
            "a:1": _doc({"g_n": ["sum", "counter", 1]},
                        rules=[{"name": "hot", "state": "firing"}]),
        }))
        set_global_observer(obs)
        try:
            obs.scrape_once()
            status, _, body = fleet_service(None, _Http("/fleet"))
            assert status == 200
            assert "1/2 members live" in body
            assert "a:1" in body and "b:2" in body
            assert "hot" in body
            status, ctype, body = fleet_service(
                None, _Http("/fleet", {"format": "json"}))
            assert status == 200 and "json" in ctype
            doc = json.loads(body)
            assert doc["live"] == 1 and len(doc["members"]) == 2
            assert doc["firing"] == {"a:1": ["hot"]}
        finally:
            set_global_observer(None)
            obs.hide_all()

    def test_trace_404_when_no_spans(self):
        from brpc_tpu.builtin.services import fleet_service

        obs = FleetObserver("a:1", fetch=_stub_fetch({"a:1": _doc({})}))
        set_global_observer(obs)
        try:
            obs.scrape_once()
            status, _, body = fleet_service(
                None, _Http("/fleet/trace/deadbeef"))
            assert status == 404
        finally:
            set_global_observer(None)
            obs.hide_all()


# ------------------------------------------------------------------- slo
class TestSloSpec:
    def test_stem_derivation_and_bound_ms(self):
        obj = SloObjective.from_spec(
            "echo:var=rpc_method_echoservice_echo,bound_ms=50,"
            "objective=0.02,fast_s=5,slow_s=30,tenant=gold")
        assert obj.name == "echo"
        assert obj.latency_var == "rpc_method_echoservice_echo_latency_p99"
        assert obj.errors_var == "rpc_method_echoservice_echo_errors"
        assert obj.total_var == "rpc_method_echoservice_echo_count"
        assert obj.latency_bound_us == 50000.0
        assert obj.objective == 0.02
        assert (obj.fast_window_s, obj.slow_window_s) == (5, 30)
        assert obj.tenant == "gold"

    def test_explicit_vars_override_stem(self):
        obj = SloObjective.from_spec(
            "x:var=stem,latency_var=custom_p99,bound_us=1500")
        assert obj.latency_var == "custom_p99"
        assert obj.latency_bound_us == 1500.0
        assert obj.errors_var == "stem_errors"

    def test_invalid_specs_rejected(self):
        with pytest.raises(ValueError):
            SloObjective.from_spec(":var=x")          # no name
        with pytest.raises(ValueError):
            SloObjective.from_spec("x:novalue")       # piece without =
        with pytest.raises(ValueError):
            SloObjective("x", latency_var="v", objective=0.0)
        with pytest.raises(ValueError):
            SloObjective("x", latency_var="v", fast_window_s=10,
                         slow_window_s=5)
        with pytest.raises(ValueError):
            SloObjective("x")                         # no vars at all

    def test_objectives_flag_installs_on_global_engine(self):
        _flags.set_flag("slo_objectives",
                        "flagged:var=rpc_method_x,bound_ms=10")
        try:
            names = [o.name for o in global_slo().objectives()]
            assert "flagged" in names
            assert any(r.name == "slo_burn_flagged"
                       for r in global_watch().rules())
        finally:
            global_slo().clear()
            _flags.set_flag("slo_objectives", "")
        # a bad spec string is rejected by the validator, not half-applied
        with pytest.raises(_flags.FlagError):
            _flags.set_flag("slo_objectives", "broken spec")


class TestSloBurn:
    def test_latency_burn_multi_window_gate(self):
        from brpc_tpu.metrics.series import SeriesRegistry

        holder = {"p99": 50.0}
        PassiveStatus(lambda: holder["p99"]).expose("t_slo_p99")
        engine = SloEngine()
        engine.add(SloObjective(
            "t", latency_var="t_slo_p99", latency_bound_us=100.0,
            objective=0.1, fast_window_s=4, slow_window_s=8))
        try:
            # a private registry so the 1Hz background sampler can't add
            # extra ticks under the exact-arithmetic assertions below
            reg = SeriesRegistry()
            for _ in range(8):
                reg.tick()                       # healthy baseline
            engine.evaluate(reg)
            state = engine._state["t"]
            assert state["burn"] == 0.0
            assert state["budget_left"] == 1.0
            holder["p99"] = 500.0                # breach the 100us bound
            for _ in range(2):
                reg.tick()
            engine.evaluate(reg)
            state = engine._state["t"]
            # fast window (4s): 2/4 violations / 0.1 objective = 5
            assert state["burn_fast"] == pytest.approx(5.0)
            # slow window (8s): 2/8 / 0.1 = 2.5; headline = min(fast, slow)
            assert state["burn_slow"] == pytest.approx(2.5)
            assert state["burn"] == pytest.approx(2.5)
            assert state["budget_left"] == 0.0
            # the exposed gauge reads the cache, not the series registry
            assert get_exposed("g_slo_t_burn").get_value() == \
                pytest.approx(2.5)
            assert get_exposed("g_slo_t_budget_left").get_value() == 0.0
        finally:
            engine.clear()

    def test_error_burn_from_counter_deltas(self):
        from brpc_tpu.metrics.series import SeriesRegistry

        errors = Adder("t_slo_e")
        errors.expose_as("t_slo_e")
        total = Adder("t_slo_n")
        total.expose_as("t_slo_n")
        engine = SloEngine()
        engine.add(SloObjective(
            "e", errors_var="t_slo_e", total_var="t_slo_n",
            objective=0.1, fast_window_s=4, slow_window_s=8))
        try:
            reg = SeriesRegistry()
            reg.tick()
            total.put(100)
            errors.put(5)
            reg.tick()
            engine.evaluate(reg)
            # 5 errors / 100 requests = 5% rate, / 10% objective = 0.5
            state = engine._state["e"]
            assert state["burn_fast"] == pytest.approx(0.5)
            assert state["burn"] <= 1.0
        finally:
            engine.clear()

    def test_rule_bound_reloadable_via_flag(self):
        engine = SloEngine()
        engine.add(SloObjective("r", latency_var="v", latency_bound_us=1))
        try:
            rule = {r.name: r for r in global_watch().rules()}["slo_burn_r"]
            assert rule.bound() == 1.0
            _flags.set_flag("slo_burn_threshold", 2.5)
            assert rule.bound() == 2.5
        finally:
            _flags.set_flag("slo_burn_threshold", 1.0)
            engine.clear()

    def test_slo_builtin_text_and_json(self):
        from brpc_tpu.builtin.services import slo_service

        status, _, body = slo_service(None, _Http("/slo"))
        assert status == 200 and "no slo objectives" in body
        engine = global_slo()
        engine.add(SloObjective(
            "b", latency_var="v_p99", latency_bound_us=2000.0))
        try:
            status, _, body = slo_service(None, _Http("/slo"))
            assert "b" in body and "burn threshold" in body
            status, ctype, body = slo_service(
                None, _Http("/slo", {"format": "json"}))
            doc = json.loads(body)
            assert doc["source"] == "local"
            assert doc["objectives"][0]["name"] == "b"
            assert doc["objectives"][0]["rule"]["name"] == "slo_burn_b"
        finally:
            engine.clear()

    def test_fleet_source_reads_observer_merged_series(self):
        obs = FleetObserver("a:1,b:2", fetch=_stub_fetch({
            "a:1": _doc({"m_p99": ["max", "gauge", 900.0]},
                        series={"m_p99": {"second": [900.0] * 4,
                                          "count": 4}}),
            "b:2": _doc({"m_p99": ["max", "gauge", 10.0]},
                        series={"m_p99": {"second": [10.0] * 4,
                                          "count": 4}}),
        }))
        engine = SloEngine().attach_observer(obs)
        engine.add(SloObjective(
            "f", latency_var="m_p99", latency_bound_us=100.0,
            objective=0.5, fast_window_s=2, slow_window_s=4))
        try:
            obs.scrape_once()
            engine.evaluate(global_series())
            # merged p99 = max(900, 10) = 900 > 100us bound every second:
            # burn = 1.0 violation rate / 0.5 objective = 2 on both windows
            state = engine._state["f"]
            assert state["burn_fast"] == pytest.approx(2.0)
            assert state["burn"] == pytest.approx(2.0)
            assert engine.to_dict()["source"] == "fleet"
        finally:
            engine.clear()
            obs.hide_all()


# --------------------------------------------------- 2-real-server e2e
class TestFleetE2E:
    def _start_pair(self):
        from brpc_tpu.rpc import Server
        from tests.test_http import EchoServiceImpl

        a = Server().add_service(EchoServiceImpl()).start("127.0.0.1:0")
        b = Server().add_service(EchoServiceImpl()).start("127.0.0.1:0")
        return a, b

    def test_cluster_adder_exactness_over_real_scrape(self):
        from brpc_tpu.policy.http_protocol import http_fetch

        a, b = self._start_pair()
        counter = Adder("g_fleet_e2e_reqs")
        counter.expose_as("g_fleet_e2e_reqs")
        addr_a = str(a.listen_endpoint())
        addr_b = str(b.listen_endpoint())
        obs = FleetObserver(f"list://{addr_a},{addr_b}")
        try:
            counter.put(7)
            assert obs.scrape_once() == 2
            # acceptance: the cluster Adder aggregate equals the sum of
            # independently fetched member /vars values, exactly
            member_sum = 0
            for addr in (addr_a, addr_b):
                resp = http_fetch(addr, "GET", "/vars?series=json")
                assert resp.status == 200
                doc = json.loads(bytes(resp.body).decode())
                member_sum += doc["vars"]["g_fleet_e2e_reqs"][2]
            assert obs.cluster_value("g_fleet_e2e_reqs") == member_sum
            assert get_exposed(
                "cluster_g_fleet_e2e_reqs").get_value() == member_sum
            # /fleet over real HTTP from a member port
            set_global_observer(obs)
            resp = http_fetch(addr_a, "GET", "/fleet")
            assert resp.status == 200
            assert addr_b.encode() in bytes(resp.body)
            assert b"2/2 members live" in bytes(resp.body)
        finally:
            set_global_observer(None)
            obs.hide_all()
            for srv in (a, b):
                srv.stop()
                srv.join(timeout=2)

    def test_real_member_death_marks_stale(self):
        a, b = self._start_pair()
        addr_a = str(a.listen_endpoint())
        addr_b = str(b.listen_endpoint())
        obs = FleetObserver(f"list://{addr_a},{addr_b}")
        try:
            assert obs.scrape_once() == 2
            b.stop()
            b.join(timeout=2)
            assert obs.scrape_once() == 1   # observer survives the death
            dead = [m for m in obs.members() if m.addr == addr_b][0]
            assert not dead.live() and dead.stale()
            live = [m for m in obs.members() if m.addr == addr_a][0]
            assert live.live()
        finally:
            obs.hide_all()
            a.stop()
            a.join(timeout=2)

    def test_seeded_latency_spike_flips_slo_burn_rule(self, fault_enabled):
        """Acceptance: a per-method latency spike seeded on one member via
        rpc.handler.delay drives the observer's slo_burn rule to firing,
        then back to ok once the spike rolls out of the percentile
        window (ticks driven manually — no wall-clock waits)."""
        from brpc_tpu.metrics import global_collector
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, Stub
        from tests.test_http import ECHO_DESC

        a, b = self._start_pair()
        addr_a = str(a.listen_endpoint())
        addr_b = str(b.listen_endpoint())
        obs = FleetObserver(f"list://{addr_a},{addr_b}")
        engine = global_slo().attach_observer(obs)   # /slo reads this one
        # native protocol: its dispatch path carries the rpc.handler.delay
        # fault point (the http lane has no injection sites)
        stub = Stub(Channel().init(addr_a), ECHO_DESC)

        def pump(n):
            for i in range(n):
                assert stub.Echo(
                    echo_pb2.EchoRequest(message=str(i))).message == str(i)

        def step():
            global_collector().tick_all()   # sweep vars into series
            obs.scrape_once()               # pull member series
            engine.evaluate(global_series())  # recompute burn cache
            global_collector().tick_all()   # sample g_slo_*, run watch

        try:
            engine.add(SloObjective(
                "echo", latency_var="rpc_method_echoservice_echo_latency_p99",
                latency_bound_us=20000.0, objective=0.25,
                fast_window_s=4, slow_window_s=8))
            rule = {r.name: r
                    for r in global_watch().rules()}["slo_burn_echo"]
            pump(5)                          # healthy baseline
            for _ in range(4):
                step()
            assert rule.state in (STATE_OK, "no_data")
            # the spike: every Echo on member a delayed 30ms > 20ms bound
            fault.arm("rpc.handler.delay", mode="always", delay_ms=30)
            deadline = time.monotonic() + 30.0
            while rule.state != STATE_FIRING:
                assert time.monotonic() < deadline, \
                    f"rule never fired (observed={rule.observed})"
                pump(2)
                step()
            assert rule.state == STATE_FIRING
            # /slo shows the burn from the fleet-merged series
            from brpc_tpu.builtin.services import slo_service

            _, _, body = slo_service(
                None, _Http("/slo", {"format": "json"}))
            doc = json.loads(body)
            echo = [o for o in doc["objectives"] if o["name"] == "echo"][0]
            assert doc["source"] == "fleet"
            assert echo["burn"] > 1.0
            # recovery: disarm, fast traffic rolls the spike out of the
            # percentile window, the rule clears back to ok
            fault.disarm("rpc.handler.delay")
            deadline = time.monotonic() + 30.0
            while rule.state != STATE_OK:
                assert time.monotonic() < deadline, \
                    f"rule never cleared (observed={rule.observed})"
                pump(4)
                step()
            assert rule.state == STATE_OK
        finally:
            engine.clear()
            engine.attach_observer(None)
            obs.hide_all()
            for srv in (a, b):
                srv.stop()
                srv.join(timeout=2)
