"""Speculative decoding: k-token draft + one fused verify, exact oracle.

Layers, cheapest first:

* the draft lane pure-host: prompt-lookup matching, the AdaptiveK
  controller's shrink/collapse policy, the misdraft fault;
* the KV ledger's rollback primitive — ``truncate_sequence`` frees only
  the tail, respects shared refcounts (prefix-cache forks), and keeps
  the armed audit green;
* the model's ``verify_step`` against sequential ``decode_step``s — the
  same-launch write-before-gather semantics that make k+1 rows in one
  program equal k+1 steps;
* the engine end to end — the exact oracle (speculative outputs
  list-equal to the non-speculative lane on both committed corpus
  schedules, with the (1,1) dispatch audit armed), TokenDelta
  ``accepted`` framing, variable-spend budgeting;
* misdraft chaos — accept rate pinned ~0 still terminates bit-identical,
  leaks zero blocks, and the collapse guard bounds the wasted rows;
* the committed repetition-heavy corpus replayed through the
  rpc_replay→trace_diff gate, like the base corpus.
"""

import json
import os
import threading
import time

import numpy as np
import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.serving import (
    EngineConfig,
    KVCacheConfig,
    LlmServingService,
    ModelConfig,
    PagedKVCache,
    ServingEngine,
    TinyTransformer,
)
from brpc_tpu.serving import speculative as spec

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS_SPEC = os.path.join(REPO, "tests", "data", "serving_corpus_spec")

# mixed synth-prompt schedule (the base corpus shape) + repetitive
# motif prompts (the spec corpus shape, tokens < the test vocab of 64)
BASE_SCHED = [(16, 4), (32, 8), (16, 6), (16, 4), (32, 8), (16, 6)]
_MOTIFS = [[7, 12, 19, 3, 12, 19], [41, 41, 9, 33, 41, 41, 9],
           [50, 5, 60, 5, 50, 5, 60]]
REP_SCHED = [(18, 16, 0), (21, 24, 1), (16, 16, 2), (18, 24, 0)]


def _motif_prompt(plen, motif):
    m = _MOTIFS[motif % len(_MOTIFS)]
    return np.asarray((m * (plen // len(m) + 1))[:plen], dtype=np.int32)


def _gen(engine, prompt, max_new, stream_id=0, timeout=120.0):
    ev = threading.Event()
    box = {}
    code, _ = engine.submit(np.asarray(prompt, dtype=np.int32), max_new,
                            stream_id=stream_id,
                            done=lambda r, b=box, e=ev: (b.update(r=r),
                                                         e.set()))
    assert code == 0, f"submit rejected: {code}"
    assert ev.wait(timeout), "generation timed out"
    return list(box["r"].tokens)


def _run_base(engine):
    """BASE_SCHED submitted open-loop, all responses collected in order."""
    evs = []
    for plen, max_new in BASE_SCHED:
        ev, box = threading.Event(), {}
        code, _ = engine.submit(engine.model.synth_prompt(plen), max_new,
                                done=lambda r, b=box, e=ev: (b.update(r=r),
                                                             e.set()))
        assert code == 0
        evs.append((ev, box))
    return [(e.wait(180), list(b["r"].tokens))[1] for e, b in evs]


def _run_rep(engine):
    evs = []
    for plen, max_new, motif in REP_SCHED:
        ev, box = threading.Event(), {}
        code, _ = engine.submit(_motif_prompt(plen, motif), max_new,
                                done=lambda r, b=box, e=ev: (b.update(r=r),
                                                             e.set()))
        assert code == 0
        evs.append((ev, box))
    return [(e.wait(180), list(b["r"].tokens))[1] for e, b in evs]


# ---------------------------------------------------------------- draft lane
class TestDrafter:
    def test_longest_ngram_most_recent_occurrence_wins(self):
        #           0  1  2  3  4  5  6  7
        history = [1, 2, 3, 9, 1, 2, 3, 9]
        # trailing 3-gram (2,3,9) last occurred at 1..3 -> continuation 1,2
        # wait: occurrence search excludes the tail itself
        assert spec.draft_tokens(history, 2) == [1, 2]

    def test_shorter_ngram_fallback(self):
        history = [5, 6, 7, 8, 6]
        # no 3- or 2-gram recurs; trailing 1-gram 6 followed 5 -> drafts 7, 8
        assert spec.draft_tokens(history, 3) == [7, 8, 6]

    def test_no_match_returns_empty(self):
        assert spec.draft_tokens([1, 2, 3, 4, 5], 4) == []
        assert spec.draft_tokens([1], 4) == []
        assert spec.draft_tokens([1, 1, 1], 0) == []

    def test_draft_capped_at_k(self):
        history = [1, 2, 3, 4, 1, 2]
        d = spec.draft_tokens(history, 2)
        assert d == [3, 4]

    def test_accept_longest_prefix(self):
        a, committed = spec.accept_longest_prefix([5, 6, 7], [5, 6, 9, 8])
        assert a == 2 and committed == [5, 6, 9]
        a, committed = spec.accept_longest_prefix([5, 6, 7], [5, 6, 7, 8])
        assert a == 3 and committed == [5, 6, 7, 8]  # full accept + bonus
        a, committed = spec.accept_longest_prefix([], [4])
        assert a == 0 and committed == [4]  # empty draft = plain decode

    def test_misdraft_fault_forces_garbage(self):
        _flags.set_flag("fault_injection_enabled", True)
        try:
            fault.arm("serving.spec.misdraft", mode="always")
            history = [1, 2, 3, 1, 2, 3]
            d = spec.draft_tokens(history, 4, vocab=64)
            # the real matcher would draft [1, 2, 3, ...]; the fault
            # replaces it with the deterministic walk off the last token
            assert d == [4, 5, 6, 7]
            assert all(0 <= t < 64 for t in d)
        finally:
            fault.disarm_all()
            _flags.set_flag("fault_injection_enabled", False)


class TestAdaptiveK:
    def test_grows_on_full_accept(self):
        ctl = spec.AdaptiveK(4)
        ctl.k = 2
        ctl.update(drafted=2, accepted=2)
        assert ctl.k == 3
        ctl.update(drafted=3, accepted=3)
        assert ctl.k == 4
        ctl.update(drafted=4, accepted=4)
        assert ctl.k == 4  # capped

    def test_partial_accept_re_aims(self):
        ctl = spec.AdaptiveK(8)
        ctl.update(drafted=8, accepted=2)
        assert ctl.k == 3
        assert not ctl.collapsed

    def test_collapse_after_zero_streak(self):
        ctl = spec.AdaptiveK(4, collapse_after=4)
        ks = []
        for _ in range(4):
            ctl.update(drafted=max(1, ctl.k), accepted=0)
            ks.append(ctl.k)
        assert ks == [2, 1, 1, 0]
        assert ctl.collapsed
        # collapsed is terminal: empty drafts never resurrect k
        ctl.update(drafted=0, accepted=0)
        assert ctl.k == 0

    def test_accept_resets_streak(self):
        ctl = spec.AdaptiveK(4, collapse_after=3)
        ctl.update(drafted=4, accepted=0)
        ctl.update(drafted=2, accepted=0)
        ctl.update(drafted=1, accepted=1)  # full accept for drafted=1
        assert ctl.zero_streak == 0 and not ctl.collapsed


# ------------------------------------------------------------ KV rollback
def _small_kv(num_blocks=16, block_size=8):
    kv = PagedKVCache(KVCacheConfig(block_size=block_size,
                                    num_blocks=num_blocks), 1, 8)
    kv._check = True
    return kv


class TestTruncateRollback:
    def test_truncate_frees_only_the_tail(self):
        kv = _small_kv()
        kv.alloc_sequence(1, 10)          # 2 blocks
        kv.extend_sequence(1, 30)         # 4 blocks (speculative headroom)
        assert kv.used_blocks == 4
        freed = kv.truncate_sequence(1, 12)
        assert freed == 2                 # back to blocks_for(12) == 2
        assert kv.used_blocks == 2
        assert kv.seq_len(1) == 12
        kv.free_sequence(1)
        kv.assert_idle("after truncate roundtrip")

    def test_truncate_noop_when_within_coverage(self):
        kv = _small_kv()
        kv.alloc_sequence(1, 16)
        assert kv.truncate_sequence(1, 16) == 0
        kv.free_sequence(1)
        kv.assert_idle()

    def test_truncate_respects_shared_refcounts(self):
        # a prefix-cache-style fork shares blocks; rollback on one
        # sequence must not free the other's tail
        kv = _small_kv()
        kv.alloc_sequence(1, 24)          # 3 blocks
        kv.fork_sequence(1, 2)            # shared refcount 2
        kv.extend_sequence(2, 40)         # +2 private tail blocks
        assert kv.used_blocks == 5
        freed = kv.truncate_sequence(2, 24)
        assert freed == 2                 # only the private tail came back
        assert kv.used_blocks == 3
        assert kv.block_table(1) == kv.block_table(2)
        kv.free_sequence(2)
        assert kv.used_blocks == 3        # still held by seq 1
        kv.free_sequence(1)
        kv.assert_idle("after shared truncate")

    def test_truncate_unknown_sequence_raises(self):
        kv = _small_kv()
        with pytest.raises(KeyError):
            kv.truncate_sequence(77, 8)

    def test_truncate_discards_quiesce_mark(self):
        kv = _small_kv()
        kv.alloc_sequence(1, 24)
        kv.quiesce_sequence(1)
        kv.truncate_sequence(1, 8)
        with pytest.raises(AssertionError):
            kv.export_chain(1)            # chain mutated, mark gone
        kv.free_sequence(1)
        kv.assert_idle()

    def test_sharded_truncate_routes_to_owner(self):
        from brpc_tpu.serving import ShardedKVCache

        kv = ShardedKVCache(KVCacheConfig(block_size=8, num_blocks=32),
                            1, 8)
        kv._check = True
        kv.alloc_sequence(5, 10)
        kv.extend_sequence(5, 40)
        freed = kv.truncate_sequence(5, 10)
        assert freed == 3                 # 5 blocks back to blocks_for(10)
        kv.free_sequence(5)
        kv.assert_idle("sharded truncate teardown")


# ------------------------------------------------- verify == sequential
@pytest.mark.slow
def test_verify_step_equals_sequential_decode():
    """k+1 rows in ONE verify launch produce the same argmax stream as
    k+1 sequential decode steps: per layer, all rows' K/V writes land
    before any gather and the causal mask keeps row j inside its own
    prefix — the prefill_suffix semantics, batched."""
    cfg = ModelConfig(vocab=64, d_model=16, n_heads=2, n_layers=1,
                      max_context=128)
    kv = PagedKVCache(KVCacheConfig(block_size=8, num_blocks=64),
                      cfg.n_layers, cfg.kv_dim)
    kv._check = True
    model = TinyTransformer(cfg, kv)
    try:
        prompt = model.synth_prompt(16)
        k = 4

        # reference: prefill + k+1 sequential decode steps
        kv.alloc_sequence(1, len(prompt) + 1)
        t = kv.block_table(1)
        seq_tokens = [model.prefill(prompt, t)]
        for i in range(k + 1):
            ctx = len(prompt) + len(seq_tokens)
            table = kv.extend_sequence(1, ctx)
            out = model.decode_step(
                np.asarray([seq_tokens[-1]], dtype=np.int32),
                np.asarray([ctx - 1], dtype=np.int32), [table])
            seq_tokens.append(int(out[0]))
        kv.free_sequence(1)

        # speculative: one verify launch over a perfect draft
        kv.alloc_sequence(2, len(prompt) + 1)
        t = kv.block_table(2)
        first = model.prefill(prompt, t)
        assert first == seq_tokens[0]
        draft = seq_tokens[1:k + 1]       # the true continuation
        ctx = len(prompt) + 1             # prompt + first token committed
        table = kv.extend_sequence(2, ctx + k)
        outs = model.verify_step([first], [ctx - 1], [table], [draft])
        m = [int(x) for x in outs[0]]
        assert m == seq_tokens[1:k + 2], (
            "verify argmax diverged from sequential decode")
        kv.free_sequence(2)
        kv.assert_idle("verify-vs-sequential teardown")
    finally:
        model.close()


# -------------------------------------------------------- engine fixtures
def _build_engine(spec_k):
    cfg = ModelConfig(vocab=64, d_model=16, n_heads=2, n_layers=1,
                      max_context=256)
    kv = PagedKVCache(KVCacheConfig(block_size=8, num_blocks=64),
                      cfg.n_layers, cfg.kv_dim)
    kv._check = True  # arms the engine's (1,1) dispatch assert per step
    model = TinyTransformer(cfg, kv)
    return ServingEngine(model, kv,
                         EngineConfig(max_batch=4, token_budget=128,
                                      idle_wait_s=0.005, spec_k=spec_k),
                         prefix_cache=False).start()


@pytest.fixture(scope="module")
def lanes():
    """Baseline (spec_k=0) and speculative (spec_k=4) engines over
    identical models; warmup runs both schedules twice through each so
    every jit bucket is hot before any timed or counted assertion."""
    base = _build_engine(0)
    sp = _build_engine(4)
    for eng in (base, sp):
        for _ in range(2):
            _run_base(eng)
            _run_rep(eng)
    yield base, sp
    for eng in (base, sp):
        eng.stop()
        eng.kv.assert_idle("spec lanes teardown")
        eng.model.close()


# ------------------------------------------------------------ exact oracle
class TestSpecOracle:
    def test_base_schedule_bit_identical(self, lanes):
        base, sp = lanes
        assert _run_base(base) == _run_base(sp)
        assert sp.kv.used_blocks == 0  # rollback leaked nothing

    def test_repetitive_schedule_bit_identical_fewer_steps(self, lanes):
        base, sp = lanes
        s0b, s0s = base.steps, sp.steps
        out_b = _run_rep(base)
        out_s = _run_rep(sp)
        assert out_b == out_s
        steps_b, steps_s = base.steps - s0b, sp.steps - s0s
        # the whole point: prompt-lookup hits on repetitive traffic, so
        # the speculative lane commits multiple tokens per step
        assert steps_s < steps_b, (steps_s, steps_b)
        st = sp.spec_stats
        assert st is not None and st.accepted > 0
        assert sp.kv.used_blocks == 0

    def test_spec_corpus_schedule_bit_identical(self, lanes):
        """The committed spec-corpus schedule shape (motif prompts),
        exact list-equality — the oracle the ISSUE gates on, at the
        test-model scale; the full recorded corpus replays below."""
        base, sp = lanes
        assert _run_rep(base) == _run_rep(sp)

    def test_snapshot_and_gauges_surface(self, lanes):
        _, sp = lanes
        snap = sp.snapshot()["spec"]
        assert snap is not None and snap["k_max"] == 4
        assert snap["drafted"] >= snap["accepted"] >= 0
        assert 0.0 <= snap["accept_rate"] <= 1.0
        assert spec.accept_rate() >= 0.0  # passive gauge computes

    def test_serving_builtin_renders_spec_line(self, lanes):
        import types

        from brpc_tpu.builtin.services import serving_service

        base, sp = lanes
        status, _ctype, text = serving_service(
            None, types.SimpleNamespace(query={}, path="/serving"))
        assert status == 200
        assert "spec: k_max=4" in text
        assert "accept_rate=" in text and "collapsed_seqs=" in text
        status, _ctype, body = serving_service(
            None, types.SimpleNamespace(query={"format": "json"},
                                        path="/serving"))
        assert status == 200
        snaps = json.loads(body)["engines"]
        specs = [e["spec"] for e in snaps if e.get("spec")]
        assert any(s["k_max"] == 4 and s["drafted"] > 0 for s in specs)
        # the non-speculative lane advertises no spec section at all
        assert any(e.get("spec") is None for e in snaps)

    def test_token_budget_counts_draft_rows(self, lanes):
        _, sp = lanes
        from brpc_tpu.serving.engine import Sequence

        seq = Sequence(np.zeros(4, dtype=np.int32), 8)
        assert sp._decode_cost(seq) == 5  # 1 + spec_k before first step
        seq.spec = spec.AdaptiveK(4)
        seq.spec.k = 2
        assert sp._decode_cost(seq) == 3
        seq.spec.k = 0                    # collapsed: plain decode cost
        assert sp._decode_cost(seq) == 1

    def test_streaming_frames_carry_accepted_counts(self, lanes,
                                                    monkeypatch):
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.rpc import stream as _stream

        _, sp = lanes
        frames = []
        monkeypatch.setattr(
            _stream, "stream_write",
            lambda sid, payload: (frames.append(
                serving_pb2.TokenDelta.FromString(payload)), 0)[1])
        plen, max_new, motif = REP_SCHED[1]
        toks = _gen(sp, _motif_prompt(plen, motif), max_new, stream_id=7)
        assert [t for f in frames for t in f.tokens] == toks
        assert frames[-1].done
        # repetitive prompt -> some frame committed accepted drafts, and
        # no frame claims more accepted than it carries tokens
        assert any(f.accepted > 0 for f in frames)
        assert all(f.accepted <= len(f.tokens) for f in frames)


# -------------------------------------------------------- misdraft chaos
@pytest.fixture
def fault_enabled():
    _flags.set_flag("fault_injection_enabled", True)
    yield
    fault.disarm_all()
    _flags.set_flag("fault_injection_enabled", False)


@pytest.mark.chaos
class TestMisdraftChaos:
    def test_garbage_drafts_terminate_bit_identical_no_leaks(
            self, lanes, fault_enabled):
        base, sp = lanes
        out_b = _run_rep(base)

        st = sp.spec_stats
        d0, a0 = st.drafted, st.accepted
        fault.arm("serving.spec.misdraft", mode="always")
        try:
            out_s = _run_rep(sp)
        finally:
            fault.disarm_all()
        # bit-identical even with every draft adversarial: the verifier
        # rejects, the bonus token carries the stream, rollback cleans up
        assert out_s == out_b
        assert sp.kv.used_blocks == 0, "misdraft run leaked KV blocks"
        drafted = st.drafted - d0
        accepted = st.accepted - a0
        assert drafted > 0
        # the walk never matches the argmax stream -> accept rate ~0
        assert accepted / drafted < 0.2, (accepted, drafted)
        # the collapse guard bounds the waste: each sequence stops
        # drafting after the zero-accept streak (4+2+1+1 rows max, plus
        # slack for the rare accidental accept resetting a streak)
        assert drafted <= len(REP_SCHED) * 16, drafted
        assert st.collapsed_seqs > 0

    def test_throughput_degrades_gracefully(self, lanes, fault_enabled):
        """Auto-disable via the adaptive-k floor: once collapsed, steps
        are plain decodes, so the misdraft lane's step count matches the
        baseline's (1 token/step) and wall time stays within 0.8x."""
        base, sp = lanes
        t0 = time.perf_counter()
        out_b = _run_rep(base)
        base_s = time.perf_counter() - t0

        fault.arm("serving.spec.misdraft", mode="always")
        s0 = sp.steps
        try:
            t0 = time.perf_counter()
            out_s = _run_rep(sp)
            spec_s = time.perf_counter() - t0
        finally:
            fault.disarm_all()
        assert out_s == out_b
        # deterministic half of the floor: rejected steps commit exactly
        # the bonus token, so the misdraft lane needs no more steps than
        # the baseline schedule (modulo admission batching)
        tokens_total = sum(mn for _, mn, _ in REP_SCHED)
        assert sp.steps - s0 <= tokens_total + len(REP_SCHED)
        # wall-clock half, generous slack for CI noise — the bench lane
        # (test_bench_quick) gates the real 0.8x/1.3x floors
        assert spec_s <= base_s / 0.5, (spec_s, base_s)


# ------------------------------------------- corpus replay/diff gate
def test_spec_corpus_replays_and_phases_hold(tmp_path):
    """The committed repetition-heavy corpus
    (tools/record_serving_corpus_spec.py) replayed against a fresh
    SPECULATIVE serving stack: every recorded Generate succeeds with the
    recorded token counts, drafting actually hits (accept rate well
    above zero), spans carry the engine phases, and trace_diff holds the
    p50 phase timelines."""
    from brpc_tpu.metrics.collector import global_collector
    from brpc_tpu.rpc import Server
    from brpc_tpu.trace import span as _span
    from tools import record_serving_corpus_spec as recorder
    from tools import rpc_replay, trace_diff

    dumps = [f for f in os.listdir(CORPUS_SPEC) if f.endswith(".dump")]
    assert dumps, ("committed spec corpus missing; run "
                   "tools/record_serving_corpus_spec")

    _flags.set_flag("rpcz_sample_ratio", "1.0")
    _flags.set_flag("collector_max_samples_per_second", "0")
    global_collector()._deny_until = 0.0
    engine = recorder.build_engine()
    try:
        recorder.warm_engine(engine)
        _span.reset_for_test()
        server = Server().add_service(LlmServingService(engine)) \
            .start("127.0.0.1:0")
        try:
            rc = rpc_replay.main([
                "--dump", CORPUS_SPEC,
                "--server", str(server.listen_endpoint()),
                "--rate-mult", "2", "--timeout-ms", "30000",
                "--report-interval", "0"])
            assert rc == 0
            deadline = time.monotonic() + 5.0
            while (len([s for s in _span.recent_spans(200)
                        if s.kind == _span.KIND_SERVER])
                   < len(recorder.SCHEDULE)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        finally:
            server.stop()
            server.join(timeout=2)
        spans = [s for s in _span.recent_spans(200)
                 if s.kind == _span.KIND_SERVER]
        assert len(spans) >= len(recorder.SCHEDULE)
        with_phases = [s for s in spans
                       if "prefill_us" in s.phases
                       and "decode_us" in s.phases]
        assert with_phases, "no replayed span carries the engine phases"
        # the corpus is repetition-heavy BY CONSTRUCTION — if drafting
        # stopped hitting on it, the speculative lane silently lost its
        # reason to exist; gate on the engine's own accept rate
        st = engine.spec_stats
        assert st is not None and st.drafted > 0
        assert st.accept_rate() > 0.5, st.snapshot()
        replayed = tmp_path / "replayed.json"
        replayed.write_text(json.dumps(
            {"spans": [s.to_dict() for s in _span.recent_spans(200)]}))
        rc = trace_diff.main([CORPUS_SPEC, str(replayed),
                              "--percentile", "50",
                              "--min-delta-us", "50000"])
        assert rc == 0
    finally:
        engine.stop()
        engine.kv.assert_idle("spec corpus gate teardown")
        engine.model.close()
        _flags.set_flag("rpcz_sample_ratio", "1.0")
        _flags.set_flag("collector_max_samples_per_second", "1000")


# -------------------------------------------------- watch rule / flag
def test_spec_collapse_rule_installed_with_reloadable_bound():
    from brpc_tpu.metrics.watch import (KIND_THRESHOLD, global_watch,
                                        install_default_rules)

    install_default_rules()
    rule = {r.name: r for r in global_watch().rules()}["serving_spec_collapse"]
    assert rule.var == "g_serving_spec_accept_rate"
    assert rule.kind == KIND_THRESHOLD and rule.op == "<"
    assert rule.value_fn is not None
    assert rule.value_fn() == pytest.approx(
        _flags.get("serving_spec_accept_rate_min"))
    _flags.set_flag("serving_spec_accept_rate_min", "0.4")
    try:
        assert rule.value_fn() == pytest.approx(0.4)
    finally:
        _flags.set_flag("serving_spec_accept_rate_min", "0.2")


def test_accept_rate_gauge_windows_and_idles_high():
    spec.reset_rate_window()
    assert spec.accept_rate() == 1.0  # idle engines must not alarm
    spec.note_step(10, 1)
    spec.note_step(10, 1)
    assert spec.accept_rate() == pytest.approx(0.1)
    spec.reset_rate_window()
