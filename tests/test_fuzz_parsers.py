"""CI smoke for the parser fuzzers (VERDICT r1 #7).

Mirrors the reference's fuzzing harnesses (test/fuzzing/fuzz_*.cpp) at a
CI-sized budget; the deep campaign is ``python tools/fuzz.py --iters
100000`` (run per round, results recorded in the fuzz harness docstring).
Deterministic seed so a CI failure reproduces locally.
"""

import os
import sys

import pytest

sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools"))

import fuzz  # noqa: E402

ITERS = int(os.environ.get("FUZZ_ITERS", "3000"))


@pytest.mark.parametrize("target", sorted(fuzz._allowed().keys()))
def test_fuzz_parser(target):
    executed = fuzz.run_target(target, ITERS, seed=0xC0FFEE)
    if executed == 0:
        pytest.skip(f"{target}: backing engine unavailable")
    assert executed == ITERS
