"""Native C++ dataplane tests (VERDICT r1 #3 — the native hot path).

Pattern follows the reference's RPC integration tests (SURVEY §4): real
loopback sockets, client and server through the public API, no mock
transport. Covers both lanes (native engine / Python stack) in every
pairing, the C++ native-service fast path, the DETACH fallback for
non-TRPC protocols on a native port, and failure fanout.
"""

import ctypes
import socket as _socket
import threading
import time

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    RpcError,
    Server,
    ServerOptions,
    Service,
    Stub,
)
from brpc_tpu.rpc.native_transport import (
    bench_echo_native,
    dataplane_available,
    get_dataplane,
)

pytestmark = pytest.mark.skipif(
    not dataplane_available(), reason="native dataplane did not build")

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def native_server():
    server = Server(ServerOptions(native_dataplane=True))
    server.add_service(EchoImpl())
    server.start("127.0.0.1:0")
    yield server
    server.stop()
    server.join()


def _stub(server, native=False, timeout_ms=10000):
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=timeout_ms,
                                native_transport=native))
    ch.init(str(server.listen_endpoint()))
    return Stub(ch, ECHO)


class TestNativeServer:
    def test_python_client_native_server(self, native_server):
        stub = _stub(native_server, native=False)
        r = stub.Echo(echo_pb2.EchoRequest(message="py", payload=b"p" * 1000))
        assert r.message == "py" and r.payload == b"p" * 1000

    def test_native_client_native_server(self, native_server):
        stub = _stub(native_server, native=True)
        r = stub.Echo(echo_pb2.EchoRequest(message="nn", payload=b"n" * 1000))
        assert r.message == "nn" and r.payload == b"n" * 1000

    def test_native_client_python_server(self):
        server = Server(ServerOptions())
        server.add_service(EchoImpl())
        server.start("127.0.0.1:0")
        try:
            stub = _stub(server, native=True)
            r = stub.Echo(echo_pb2.EchoRequest(message="np"))
            assert r.message == "np"
        finally:
            server.stop()
            server.join()

    def test_attachment_roundtrip(self, native_server):
        stub = _stub(native_server, native=True)
        att = bytes(range(256)) * 64
        cntl = Controller()
        cntl.request_attachment = att
        r = stub.Echo(echo_pb2.EchoRequest(message="a"), controller=cntl)
        assert r.message == "a"
        assert cntl.response_attachment == att

    def test_large_payload(self, native_server):
        stub = _stub(native_server, native=True, timeout_ms=30000)
        payload = b"\x5a" * (8 << 20)
        r = stub.Echo(echo_pb2.EchoRequest(message="big", payload=payload))
        assert r.payload == payload

    def test_concurrent_calls(self, native_server):
        stub = _stub(native_server, native=True)
        errs = []

        def worker(i):
            try:
                for k in range(30):
                    msg = f"t{i}.{k}"
                    r = stub.Echo(echo_pb2.EchoRequest(message=msg))
                    assert r.message == msg
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs

    def test_native_echo_fastpath(self, native_server):
        """C++-answered service: correct wire response, no Python handler."""
        native_server.register_native_echo("EchoService", "Echo")
        calls_before = native_server.requests_processed.get_value()
        stub = _stub(native_server, native=True)
        att = b"fast" * 100
        cntl = Controller()
        cntl.request_attachment = att
        r = stub.Echo(echo_pb2.EchoRequest(message="cxx", payload=b"zz"),
                      controller=cntl)
        assert r.message == "cxx" and r.payload == b"zz"
        assert cntl.response_attachment == att
        # the Python service never saw it
        assert native_server.requests_processed.get_value() == calls_before

    def test_server_stop_fails_clients(self, native_server):
        stub = _stub(native_server, native=True, timeout_ms=2000)
        stub.Echo(echo_pb2.EchoRequest(message="ok"))
        native_server.stop()
        native_server.join()
        with pytest.raises(RpcError):
            for _ in range(5):  # conn teardown may race the first call
                stub.Echo(echo_pb2.EchoRequest(message="down"))
                time.sleep(0.1)


class TestDetach:
    def test_http_on_native_port(self, native_server):
        """Non-TRPC bytes on a native port detach to the Python stack: the
        builtin HTTP dashboard answers on the same listener."""
        ep = native_server.listen_endpoint()
        with _socket.create_connection((ep.host, ep.port), timeout=5) as s:
            s.sendall(b"GET /health HTTP/1.1\r\nHost: t\r\n"
                      b"Connection: close\r\n\r\n")
            s.settimeout(5)
            data = b""
            while True:
                try:
                    chunk = s.recv(4096)
                except (TimeoutError, OSError):
                    break
                if not chunk:
                    break
                data += chunk
        assert data.startswith(b"HTTP/1.1 200")

    def test_trpc_still_works_after_detach(self, native_server):
        self.test_http_on_native_port(native_server)
        stub = _stub(native_server, native=True)
        assert stub.Echo(echo_pb2.EchoRequest(message="after")).message \
            == "after"


class TestNativeLaneBench:
    def test_bench_echo_native_smoke(self, native_server):
        native_server.register_native_echo("EchoService", "Echo")
        ep = native_server.listen_endpoint()
        res = bench_echo_native(ep.host, ep.port, conns=2, depth=2,
                                payload=64, duration_ms=200)
        assert res is not None
        assert res["qps"] > 100, res
        assert res["p99_us"] > 0


class TestEngineBasics:
    def test_connect_refused(self):
        dp = get_dataplane()
        # grab a port that is closed: bind+close
        s = _socket.socket()
        s.bind(("127.0.0.1", 0))
        port = s.getsockname()[1]
        s.close()
        from brpc_tpu.butil.endpoint import EndPoint

        with pytest.raises(ConnectionError):
            dp.connect(EndPoint.from_ip_port("127.0.0.1", port),
                       timeout_ms=500)

    def test_peer_close_errors_pending(self, native_server):
        """Kill the server mid-call: pending ids get errored, not hung."""
        stub = _stub(native_server, native=True, timeout_ms=3000)
        stub.Echo(echo_pb2.EchoRequest(message="warm"))
        native_server.stop()
        native_server.join()
        t0 = time.monotonic()
        with pytest.raises(RpcError):
            stub.Echo(echo_pb2.EchoRequest(message="x"))
        # failed fast via socket error, not the 3s timeout
        assert time.monotonic() - t0 < 2.5


class TestNativeTpuTunnel:
    """The graft's native lane: TPUC shm tunnel in the C++ engine
    (reference RdmaEndpoint blueprint) + interop with the Python
    transport implementation of the same wire format."""

    @pytest.fixture()
    def tpu_native_server(self):
        server = Server(ServerOptions(native_dataplane=True))
        server.add_service(EchoImpl())
        server.start("tpu://127.0.0.1:0/0")
        yield server
        server.stop()
        server.join()

    def test_native_client_native_server(self, tpu_native_server):
        stub = _stub(tpu_native_server, native=True, timeout_ms=15000)
        r = stub.Echo(echo_pb2.EchoRequest(message="nn",
                                           payload=b"t" * 500000))
        assert r.message == "nn" and len(r.payload) == 500000

    def test_python_client_native_server(self, tpu_native_server):
        stub = _stub(tpu_native_server, native=False, timeout_ms=15000)
        r = stub.Echo(echo_pb2.EchoRequest(message="pn",
                                           payload=b"p" * 300000))
        assert r.message == "pn" and len(r.payload) == 300000

    def test_native_client_python_server(self):
        server = Server(ServerOptions())  # Python tpu transport end
        server.add_service(EchoImpl())
        server.start("tpu://127.0.0.1:0/0")
        try:
            stub = _stub(server, native=True, timeout_ms=15000)
            r = stub.Echo(echo_pb2.EchoRequest(message="np",
                                               payload=b"q" * 300000))
            assert r.message == "np" and len(r.payload) == 300000
        finally:
            server.stop()
            server.join()

    def test_attachment_and_fastpath(self, tpu_native_server):
        tpu_native_server.register_native_echo("EchoService", "Echo")
        stub = _stub(tpu_native_server, native=True, timeout_ms=15000)
        att = bytes(range(256)) * 2048  # 512KB through the block path
        cntl = Controller()
        cntl.request_attachment = att
        r = stub.Echo(echo_pb2.EchoRequest(message="fast"), controller=cntl)
        assert r.message == "fast" and cntl.response_attachment == att

    def test_ordinal_mismatch_refused(self, tpu_native_server):
        ep = tpu_native_server.listen_endpoint()
        from brpc_tpu.butil.endpoint import EndPoint
        from brpc_tpu.rpc.native_transport import get_dataplane

        wrong = EndPoint.from_tpu(ep.host, 7, port=ep.port)
        with pytest.raises(ConnectionError):
            get_dataplane().connect_tpu(wrong, timeout_ms=3000)

    def test_server_stop_fails_tunnel_clients(self, tpu_native_server):
        stub = _stub(tpu_native_server, native=True, timeout_ms=3000)
        stub.Echo(echo_pb2.EchoRequest(message="ok"))
        tpu_native_server.stop()
        tpu_native_server.join()
        with pytest.raises(RpcError):
            for _ in range(5):
                stub.Echo(echo_pb2.EchoRequest(message="down"))
                time.sleep(0.1)


class TestTunnelStress:
    def test_concurrent_mixed_sizes_shared_tunnel(self):
        """8 threads × mixed payload sizes over ONE shared tunnel conn:
        stream ordering, credit accounting, and payload integrity must
        hold under contention."""
        server = Server(ServerOptions(native_dataplane=True))
        server.add_service(EchoImpl())
        server.start("tpu://127.0.0.1:0/0")
        try:
            stub = _stub(server, native=True, timeout_ms=30000)
            sizes = [7, 1000, 65536, 300000, 1 << 20]
            errs = []

            def worker(seed):
                try:
                    for k in range(12):
                        size = sizes[(seed + k) % len(sizes)]
                        fill = bytes([(seed * 31 + k) & 0xFF])
                        cntl = Controller()
                        cntl.timeout_ms = 30000
                        cntl.request_attachment = fill * size
                        r = stub.Echo(echo_pb2.EchoRequest(
                            message=f"{seed}.{k}"), controller=cntl)
                        assert r.message == f"{seed}.{k}"
                        assert cntl.response_attachment == fill * size, \
                            f"payload corrupted at {seed}.{k}"
                except Exception as e:  # pragma: no cover
                    errs.append(e)

            ts = [threading.Thread(target=worker, args=(i,))
                  for i in range(8)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errs, errs
        finally:
            server.stop()
            server.join()

    def test_idle_sweep_closes_native_conns(self):
        server = Server(ServerOptions(native_dataplane=True,
                                      idle_timeout_s=1))
        server.add_service(EchoImpl())
        server.start("127.0.0.1:0")
        try:
            stub = _stub(server, native=True, timeout_ms=3000)
            stub.Echo(echo_pb2.EchoRequest(message="warm"))
            dp = server._native_dp
            assert len(dp.server_socks(server)) >= 1
            deadline = time.monotonic() + 12  # sweep ticks every 5s
            while time.monotonic() < deadline:
                left = len(dp.server_socks(server))
                if left == 0:
                    break
                time.sleep(0.3)
            assert left == 0, f"{left} native conns survived the idle sweep"
        finally:
            server.stop()
            server.join()

    def test_cpp_fastpath_traffic_keeps_conn_alive(self):
        """Traffic answered entirely in C++ never touches Python's
        last_active — the sweep must consult the engine's counters, not
        kill a busy conn (regression for the sweep's blind spot)."""
        server = Server(ServerOptions(native_dataplane=True,
                                      idle_timeout_s=1))
        server.add_service(EchoImpl())
        server.start("127.0.0.1:0")
        server.register_native_echo("EchoService", "Echo")
        try:
            stub = _stub(server, native=True, timeout_ms=3000)
            deadline = time.monotonic() + 7  # beyond limit + sweep tick
            while time.monotonic() < deadline:
                r = stub.Echo(echo_pb2.EchoRequest(message="alive"))
                assert r.message == "alive"
                time.sleep(0.05)
            assert len(server._native_dp.server_socks(server)) >= 1
        finally:
            server.stop()
            server.join()


class TestNativeFailover:
    def test_lb_retry_steers_around_dead_native_server(self):
        """Two native servers behind an rr LB; one dies under continuous
        load — retries + feedback keep every call succeeding on the
        survivor (reference failure-detection story on the native lane)."""

        class NamedEcho(Service):
            DESCRIPTOR = ECHO

            def __init__(self, name):
                super().__init__()
                self.name = name

            def Echo(self, cntl, request, done):
                return echo_pb2.EchoResponse(message=self.name)

        servers = []
        for name in ("a", "b"):
            s = Server(ServerOptions(native_dataplane=True))
            s.add_service(NamedEcho(name))
            s.start("127.0.0.1:0")
            servers.append(s)
        try:
            url = ",".join(str(s.listen_endpoint()) for s in servers)
            ch = Channel(ChannelOptions(timeout_ms=3000, max_retry=3,
                                        native_transport=True))
            ch.init(f"list://{url}", "rr")
            stub = Stub(ch, ECHO)
            seen = set()
            for _ in range(10):
                seen.add(stub.Echo(echo_pb2.EchoRequest(message="x")).message)
            assert seen == {"a", "b"}
            servers[0].stop()
            servers[0].join()
            after = set()
            for _ in range(20):
                after.add(stub.Echo(
                    echo_pb2.EchoRequest(message="x")).message)
            assert after == {"b"}, after  # every call succeeded via retry
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=2)


class TestTunnelGarbageResilience:
    def test_garbage_on_tpu_listener_kills_only_that_conn(self):
        """Raw TCP garbage at a native tpu listener must fail that conn
        alone; real tunnel clients keep working."""
        import socket as _socket

        server = Server(ServerOptions(native_dataplane=True))
        server.add_service(EchoImpl())
        server.start("tpu://127.0.0.1:0/0")
        try:
            ep = server.listen_endpoint()
            stub = _stub(server, native=True, timeout_ms=10000)
            stub.Echo(echo_pb2.EchoRequest(message="before"))
            for payload in (b"TPUC" + b"\xff" * 64,        # bad frame
                            b"TPUC\x03" + b"\x7f\xff\xff\xff",  # huge len
                            b"\x00" * 32):                 # not TPUC at all
                with _socket.create_connection((ep.host, ep.port),
                                               timeout=5) as s:
                    s.sendall(payload)
                    s.settimeout(2)
                    try:
                        while s.recv(4096):
                            pass
                    except (TimeoutError, OSError):
                        pass
            r = stub.Echo(echo_pb2.EchoRequest(message="after"))
            assert r.message == "after"  # the real tunnel survived
        finally:
            server.stop()
            server.join()

    def test_malformed_zero_copy_data_frames(self):
        """The zero-copy DATA route parses peer-controlled block refs and
        an embedded TRPC header straight out of pool memory — hostile
        geometries (bad indices, lying lengths, split headers, random
        fuzz) must fail ONLY the offending conn, never the process or
        innocent tunnels (round-3 surface; reference trust model is
        rdma_endpoint.cpp's, ours must still not crash)."""
        import random
        import socket as _socket
        import struct as _struct

        server = Server(ServerOptions(native_dataplane=True))
        server.add_service(EchoImpl())
        server.start("tpu://127.0.0.1:0/0")
        server.register_native_echo("EchoService", "Echo")
        try:
            ep = server.listen_endpoint()
            stub = _stub(server, native=True, timeout_ms=10000)
            stub.Echo(echo_pb2.EchoRequest(message="before"))

            def data_frame(body: bytes) -> bytes:
                return b"TPUC\x03" + _struct.pack("!I", len(body)) + body

            def hello() -> bytes:
                j = (b'{"v": 1, "pool": "nonexistent_pool_zz", '
                     b'"bs": 4096, "bc": 4, "ordinal": 0, "pid": 1}')
                return b"TPUC\x01" + _struct.pack("!I", len(j)) + j

            rng = random.Random(7)
            attacks = [
                # block index beyond the pool
                _struct.pack("!II", 0, 1) + _struct.pack("!II", 9999, 64),
                # length beyond the block size
                _struct.pack("!II", 0, 1) + _struct.pack("!II", 0, 1 << 30),
                # nsegs lies about the body size
                _struct.pack("!II", 0, 4096),
                # zero-length segment
                _struct.pack("!II", 0, 2) + _struct.pack("!II", 0, 0) * 2,
            ] + [bytes(rng.randrange(256) for _ in range(rng.randrange(
                1, 128))) for _ in range(20)]
            for body in attacks:
                with _socket.create_connection((ep.host, ep.port),
                                               timeout=5) as s:
                    s.sendall(hello())
                    s.sendall(data_frame(body))
                    s.settimeout(1)
                    try:
                        while s.recv(4096):
                            pass
                    except (TimeoutError, OSError):
                        pass
            r = stub.Echo(echo_pb2.EchoRequest(message="after"))
            assert r.message == "after"  # engine + real tunnel survived
        finally:
            server.stop()
            server.join()


class TestShutdownQuiesce:
    """dp_rt_shutdown must quiesce TPUC sender workers mid-traffic
    (ADVICE r2 medium: detached senders leaked threads/conns/shm and could
    UAF the Runtime at shutdown)."""

    def test_shutdown_under_tunnel_load_returns_promptly(self):
        from brpc_tpu import native

        lib = native.load_dataplane()
        if lib is None:
            pytest.skip("native engine unavailable")
        rt = lib.dp_rt_create(2, 0)
        lid = lib.dp_listen(rt, b"127.0.0.1", 0)
        assert lid >= 0
        lib.dp_listener_set_tpu(rt, lid, 0)
        lib.dp_register_echo(rt, lid, b"EchoService", b"Echo")
        port = lib.dp_listen_port(rt, lid)

        # drive large echoes through the tunnel from a separate bench
        # runtime so per-conn sender workers are live when we shut down
        result = {}

        def bench():
            outs = [ctypes.c_double() for _ in range(5)]
            result["rc"] = lib.dp_bench_echo2(
                b"127.0.0.1", port, 1, 2, 4, 1 << 20, 8000,
                b"EchoService", b"Echo",
                *[ctypes.byref(o) for o in outs])

        t = threading.Thread(target=bench, daemon=True)
        t.start()
        time.sleep(1.0)  # let traffic flow

        done = threading.Event()

        def shut():
            lib.dp_rt_shutdown(rt)
            done.set()

        s = threading.Thread(target=shut, daemon=True)
        s.start()
        assert done.wait(15), "dp_rt_shutdown hung (sender quiesce broken)"
        t.join(timeout=20)
        assert not t.is_alive()
