"""RTMP live-media stack tests (reference policy/rtmp_protocol.cpp,
rtmp.cpp): handshake, chunk mux/demux, AMF0 command plane, and the
publish -> relay -> play path with a real publisher + player pair over
loopback (SURVEY §4: no mocks)."""

import struct
import threading
import time

import pytest

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy import amf0
from brpc_tpu.policy.rtmp import (
    MSG_AUDIO,
    MSG_DATA_AMF0,
    MSG_VIDEO,
    ChunkReader,
    RtmpClient,
    RtmpService,
    pack_chunks,
)
from brpc_tpu.rpc import Server, ServerOptions


class TestAmf0:
    def test_roundtrip(self):
        vals = ["connect", 1.0, {"app": "live", "ok": True, "n": None},
                [1.0, "two", False]]
        assert amf0.decode_all(amf0.encode(*vals)) == vals

    def test_long_string(self):
        s = "x" * 70000
        assert amf0.decode_all(amf0.encode(s)) == [s]

    def test_malformed(self):
        with pytest.raises(amf0.Amf0Error):
            amf0.decode_all(b"\x00\x01")   # truncated number
        with pytest.raises(amf0.Amf0Error):
            amf0.decode_all(b"\x7f")       # unknown marker


class TestChunkLayer:
    def test_single_and_multi_chunk(self):
        r = ChunkReader()
        r.chunk_size = 4096
        small = pack_chunks(3, MSG_AUDIO, 1, b"a" * 100)
        big = pack_chunks(4, MSG_VIDEO, 1, b"v" * 10000)
        buf = IOBuf(small + big)
        msgs = r.feed(buf)
        assert [(m[1], len(m[3])) for m in msgs] == [(MSG_AUDIO, 100),
                                                     (MSG_VIDEO, 10000)]

    def test_partial_delivery(self):
        r = ChunkReader()
        r.chunk_size = 4096
        wire = pack_chunks(3, MSG_AUDIO, 1, b"z" * 5000)
        buf = IOBuf(wire[:2000])
        assert r.feed(buf) == []
        buf.append(wire[2000:])
        msgs = r.feed(buf)
        assert len(msgs) == 1 and msgs[0][3] == b"z" * 5000

    def test_fmt3_before_fmt0_rejected(self):
        r = ChunkReader()
        with pytest.raises(ValueError):
            r.feed(IOBuf(bytes([0xC3]) + b"xx"))


@pytest.fixture()
def rtmp_server():
    service = RtmpService()
    server = Server(ServerOptions(rtmp_service=service))
    server.start("127.0.0.1:0")
    yield server, service
    server.stop()
    server.join(timeout=2)


class TestPublishPlay:
    def test_live_relay(self, rtmp_server):
        server, service = rtmp_server
        ep = server.listen_endpoint()
        pub = RtmpClient(ep.host, ep.port, app="live")
        sub = RtmpClient(ep.host, ep.port, app="live")
        try:
            pub_sid = pub.create_stream()
            pub.publish("cam0", pub_sid)
            got = []
            event = threading.Event()

            def on_frame(mtype, sid, payload):
                got.append((mtype, payload))
                if len(got) >= 4:
                    event.set()

            sub.on_frame = on_frame
            sub_sid = sub.create_stream()
            sub.play("cam0", sub_sid)
            pub.send_metadata(pub_sid, "@setDataFrame",
                              {"width": 640.0, "height": 480.0})
            pub.send_frame(MSG_VIDEO, pub_sid, b"\x17keyframe" + b"v" * 5000)
            pub.send_frame(MSG_AUDIO, pub_sid, b"\xaf\x01" + b"a" * 100)
            pub.send_frame(MSG_VIDEO, pub_sid, b"\x27delta" + b"d" * 2000)
            assert event.wait(5), got
            kinds = [k for k, _ in got]
            assert MSG_DATA_AMF0 in kinds
            assert kinds.count(MSG_VIDEO) == 2 and MSG_AUDIO in kinds
            video = [p for k, p in got if k == MSG_VIDEO]
            assert video[0].startswith(b"\x17keyframe")
            assert "cam0" in service.stream_names()
        finally:
            pub.close()
            sub.close()

    def test_late_joiner_gets_metadata(self, rtmp_server):
        server, _ = rtmp_server
        ep = server.listen_endpoint()
        pub = RtmpClient(ep.host, ep.port)
        try:
            sid = pub.create_stream()
            pub.publish("meta-stream", sid)
            pub.send_metadata(sid, "@setDataFrame", {"fps": 30.0})
            time.sleep(0.1)
            late = RtmpClient(ep.host, ep.port)
            try:
                got = []
                ev = threading.Event()
                late.on_frame = lambda t, s, p: (got.append((t, p)),
                                                 ev.set())
                lsid = late.create_stream()
                late.play("meta-stream", lsid)
                assert ev.wait(5)
                assert got[0][0] == MSG_DATA_AMF0
                vals = amf0.decode_all(got[0][1])
                assert vals[1]["fps"] == 30.0
            finally:
                late.close()
        finally:
            pub.close()

    def test_rpc_still_served_on_same_port(self, rtmp_server):
        """RTMP coexists with every other protocol on one port."""
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, ChannelOptions, Service, Stub

        server, _ = rtmp_server

        class EchoImpl(Service):
            DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

            def Echo(self, cntl, request, done):
                return echo_pb2.EchoResponse(message=request.message)

        server.add_service(EchoImpl())
        ch = Channel(ChannelOptions(timeout_ms=3000))
        ch.init(str(server.listen_endpoint()))
        stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
        assert stub.Echo(echo_pb2.EchoRequest(message="rpc")).message == "rpc"


class TestExtendedTimestamp:
    def test_ext_ts_multichunk_roundtrip(self):
        from brpc_tpu.policy.rtmp import MSG_SET_CHUNK_SIZE
        import struct as _s

        r = ChunkReader()
        wire = pack_chunks(2, MSG_SET_CHUNK_SIZE, 0, _s.pack(">I", 4096))
        # 4.66h into a stream: timestamp needs the extended field, payload
        # spans several chunks (each continuation repeats the ext field)
        ts = 0x1000000 + 123
        wire += pack_chunks(4, MSG_VIDEO, 1, b"v" * 10000, timestamp=ts)
        msgs = ChunkReader().feed(IOBuf(wire)) if False else r.feed(
            IOBuf(wire))
        assert msgs[-1][1] == MSG_VIDEO and len(msgs[-1][3]) == 10000
        assert msgs[-1][4] == ts

    def test_timestamp_passthrough_relay(self, rtmp_server):
        server, _ = rtmp_server
        ep = server.listen_endpoint()
        pub = RtmpClient(ep.host, ep.port)
        sub = RtmpClient(ep.host, ep.port)
        try:
            got = []
            ev = threading.Event()
            sub.on_frame = lambda t, s, p: None
            orig = sub._on_message

            def spy(mtype, sid, payload, timestamp=0):
                if mtype == MSG_VIDEO:
                    got.append(timestamp)
                    ev.set()
                orig(mtype, sid, payload, timestamp)

            sub._on_message = spy
            psid = pub.create_stream()
            pub.publish("ts-stream", psid)
            ssid = sub.create_stream()
            sub.play("ts-stream", ssid)
            pub.send_frame(MSG_VIDEO, psid, b"\x17f", timestamp=40000)
            assert ev.wait(5)
            assert got[0] == 40000  # publisher timestamps survive the relay
        finally:
            pub.close()
            sub.close()


class TestRegistryGc:
    def test_idle_streams_are_released(self, rtmp_server):
        """A publisher cycling fresh names must not grow the registry
        forever (ADVICE r2: unbounded _streams)."""
        server, service = rtmp_server
        ep = server.listen_endpoint()
        pub = RtmpClient(ep.host, ep.port, app="live")
        try:
            sid = pub.create_stream()
            pub.publish("scan-a", sid)
        finally:
            pub.close()
        deadline = time.time() + 5
        while service.stream_names() and time.time() < deadline:
            time.sleep(0.05)
        assert service.stream_names() == []

    def test_stream_with_subscriber_survives(self, rtmp_server):
        server, service = rtmp_server
        ep = server.listen_endpoint()
        sub = RtmpClient(ep.host, ep.port, app="live")
        pub = RtmpClient(ep.host, ep.port, app="live")
        try:
            sub.play("held", sub.create_stream())
            pub.publish("held", pub.create_stream())
            pub.close()  # publisher leaves; viewer still holds the stream
            deadline = time.time() + 2
            while "held" in service.stream_names() \
                    and time.time() < deadline:
                time.sleep(0.05)
            assert "held" in service.stream_names()
        finally:
            pub.close()
            sub.close()
