"""gRPC / HTTP/2 protocol tests: HPACK RFC 7541 vectors, h2 framing, and
client+server integration over real loopback sockets (the reference's
per-protocol conformance pattern, test/brpc_http_rpc_protocol_unittest.cpp)."""

import struct
import threading
import time

import pytest

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy import h2 as _h2
from brpc_tpu.policy.compress import COMPRESS_GZIP
from brpc_tpu.policy.grpc_protocol import (
    BRPC_TO_GRPC,
    decode_timeout,
    encode_timeout,
)
from brpc_tpu.policy.hpack import (
    HpackDecoder,
    HpackEncoder,
    HpackError,
    huffman_decode,
    huffman_encode,
)
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    RpcError,
    Server,
    Service,
    Stub,
    errors,
)

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


# ---------------------------------------------------------------- HPACK unit
class TestHuffman:
    # RFC 7541 Appendix C reference encodings
    VECTORS = [
        (b"www.example.com", "f1e3c2e5f23a6ba0ab90f4ff"),
        (b"no-cache", "a8eb10649cbf"),
        (b"custom-key", "25a849e95ba97d7f"),
        (b"custom-value", "25a849e95bb8e8b4bf"),
        (b"302", "6402"),
        (b"private", "aec3771a4b"),
        (b"Mon, 21 Oct 2013 20:13:21 GMT",
         "d07abe941054d444a8200595040b8166e082a62d1bff"),
        (b"https://www.example.com", "9d29ad171863c78f0b97c8e9ae82ae43d3"),
        (b"gzip", "9bd9ab"),
    ]

    def test_rfc_vectors(self):
        for raw, hexenc in self.VECTORS:
            assert huffman_encode(raw).hex() == hexenc
            assert huffman_decode(bytes.fromhex(hexenc)) == raw

    def test_all_bytes_roundtrip(self):
        data = bytes(range(256)) * 3
        assert huffman_decode(huffman_encode(data)) == data

    def test_bad_padding_rejected(self):
        with pytest.raises(HpackError):
            huffman_decode(huffman_encode(b"abc") + b"\x00")


class TestHpack:
    def test_rfc_c3_request_sequence(self):
        d = HpackDecoder()
        h1 = d.decode(bytes.fromhex(
            "828684410f7777772e6578616d706c652e636f6d"))
        assert h1 == [(":method", "GET"), (":scheme", "http"),
                      (":path", "/"), (":authority", "www.example.com")]
        h2 = d.decode(bytes.fromhex("828684be58086e6f2d6361636865"))
        assert h2[-1] == ("cache-control", "no-cache")
        h3 = d.decode(bytes.fromhex(
            "828785bf400a637573746f6d2d6b65790c637573746f6d2d76616c7565"))
        assert h3[1] == (":scheme", "https")
        assert h3[-1] == ("custom-key", "custom-value")

    def test_rfc_c6_response_sequence_with_eviction(self):
        d = HpackDecoder(max_table_size=256)
        r1 = d.decode(bytes.fromhex(
            "488264025885aec3771a4b6196d07abe941054d444a8200595040b8166"
            "e082a62d1bff6e919d29ad171863c78f0b97c8e9ae82ae43d3"))
        assert r1[0] == (":status", "302")
        assert r1[3] == ("location", "https://www.example.com")
        r2 = d.decode(bytes.fromhex("4883640effc1c0bf"))
        assert r2[0] == (":status", "307")

    def test_encoder_decoder_roundtrip_with_dynamic_table(self):
        enc, dec = HpackEncoder(), HpackDecoder()
        headers = [(":method", "POST"), (":path", "/pkg.Echo/Call"),
                   ("content-type", "application/grpc"),
                   ("x-request-id", "abc-123-def")]
        for _ in range(3):  # later rounds hit the dynamic table
            assert dec.decode(enc.encode(headers)) == headers
        # second block should be far smaller (all indexed)
        first = HpackEncoder().encode(headers)
        enc2 = HpackEncoder()
        enc2.encode(headers)
        assert len(enc2.encode(headers)) < len(first) / 3


# ------------------------------------------------------------------- h2 unit
class TestH2Framing:
    def test_frame_roundtrip(self):
        f = _h2.pack_frame(_h2.DATA, _h2.FLAG_END_STREAM, 5, b"hello")
        assert len(f) == 9 + 5
        n = (f[0] << 16) | (f[1] << 8) | f[2]
        assert n == 5 and f[3] == _h2.DATA and f[4] == _h2.FLAG_END_STREAM
        assert struct.unpack("!I", f[5:9])[0] == 5
        assert f[9:] == b"hello"

    def test_grpc_timeout_codec(self):
        assert decode_timeout(encode_timeout(250)) == 250
        assert decode_timeout("2S") == 2000
        assert decode_timeout("90M") == 90 * 60000
        assert decode_timeout("500u") == 1  # sub-ms rounds up
        assert decode_timeout("oops") is None


# -------------------------------------------------------------- integration
class GrpcEchoImpl(Service):
    DESCRIPTOR = ECHO_DESC

    def __init__(self):
        super().__init__()
        self.calls = 0

    def Echo(self, cntl, request, done):
        self.calls += 1
        if request.message == "fail":
            cntl.set_failed(errors.EINTERNAL, "requested failure")
            return None
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        return echo_pb2.EchoResponse(
            message=request.message, payload=request.payload)


@pytest.fixture()
def grpc_server():
    impl = GrpcEchoImpl()
    server = Server().add_service(impl).start("127.0.0.1:0")
    yield server, impl
    server.stop()
    server.join(timeout=2)


def grpc_stub(server, **opts):
    opts.setdefault("protocol", "grpc")
    ch = Channel(ChannelOptions(**opts)).init(str(server.listen_endpoint()))
    return ch, Stub(ch, ECHO_DESC)


class TestGrpcEndToEnd:
    def test_unary_echo(self, grpc_server):
        server, impl = grpc_server
        _, stub = grpc_stub(server)
        resp = stub.Echo(echo_pb2.EchoRequest(message="hello-grpc"))
        assert resp.message == "hello-grpc"
        assert impl.calls == 1

    def test_many_calls_multiplex_one_connection(self, grpc_server):
        server, impl = grpc_server
        _, stub = grpc_stub(server)
        for i in range(32):
            assert stub.Echo(echo_pb2.EchoRequest(message=f"m{i}")).message == f"m{i}"
        assert server.connection_count() == 1  # h2 multiplexes
        assert impl.calls == 32

    def test_concurrent_streams(self, grpc_server):
        server, _ = grpc_server
        _, stub = grpc_stub(server, timeout_ms=5000)
        results, lock = [], threading.Lock()

        def worker(i):
            r = stub.Echo(echo_pb2.EchoRequest(message=f"c{i}", sleep_us=10000))
            with lock:
                results.append(r.message)

        threads = [threading.Thread(target=worker, args=(i,)) for i in range(16)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert sorted(results) == sorted(f"c{i}" for i in range(16))

    def test_async_call(self, grpc_server):
        server, _ = grpc_server
        _, stub = grpc_stub(server)
        ev = threading.Event()
        got = []

        def on_done(cntl):
            got.append(cntl)
            ev.set()

        stub.Echo(echo_pb2.EchoRequest(message="async"), done=on_done)
        assert ev.wait(5)
        assert not got[0].failed()
        assert got[0].response.message == "async"

    def test_error_maps_to_grpc_status_and_back(self, grpc_server):
        server, _ = grpc_server
        _, stub = grpc_stub(server)
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="fail"))
        assert ei.value.error_code == errors.EINTERNAL
        assert "requested failure" in str(ei.value)

    def test_no_such_method_is_unimplemented(self, grpc_server):
        server, _ = grpc_server
        ch, _ = grpc_stub(server)
        from brpc_tpu.rpc.channel import MethodDescriptor

        md = MethodDescriptor("EchoService", "Nope",
                              echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        cntl = Controller()
        with pytest.raises(RpcError):
            ch.call_method(md, echo_pb2.EchoRequest(message="x"),
                           controller=cntl)
        assert cntl.error_code == errors.ENOMETHOD

    def test_large_payload_flow_control(self, grpc_server):
        # 4 MB payload: exceeds the default 64 KB peer window before the
        # server's SETTINGS arrive -> exercises queued sends + WINDOW_UPDATE
        server, _ = grpc_server
        _, stub = grpc_stub(server, timeout_ms=15000)
        blob = bytes(range(256)) * (4 * 1024 * 16)  # 4 MiB
        resp = stub.Echo(echo_pb2.EchoRequest(message="big", payload=blob))
        assert resp.payload == blob

    def test_gzip_compression(self, grpc_server):
        server, _ = grpc_server
        _, stub = grpc_stub(server, compress_type=COMPRESS_GZIP)
        blob = b"z" * 100000
        resp = stub.Echo(echo_pb2.EchoRequest(message="zip", payload=blob))
        assert resp.payload == blob

    def test_deadline_exceeded(self, grpc_server):
        server, _ = grpc_server
        _, stub = grpc_stub(server, timeout_ms=80, max_retry=0)
        cntl = Controller()
        with pytest.raises(RpcError):
            stub.Echo(echo_pb2.EchoRequest(message="slow", sleep_us=500000),
                      controller=cntl)
        assert cntl.error_code == errors.ERPCTIMEDOUT

    def test_mixed_protocols_same_server(self, grpc_server):
        # one server port speaks trpc_std AND grpc simultaneously
        server, impl = grpc_server
        _, gstub = grpc_stub(server)
        ch = Channel(ChannelOptions()).init(str(server.listen_endpoint()))
        tstub = Stub(ch, ECHO_DESC)
        assert gstub.Echo(echo_pb2.EchoRequest(message="g")).message == "g"
        assert tstub.Echo(echo_pb2.EchoRequest(message="t")).message == "t"
        assert impl.calls == 2


class TestGrpcWire:
    """Craft raw h2/gRPC bytes against the server — wire conformance from a
    from-scratch client (nothing shared with our client stack)."""

    def test_handmade_grpc_client(self, grpc_server):
        import socket as pysocket

        server, _ = grpc_server
        ep = server.listen_endpoint()
        enc, dec = HpackEncoder(), HpackDecoder()
        s = pysocket.create_connection((ep.host, ep.port), timeout=5)
        try:
            req = echo_pb2.EchoRequest(message="raw-wire").SerializeToString()
            body = b"\x00" + len(req).to_bytes(4, "big") + req
            block = enc.encode([
                (":method", "POST"), (":scheme", "http"),
                (":path", "/EchoService/Echo"), (":authority", "test"),
                ("content-type", "application/grpc"), ("te", "trailers"),
            ])
            s.sendall(
                _h2.PREFACE
                + _h2.pack_settings([])
                + _h2.pack_frame(_h2.HEADERS,
                                 _h2.FLAG_END_HEADERS, 1, block)
                + _h2.pack_frame(_h2.DATA, _h2.FLAG_END_STREAM, 1, body))
            # read frames until stream 1's trailers (END_STREAM headers)
            buf = b""
            data = b""
            trailers = {}
            deadline = time.time() + 5
            done = False
            while not done and time.time() < deadline:
                chunk = s.recv(65536)
                assert chunk, "server closed early"
                buf += chunk
                while len(buf) >= 9:
                    n = (buf[0] << 16) | (buf[1] << 8) | buf[2]
                    if len(buf) < 9 + n:
                        break
                    ftype, flags = buf[3], buf[4]
                    sid = struct.unpack("!I", buf[5:9])[0] & 0x7FFFFFFF
                    payload = buf[9:9 + n]
                    buf = buf[9 + n:]
                    if ftype == _h2.SETTINGS and not flags & _h2.FLAG_ACK:
                        s.sendall(_h2.pack_settings([], ack=True))
                    elif ftype == _h2.DATA and sid == 1:
                        data += payload
                    elif ftype == _h2.HEADERS and sid == 1:
                        hdrs = dict(dec.decode(payload))
                        if flags & _h2.FLAG_END_STREAM:
                            trailers = hdrs
                            done = True
            assert trailers.get("grpc-status") == "0", trailers
            assert data[0] == 0
            resp = echo_pb2.EchoResponse()
            resp.ParseFromString(data[5:])
            assert resp.message == "raw-wire"
        finally:
            s.close()


class TestGrpcHealth:
    def test_builtin_health_check(self, grpc_server):
        from brpc_tpu.proto import health_pb2

        server, _ = grpc_server
        ch = Channel(ChannelOptions(protocol="grpc")).init(
            str(server.listen_endpoint()))
        stub = Stub(ch, health_pb2.DESCRIPTOR.services_by_name["Health"])
        resp = stub.Check(health_pb2.HealthCheckRequest())
        assert resp.status == health_pb2.HealthCheckResponse.SERVING
        resp = stub.Check(health_pb2.HealthCheckRequest(
            service="grpc.health.v1.Health"))
        assert resp.status == health_pb2.HealthCheckResponse.SERVING
        resp = stub.Check(health_pb2.HealthCheckRequest(service="Nope"))
        assert resp.status == health_pb2.HealthCheckResponse.SERVICE_UNKNOWN


class TestPlainHttp2Dashboard:
    def test_h2_get_health(self, grpc_server):
        """A non-grpc HTTP/2 GET (curl --http2 style) reaches the builtin
        dashboard on the same port."""
        import socket as _socket
        import struct as _struct

        from brpc_tpu.policy.h2 import PREFACE, pack_frame, pack_settings
        from brpc_tpu.policy.hpack import HpackDecoder, HpackEncoder

        server, _impl = grpc_server
        ep = server.listen_endpoint()
        enc = HpackEncoder()
        hdrs = enc.encode([(":method", "GET"), (":scheme", "http"),
                           (":path", "/health"), (":authority", "t")])
        with _socket.create_connection((ep.host, ep.port), timeout=5) as s:
            s.sendall(PREFACE + pack_settings([]) +
                      pack_frame(1, 0x4 | 0x1, 1, hdrs))
            s.settimeout(5)
            buf = b""
            status = None
            body = b""
            dec = HpackDecoder()
            done = False
            while not done:
                chunk = s.recv(65536)
                if not chunk:
                    break
                buf += chunk
                while len(buf) >= 9:
                    ln = (buf[0] << 16) | (buf[1] << 8) | buf[2]
                    if len(buf) < 9 + ln:
                        break
                    ftype, flags = buf[3], buf[4]
                    payload = buf[9:9 + ln]
                    buf = buf[9 + ln:]
                    if ftype == 1:  # HEADERS
                        got = dict(dec.decode(payload))
                        status = got.get(":status")
                    elif ftype == 0:  # DATA
                        body += payload
                        if flags & 0x1:
                            done = True
                            break
        assert status == "200", status
        assert body  # /health answered over plain h2
