"""Redis protocol tests: RESP codec units, pipelined client over loopback,
and server-side RedisService answering a raw RESP client (the reference's
test/brpc_redis_unittest.cpp pattern)."""

import socket as pysocket
import threading
import time

import pytest

from brpc_tpu.policy.redis_protocol import (
    REPLY_ARRAY,
    REPLY_BULK,
    REPLY_ERROR,
    REPLY_INTEGER,
    REPLY_STRING,
    RedisReply,
    RedisRequest,
    RedisResponse,
    RedisService,
    count_commands,
    pack_command,
    pack_reply,
    parse_reply,
    redis_method,
)
from brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions, errors
from brpc_tpu.rpc.channel import RpcError


# ------------------------------------------------------------------ codec
class TestRespCodec:
    def test_pack_command(self):
        assert pack_command("SET", "k", "v") == \
            b"*3\r\n$3\r\nSET\r\n$1\r\nk\r\n$1\r\nv\r\n"
        assert pack_command("INCRBY", "c", 42) == \
            b"*3\r\n$6\r\nINCRBY\r\n$1\r\nc\r\n$2\r\n42\r\n"

    def test_parse_simple_types(self):
        r, p = parse_reply(b"+OK\r\n", 0)
        assert r.type == REPLY_STRING and r.value == "OK" and p == 5
        r, _ = parse_reply(b"-ERR boom\r\n", 0)
        assert r.is_error() and r.value == "ERR boom"
        r, _ = parse_reply(b":1234\r\n", 0)
        assert r.type == REPLY_INTEGER and r.value == 1234
        r, _ = parse_reply(b"$5\r\nhello\r\n", 0)
        assert r.type == REPLY_BULK and r.value == b"hello"
        r, _ = parse_reply(b"$-1\r\n", 0)
        assert r.type == REPLY_BULK and r.is_nil()

    def test_parse_nested_array(self):
        wire = b"*2\r\n*2\r\n:1\r\n:2\r\n$3\r\nabc\r\n"
        r, p = parse_reply(wire, 0)
        assert p == len(wire)
        assert r.type == REPLY_ARRAY
        assert r.value[0].value[1].value == 2
        assert r.value[1].value == b"abc"

    def test_incomplete_returns_none(self):
        assert parse_reply(b"$10\r\nhel", 0)[0] is None
        assert parse_reply(b"*2\r\n:1\r\n", 0)[0] is None

    def test_reply_roundtrip(self):
        replies = [
            RedisReply(REPLY_STRING, "OK"),
            RedisReply(REPLY_ERROR, "ERR no"),
            RedisReply(REPLY_INTEGER, -7),
            RedisReply(REPLY_BULK, b"\x00binary\xff"),
            RedisReply(REPLY_BULK, None),
            RedisReply(REPLY_ARRAY, [RedisReply(REPLY_INTEGER, 1),
                                     RedisReply(REPLY_BULK, b"x")]),
        ]
        wire = b"".join(pack_reply(r) for r in replies)
        resp = RedisResponse()
        resp.ParseFromString(wire)
        assert resp.reply_size == len(replies)
        assert resp.reply(3).value == b"\x00binary\xff"
        assert resp.reply(4).is_nil()
        assert resp.reply(5).value[1].value == b"x"

    def test_count_commands(self):
        req = RedisRequest()
        req.add_command("SET", "a", "1").add_command("GET", "a")
        assert count_commands(req.SerializeToString()) == 2


# --------------------------------------------------------------- server side
def make_kv_service():
    store = {}
    svc = RedisService()
    svc.add_command_handler(
        "set", lambda a: (store.__setitem__(a[1], a[2]),
                          RedisReply(REPLY_STRING, "OK"))[1])
    svc.add_command_handler(
        "get", lambda a: RedisReply(REPLY_BULK, store.get(a[1])))
    svc.add_command_handler(
        "del", lambda a: RedisReply(
            REPLY_INTEGER, 1 if store.pop(a[1], None) is not None else 0))
    return svc, store


@pytest.fixture()
def redis_server():
    svc, store = make_kv_service()
    server = Server(ServerOptions(redis_service=svc)).start("127.0.0.1:0")
    yield server, store
    server.stop()
    server.join(timeout=2)


class TestRedisClientServer:
    def test_pipelined_set_get(self, redis_server):
        server, _ = redis_server
        ch = Channel(ChannelOptions(protocol="redis")).init(
            str(server.listen_endpoint()))
        req = RedisRequest()
        req.add_command("SET", "k1", "v1")
        req.add_command("GET", "k1")
        req.add_command("GET", "missing")
        resp = ch.call_method(redis_method(), req, RedisResponse())
        assert resp.reply_size == 3
        assert resp.reply(0).value == "OK"
        assert resp.reply(1).value == b"v1"
        assert resp.reply(2).is_nil()

    def test_many_rpcs_one_connection(self, redis_server):
        server, _ = redis_server
        ch = Channel(ChannelOptions(protocol="redis")).init(
            str(server.listen_endpoint()))
        for i in range(50):
            req = RedisRequest().add_command("SET", f"k{i}", f"v{i}")
            req.add_command("GET", f"k{i}")
            resp = ch.call_method(redis_method(), req, RedisResponse())
            assert resp.reply(1).value == f"v{i}".encode()
        assert server.connection_count() == 1

    def test_concurrent_clients_keep_order(self, redis_server):
        server, _ = redis_server
        ch = Channel(ChannelOptions(protocol="redis", timeout_ms=5000)).init(
            str(server.listen_endpoint()))
        bad = []

        def worker(i):
            for j in range(20):
                req = RedisRequest().add_command("SET", f"w{i}", f"{i}.{j}")
                req.add_command("GET", f"w{i}")
                r = ch.call_method(redis_method(), req, RedisResponse())
                if r.reply(1).value != f"{i}.{j}".encode():
                    bad.append((i, j, r.reply(1).value))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not bad

    def test_unknown_command_is_error_reply(self, redis_server):
        server, _ = redis_server
        ch = Channel(ChannelOptions(protocol="redis")).init(
            str(server.listen_endpoint()))
        resp = ch.call_method(redis_method(),
                              RedisRequest().add_command("FLUSHALL"),
                              RedisResponse())
        assert resp.reply(0).is_error()

    def test_builtin_ping(self, redis_server):
        server, _ = redis_server
        ch = Channel(ChannelOptions(protocol="redis")).init(
            str(server.listen_endpoint()))
        resp = ch.call_method(redis_method(),
                              RedisRequest().add_command("PING"),
                              RedisResponse())
        assert resp.reply(0).value == "PONG"

    def test_raw_resp_client_like_redis_cli(self, redis_server):
        """A plain socket speaking RESP (what redis-cli sends)."""
        server, _ = redis_server
        ep = server.listen_endpoint()
        s = pysocket.create_connection((ep.host, ep.port), timeout=5)
        try:
            s.sendall(pack_command("SET", "raw", "yes")
                      + pack_command("GET", "raw"))
            got = b""
            while b"yes" not in got:
                chunk = s.recv(4096)
                assert chunk
                got += chunk
            assert got == b"+OK\r\n$3\r\nyes\r\n"
        finally:
            s.close()

    def test_timeout_then_recovery(self, redis_server):
        server, _ = redis_server
        svc = server.options.redis_service
        gate = threading.Event()
        svc.add_command_handler("slow", lambda a: (gate.wait(3),
                                                   RedisReply(REPLY_STRING, "done"))[1])
        ch = Channel(ChannelOptions(protocol="redis", timeout_ms=100,
                                    max_retry=0)).init(
            str(server.listen_endpoint()))
        with pytest.raises(RpcError) as ei:
            ch.call_method(redis_method(),
                           RedisRequest().add_command("SLOW"),
                           RedisResponse())
        assert ei.value.error_code == errors.ERPCTIMEDOUT
        gate.set()
        # the late reply for the timed-out call must be discarded and the
        # next RPC must still line up correctly
        time.sleep(0.1)
        resp = ch.call_method(redis_method(),
                              RedisRequest().add_command("PING"),
                              RedisResponse())
        assert resp.reply(0).value == "PONG"


class TestReviewRegressions:
    def test_nil_bulk_command_does_not_desync_batch(self, redis_server):
        """A $-1 element inside a command must not drop the batch's replies
        (positional correlation would desync for every later RPC)."""
        server, _ = redis_server
        ch = Channel(ChannelOptions(protocol="redis", timeout_ms=2000)).init(
            str(server.listen_endpoint()))
        import socket as pysocket

        ep = server.listen_endpoint()
        s = pysocket.create_connection((ep.host, ep.port), timeout=5)
        try:
            s.sendall(pack_command("SET", "nb", "1")
                      + b"*1\r\n$-1\r\n"
                      + pack_command("GET", "nb"))
            got = b""
            while got.count(b"\r\n") < 3:
                chunk = s.recv(4096)
                assert chunk, f"connection died after {got!r}"
                got += chunk
            assert got.startswith(b"+OK\r\n-ERR")
            assert got.endswith(b"$1\r\n1\r\n")
        finally:
            s.close()

    def test_mixed_stateful_protocols_same_endpoint(self, redis_server):
        """grpc and redis channels to the same host:port must not share a
        socket (each connection-scoped protocol owns its connection)."""
        server, _ = redis_server
        ep = str(server.listen_endpoint())
        rch = Channel(ChannelOptions(protocol="redis")).init(ep)
        from brpc_tpu.proto import health_pb2
        from brpc_tpu.rpc import Stub

        gch = Channel(ChannelOptions(protocol="grpc")).init(ep)
        hstub = Stub(gch, health_pb2.DESCRIPTOR.services_by_name["Health"])
        for _ in range(3):  # interleave the two protocols
            r = rch.call_method(redis_method(),
                                RedisRequest().add_command("PING"),
                                RedisResponse())
            assert r.reply(0).value == "PONG"
            assert hstub.Check(health_pb2.HealthCheckRequest()).status == 1
        assert server.connection_count() == 2
