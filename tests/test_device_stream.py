"""Streaming RPC -> device lane (VERDICT r4 #6): handle records ride the
stream, payload stays in HBM (the test substrate's virtual device), the
credit window bounds DEVICE-POOL OCCUPANCY, and consumption is on-device.
Reference semantics: stream.cpp:318,354,631 credit protocol."""

import threading
import time

import numpy as np
import pytest

from brpc_tpu.rpc import Server
from brpc_tpu.rpc.stream import get_stream, stream_close
from brpc_tpu.tpu.device_lane import DeviceStore
from brpc_tpu.tpu.device_stream import (DeviceStreamEchoService,
                                        open_device_stream, pack_record,
                                        record_measure, send_handle)


@pytest.fixture()
def device_stream_server():
    store = DeviceStore()
    impl = DeviceStreamEchoService(store)
    server = Server().add_service(impl).start("127.0.0.1:0")
    yield server, impl, store
    server.stop()
    server.join(timeout=2)


class TestDeviceStream:
    def test_records_measure_hbm_bytes(self):
        rec = pack_record(7, 1 << 20) + pack_record(9, 4096)
        assert record_measure(rec) == (1 << 20) + 4096

    def test_blocks_flow_and_are_consumed_on_device(self,
                                                    device_stream_server):
        server, impl, store = device_stream_server
        sid = open_device_stream(str(server.listen_endpoint()),
                                 window_bytes=1 << 20)
        try:
            total = 0
            for i in range(8):
                data = bytes([i]) * 4096
                h, n = store.put(data)
                assert send_handle(sid, h, n) == 0
                total += n
            deadline = time.monotonic() + 5
            while impl.consumed_blocks < 8 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert impl.consumed_blocks == 8
            assert impl.consumed_bytes == total
            assert impl.errors == 0
            # consumed blocks were freed: residency returns to zero
            store.fence()
            count, resident, moved = store.stats()
            assert count == 0, (count, resident)
            # and the consume MOVED the bytes on-device (transient copy)
            assert moved >= total
        finally:
            stream_close(sid)

    def test_window_bounds_hbm_occupancy(self, device_stream_server):
        """The writer must stall when the receiver holds `window` bytes
        of unconsumed blocks — the §5.7 credit semantics with HBM
        occupancy as the unit."""
        server, impl, store = device_stream_server
        block = 256 * 1024
        window = 2 * block  # at most 2 unconsumed blocks in flight
        # gate consumption so blocks pile up at the receiver
        gate = threading.Event()
        orig_consume = impl._consume

        def gated_consume(h, n):
            gate.wait(10)
            orig_consume(h, n)

        impl._consume = gated_consume
        sid = open_device_stream(str(server.listen_endpoint()),
                                 window_bytes=window)
        try:
            sent = []

            def producer():
                for i in range(5):
                    h, n = store.put(bytes([i]) * block)
                    rc = send_handle(sid, h, n, timeout=20)
                    sent.append((time.monotonic(), rc))

            t = threading.Thread(target=producer)
            t.start()
            time.sleep(0.8)
            # window = 2 blocks -> writes 1..2 pass, write 3+ is parked
            # (the 3rd may pass the in-flight check edge; assert <= 3)
            n_before = len(sent)
            assert 2 <= n_before <= 3, sent
            gate.set()  # consumer drains; credits return; writer resumes
            t.join(timeout=20)
            assert len(sent) == 5 and all(rc == 0 for _, rc in sent), sent
            deadline = time.monotonic() + 5
            while impl.consumed_blocks < 5 and time.monotonic() < deadline:
                time.sleep(0.01)
            assert impl.consumed_blocks == 5
        finally:
            stream_close(sid)

    def test_payload_integrity_through_hbm(self, device_stream_server):
        """End-to-end bit check: producer stages bytes, consumer copies
        on-device into a persistent handle, host verifies via get()."""
        server, impl, store = device_stream_server
        kept = []
        orig_consume = impl._consume

        def keeping_consume(h, n):
            out = store.copy(h)  # persistent copy, keeps the bytes
            kept.append(out[0])
            store.free(h)

        impl._consume = keeping_consume
        sid = open_device_stream(str(server.listen_endpoint()))
        try:
            payload = np.random.default_rng(3).integers(
                0, 256, size=65536, dtype=np.uint8).tobytes()
            h, n = store.put(payload)
            assert send_handle(sid, h, n) == 0
            deadline = time.monotonic() + 5
            while not kept and time.monotonic() < deadline:
                time.sleep(0.01)
            assert kept
            assert store.get(kept[0]) == payload
        finally:
            stream_close(sid)
