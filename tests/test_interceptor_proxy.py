"""Interceptor hook + generic master service (VERDICT r1 #9/#10; reference
interceptor.h, baidu_master_service.cpp, example/baidu_proxy_and_generic_call).

The proxy test is the reference's flagship use case: a middle server with
NO knowledge of the Echo schema forwards raw bytes to a backend and
relays the raw response — a transparent protocol-level proxy.
"""

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    GenericService,
    MethodDescriptor,
    RawMessage,
    RpcError,
    Server,
    ServerOptions,
    Service,
    Stub,
    errors,
)

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


class TestInterceptor:
    def test_rejects_before_dispatch(self):
        hits = []

        def interceptor(cntl):
            hits.append((cntl.service_name, cntl.method_name))
            if cntl.method_name == "Echo" and cntl.log_id == 13:
                return (errors.EREQUEST, "log_id 13 is cursed")
            return None

        server = Server(ServerOptions(interceptor=interceptor))
        impl = EchoImpl()
        server.add_service(impl)
        server.start("127.0.0.1:0")
        try:
            ch = Channel(ChannelOptions(timeout_ms=3000, max_retry=0))
            ch.init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO)
            assert stub.Echo(echo_pb2.EchoRequest(message="a")).message == "a"
            from brpc_tpu.rpc import Controller

            cntl = Controller()
            cntl.log_id = 13
            with pytest.raises(RpcError) as ei:
                stub.Echo(echo_pb2.EchoRequest(message="b"), controller=cntl)
            assert ei.value.error_code == errors.EREQUEST
            assert ("EchoService", "Echo") in hits
        finally:
            server.stop()
            server.join(timeout=2)

    def test_interceptor_exception_maps_to_einternal(self):
        server = Server(ServerOptions(
            interceptor=lambda cntl: (_ for _ in ()).throw(RuntimeError("x"))))
        server.add_service(EchoImpl())
        server.start("127.0.0.1:0")
        try:
            ch = Channel(ChannelOptions(timeout_ms=3000, max_retry=0))
            ch.init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO)
            with pytest.raises(RpcError) as ei:
                stub.Echo(echo_pb2.EchoRequest(message="x"))
            assert ei.value.error_code == errors.EINTERNAL
        finally:
            server.stop()
            server.join(timeout=2)


class TestGenericProxy:
    def test_transparent_proxy(self):
        backend = Server().add_service(EchoImpl()).start("127.0.0.1:0")

        class Forwarder(GenericService):
            """Schema-blind proxy: raw request bytes in, raw bytes out."""

            def __init__(self, backend_addr):
                super().__init__()
                self._ch = Channel(ChannelOptions(timeout_ms=5000))
                self._ch.init(backend_addr)

            def Process(self, cntl, request, done):
                md = MethodDescriptor(cntl.service_name, cntl.method_name,
                                      RawMessage, RawMessage)
                fwd = Controller()
                fwd.request_attachment = cntl.request_attachment
                out = self._ch.call_method(md, request, controller=fwd)
                if fwd.failed():
                    cntl.set_failed(fwd.error_code, fwd.error_text())
                    return RawMessage()
                cntl.response_attachment = fwd.response_attachment
                return out

        from brpc_tpu.rpc import Controller

        proxy = Server()
        proxy.set_master_service(Forwarder(str(backend.listen_endpoint())))
        proxy.start("127.0.0.1:0")
        try:
            # typed client -> generic proxy -> typed backend
            ch = Channel(ChannelOptions(timeout_ms=5000))
            ch.init(str(proxy.listen_endpoint()))
            stub = Stub(ch, ECHO)
            cntl = Controller()
            cntl.request_attachment = b"att-bytes"
            r = stub.Echo(echo_pb2.EchoRequest(message="via-proxy",
                                               payload=b"p" * 2000),
                          controller=cntl)
            assert r.message == "via-proxy" and r.payload == b"p" * 2000
            assert cntl.response_attachment == b"att-bytes"
        finally:
            proxy.stop()
            proxy.join(timeout=2)
            backend.stop()
            backend.join(timeout=2)

    def test_master_service_requires_star_method(self):
        with pytest.raises(ValueError):
            Server().set_master_service(EchoImpl())
