"""Single-port multiprotocol soak: one Server simultaneously serving
trpc_std RPC, HTTP/1.1 JSON RPC, gRPC (h2), the h2 dashboard, redis,
mongo, and RTMP
from concurrent clients — the reference's single-port story under
cross-protocol concurrency."""

import threading

import pytest

from brpc_tpu.policy.mongo_protocol import (MongoRequest, MongoService,
                                            mongo_method)
from brpc_tpu.policy.redis_protocol import (REPLY_BULK, REPLY_STRING,
                                             RedisReply, RedisService)
from brpc_tpu.policy.rtmp import MSG_VIDEO, RtmpClient, RtmpService
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (Channel, ChannelOptions, Server, ServerOptions,
                          Service, Stub)

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message)


@pytest.fixture()
def kitchen_sink_server():
    kv = {}
    redis = RedisService()

    def _set(args):
        kv[args[1]] = args[2]
        return RedisReply(REPLY_STRING, "OK")

    redis.add_command_handler("SET", _set)
    redis.add_command_handler(
        "GET", lambda args: RedisReply(REPLY_BULK, kv.get(args[1])))
    server = Server(ServerOptions(redis_service=redis,
                                  mongo_service=MongoService(),
                                  rtmp_service=RtmpService()))
    server.add_service(EchoImpl())
    server.start("127.0.0.1:0")
    yield server
    server.stop()
    server.join(timeout=2)


def test_six_protocols_concurrently(kitchen_sink_server):
    server = kitchen_sink_server
    ep = server.listen_endpoint()
    addr = str(ep)
    errs = []
    rounds = 15

    def guard(fn):
        def run():
            try:
                fn()
            except Exception as e:  # pragma: no cover
                errs.append((fn.__name__, repr(e)))
        return run

    @guard
    def trpc_client():
        stub = Stub(Channel(ChannelOptions(timeout_ms=5000)).init(addr),
                    ECHO)
        for i in range(rounds):
            assert stub.Echo(echo_pb2.EchoRequest(
                message=f"t{i}")).message == f"t{i}"

    @guard
    def http_client():
        import json
        import urllib.request

        for i in range(rounds):
            req = urllib.request.Request(
                f"http://{addr}/EchoService/Echo",
                data=json.dumps({"message": f"h{i}"}).encode(),
                headers={"Content-Type": "application/json"})
            body = json.load(urllib.request.urlopen(req, timeout=5))
            assert body["message"] == f"h{i}"

    @guard
    def grpc_client():
        stub = Stub(Channel(ChannelOptions(protocol="grpc",
                                           timeout_ms=5000)).init(addr),
                    ECHO)
        for i in range(rounds):
            assert stub.Echo(echo_pb2.EchoRequest(
                message=f"g{i}")).message == f"g{i}"

    @guard
    def redis_client():
        from brpc_tpu.policy.redis_protocol import (RedisRequest,
                                                    RedisResponse,
                                                    redis_method)

        ch = Channel(ChannelOptions(protocol="redis",
                                    timeout_ms=5000)).init(addr)
        for i in range(rounds):
            req = RedisRequest().add_command("SET", f"k{i}", f"v{i}")
            req.add_command("GET", f"k{i}")
            resp = ch.call_method(redis_method(), req,
                                  response=RedisResponse())
            assert resp.reply(1).value == f"v{i}".encode()

    @guard
    def mongo_client():
        ch = Channel(ChannelOptions(protocol="mongo",
                                    timeout_ms=5000)).init(addr)
        for _ in range(rounds):
            assert ch.call_method(mongo_method(),
                                  MongoRequest({"ping": 1})).ok

    @guard
    def h2_dashboard_client():
        # plain HTTP/2 (no grpc content-type) hits the builtin dashboard
        import socket as _socket

        from brpc_tpu.policy.h2 import PREFACE, pack_frame, pack_settings
        from brpc_tpu.policy.hpack import HpackEncoder

        for _ in range(max(3, rounds // 5)):
            enc = HpackEncoder()
            hdrs = enc.encode([(":method", "GET"), (":scheme", "http"),
                               (":path", "/health"), (":authority", "t")])
            with _socket.create_connection((ep.host, ep.port),
                                           timeout=5) as s:
                s.sendall(PREFACE + pack_settings([]) +
                          pack_frame(1, 0x4 | 0x1, 1, hdrs))
                s.settimeout(5)
                data = b""
                while b"OK" not in data:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    data += chunk
                assert b"OK" in data

    @guard
    def rtmp_pair():
        pub = RtmpClient(ep.host, ep.port)
        sub = RtmpClient(ep.host, ep.port)
        try:
            got = threading.Event()
            sub.on_frame = lambda t, s, p: got.set()
            psid = pub.create_stream()
            pub.publish("mix", psid)
            ssid = sub.create_stream()
            sub.play("mix", ssid)
            # keep sending until the subscriber sees a frame: play() is
            # fire-and-forget, so a one-shot burst could race an
            # un-registered subscriber on a loaded machine
            import time as _time

            deadline = _time.monotonic() + 10
            i = 0
            while not got.is_set() and _time.monotonic() < deadline:
                pub.send_frame(MSG_VIDEO, psid, b"\x17" + bytes(200),
                               timestamp=i * 33)
                i += 1
                _time.sleep(0.02)
            assert got.wait(1)
        finally:
            pub.close()
            sub.close()

    threads = [threading.Thread(target=fn) for fn in
               (trpc_client, http_client, grpc_client, redis_client,
                mongo_client, h2_dashboard_client, rtmp_pair)]
    for t in threads:
        t.start()
    for t in threads:
        t.join(timeout=30)
    assert not any(t.is_alive() for t in threads), "client thread hung"
    assert not errs, errs
