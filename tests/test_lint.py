"""tpulint rule tests: every rule fires on a bad fixture, stays quiet on
the matching good one, and honors suppression comments — plus the
meta-test that keeps the real tree at zero unsuppressed findings."""

import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

from brpc_tpu.analysis import list_rules, run_lint

REPO = Path(__file__).resolve().parent.parent

EXPECTED_RULES = {
    "no-blocking-in-poller", "acquire-release", "monotonic-clock",
    "lock-order", "version-guard", "metric-flag-hygiene", "bounded-spin",
    "named-thread", "cross-process-ownership", "metric-churn",
    "no-per-token-host-sync", "no-per-op-step-dispatch",
    "cow-before-write", "quiesce-before-migrate",
    "draft-no-device-sync", "shed-before-queue", "budget-gated-scrape",
}


def _lint(tmp_path, files, rules=None):
    """Write {relpath: source} fixtures under tmp_path and lint the dir."""
    for rel, src in files.items():
        p = tmp_path / rel
        p.parent.mkdir(parents=True, exist_ok=True)
        p.write_text(textwrap.dedent(src))
    return run_lint(str(tmp_path), rules=rules)


def _rules_hit(result):
    return {f.rule for f in result.findings}


# ---------------------------------------------------------------- registry
def test_all_rules_registered():
    assert {n for n, _ in list_rules()} == EXPECTED_RULES


def test_unknown_rule_rejected(tmp_path):
    with pytest.raises(ValueError, match="unknown lint rule"):
        run_lint(str(tmp_path), rules=["no-such-rule"])


def test_syntax_error_surfaces_as_finding(tmp_path):
    res = _lint(tmp_path, {"broken.py": "def f(:\n"})
    assert [f.rule for f in res.findings] == ["parse-error"]


# ------------------------------------------------- no-blocking-in-poller
class TestNoBlockingInPoller:
    def test_sleep_in_dispatcher_module_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/event_dispatcher.py": """\
            import time
            def run_once(self):
                time.sleep(0.1)
            """}, rules=["no-blocking-in-poller"])
        assert len(res.findings) == 1
        assert res.findings[0].line == 3

    def test_untimed_acquire_in_cut_loop_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/input_messenger.py": """\
            def cut(self):
                self._lock.acquire()
            """}, rules=["no-blocking-in-poller"])
        assert "no-blocking-in-poller" in _rules_hit(res)

    def test_timed_and_nonblocking_acquire_pass(self, tmp_path):
        res = _lint(tmp_path, {"rpc/input_messenger.py": """\
            def cut(self):
                self._lock.acquire(timeout=1.0)
                self._lock.acquire(blocking=False)
                self._cond.wait(0.5)
            """}, rules=["no-blocking-in-poller"])
        assert res.clean

    def test_same_code_outside_poller_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/server.py": """\
            import time
            def accept_loop(self):
                time.sleep(0.1)
                self._lock.acquire()
            """}, rules=["no-blocking-in-poller"])
        assert res.clean

    def test_poller_context_marker_extends_scope(self, tmp_path):
        res = _lint(tmp_path, {"anywhere.py": """\
            from brpc_tpu.analysis.markers import poller_context
            @poller_context
            def on_data(self, body):
                self._lock.acquire()
            """}, rules=["no-blocking-in-poller"])
        assert len(res.findings) == 1
        assert res.findings[0].line == 4

    def test_suppression_comment_silences(self, tmp_path):
        res = _lint(tmp_path, {"rpc/event_dispatcher.py": """\
            import time
            def run_once(self):
                time.sleep(0.1)  # tpulint: disable=no-blocking-in-poller
            """}, rules=["no-blocking-in-poller"])
        assert res.clean and len(res.suppressed) == 1


# --------------------------------------------------------- acquire-release
class TestAcquireRelease:
    def test_bare_acquire_fires(self, tmp_path):
        res = _lint(tmp_path, {"tpu/transport.py": """\
            def send(self, win):
                got = win.acquire(4)
                self.post(got)
            """}, rules=["acquire-release"])
        assert len(res.findings) == 1
        assert "release" in res.findings[0].message

    def test_release_in_except_passes(self, tmp_path):
        res = _lint(tmp_path, {"tpu/transport.py": """\
            def send(self, win):
                got = win.acquire(4)
                try:
                    self.post(got)
                except BaseException:
                    win.release(got)
                    raise
            """}, rules=["acquire-release"])
        assert res.clean

    def test_release_in_finally_passes(self, tmp_path):
        res = _lint(tmp_path, {"butil/iobuf.py": """\
            def borrow(self, pool):
                pool.add_export()
                try:
                    self.use(pool)
                finally:
                    pool.drop_export()
            """}, rules=["acquire-release"])
        assert res.clean

    def test_release_hook_kwarg_passes(self, tmp_path):
        res = _lint(tmp_path, {"tpu/transport.py": """\
            def on_data(self, pool, view):
                pool.add_export()
                self.buf.append_user_data(view, release=self._hook)
            """}, rules=["acquire-release"])
        assert res.clean

    def test_wrapper_forwarding_ownership_passes(self, tmp_path):
        # a method NAMED acquire forwards ownership to its caller
        res = _lint(tmp_path, {"tpu/transport.py": """\
            def acquire(self, n):
                return self._inner.acquire(n)
            """}, rules=["acquire-release"])
        assert res.clean

    def test_out_of_scope_module_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/stream.py": """\
            def f(self, win):
                got = win.acquire(4)
            """}, rules=["acquire-release"])
        assert res.clean


# --------------------------------------------------------- monotonic-clock
class TestMonotonicClock:
    def test_wall_clock_in_trace_fires(self, tmp_path):
        res = _lint(tmp_path, {"trace/span.py": """\
            import time
            def stamp(self):
                self.t = time.time()
            """}, rules=["monotonic-clock"])
        assert len(res.findings) == 1

    def test_wall_clock_in_transport_fires(self, tmp_path):
        res = _lint(tmp_path, {"tpu/transport.py": """\
            import time as _time
            def stamp(self):
                return _time.time()
            """}, rules=["monotonic-clock"])
        assert len(res.findings) == 1

    def test_monotonic_passes(self, tmp_path):
        res = _lint(tmp_path, {"trace/span.py": """\
            import time
            def stamp(self):
                self.t = time.monotonic()
                self.n = time.perf_counter_ns()
            """}, rules=["monotonic-clock"])
        assert res.clean

    def test_wall_clock_outside_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"policy/auth.py": """\
            import time
            def now(self):
                return time.time()
            """}, rules=["monotonic-clock"])
        assert res.clean

    def test_suppression_on_comment_line_above(self, tmp_path):
        res = _lint(tmp_path, {"trace/span.py": """\
            import time
            def stamp(self):
                # display-only wall clock
                # tpulint: disable=monotonic-clock
                self.t = time.time()
            """}, rules=["monotonic-clock"])
        assert res.clean and len(res.suppressed) == 1


# -------------------------------------------------------------- lock-order
class TestLockOrder:
    def test_opposite_nesting_orders_fire(self, tmp_path):
        res = _lint(tmp_path, {"rpc/thing.py": """\
            class Thing:
                def f(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
                def g(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """}, rules=["lock-order"])
        assert len(res.findings) == 1
        assert "cycle" in res.findings[0].message

    def test_consistent_order_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/thing.py": """\
            class Thing:
                def f(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
                def g(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
            """}, rules=["lock-order"])
        assert res.clean

    def test_cycle_through_method_call_fires(self, tmp_path):
        # f holds a_lock while calling h (which takes b_lock);
        # g nests b_lock -> a_lock: cycle via one-level propagation
        res = _lint(tmp_path, {"tpu/thing.py": """\
            class Thing:
                def f(self):
                    with self._a_lock:
                        self.h()
                def h(self):
                    with self._b_lock:
                        pass
                def g(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """}, rules=["lock-order"])
        assert "lock-order" in _rules_hit(res)

    def test_sequential_acquisition_passes(self, tmp_path):
        res = _lint(tmp_path, {"tpu/thing.py": """\
            class Thing:
                def f(self):
                    with self._a_lock:
                        pass
                    with self._b_lock:
                        pass
                def g(self):
                    with self._b_lock:
                        pass
                    with self._a_lock:
                        pass
            """}, rules=["lock-order"])
        assert res.clean

    def test_outside_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"metrics/thing.py": """\
            class Thing:
                def f(self):
                    with self._a_lock:
                        with self._b_lock:
                            pass
                def g(self):
                    with self._b_lock:
                        with self._a_lock:
                            pass
            """}, rules=["lock-order"])
        assert res.clean


# ------------------------------------------------------------ version-guard
class TestVersionGuard:
    def test_direct_shard_map_import_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/x.py": """\
            from jax.experimental.shard_map import shard_map
            """}, rules=["version-guard"])
        assert len(res.findings) == 1

    def test_check_vma_kwarg_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/x.py": """\
            def f(smap, body, mesh):
                return smap(body, mesh=mesh, check_vma=False)
            """}, rules=["version-guard"])
        assert len(res.findings) == 1

    def test_lax_pvary_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/x.py": """\
            from jax import lax
            def f(x):
                return lax.pvary(x, "i")
            """}, rules=["version-guard"])
        assert len(res.findings) == 1

    def test_shim_modules_exempt(self, tmp_path):
        res = _lint(tmp_path, {"tpu/collective.py": """\
            from jax.experimental.shard_map import shard_map
            def f(smap, body, mesh):
                return smap(body, mesh=mesh, check_vma=False)
            """}, rules=["version-guard"])
        assert res.clean

    def test_plain_jax_usage_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/x.py": """\
            import jax
            import jax.numpy as jnp
            def f(x):
                return jax.jit(jnp.sum)(x)
            """}, rules=["version-guard"])
        assert res.clean


# ---------------------------------------------------- metric-flag-hygiene
class TestMetricFlagHygiene:
    def test_unnamed_g_metric_fires(self, tmp_path):
        res = _lint(tmp_path, {"mod.py": """\
            from brpc_tpu.metrics.reducer import Adder
            g_orphan = Adder()
            """}, rules=["metric-flag-hygiene"])
        assert len(res.findings) == 1
        assert "never exposed" in res.findings[0].message

    def test_mismatched_registration_fires(self, tmp_path):
        res = _lint(tmp_path, {"mod.py": """\
            from brpc_tpu.metrics.reducer import Adder
            g_reads = Adder("g_writes")
            """}, rules=["metric-flag-hygiene"])
        assert len(res.findings) == 1
        assert "mismatched" in res.findings[0].message

    def test_duplicate_exposure_fires(self, tmp_path):
        res = _lint(tmp_path, {
            "a.py": 'from m import Adder\ng_dup = Adder("g_dup")\n',
            "b.py": 'from m import Adder\ng_dup = Adder("g_dup")\n',
        }, rules=["metric-flag-hygiene"])
        assert len(res.findings) == 1
        assert "more than once" in res.findings[0].message

    def test_undeclared_flag_read_fires(self, tmp_path):
        res = _lint(tmp_path, {"mod.py": """\
            from brpc_tpu import flags
            def f():
                return flags.get("never_defined_anywhere")
            """}, rules=["metric-flag-hygiene"])
        assert len(res.findings) == 1
        assert "FlagError" in res.findings[0].message

    def test_clean_registration_passes(self, tmp_path):
        res = _lint(tmp_path, {"mod.py": """\
            from brpc_tpu import flags
            from brpc_tpu.metrics.reducer import Adder
            from brpc_tpu.metrics.status import PassiveStatus
            g_named = Adder("g_named")
            g_passive = PassiveStatus(lambda: 1).expose("g_passive")
            flags.define("my_knob", 3, "a knob")
            def f():
                return flags.get("my_knob")
            """}, rules=["metric-flag-hygiene"])
        assert res.clean


# ------------------------------------------------------------ bounded-spin
class TestBoundedSpin:
    def test_pure_busy_wait_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            def wait_ready(self):
                while not self._ready:
                    pass
            """}, rules=["bounded-spin"])
        assert len(res.findings) == 1
        assert res.findings[0].line == 2

    def test_spin_budget_reference_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            def wait_ready(self, spin):
                while not self._ready:
                    if not spin.spin():
                        break
            """}, rules=["bounded-spin"])
        assert res.clean

    def test_park_in_body_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            import time
            def wait_ready(self):
                while not self._ready:
                    time.sleep(0.001)
            """}, rules=["bounded-spin"])
        assert res.clean

    def test_consuming_call_in_condition_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            import os
            def drain(self, fd):
                while os.read(fd, 4096):
                    pass
            """}, rules=["bounded-spin"])
        assert res.clean

    def test_progress_on_condition_variable_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            def count(self, n):
                while n > 0:
                    n -= 1
            """}, rules=["bounded-spin"])
        assert res.clean

    def test_mutating_receiver_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            def drain(self, q):
                while q:
                    q.popleft()
            """}, rules=["bounded-spin"])
        assert res.clean

    def test_break_in_body_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            def probe(self):
                while True:
                    if self._ready:
                        break
            """}, rules=["bounded-spin"])
        assert res.clean


# ------------------------------------------------------------ named-thread
class TestNamedThread:
    def test_anonymous_thread_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            import threading
            def spawn(self):
                t = threading.Thread(target=self._run, daemon=True)
                t.start()
            """}, rules=["named-thread"])
        assert len(res.findings) == 1
        assert res.findings[0].line == 3
        assert "name=" in res.findings[0].message

    def test_bare_import_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            from threading import Thread
            def spawn(self):
                Thread(target=self._run).start()
            """}, rules=["named-thread"])
        assert len(res.findings) == 1

    def test_named_thread_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            import threading
            def spawn(self):
                threading.Thread(target=self._run, name="rpc-healer",
                                 daemon=True).start()
            """}, rules=["named-thread"])
        assert res.clean

    def test_kwargs_splat_passes(self, tmp_path):
        # **kw may carry name= — can't prove absence statically
        res = _lint(tmp_path, {"rpc/foo.py": """\
            import threading
            def spawn(self, **kw):
                threading.Thread(target=self._run, **kw).start()
            """}, rules=["named-thread"])
        assert res.clean

    def test_unrelated_thread_class_passes(self, tmp_path):
        # a local class merely NAMED Thread is not threading.Thread
        res = _lint(tmp_path, {"rpc/foo.py": """\
            class Thread:
                pass
            def f():
                return Thread()
            """}, rules=["named-thread"])
        assert res.clean

    def test_suppression_comment_silences(self, tmp_path):
        res = _lint(tmp_path, {"rpc/foo.py": """\
            import threading
            def spawn(self):
                # tpulint: disable=named-thread
                threading.Thread(target=self._run).start()
            """}, rules=["named-thread"])
        assert res.clean and len(res.suppressed) == 1


# ------------------------------------------------- budget-gated-scrape
class TestBudgetGatedScrape:
    RULE = ["budget-gated-scrape"]

    def test_unbudgeted_sleep_loop_fires(self, tmp_path):
        res = _lint(tmp_path, {"fleet/observer.py": """\
            import time
            def run(self):
                while not self._stop.is_set():
                    self.scrape_once()
                    time.sleep(2.0)
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["budget-gated-scrape"]
        assert res.findings[0].line == 3
        assert "ask_to_be_sampled" in res.findings[0].message
        assert "flags.get" in res.findings[0].message

    def test_flag_read_without_budget_still_fires(self, tmp_path):
        res = _lint(tmp_path, {"fleet/observer.py": """\
            def run(self):
                while not self._stop.is_set():
                    self.scrape_once()
                    self._stop.wait(_flags.get("fleet_scrape_interval_s"))
            """}, rules=self.RULE)
        assert len(res.findings) == 1
        assert "ask_to_be_sampled" in res.findings[0].message
        assert "flags.get" not in res.findings[0].message

    def test_budget_without_flag_still_fires(self, tmp_path):
        res = _lint(tmp_path, {"fleet/observer.py": """\
            def run(self):
                while not self._stop.is_set():
                    if global_collector().ask_to_be_sampled():
                        self.scrape_once()
                    self._stop.wait(2.0)
            """}, rules=self.RULE)
        assert len(res.findings) == 1
        assert "flags.get" in res.findings[0].message

    def test_both_legs_pass(self, tmp_path):
        # the canonical observer loop: reloadable interval + budget draw
        res = _lint(tmp_path, {"fleet/observer.py": """\
            def run(self):
                while not self._stop.is_set():
                    if global_collector().ask_to_be_sampled():
                        self.scrape_once()
                    self._stop.wait(_flags.get("fleet_scrape_interval_s"))
            """}, rules=self.RULE)
        assert res.clean

    def test_wait_in_loop_condition_counts_as_periodic(self, tmp_path):
        res = _lint(tmp_path, {"fleet/poller.py": """\
            def run(self):
                while not self._stop.wait(1.0):
                    self.scrape_once()
            """}, rules=self.RULE)
        assert not res.clean

    def test_non_periodic_fleet_code_passes(self, tmp_path):
        res = _lint(tmp_path, {"fleet/merge.py": """\
            def merge(values):
                total = 0.0
                for v in values:
                    total += v
                return total
            """}, rules=self.RULE)
        assert res.clean

    def test_same_loop_outside_fleet_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"shard/worker.py": """\
            import time
            def run(self):
                while True:
                    self.pump()
                    time.sleep(0.5)
            """}, rules=self.RULE)
        assert res.clean

    def test_suppression_comment(self, tmp_path):
        res = _lint(tmp_path, {"fleet/observer.py": """\
            import time
            def run(self):
                while True:  # tpulint: disable=budget-gated-scrape
                    self.scrape_once()
                    time.sleep(2.0)
            """}, rules=self.RULE)
        assert res.clean and len(res.suppressed) == 1


# ------------------------------------------------------------- suppression
def test_disable_all_wildcard(tmp_path):
    res = _lint(tmp_path, {"trace/span.py": """\
        import time
        def stamp(self):
            self.t = time.time()  # tpulint: disable=all
        """})
    assert res.clean and res.suppressed


# ---------------------------------------------------------------- meta-test
def test_repo_tree_has_zero_unsuppressed_findings():
    """The tentpole's acceptance bar: the shipped package itself is clean.
    Every suppression in-tree is a deliberate, commented exception."""
    res = run_lint(str(REPO / "brpc_tpu"))
    assert res.clean, "\n" + "\n".join(f.format() for f in res.findings)


def test_cli_exit_codes(tmp_path):
    env = dict(PYTHONPATH=str(REPO), PATH="/usr/bin:/bin",
               JAX_PLATFORMS="cpu")
    clean = subprocess.run(
        [sys.executable, str(REPO / "tools" / "tpulint.py"),
         str(REPO / "brpc_tpu")],
        capture_output=True, text=True, env=env)
    assert clean.returncode == 0, clean.stdout + clean.stderr

    bad = tmp_path / "trace" / "bad.py"
    bad.parent.mkdir(parents=True)
    bad.write_text("import time\nt = time.time()\n")
    dirty = subprocess.run(
        [sys.executable, str(REPO / "tools" / "tpulint.py"), str(tmp_path)],
        capture_output=True, text=True, env=env)
    assert dirty.returncode == 1
    assert "[monotonic-clock]" in dirty.stdout


def test_cli_list_rules():
    env = dict(PYTHONPATH=str(REPO), PATH="/usr/bin:/bin")
    out = subprocess.run(
        [sys.executable, str(REPO / "tools" / "tpulint.py"), "--list-rules"],
        capture_output=True, text=True, env=env)
    assert out.returncode == 0
    for rule in EXPECTED_RULES:
        assert rule in out.stdout


class TestCrossProcessOwnership:
    RULE = ["cross-process-ownership"]

    def test_pickle_import_flagged(self, tmp_path):
        res = _lint(tmp_path, {"shard/bad.py": """\
            import pickle
            def ship(ring, obj):
                ring.push(1, pickle.dumps(obj))
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["cross-process-ownership"]

    def test_from_pickle_import_flagged(self, tmp_path):
        res = _lint(tmp_path, {"shard/bad.py": """\
            from pickle import dumps
            """}, rules=self.RULE)
        assert not res.clean

    def test_mp_queue_import_flagged(self, tmp_path):
        res = _lint(tmp_path, {"shard/bad.py": """\
            from multiprocessing import Queue
            """}, rules=self.RULE)
        assert not res.clean
        assert "flat bytes" in res.findings[0].message

    def test_mp_queue_call_flagged(self, tmp_path):
        res = _lint(tmp_path, {"shard/bad.py": """\
            import multiprocessing
            def mk():
                return multiprocessing.Queue()
            """}, rules=self.RULE)
        assert not res.clean

    def test_tainted_iobuf_to_push_flagged(self, tmp_path):
        res = _lint(tmp_path, {"shard/bad.py": """\
            from brpc_tpu.butil.iobuf import IOBuf
            def ship(ring, data):
                packet = IOBuf(data)
                ring.push(3, packet)
            """}, rules=self.RULE)
        assert not res.clean
        assert "packet" in res.findings[0].message

    def test_owned_attr_to_send_flagged(self, tmp_path):
        res = _lint(tmp_path, {"shard/bad.py": """\
            def ship(conn, sock):
                buf = sock.read_buf
                conn.send(buf)
            """}, rules=self.RULE)
        assert not res.clean

    def test_shared_memory_import_allowed(self, tmp_path):
        res = _lint(tmp_path, {"shard/ok.py": """\
            from multiprocessing import shared_memory, resource_tracker
            def attach(name):
                seg = shared_memory.SharedMemory(name=name)
                resource_tracker.unregister("/" + name, "shared_memory")
                return seg
            """}, rules=self.RULE)
        assert res.clean

    def test_handles_and_indices_pass(self, tmp_path):
        res = _lint(tmp_path, {"shard/ok.py": """\
            import struct
            def ship(ring, name, indices, total):
                body = struct.pack("!I", total) + name.encode()
                ring.push(7, body)
                ring.push(8, struct.pack(f"!{len(indices)}I", *indices))
            """}, rules=self.RULE)
        assert res.clean

    def test_outside_shard_scope_ignored(self, tmp_path):
        # the contract binds shard/ only; transport may pickle for dumps
        res = _lint(tmp_path, {"tpu/other.py": """\
            import pickle
            """}, rules=self.RULE)
        assert res.clean


# --------------------------------------------------------- metric-churn
class TestMetricChurn:
    RULE = ["metric-churn"]

    def test_adder_in_dispatch_function_fires(self, tmp_path):
        res = _lint(tmp_path, {"rpc/server_processing.py": """\
            from brpc_tpu.metrics.reducer import Adder
            def process_rpc_request(server, sock, msg):
                errors = Adder("g_oops_per_request")
                errors.put(1)
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["metric-churn"]
        assert "Adder" in res.findings[0].message

    def test_latency_recorder_in_transport_method_fires(self, tmp_path):
        res = _lint(tmp_path, {"tpu/transport.py": """\
            from brpc_tpu.metrics.latency_recorder import LatencyRecorder
            class TpuEndpoint:
                def on_data(self, frame):
                    rec = LatencyRecorder()
                    rec.record(1)
            """}, rules=self.RULE)
        assert not res.clean
        assert "TpuEndpoint.on_data" in res.findings[0].message

    def test_expose_in_batch_function_fires(self, tmp_path):
        res = _lint(tmp_path, {"batch/runtime.py": """\
            def flush(self, batch):
                self._qps_var.expose("g_batch_qps")
            """}, rules=self.RULE)
        assert not res.clean
        assert "expose" in res.findings[0].message

    def test_window_in_worker_loop_fires(self, tmp_path):
        res = _lint(tmp_path, {"shard/worker.py": """\
            from brpc_tpu.metrics.window import Window
            def run(self):
                while True:
                    w = Window(self._adder, 10)
            """}, rules=self.RULE)
        assert not res.clean

    def test_module_level_construction_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/server_processing.py": """\
            from brpc_tpu.metrics.reducer import Adder
            g_requests = Adder("g_requests")
            def process_rpc_request(server, sock, msg):
                g_requests.put(1)
            """}, rules=self.RULE)
        assert res.clean

    def test_same_code_outside_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/server.py": """\
            from brpc_tpu.metrics.latency_recorder import LatencyRecorder
            def on_response(self):
                rec = LatencyRecorder()
            """}, rules=self.RULE)
        assert res.clean

    def test_suppression_honored(self, tmp_path):
        res = _lint(tmp_path, {"rpc/event_dispatcher.py": """\
            from brpc_tpu.metrics.reducer import Adder
            def __init__(self):
                self.n = Adder()  # tpulint: disable=metric-churn
            """}, rules=self.RULE)
        assert res.clean
        assert len(res.suppressed) == 1


class TestNoPerTokenHostSync:
    RULE = ["no-per-token-host-sync"]

    def test_item_in_decode_loop_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/engine.py": """\
            def step(self, batch):
                for seq in batch:
                    tok = self.model.decode_one(seq)
                    seq.append(tok.item())
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["no-per-token-host-sync"]
        assert res.findings[0].line == 4

    def test_block_until_ready_in_while_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/model.py": """\
            def generate(self, seq):
                while not seq.done:
                    nxt = self._decode(seq)
                    nxt.block_until_ready()
            """}, rules=self.RULE)
        assert not res.clean
        assert "block_until_ready" in res.findings[0].message

    def test_device_get_and_asarray_in_loop_fire(self, tmp_path):
        res = _lint(tmp_path, {"serving/engine.py": """\
            import jax
            import numpy as np
            def drain(self, seqs):
                for s in seqs:
                    a = jax.device_get(s.logits)
                    b = np.asarray(s.next_token)
            """}, rules=self.RULE)
        assert len(res.findings) == 2

    def test_one_sync_per_step_outside_loop_passes(self, tmp_path):
        # the engine's own discipline: build host inputs in the loop,
        # ONE materialization after the fused call
        res = _lint(tmp_path, {"serving/model.py": """\
            import numpy as np
            def decode_step(self, tokens, tables):
                slot_tables = np.zeros((8, 64))
                for i, t in enumerate(tables):
                    slot_tables[i] = self._slots_for(t)
                nxt = self._fn(tokens, slot_tables)
                return np.asarray(nxt)
            """}, rules=self.RULE)
        assert res.clean

    def test_jnp_asarray_in_loop_passes(self, tmp_path):
        # device-side asarray is a placement op, not a host sync
        res = _lint(tmp_path, {"serving/model.py": """\
            import jax.numpy as jnp
            def stage(self, chunks):
                for c in chunks:
                    x = jnp.asarray(c)
                    self.push(x)
            """}, rules=self.RULE)
        assert res.clean

    def test_same_code_outside_serving_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"tpu/device_lane.py": """\
            import numpy as np
            def pump(self, arrs):
                for a in arrs:
                    out = np.asarray(a)
            """}, rules=self.RULE)
        assert res.clean

    def test_sync_in_nested_def_not_charged_to_loop(self, tmp_path):
        # the closure runs when called, not per iteration of this loop
        res = _lint(tmp_path, {"serving/engine.py": """\
            def arm(self, seqs):
                for s in seqs:
                    def finish(r, s=s):
                        return r.item()
                    s.on_done = finish
            """}, rules=self.RULE)
        assert res.clean

    def test_nested_loops_report_once(self, tmp_path):
        res = _lint(tmp_path, {"serving/engine.py": """\
            def sweep(self, groups):
                for g in groups:
                    for s in g:
                        v = s.logits.item()
            """}, rules=self.RULE)
        assert len(res.findings) == 1

    def test_suppression_honored(self, tmp_path):
        res = _lint(tmp_path, {"serving/debug.py": """\
            def trace_tokens(self, seqs):
                for s in seqs:
                    print(s.tok.item())  # tpulint: disable=no-per-token-host-sync
            """}, rules=self.RULE)
        assert res.clean
        assert len(res.suppressed) == 1


class TestNoPerOpStepDispatch:
    RULE = ["no-per-op-step-dispatch"]

    def test_store_copy_in_loop_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/engine.py": """\
            def stage(self, handles):
                for h in handles:
                    out = self.store.copy(h)
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["no-per-op-step-dispatch"]
        assert res.findings[0].line == 3
        assert "coalesced" in res.findings[0].message

    def test_transient_copy_in_loop_passes(self, tmp_path):
        # transient copies enter the dispatcher's coalescing queue — the
        # async fused path, exactly what the rule steers toward
        res = _lint(tmp_path, {"tpu/device_stream.py": """\
            def pump(self, handle):
                while self.live:
                    ok = self.store.copy(handle, transient=True)
            """}, rules=self.RULE)
        assert res.clean

    def test_stub_copy_rpc_in_loop_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/bench_lane.py": """\
            def blast(self, stub, req):
                for _ in range(1000):
                    stub.Copy(req)
            """}, rules=self.RULE)
        assert not res.clean
        assert "nbytes=-k" in res.findings[0].message

    def test_device_put_per_item_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/model.py": """\
            import jax
            def load(self, parts):
                for p in parts:
                    self._parts.append(jax.device_put(p))
            """}, rules=self.RULE)
        assert not res.clean
        assert "transfer once" in res.findings[0].message

    def test_single_dispatch_outside_loop_passes(self, tmp_path):
        # the contract itself: build host inputs in the loop, ONE fused
        # dispatch after it
        res = _lint(tmp_path, {"serving/model.py": """\
            import jax
            import numpy as np
            def decode_step(self, tokens, tables):
                slot_tables = np.zeros((8, 64))
                for i, t in enumerate(tables):
                    slot_tables[i] = self._slots_for(t)
                pools = jax.device_put(slot_tables)
                return self.store.copy(self._h)
            """}, rules=self.RULE)
        assert res.clean

    def test_plain_list_copy_in_loop_passes(self, tmp_path):
        # .copy() on non-store receivers (lists, dicts, arrays) is host
        # work, not a device dispatch
        res = _lint(tmp_path, {"serving/engine.py": """\
            def snapshot(self, tables):
                out = []
                for t in tables:
                    out.append(t.copy())
                return out
            """}, rules=self.RULE)
        assert res.clean

    def test_same_code_outside_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/replay.py": """\
            def blast(self, stub, req):
                for _ in range(1000):
                    stub.Copy(req)
            """}, rules=self.RULE)
        assert res.clean

    def test_dispatch_in_nested_def_not_charged_to_loop(self, tmp_path):
        # the callback runs when fired, not per iteration of this loop —
        # it's how the device lane's async Copy chain re-issues itself
        res = _lint(tmp_path, {"serving/bench_lane.py": """\
            def arm(self, stub, reqs):
                for req in reqs:
                    def fire(r=req):
                        stub.Copy(r)
                    self._cbs.append(fire)
            """}, rules=self.RULE)
        assert res.clean

    def test_suppression_honored(self, tmp_path):
        res = _lint(tmp_path, {"serving/debug.py": """\
            def probe(self, handles):
                for h in handles:
                    self.store.copy(h)  # tpulint: disable=no-per-op-step-dispatch
            """}, rules=self.RULE)
        assert res.clean
        assert len(res.suppressed) == 1


# --------------------------------------------------------- cow-before-write
class TestCowBeforeWrite:
    RULE = ["cow-before-write"]

    def test_bare_pool_write_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/model.py": """\
            def prefill(self, tokens, table):
                kpool, vpool = self._fn(tokens, table)
                self.kv.update_pools(kpool, vpool)
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["cow-before-write"]
        assert res.findings[0].line == 3
        assert "cow-split" in res.findings[0].message

    def test_assert_writable_guard_passes(self, tmp_path):
        # the house contract: prove exclusivity before the scatter commits
        res = _lint(tmp_path, {"serving/model.py": """\
            def prefill(self, tokens, table):
                self.kv.assert_writable(table, 0, len(tokens))
                kpool, vpool = self._fn(tokens, table)
                self.kv.update_pools(kpool, vpool)
            """}, rules=self.RULE)
        assert res.clean

    def test_cow_split_call_passes(self, tmp_path):
        res = _lint(tmp_path, {"serving/engine.py": """\
            def step(self, seq, k, v):
                self.kv.cow_block(seq.seq_id, 0)
                self.kv.update_pools(k, v)
            """}, rules=self.RULE)
        assert res.clean

    def test_refcount_eq_one_check_passes(self, tmp_path):
        res = _lint(tmp_path, {"serving/custom_cache.py": """\
            def swap(self, block, k, v):
                if self._ref.get(block, 0) == 1:
                    self.update_pools(k, v)
            """}, rules=self.RULE)
        assert res.clean

    def test_cow_named_function_exempt(self, tmp_path):
        # the split implementations themselves ARE the guard
        res = _lint(tmp_path, {"serving/kv_cache.py": """\
            def _cow_copy_block_device(self, dst, src):
                k = self.k_pool
                self.update_pools(k, k)
            """}, rules=self.RULE)
        assert res.clean

    def test_same_code_outside_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"tpu/device_lane.py": """\
            def stage(self, k, v):
                self.kv.update_pools(k, v)
            """}, rules=self.RULE)
        assert res.clean

    def test_suppression_honored(self, tmp_path):
        res = _lint(tmp_path, {"serving/debug.py": """\
            def poke(self, k, v):
                self.kv.update_pools(k, v)  # tpulint: disable=cow-before-write
            """}, rules=self.RULE)
        assert res.clean
        assert len(res.suppressed) == 1


class TestQuiesceBeforeMigrate:
    RULE = ["quiesce-before-migrate"]

    def test_bare_export_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/migration.py": """\
            def migrate(self, seq, kv):
                table, ntokens = kv.export_chain(seq.seq_id)
                self._stream(table)
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["quiesce-before-migrate"]
        assert res.findings[0].line == 2
        assert "quiesce" in res.findings[0].message

    def test_quiesce_guard_passes(self, tmp_path):
        # the house contract: audit + mark read-only before the chain
        # leaves the shard
        res = _lint(tmp_path, {"serving/migration.py": """\
            def migrate(self, seq, kv):
                kv.quiesce_sequence(seq.seq_id)
                table, ntokens = kv.export_chain(seq.seq_id)
                self._stream(table)
            """}, rules=self.RULE)
        assert res.clean

    def test_export_named_function_exempt(self, tmp_path):
        # the quiesce/export implementations themselves ARE the contract
        res = _lint(tmp_path, {"serving/kv_cache.py": """\
            def export_chain(self, seq_id):
                return self.pools[0].export_chain(seq_id)
            """}, rules=self.RULE)
        assert res.clean

    def test_same_code_outside_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"tools/debug_dump.py": """\
            def dump(self, seq, kv):
                return kv.export_chain(seq.seq_id)
            """}, rules=self.RULE)
        assert res.clean

    def test_suppression_honored(self, tmp_path):
        res = _lint(tmp_path, {"serving/debug.py": """\
            def peek(self, seq, kv):
                return kv.export_chain(seq.seq_id)  # tpulint: disable=quiesce-before-migrate
            """}, rules=self.RULE)
        assert res.clean
        assert len(res.suppressed) == 1


class TestDraftNoDeviceSync:
    RULE = ["draft-no-device-sync"]

    def test_jax_import_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/speculative.py": """\
            import jax

            def draft_tokens(history, k):
                return history[-k:]
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["draft-no-device-sync"]
        assert res.findings[0].line == 1
        assert "host-side" in res.findings[0].message

    def test_jax_from_import_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/speculative.py": """\
            from jax import numpy as jnp

            def draft_tokens(history, k):
                return list(jnp.asarray(history)[-k:])
            """}, rules=self.RULE)
        assert not res.clean

    def test_jit_call_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/speculative.py": """\
            def draft_tokens(history, k, matcher):
                fn = matcher.jit(history)
                out = fn(k)
                out.block_until_ready()
                return out
            """}, rules=self.RULE)
        assert len(res.findings) == 2
        assert "ONE launch" in res.findings[0].message

    def test_host_side_matcher_passes(self, tmp_path):
        # the house drafter: pure Python over committed token history
        res = _lint(tmp_path, {"serving/speculative.py": """\
            def draft_tokens(history, k, ngram_max=3):
                h = [int(t) for t in history]
                for n in range(min(ngram_max, len(h) - 1), 0, -1):
                    tail = h[-n:]
                    for j in range(len(h) - n - 1, -1, -1):
                        if h[j:j + n] == tail:
                            return h[j + n:j + n + k]
                return []
            """}, rules=self.RULE)
        assert res.clean

    def test_same_code_outside_scope_passes(self, tmp_path):
        # jit/device dispatch is the model's job — only the draft lane
        # is pinned host-side
        res = _lint(tmp_path, {"serving/model.py": """\
            import jax

            def decode_fn(self, bucket):
                return jax.jit(self._impl)
            """}, rules=self.RULE)
        assert res.clean

    def test_suppression_honored(self, tmp_path):
        res = _lint(tmp_path, {"serving/speculative.py": """\
            import jax  # tpulint: disable=draft-no-device-sync

            def draft_tokens(history, k):
                return history[-k:]
            """}, rules=self.RULE)
        assert res.clean
        assert len(res.suppressed) == 1


class TestShedBeforeQueue:
    RULE = ["shed-before-queue"]

    def test_unchecked_append_fires(self, tmp_path):
        res = _lint(tmp_path, {"serving/engine.py": """\
            def submit(self, seq):
                self._waiting.append(seq)
                self._cv.notify()
            """}, rules=self.RULE)
        assert [f.rule for f in res.findings] == ["shed-before-queue"]
        assert res.findings[0].line == 2
        assert "admission" in res.findings[0].message

    def test_tenant_lane_append_fires(self, tmp_path):
        # the per-tenant lanes are waiting queues too — a scheduler
        # helper that grows one without re-checking is the same bug
        res = _lint(tmp_path, {"serving/qos.py": """\
            def requeue(self, t, seq):
                t.waiting.append(seq)
            """}, rules=self.RULE)
        assert not res.clean

    def test_admission_check_guard_passes(self, tmp_path):
        res = _lint(tmp_path, {"serving/qos.py": """\
            def enqueue(self, seq):
                code = self.admission_check(seq.tenant_id, seq.priority)
                if code != 0:
                    return code
                t = self.tenant(seq.tenant_id)
                t.waiting.append(seq)
                return 0
            """}, rules=self.RULE)
        assert res.clean

    def test_can_admit_guard_passes(self, tmp_path):
        # the pre-QoS watermark check also satisfies the contract: the
        # append is still behind an admission predicate
        res = _lint(tmp_path, {"serving/engine.py": """\
            def submit(self, seq, need):
                if not self.kv.can_admit(need):
                    return 1
                self._waiting.append(seq)
                return 0
            """}, rules=self.RULE)
        assert res.clean

    def test_other_queues_exempt(self, tmp_path):
        # only the waiting lanes are admission-gated; adoption/pending
        # lists have their own ownership contracts
        res = _lint(tmp_path, {"serving/engine.py": """\
            def adopt(self, seq):
                self._adopted_pending.append(seq)
            """}, rules=self.RULE)
        assert res.clean

    def test_same_code_outside_scope_passes(self, tmp_path):
        res = _lint(tmp_path, {"rpc/stream.py": """\
            def push(self, frame):
                self._waiting.append(frame)
            """}, rules=self.RULE)
        assert res.clean

    def test_suppression_honored(self, tmp_path):
        res = _lint(tmp_path, {"serving/debug.py": """\
            def inject(self, seq):
                self._waiting.append(seq)  # tpulint: disable=shed-before-queue
            """}, rules=self.RULE)
        assert res.clean
        assert len(res.suppressed) == 1
