"""Thrift framed protocol tests: TBinary codec units plus client+server
integration over loopback (the reference's brpc_thrift_* test pattern)."""

import struct
import threading

import pytest

from brpc_tpu.policy.thrift_protocol import (
    MT_CALL,
    MT_REPLY,
    ThriftBinaryReader,
    ThriftBinaryWriter,
    ThriftRawMessage,
    ThriftService,
    pack_message,
    thrift_method,
    unpack_message,
)
from brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions, errors
from brpc_tpu.rpc.channel import RpcError


class TestTBinaryCodec:
    def test_struct_roundtrip(self):
        body = (ThriftBinaryWriter()
                .write_bool(1, True)
                .write_byte(2, -5)
                .write_i16(3, 1000)
                .write_i32(4, -70000)
                .write_i64(5, 1 << 40)
                .write_double(6, 2.5)
                .write_string(7, "héllo")
                .field_stop().bytes())
        fields = ThriftBinaryReader(body).read_struct()
        assert fields[1][1] is True
        assert fields[2][1] == -5
        assert fields[3][1] == 1000
        assert fields[4][1] == -70000
        assert fields[5][1] == 1 << 40
        assert fields[6][1] == 2.5
        assert fields[7][1].decode() == "héllo"

    def test_nested_struct(self):
        inner = (ThriftBinaryWriter().write_i32(1, 7).field_stop().bytes())
        outer = (ThriftBinaryWriter()
                 .write_struct(1, inner)
                 .write_string(2, "x")
                 .field_stop().bytes())
        fields = ThriftBinaryReader(outer).read_struct()
        assert ThriftBinaryReader(fields[1][1]).read_struct()[1][1] == 7
        assert fields[2][1] == b"x"

    def test_message_roundtrip(self):
        frame = pack_message(MT_CALL, "Echo", 42, b"\x00")
        n = struct.unpack("!I", frame[:4])[0]
        assert len(frame) == 4 + n
        mtype, name, seqid, body = unpack_message(frame[4:])
        assert (mtype, name, seqid, body) == (MT_CALL, "Echo", 42, b"\x00")


def make_echo_service():
    svc = ThriftService()

    def echo(args_body: bytes) -> bytes:
        fields = ThriftBinaryReader(args_body).read_struct()
        msg = fields[1][1]
        return (ThriftBinaryWriter().write_string(0, msg)
                .field_stop().bytes())

    def boom(args_body: bytes) -> bytes:
        raise RuntimeError("kaput")

    svc.add_method("Echo", echo).add_method("Boom", boom)
    return svc


@pytest.fixture()
def thrift_server():
    server = Server(ServerOptions(
        thrift_service=make_echo_service())).start("127.0.0.1:0")
    yield server
    server.stop()
    server.join(timeout=2)


def thrift_channel(server, **opts):
    opts.setdefault("protocol", "thrift")
    return Channel(ChannelOptions(**opts)).init(str(server.listen_endpoint()))


def call_echo(ch, text, **kw):
    args = (ThriftBinaryWriter().write_string(1, text).field_stop().bytes())
    resp = ch.call_method(thrift_method("Echo"), ThriftRawMessage(args),
                          ThriftRawMessage(), **kw)
    return ThriftBinaryReader(resp.body).read_struct()[0][1].decode()


class TestThriftEndToEnd:
    def test_echo(self, thrift_server):
        ch = thrift_channel(thrift_server)
        assert call_echo(ch, "hello-thrift") == "hello-thrift"

    def test_pipelined_and_concurrent(self, thrift_server):
        ch = thrift_channel(thrift_server, timeout_ms=5000)
        bad = []

        def worker(i):
            for j in range(20):
                try:
                    got = call_echo(ch, f"{i}.{j}")
                except Exception as e:
                    bad.append((i, j, repr(e)))
                    return
                if got != f"{i}.{j}":
                    bad.append((i, j, got))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not bad
        assert thrift_server.connection_count() == 1

    def test_unknown_method_returns_exception(self, thrift_server):
        ch = thrift_channel(thrift_server)
        with pytest.raises(RpcError) as ei:
            ch.call_method(thrift_method("Nope"), ThriftRawMessage(),
                           ThriftRawMessage())
        assert ei.value.error_code == errors.EINTERNAL
        assert "unknown method" in str(ei.value)

    def test_handler_exception_maps_to_error(self, thrift_server):
        ch = thrift_channel(thrift_server)
        with pytest.raises(RpcError) as ei:
            ch.call_method(thrift_method("Boom"), ThriftRawMessage(),
                           ThriftRawMessage())
        assert "kaput" in str(ei.value)
