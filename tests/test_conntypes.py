"""Connection types (reference channel.h:90-95): single / pooled / short
on both the Python socket lane and the native engine lane (VERDICT r2 #4).
"""

import threading
import time

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (Channel, ChannelOptions, Controller, Server,
                          ServerOptions, Service, Stub)

SVC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class CountingEcho(Service):
    DESCRIPTOR = SVC

    def __init__(self):
        super().__init__()
        self.seen_peers = set()
        self._lock = threading.Lock()

    def Echo(self, cntl, request, done):
        with self._lock:
            self.seen_peers.add(str(cntl.peer))
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


def _server(native=False):
    srv = Server(ServerOptions(native_dataplane=native))
    svc = CountingEcho()
    srv.add_service(svc)
    srv.start("127.0.0.1:0")
    return srv, svc


def _channel(ep, ctype, native=False, **kw):
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=8000,
                                connection_type=ctype,
                                native_transport=native, **kw))
    ch.init(str(ep))
    return ch


@pytest.mark.parametrize("native", [False, True])
def test_pooled_reuses_sequentially(native):
    srv, svc = _server(native)
    try:
        ch = _channel(srv.listen_endpoint(), "pooled", native)
        stub = Stub(ch, SVC)
        for i in range(10):
            assert stub.Echo(echo_pb2.EchoRequest(message=str(i))).message \
                == str(i)
        # sequential calls check the same connection in and out: one peer
        assert len(svc.seen_peers) == 1, svc.seen_peers
    finally:
        srv.stop()
        srv.join(timeout=3)


@pytest.mark.parametrize("native", [False, True])
def test_pooled_grows_with_concurrency(native):
    srv, svc = _server(native)
    try:
        ch = _channel(srv.listen_endpoint(), "pooled", native)
        stub = Stub(ch, SVC)
        errs = []

        def worker():
            try:
                for _ in range(4):
                    stub.Echo(echo_pb2.EchoRequest(message="c",
                                                   sleep_us=30000))
            except Exception as e:  # pragma: no cover
                errs.append(e)

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not errs, errs
        # concurrent checkouts forced >1 connection, bounded by concurrency
        assert 2 <= len(svc.seen_peers) <= 4, svc.seen_peers
        # steady state: sequential traffic reuses the pool (no growth)
        before = set(svc.seen_peers)
        for _ in range(6):
            stub.Echo(echo_pb2.EchoRequest(message="s"))
        assert svc.seen_peers == before
    finally:
        srv.stop()
        srv.join(timeout=3)


@pytest.mark.parametrize("native", [False, True])
def test_short_dials_per_call(native):
    srv, svc = _server(native)
    try:
        ch = _channel(srv.listen_endpoint(), "short", native)
        stub = Stub(ch, SVC)
        for i in range(5):
            stub.Echo(echo_pb2.EchoRequest(message=str(i)))
        # every call came from a fresh source port
        assert len(svc.seen_peers) == 5, svc.seen_peers
        # and the connections do not linger server-side
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            if srv.connection_count() <= 1:
                break
            time.sleep(0.05)
        assert srv.connection_count() <= 1
    finally:
        srv.stop()
        srv.join(timeout=3)


def test_single_shares_one_connection():
    srv, svc = _server(False)
    try:
        ch = _channel(srv.listen_endpoint(), "single", False)
        ch2 = _channel(srv.listen_endpoint(), "single", False)
        for c in (ch, ch2):
            stub = Stub(c, SVC)
            for _ in range(3):
                stub.Echo(echo_pb2.EchoRequest(message="x"))
        assert len(svc.seen_peers) == 1, svc.seen_peers
    finally:
        srv.stop()
        srv.join(timeout=3)


def test_pooled_attachment_roundtrip_native():
    # the bulk-throughput shape: pooled conns carrying 1MB attachments
    srv, svc = _server(True)
    try:
        ch = _channel(srv.listen_endpoint(), "pooled", True)
        stub = Stub(ch, SVC)
        blob = b"\x77" * (1 << 20)
        for _ in range(4):
            cntl = Controller()
            cntl.request_attachment = blob
            r = stub.Echo(echo_pb2.EchoRequest(message="big"),
                          controller=cntl)
            assert r.message == "big"
            assert cntl.response_attachment == blob
    finally:
        srv.stop()
        srv.join(timeout=3)


def test_pooled_failed_checkout_not_reused():
    # a conn that dies mid-checkout must not return to the pool
    from brpc_tpu.rpc.socket_map import global_socket_map
    from brpc_tpu.butil.endpoint import EndPoint

    srv, svc = _server(False)
    ep = srv.listen_endpoint()
    sm = global_socket_map()
    sock = sm.get_pooled(ep)
    sock.set_failed(1009, "simulated death")
    sm.return_pooled(sock, reusable=True)  # failed: must be dropped
    assert sm.pooled_idle_count(ep) == 0
    sock2 = sm.get_pooled(ep)
    assert sock2 is not sock and not sock2.failed
    sm.return_pooled(sock2, reusable=True)
    assert sm.pooled_idle_count(ep) == 1
    srv.stop()
    srv.join(timeout=3)
