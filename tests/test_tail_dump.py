"""Tail-based trace retention (trace/tail.py).

Covers the settle-time decision table (error / QoS-shed / slow-vs-p99 /
watch correlation), the deferred-decision ring (hold, expiry, eviction),
the commit token bucket, the end-to-end wiring through a real server's
dump stream and the ``/rpcz?retained=tail`` + ``/dump`` builtins, and the
headline precision claim: tail retention recovers the delayed-request
traces that head sampling statistically discards.
"""

import json
import time

import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.metrics.variable import clear_registry
from brpc_tpu.metrics.watch import (STATE_FIRING, WatchRule, global_watch)
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, Server, ServerOptions, Stub
from brpc_tpu.rpc.errors import EINTERNAL, ELIMIT, EOVERCROWDED
from brpc_tpu.trace import span as _span
from brpc_tpu.trace.rpc_dump import RpcDumpLoader
from brpc_tpu.trace.tail import (REASON_ERROR, REASON_SHED, REASON_SLOW,
                                 TailRetainer, g_dump_tail_dropped,
                                 g_dump_tail_retained, g_dump_tail_shed)
from tests.test_http import ECHO_DESC, EchoServiceImpl

_TAIL_FLAGS = ("rpc_dump_tail", "rpc_dump_tail_slow_x",
               "rpc_dump_tail_max_per_sec", "rpc_dump_tail_hold_s",
               "rpc_dump_tail_ring", "rpc_dump_ratio",
               "rpc_dump_max_per_sec")


@pytest.fixture(autouse=True)
def _clean_state():
    saved = {name: _flags.get(name) for name in _TAIL_FLAGS}
    _span.reset_for_test()
    yield
    fault.disarm_all()
    for name, value in saved.items():
        _flags.set_flag(name, value)
    _span.reset_for_test()
    clear_registry()


@pytest.fixture()
def tail_on():
    _flags.set_flag("rpc_dump_tail", True)
    yield


@pytest.fixture()
def fault_enabled():
    _flags.set_flag("fault_injection_enabled", True)
    yield
    fault.disarm_all()
    _flags.set_flag("fault_injection_enabled", False)


# --------------------------------------------------------------- unit layer
class _FakeDumper:
    def __init__(self):
        self.commits = []

    def commit(self, pending, span, error_code):
        self.commits.append((dict(pending), span, error_code))


class _FakeSpan:
    def __init__(self, latency_us):
        self.latency_us = latency_us
        self.retained_reason = ""


@pytest.fixture()
def retainer():
    dumper = _FakeDumper()
    r = TailRetainer(dumper)
    yield r, dumper
    r.close()


class TestDecision:
    def test_disabled_by_default(self):
        assert TailRetainer.enabled() is False
        _flags.set_flag("rpc_dump_tail", True)
        assert TailRetainer.enabled() is True

    def test_error_retained_immediately(self, retainer):
        r, dumper = retainer
        span = _FakeSpan(100.0)
        before = g_dump_tail_retained.get_value()
        r.offer({"k": 1}, span, EINTERNAL, 1000.0)
        assert len(dumper.commits) == 1
        pending, _span_out, code = dumper.commits[0]
        assert pending["retained"] == "tail"
        assert pending["retention_reason"] == REASON_ERROR
        assert code == EINTERNAL
        assert span.retained_reason == REASON_ERROR
        assert g_dump_tail_retained.get_value() == before + 1

    @pytest.mark.parametrize("code", [EOVERCROWDED, ELIMIT])
    def test_qos_shed_retained(self, retainer, code):
        r, dumper = retainer
        span = _FakeSpan(50.0)
        r.offer({}, span, code, 1000.0)
        assert dumper.commits[0][0]["retention_reason"] == REASON_SHED
        assert span.retained_reason == REASON_SHED

    def test_slow_vs_p99_retained(self, retainer):
        r, dumper = retainer
        # slow_x default 2.0: 300 > 2 * 100 retains, 150 does not
        r.offer({}, _FakeSpan(300.0), 0, 100.0)
        assert dumper.commits[0][0]["retention_reason"] == REASON_SLOW
        r.offer({}, _FakeSpan(150.0), 0, 100.0)
        assert len(dumper.commits) == 1
        assert r.state()["held"] == 1

    def test_cold_method_never_slow(self, retainer):
        # p99 == 0 (no samples yet) must not classify everything as slow
        r, dumper = retainer
        r.offer({}, _FakeSpan(1e6), 0, 0.0)
        assert not dumper.commits
        assert r.state()["held"] == 1

    def test_none_span_ignored(self, retainer):
        r, dumper = retainer
        r.offer({}, None, EINTERNAL, 0.0)
        assert not dumper.commits
        assert r.state()["held"] == 0


class TestRing:
    def test_hold_expires_unwritten(self, retainer):
        r, dumper = retainer
        _flags.set_flag("rpc_dump_tail_hold_s", 0.05)
        before = g_dump_tail_dropped.get_value()
        r.offer({}, _FakeSpan(10.0), 0, 1000.0)
        assert r.state()["held"] == 1
        time.sleep(0.08)
        r.offer({}, _FakeSpan(10.0), 0, 1000.0)  # sweeps the expired hold
        assert r.state()["held"] == 1
        assert g_dump_tail_dropped.get_value() == before + 1
        assert not dumper.commits

    def test_ring_cap_evicts_oldest(self, retainer):
        r, dumper = retainer
        _flags.set_flag("rpc_dump_tail_ring", 2)
        before = g_dump_tail_dropped.get_value()
        for _ in range(3):
            r.offer({}, _FakeSpan(10.0), 0, 1000.0)
        assert r.state()["held"] == 2
        assert g_dump_tail_dropped.get_value() == before + 1
        assert not dumper.commits

    def test_close_drops_held(self):
        dumper = _FakeDumper()
        r = TailRetainer(dumper)
        r.offer({}, _FakeSpan(10.0), 0, 1000.0)
        before = g_dump_tail_dropped.get_value()
        hooks = len(global_watch().transition_hooks)
        r.close()
        assert g_dump_tail_dropped.get_value() == before + 1
        assert len(global_watch().transition_hooks) == hooks - 1
        # offers after close are no-ops
        r.offer({}, _FakeSpan(10.0), EINTERNAL, 0.0)
        assert not dumper.commits


class TestTokenBucket:
    def test_cap_sheds_excess_commits(self, retainer):
        r, dumper = retainer
        _flags.set_flag("rpc_dump_tail_max_per_sec", 1)
        before = g_dump_tail_shed.get_value()
        r.offer({}, _FakeSpan(1.0), EINTERNAL, 0.0)
        r.offer({}, _FakeSpan(1.0), EINTERNAL, 0.0)
        assert len(dumper.commits) == 1
        assert g_dump_tail_shed.get_value() == before + 1

    def test_uncapped_when_zero(self, retainer):
        r, dumper = retainer
        _flags.set_flag("rpc_dump_tail_max_per_sec", 0)
        for _ in range(5):
            r.offer({}, _FakeSpan(1.0), EINTERNAL, 0.0)
        assert len(dumper.commits) == 5


class TestWatchCorrelation:
    def test_already_firing_rule_retains_immediately(self, retainer):
        r, dumper = retainer
        rule = global_watch().add(
            WatchRule("tail_hot", "g_x", "threshold", ">", 1.0))
        try:
            rule.state = STATE_FIRING
            r.offer({}, _FakeSpan(10.0), 0, 1000.0)
            assert dumper.commits[0][0]["retention_reason"] == "watch:tail_hot"
        finally:
            global_watch().remove("tail_hot")

    def test_transition_drains_ring(self, retainer):
        r, dumper = retainer
        # the bucket starts with a single token; a drain is a burst
        _flags.set_flag("rpc_dump_tail_max_per_sec", 0)
        spans = [_FakeSpan(10.0), _FakeSpan(20.0)]
        for sp in spans:
            r.offer({}, sp, 0, 1000.0)
        assert r.state()["held"] == 2
        rule = global_watch().add(
            WatchRule("tail_drain", "g_y", "threshold", ">", 1.0))
        try:
            # drive the registry's own transition plumbing so the hook
            # wiring (not just _on_watch) is what's under test
            global_watch()._report(rule, STATE_FIRING)
            assert len(dumper.commits) == 2
            assert all(p["retention_reason"] == "watch:tail_drain"
                       for p, _s, _c in dumper.commits)
            assert r.state()["held"] == 0
            assert all(sp.retained_reason == "watch:tail_drain"
                       for sp in spans)
        finally:
            global_watch().remove("tail_drain")


# ---------------------------------------------------------------- e2e layer
class _FailingEcho(EchoServiceImpl):
    def Echo(self, cntl, request, done):
        if request.message == "boom":
            raise RuntimeError("boom")
        return super().Echo(cntl, request, done)


def _stub_for(server):
    return Stub(Channel().init(str(server.listen_endpoint())), ECHO_DESC)


def _pump(stub, n, msg="w"):
    for _ in range(n):
        stub.Echo(echo_pb2.EchoRequest(message=msg))


class TestServerIntegration:
    def test_error_lands_in_dump_and_rpcz(self, tmp_path, tail_on):
        from brpc_tpu.policy.http_protocol import http_fetch

        _flags.set_flag("rpc_dump_ratio", 0.0)
        _flags.set_flag("rpc_dump_tail_max_per_sec", 0)
        server = Server(ServerOptions(rpc_dump_dir=str(tmp_path)))
        server.add_service(_FailingEcho()).start("127.0.0.1:0")
        try:
            stub = _stub_for(server)
            _pump(stub, 20)
            with pytest.raises(Exception):
                stub.Echo(echo_pb2.EchoRequest(message="boom"))
            deadline = time.monotonic() + 5
            while (server.rpc_dumper.sampled_count < 1
                   and time.monotonic() < deadline):
                time.sleep(0.01)
            addr = str(server.listen_endpoint())

            resp = http_fetch(addr, "GET", "/rpcz?retained=tail&format=json")
            assert resp.status == 200
            doc = json.loads(bytes(resp.body).decode())
            # warmup stragglers may legitimately be retained as slow_p99
            # alongside the seeded failure; select by reason
            errored = [s for s in doc["spans"]
                       if s["retained_reason"] == REASON_ERROR]
            assert len(errored) == 1
            assert errored[0]["error_code"] == EINTERNAL

            resp = http_fetch(addr, "GET", "/dump")
            assert resp.status == 200
            assert b"tail: enabled=True" in bytes(resp.body)
        finally:
            server.stop()
            server.join(timeout=2)
        server.rpc_dumper.close()
        records = [r for r in RpcDumpLoader(str(tmp_path))
                   if r.info.get("retention_reason") == REASON_ERROR]
        assert len(records) == 1
        rec = records[0]
        assert rec.info["retained"] == "tail"
        assert rec.info["error_code"] == EINTERNAL
        assert rec.method_key == "EchoService.Echo"

    def test_fast_traffic_not_dumped_wholesale(self, tmp_path, tail_on):
        _flags.set_flag("rpc_dump_ratio", 0.0)
        server = Server(ServerOptions(rpc_dump_dir=str(tmp_path)))
        server.add_service(EchoServiceImpl()).start("127.0.0.1:0")
        try:
            _pump(_stub_for(server), 50)
        finally:
            server.stop()
            server.join(timeout=2)
        server.rpc_dumper.close()
        # a cold-start straggler or two may genuinely exceed 2x the live
        # p99 and get retained; the point is the fast bulk is not dumped
        records = list(RpcDumpLoader(str(tmp_path)))
        assert len(records) <= 3
        assert all(r.info["retention_reason"] == REASON_SLOW
                   for r in records)


class TestTailPrecision:
    """The acceptance claim: for seeded delayed requests, tail retention
    recalls >= 90% of the delayed traces while head sampling at ratio 0.1
    recalls ~10% of them (and a pile of fast ones nobody will replay)."""

    DELAY_MS = 80
    DELAYED = 10
    # 100 fast calls between delayed ones keeps the outlier weight fraction
    # of the percentile window <= 1%, so the live p99 stays at the fast
    # value and every delayed call settles against it
    FAST_PER_CYCLE = 100

    def _run_server(self, tmp_path, service, calls):
        server = Server(ServerOptions(rpc_dump_dir=str(tmp_path)))
        server.add_service(service).start("127.0.0.1:0")
        try:
            calls(_stub_for(server))
        finally:
            server.stop()
            server.join(timeout=2)
        server.rpc_dumper.close()
        return list(RpcDumpLoader(str(tmp_path)))

    def _delayed_of(self, records):
        # seeded delay is 80ms; fast calls settle well under 60ms even
        # with scheduler noise
        return [r for r in records if r.info.get("latency_us", 0) > 60000]

    def test_tail_recalls_delayed_head_does_not(self, tmp_path, tail_on,
                                                fault_enabled):
        _flags.set_flag("rpc_dump_ratio", 0.0)
        _flags.set_flag("rpc_dump_tail_max_per_sec", 0)

        def tail_calls(stub):
            _pump(stub, self.FAST_PER_CYCLE)  # warm the percentile window
            for _ in range(self.DELAYED):
                fault.arm("rpc.handler.delay", count=1,
                          delay_ms=self.DELAY_MS)
                _pump(stub, 1, msg="delayed")
                _pump(stub, self.FAST_PER_CYCLE)

        tail_records = self._run_server(
            tmp_path / "tail", EchoServiceImpl(), tail_calls)
        tail_delayed = self._delayed_of(tail_records)
        recall = len(tail_delayed) / self.DELAYED
        assert recall >= 0.9, (
            f"tail retention recalled {len(tail_delayed)}/{self.DELAYED} "
            f"delayed traces")
        assert all(r.info["retention_reason"] == REASON_SLOW
                   for r in tail_delayed)
        assert all(r.info["retained"] == "tail" for r in tail_delayed)
        # and it is *selective*: the fast bulk is not dumped wholesale
        assert len(tail_records) <= self.DELAYED + 5

        # head sampling at ratio 0.1 over the same seeded workload:
        # the keep decision happens at arrival, blind to latency
        _flags.set_flag("rpc_dump_tail", False)
        _flags.set_flag("rpc_dump_ratio", 0.1)

        def head_calls(stub):
            for _ in range(self.DELAYED):
                fault.arm("rpc.handler.delay", count=1,
                          delay_ms=self.DELAY_MS)
                _pump(stub, 1, msg="delayed")
                _pump(stub, 9)

        head_records = self._run_server(
            tmp_path / "head", EchoServiceImpl(), head_calls)
        head_delayed = self._delayed_of(head_records)
        # Binomial(10, 0.1): P(>= 7 kept) ~ 1e-5 — head sampling cannot
        # reliably recall the delayed tail
        assert len(head_delayed) <= 6
        assert recall > len(head_delayed) / self.DELAYED
