"""Cross-process tpu:// transport tests (VERDICT r1 #1 — the graft).

Pattern follows the reference's RPC integration tests (SURVEY §4): real
sockets, no mock transport. The multi-process test is the round's
acceptance criterion: a Server in process A serving RPCs issued by a
Channel in process B over a tpu:// endpoint, bytes staged through the
shared-memory registered block pool (reference RdmaEndpoint blueprint,
rdma_endpoint.cpp:127-130 handshake, block_pool.cpp, sliding window
rdma_endpoint.h:256-261).
"""

import os
import subprocess
import sys
import threading
import time

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    Server,
    ServerOptions,
    Service,
    Stub,
)

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoServiceImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def tpu_server():
    server = Server(ServerOptions())
    server.add_service(EchoServiceImpl())
    server.start("tpu://127.0.0.1:0/0")
    yield server
    server.stop()
    server.join()


def _stub_for(server, timeout_ms=10000):
    channel = Channel(ChannelOptions(protocol="trpc_std",
                                     timeout_ms=timeout_ms))
    channel.init(str(server.listen_endpoint()))
    return Stub(channel, ECHO)


class TestTunnelLoopback:
    """Client and server roles in one process, but the full transport in
    between: TCP bootstrap, HELLO handshake, shm block pool, credits."""

    def test_endpoint_is_tpu_scheme(self, tpu_server):
        ep = tpu_server.listen_endpoint()
        assert ep.is_tpu() and ep.port != 0
        assert str(ep).startswith("tpu://")

    def test_small_inline_echo(self, tpu_server):
        stub = _stub_for(tpu_server)
        cntl = Controller()
        cntl.request_attachment = b"tail"
        r = stub.Echo(echo_pb2.EchoRequest(message="hello"), controller=cntl)
        assert r.message == "hello"
        assert cntl.response_attachment == b"tail"

    def test_block_path_roundtrip(self, tpu_server):
        stub = _stub_for(tpu_server)
        payload = bytes(range(256)) * (1024 * 1024 // 256)  # 1MB, patterned
        r = stub.Echo(echo_pb2.EchoRequest(message="big", payload=payload))
        assert r.payload == payload

    def test_payload_larger_than_window_streams(self, tpu_server):
        # 24MB > the 16MB credit window: must stream, not deadlock
        stub = _stub_for(tpu_server, timeout_ms=60000)
        payload = b"\xab" * (24 * 1024 * 1024)
        r = stub.Echo(echo_pb2.EchoRequest(message="huge", payload=payload))
        assert r.payload == payload

    def test_attachment_rides_blocks(self, tpu_server):
        stub = _stub_for(tpu_server)
        att = b"A" * (300 * 1024)  # bigger than one 256KB block
        cntl = Controller()
        cntl.request_attachment = att
        r = stub.Echo(echo_pb2.EchoRequest(message="m"), controller=cntl)
        assert cntl.response_attachment == att

    def test_concurrent_clients_interleave_safely(self, tpu_server):
        stub = _stub_for(tpu_server, timeout_ms=30000)
        errs = []

        def worker(i):
            try:
                payload = bytes([i]) * (512 * 1024 + i)
                for _ in range(3):
                    r = stub.Echo(echo_pb2.EchoRequest(message=str(i),
                                                       payload=payload))
                    assert r.payload == payload, f"worker {i} corrupted"
            except Exception as e:  # noqa: BLE001
                errs.append((i, e))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs, errs

    def test_pipelined_async_calls(self, tpu_server):
        channel = Channel(ChannelOptions(protocol="trpc_std",
                                         timeout_ms=30000))
        channel.init(str(tpu_server.listen_endpoint()))
        stub = Stub(channel, ECHO)
        done_evt = threading.Event()
        results = []
        n = 20

        def make_done(i):
            def done(cntl):
                results.append((i, cntl.error_code,
                                cntl.response.message if cntl.response else ""))
                if len(results) == n:
                    done_evt.set()
            return done

        for i in range(n):
            stub.Echo(echo_pb2.EchoRequest(message=f"m{i}"),
                      done=make_done(i))
        assert done_evt.wait(30)
        assert sorted(m for _, code, m in results if code == 0) == \
            sorted(f"m{i}" for i in range(n))

    def test_server_stop_fails_pending_cleanly(self):
        server = Server(ServerOptions())

        # a SUBCLASS scopes the name override — patching the property on
        # the shared Service base renamed every later service in the
        # process (caught when BuiltinViewService started auto-mounting)
        class _SlowSvc(Service):
            @property
            def service_name(self):
                return "EchoService"

        svc = _SlowSvc()

        gate = threading.Event()

        def slow(cntl, request, done):
            gate.wait(5)
            return echo_pb2.EchoResponse(message="late")

        svc.add_method("Echo", slow, echo_pb2.EchoRequest,
                       echo_pb2.EchoResponse)
        server.add_service(svc)
        server.start("tpu://127.0.0.1:0/0")
        stub = _stub_for(server, timeout_ms=2000)
        cntl = Controller()
        finished = threading.Event()
        stub.Echo(echo_pb2.EchoRequest(message="x"), controller=cntl,
                  done=lambda _c: finished.set())
        time.sleep(0.2)
        server.stop()
        server.join(timeout=0.5)
        gate.set()
        assert finished.wait(5)
        # either the late response made it before teardown or the call
        # failed with a socket/timeout error — never a hang
        server.join()


class TestOrdinalAddressing:
    def test_wrong_ordinal_refused(self, tpu_server):
        # server fronts device 0; dialing /3 must be refused at handshake
        ep = tpu_server.listen_endpoint()
        bad = f"tpu://{ep.host}:{ep.port}/3"
        channel = Channel(ChannelOptions(protocol="trpc_std",
                                         timeout_ms=3000, max_retry=0))
        channel.init(bad)
        stub = Stub(channel, ECHO)
        from brpc_tpu.rpc.channel import RpcError

        with pytest.raises((RpcError, ConnectionError)):
            stub.Echo(echo_pb2.EchoRequest(message="x"))
        # the right ordinal still works
        good_stub = _stub_for(tpu_server)
        assert good_stub.Echo(
            echo_pb2.EchoRequest(message="ok")).message == "ok"


class _FakeCtrl:
    """Stand-in bootstrap socket: records every frame the endpoint writes
    so tests can assert exactly which credits were ACKed, and when."""

    def __init__(self):
        self.frames = []          # raw bytes, one entry per write()
        self.failed = False
        self.remote = None
        self.error_code = 0
        self.error_text = ""
        self.on_failed_hook = None
        self.cut_batch_hook = None

    def write(self, data, id_wait=None):
        if self.failed:
            return 1
        self.frames.append(
            data.tobytes() if hasattr(data, "tobytes") else bytes(data))
        return 0

    def set_failed(self, code, reason=""):
        if self.failed:
            return
        self.failed = True
        self.error_code = code
        self.error_text = reason
        if self.on_failed_hook is not None:  # real Socket fires this too
            self.on_failed_hook(code, reason)


def _acked_indices(fake):
    """All block indices returned so far, one list per FT_ACK frame."""
    import struct

    from brpc_tpu.tpu import transport as tr

    out = []
    for raw in fake.frames:
        magic, ftype, blen = struct.unpack_from(tr.CTRL_HDR, raw)
        if ftype == tr.FT_ACK:
            body = raw[tr.CTRL_HDR_SIZE:tr.CTRL_HDR_SIZE + blen]
            vals = struct.unpack(f"!{len(body) // 4}I", body)
            # v2 ACK body: (epoch, count, *indices)
            out.append(list(vals[2:2 + vals[1]]))
    return out


def _make_endpoint():
    from brpc_tpu.policy import ensure_registered
    from brpc_tpu.tpu import transport as tr

    ensure_registered()
    fake = _FakeCtrl()
    ep = tr.TpuEndpoint(fake, role="client", target_ordinal=0,
                        block_size=64 * 1024, block_count=8)
    return tr, fake, ep


def _trpc_response_packet(payload: bytes) -> bytes:
    """A complete, well-formed trpc_std RESPONSE for a correlation id that
    does not exist — the client stack parses and then quietly drops it,
    which is exactly the 'parser consumed the bytes' event."""
    from brpc_tpu.policy.trpc_std import TrpcStdProtocol
    from brpc_tpu.proto import rpc_meta_pb2

    meta = rpc_meta_pb2.RpcMeta()
    meta.correlation_id = 0x7FFF1234
    meta.response.error_code = 0
    return TrpcStdProtocol().pack_response(meta, payload).tobytes()


def _data_frame_body(segs, epoch=0):
    """DATA body referencing pool blocks: [(idx, ln), ...]. Fake-ctrl
    endpoints are built at epoch 0, so the default matches."""
    import struct

    from brpc_tpu.tpu import transport as tr

    body = struct.pack(tr.DATA_BODY_HDR, epoch, 0, len(segs))
    for idx, ln in segs:
        body += struct.pack(tr.SEG_FMT, idx, ln)
    return body


class TestCreditReturnExactlyOnce:
    """Tentpole regression: a borrowed block's credit is released exactly
    once, only after the parser consumed the bytes — and teardown with
    borrows outstanding neither leaks credits nor double-releases."""

    def test_credit_deferred_until_parse_consumes(self):
        from brpc_tpu.butil.iobuf import IOBuf, supports_block_ownership

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        tr, fake, ep = _make_endpoint()
        try:
            pkt = _trpc_response_packet(b"\xcd" * 8192)
            half = len(pkt) // 2
            pool = ep.recv_pool
            # peer 'writes' the packet across two registered blocks
            pool._shm.buf[0:half] = pkt[:half]
            blk = pool.block_size
            pool._shm.buf[blk:blk + len(pkt) - half] = pkt[half:]

            # frame 1: only the first half — the parser cannot finish, so
            # NO credit may come back yet
            ep.on_data(IOBuf(_data_frame_body([(0, half)])))
            assert _acked_indices(fake) == []
            assert ep._borrowed_outstanding == 1
            assert ep._released_total == 0

            # frame 2: the rest — the message parses, its body is consumed
            # by the (unknown-cid) response path, credits flow back
            ep.on_data(IOBuf(_data_frame_body([(1, len(pkt) - half)])))
            deadline = time.monotonic() + 5
            while time.monotonic() < deadline:
                acked = [i for frame in _acked_indices(fake) for i in frame]
                if sorted(acked) == [0, 1]:
                    break
                time.sleep(0.01)
            acked = [i for frame in _acked_indices(fake) for i in frame]
            assert sorted(acked) == [0, 1], acked  # each EXACTLY once
            deadline = time.monotonic() + 5
            while ep._borrowed_outstanding and time.monotonic() < deadline:
                time.sleep(0.01)
            assert ep._borrowed_outstanding == 0
            assert ep._released_total == 2
        finally:
            ep.fail(0, "test done")

    def test_teardown_with_outstanding_borrow(self):
        from brpc_tpu.butil.iobuf import IOBuf, supports_block_ownership

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        tr, fake, ep = _make_endpoint()
        pkt = _trpc_response_packet(b"\xee" * 4096)
        pool = ep.recv_pool
        pool._shm.buf[0:64] = pkt[:64]   # incomplete head only
        ep.on_data(IOBuf(_data_frame_body([(0, 64)])))
        assert ep._borrowed_outstanding == 1
        assert _acked_indices(fake) == []

        frames_before = len(fake.frames)
        ep.fail(999, "test teardown")
        # the borrow was released exactly once by the teardown clear...
        assert ep._released_total == 1
        assert ep._borrowed_outstanding == 0
        # ...but its credit was NOT acked (peer is gone), and no ack frame
        # was written during/after teardown
        assert _acked_indices(fake) == []
        assert all(f[:4] == tr.CTRL_MAGIC[:4] for f in fake.frames)
        # the pool unmapped inline: no exports were left behind
        assert pool.exports == 0
        assert pool._closed

    def test_teardown_with_inflight_body_defers_pool_close(self):
        from brpc_tpu.butil.iobuf import IOBuf, supports_block_ownership

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        tr, fake, ep = _make_endpoint()
        pkt = _trpc_response_packet(b"\xaa" * 4096)
        pool = ep.recv_pool
        pool._shm.buf[0:64] = pkt[:64]
        ep.on_data(IOBuf(_data_frame_body([(0, 64)])))
        # simulate an in-flight message body still holding borrowed bytes
        held = ep.vsock.read_buf.cutn(64)
        ep.fail(999, "teardown with body in flight")
        assert ep._released_total == 0          # the borrow is still live
        assert pool.exports == 1
        assert not pool._closed                 # unmap deferred, not forced
        del held                                 # the fiber finishes
        assert ep._released_total == 1           # exactly once
        assert ep._borrowed_outstanding == 0
        assert pool.exports == 0
        tr._sweep_deferred_pools()               # retry outside the cascade
        assert pool._closed
        assert _acked_indices(fake) == []        # no credit ack after death

    def test_loopback_echo_is_zero_copy(self, tpu_server):
        """Acceptance: block-segment frames cross the receive path with
        ZERO full-payload copies — all segment bytes are borrowed, none
        copied (both directions of a loopback echo count here)."""
        from brpc_tpu.butil.iobuf import supports_block_ownership
        from brpc_tpu.tpu import transport as tr

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        stub = _stub_for(tpu_server)
        payload = b"\x5a" * (1024 * 1024)
        stub.Echo(echo_pb2.EchoRequest(message="warm", payload=payload))
        borrowed0 = tr.g_tunnel_borrowed_bytes.get_value()
        copied0 = tr.g_tunnel_copied_bytes.get_value()
        r = stub.Echo(echo_pb2.EchoRequest(message="zc", payload=payload))
        assert r.payload == payload
        borrowed = tr.g_tunnel_borrowed_bytes.get_value() - borrowed0
        copied = tr.g_tunnel_copied_bytes.get_value() - copied0
        # request (server side) + response (client side) both ride blocks
        assert borrowed >= 2 * len(payload), (borrowed, copied)
        assert copied == 0, (borrowed, copied)


class TestWindowAccounting:
    def test_credits_return_after_traffic(self, tpu_server):
        stub = _stub_for(tpu_server)
        payload = b"z" * (2 * 1024 * 1024)
        for _ in range(5):
            r = stub.Echo(echo_pb2.EchoRequest(message="w", payload=payload))
            assert len(r.payload) == len(payload)
        # after all RPCs complete the client's view of the server window
        # must be full again (all credits returned)
        from brpc_tpu.tpu import transport as tr

        with tr._remote_lock:
            vs = next(iter(tr._remote_sockets.values()))
        win = vs.endpoint.window
        deadline = time.monotonic() + 5
        while time.monotonic() < deadline:
            with win._cond:
                if len(win._free) == win.block_count:
                    break
            time.sleep(0.01)
        with win._cond:
            assert len(win._free) == win.block_count


_CHILD_SERVER = r"""
import sys
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Server, ServerOptions, Service

class EchoServiceImpl(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]
    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message="from-child:" + request.message,
                                     payload=request.payload)

server = Server(ServerOptions())
server.add_service(EchoServiceImpl())
server.start("tpu://127.0.0.1:0/0")
print(f"LISTENING {server.listen_endpoint()}", flush=True)
sys.stdin.readline()   # parent closes stdin to stop us
server.stop(); server.join()
"""


class TestTwoProcesses:
    """THE acceptance test: Channel in this process, Server in a child
    process, RPC over tpu:// with payload through the shm block pool."""

    @pytest.fixture()
    def child_server(self):
        env = dict(os.environ)
        env.setdefault("JAX_PLATFORMS", "cpu")
        proc = subprocess.Popen(
            [sys.executable, "-c", _CHILD_SERVER],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE,
            stderr=subprocess.PIPE, text=True, env=env,
            cwd=os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        )
        line = proc.stdout.readline().strip()
        assert line.startswith("LISTENING "), (
            line, proc.stderr.read() if proc.poll() is not None else "")
        yield line.split(" ", 1)[1]
        try:
            proc.stdin.close()
            proc.wait(10)
        except Exception:
            proc.kill()

    def test_cross_process_echo(self, child_server):
        channel = Channel(ChannelOptions(protocol="trpc_std",
                                         timeout_ms=15000))
        channel.init(child_server)
        stub = Stub(channel, ECHO)
        r = stub.Echo(echo_pb2.EchoRequest(message="ping"))
        assert r.message == "from-child:ping"

    def test_cross_process_bulk_payload(self, child_server):
        channel = Channel(ChannelOptions(protocol="trpc_std",
                                         timeout_ms=30000))
        channel.init(child_server)
        stub = Stub(channel, ECHO)
        payload = bytes(range(256)) * (4 * 1024 * 1024 // 256)
        cntl = Controller()
        cntl.request_attachment = b"side-channel"
        r = stub.Echo(echo_pb2.EchoRequest(message="bulk", payload=payload),
                      controller=cntl)
        assert r.payload == payload
        assert cntl.response_attachment == b"side-channel"

    def test_cross_process_concurrent(self, child_server):
        channel = Channel(ChannelOptions(protocol="trpc_std",
                                         timeout_ms=30000))
        channel.init(child_server)
        stub = Stub(channel, ECHO)
        errs = []

        def worker(i):
            try:
                payload = bytes([i]) * (256 * 1024 * (1 + i % 3))
                r = stub.Echo(echo_pb2.EchoRequest(message=str(i),
                                                   payload=payload))
                assert r.payload == payload
                assert r.message == f"from-child:{i}"
            except Exception as e:  # noqa: BLE001
                errs.append((i, repr(e)))

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(60)
        assert not errs, errs

    def test_tunnel_failure_errors_inflight_and_reconnects(self, child_server):
        channel = Channel(ChannelOptions(protocol="trpc_std",
                                         timeout_ms=10000, max_retry=0))
        channel.init(child_server)
        stub = Stub(channel, ECHO)
        # prove liveness first
        stub.Echo(echo_pb2.EchoRequest(message="alive"))
        from brpc_tpu.rpc import errors as _errors
        from brpc_tpu.tpu import transport as tr

        with tr._remote_lock:
            vs = [s for s in tr._remote_sockets.values() if not s.failed][0]
        # a call id pending on the tunnel when it dies must get the socket
        # error through the error channel (reference Socket::SetFailed fanout)
        codes = []
        evt = threading.Event()
        from brpc_tpu.fiber import call_id as _cid

        cid = _cid.id_create(
            data=None,
            on_error=lambda d, c, code: (codes.append(code),
                                         _cid.id_unlock_and_destroy(c),
                                         evt.set()))
        vs.add_pending_id(cid)
        vs.close()
        assert evt.wait(5)
        assert codes == [_errors.EFAILEDSOCKET]
        # ...and the next call transparently re-dials a fresh tunnel
        r = stub.Echo(echo_pb2.EchoRequest(message="recovered"))
        assert r.message == "from-child:recovered"
