"""Streaming RPC tests (reference test/brpc_streaming_rpc_unittest.cpp
pattern: client+server streams over loopback, flow-control pressure)."""

import threading
import time

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, Controller, Server, Service, Stub, errors
from brpc_tpu.rpc.stream import (
    StreamOptions,
    get_stream,
    stream_accept,
    stream_close,
    stream_create,
    stream_write,
)

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class StreamingEchoService(Service):
    """Accepts a stream and echoes every received message back on it."""

    DESCRIPTOR = ECHO_DESC

    def __init__(self):
        super().__init__()
        self.server_streams = []
        self.received = []
        self.closed = threading.Event()

    def Echo(self, cntl, request, done):
        def on_received(sid, msgs):
            self.received.extend(msgs)
            for m in msgs:
                stream_write(sid, m)  # echo back on the same stream

        def on_closed(sid):
            self.closed.set()

        sid = stream_accept(cntl, StreamOptions(
            on_received=on_received, on_closed=on_closed))
        self.server_streams.append(sid)
        return echo_pb2.EchoResponse(message="stream-accepted")


@pytest.fixture()
def stream_server():
    impl = StreamingEchoService()
    server = Server().add_service(impl).start("127.0.0.1:0")
    yield server, impl
    server.stop()
    server.join(timeout=2)


def connect_stream(server, on_received=None, on_closed=None, window=None):
    opts = StreamOptions(on_received=on_received, on_closed=on_closed)
    if window:
        opts.window_bytes = window
    sid = stream_create(opts)
    cntl = Controller()
    cntl.stream_id = sid
    ch = Channel().init(str(server.listen_endpoint()))
    stub = Stub(ch, ECHO_DESC)
    resp = stub.Echo(echo_pb2.EchoRequest(message="open"), controller=cntl)
    assert resp.message == "stream-accepted"
    return sid


class TestStreaming:
    def test_echo_roundtrip(self, stream_server):
        server, impl = stream_server
        got = []
        done = threading.Event()

        def on_received(sid, msgs):
            got.extend(msgs)
            if len(got) >= 3:
                done.set()

        sid = connect_stream(server, on_received)
        for i in range(3):
            assert stream_write(sid, f"msg-{i}".encode()) == 0
        assert done.wait(5)
        assert got == [b"msg-0", b"msg-1", b"msg-2"]
        assert impl.received == got

    def test_ordering_under_load(self, stream_server):
        server, impl = stream_server
        got = []
        done = threading.Event()
        N = 500

        def on_received(sid, msgs):
            got.extend(msgs)
            if len(got) >= N:
                done.set()

        sid = connect_stream(server, on_received)
        for i in range(N):
            assert stream_write(sid, str(i).encode().zfill(6)) == 0
        assert done.wait(15)
        assert got == [str(i).encode().zfill(6) for i in range(N)]

    def test_flow_control_blocks_and_recovers(self, stream_server):
        """Writer must stall when the window fills and resume on feedback
        (stream.cpp:318 AppendIfNotFull / :354 SetRemoteConsumed)."""
        server, impl = stream_server
        window = 64 * 1024
        got = []
        done = threading.Event()
        total = 32

        def on_received(sid, msgs):
            got.extend(msgs)
            if len(got) >= total:
                done.set()

        sid = connect_stream(server, on_received, window=window)
        chunk = b"z" * (16 * 1024)
        t0 = time.monotonic()
        for _ in range(total):  # 512KB through a 64KB window
            assert stream_write(sid, chunk, timeout=10) == 0
        assert done.wait(15)
        assert len(got) == total
        stream = get_stream(sid)
        # feedback advanced the window: remote_consumed caught up
        assert stream._remote_consumed > 0

    def test_nonblocking_write_overcrowded(self):
        """Deterministic: the server's consumer is gated shut, so no
        FEEDBACK can race in and free the window between the two writes."""
        gate = threading.Event()

        class Gated(Service):
            DESCRIPTOR = ECHO_DESC

            def Echo(self, cntl, request, done):
                stream_accept(cntl, StreamOptions(
                    on_received=lambda sid, msgs: gate.wait(5)))
                return echo_pb2.EchoResponse(message="ok")

        server = Server().add_service(Gated()).start("127.0.0.1:0")
        try:
            opts = StreamOptions(blocking_write=False, window_bytes=1024)
            sid = stream_create(opts)
            cntl = Controller()
            cntl.stream_id = sid
            ch = Channel().init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            stub.Echo(echo_pb2.EchoRequest(message="open"), controller=cntl)
            big = b"x" * 900
            assert stream_write(sid, big) == 0
            rc = stream_write(sid, big)  # would exceed 1024-byte window
            assert rc == errors.EOVERCROWDED
        finally:
            gate.set()
            server.stop()
            server.join(timeout=2)

    def test_close_propagates(self, stream_server):
        server, impl = stream_server
        client_closed = threading.Event()
        sid = connect_stream(server,
                             on_closed=lambda s: client_closed.set())
        stream_close(sid)
        assert impl.closed.wait(5)  # server saw the CLOSE frame
        assert client_closed.wait(5)
        assert stream_write(sid, b"late") == errors.ESTREAMCLOSED

    def test_write_to_unknown_stream(self):
        assert stream_write(999 << 32, b"x") == errors.ESTREAMCLOSED

    def test_accept_without_settings_raises(self, stream_server):
        server, impl = stream_server

        class NoStream(Service):
            DESCRIPTOR = ECHO_DESC

            def __init__(self):
                super().__init__()
                self.error = None

            def Echo(self, cntl, request, done):
                try:
                    stream_accept(cntl)
                except ValueError as e:
                    self.error = e
                return echo_pb2.EchoResponse(message="no")

        impl2 = NoStream()
        server2 = Server().add_service(impl2).start("127.0.0.1:0")
        try:
            ch = Channel().init(str(server2.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            stub.Echo(echo_pb2.EchoRequest(message="plain"))
            assert impl2.error is not None
        finally:
            server2.stop()
            server2.join(timeout=2)


class TestStreamingOverNativeLanes:
    """The same streaming semantics must hold on every transport lane:
    native TCP engine and the native TPUC shm tunnel (TSTR frames ride
    the tunnel byte stream like any other message)."""

    @pytest.mark.parametrize("listen,native_client", [
        ("127.0.0.1:0", True),            # native TCP lane
        ("tpu://127.0.0.1:0/0", True),    # native shm tunnel lane
        ("tpu://127.0.0.1:0/0", False),   # python client, native server
    ])
    def test_stream_echo_on_lane(self, listen, native_client):
        from brpc_tpu.rpc import ChannelOptions
        from brpc_tpu.rpc.native_transport import dataplane_available

        if not dataplane_available():
            pytest.skip("native engine unavailable")
        from brpc_tpu.rpc import ServerOptions

        impl = StreamingEchoService()
        server = Server(ServerOptions(native_dataplane=True))
        server.add_service(impl)
        server.start(listen)
        try:
            got = []
            done = threading.Event()

            def on_received(sid, msgs):
                got.extend(msgs)
                if len(got) >= 8:
                    done.set()

            opts = StreamOptions(on_received=on_received)
            sid = stream_create(opts)
            cntl = Controller()
            cntl.stream_id = sid
            ch = Channel(ChannelOptions(
                timeout_ms=10000,
                native_transport=native_client)).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            resp = stub.Echo(echo_pb2.EchoRequest(message="open"),
                             controller=cntl)
            assert resp.message == "stream-accepted"
            payloads = [bytes([i]) * (1000 * (i + 1)) for i in range(8)]
            for p in payloads:
                assert stream_write(sid, p) == 0
            assert done.wait(10), f"echoed {len(got)}/8"
            assert sorted(len(g) for g in got) == sorted(
                len(p) for p in payloads)
            stream_close(sid)
        finally:
            server.stop()
            server.join(timeout=2)
