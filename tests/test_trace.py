"""Deep data-path tracing: phase timelines, structured events, /rpcz
filters + JSON export, the /tpu builtin and the trace_view renderer.

Layout mirrors how the subsystem is consumed:

* span-core units — phase accumulation, the event cap, monotonic-clock
  durations immune to wall skew, JSON round-trips;
* each dispatch path observably stamps its phases — generic (TCP
  baidu_std), native/tunnel (tpu:// trpc_std), batched;
* a credit-starved window produces a measured ``credit_wait_us`` and a
  ``credit_stall`` event on the owning RPC's span;
* the HTTP surface — /rpcz query filters, ?format=json, /tpu state —
  and the offline waterfall renderer;
* sampling off leaves the hot path span-free (the zero-overhead claim).
"""

import io
import json
import threading
import time

import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.policy.http_protocol import http_fetch
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    RpcError,
    Server,
    ServerOptions,
    Service,
    Stub,
    errors,
)
from brpc_tpu.trace import span as _span

from test_tpu_transport import _stub_for, tpu_server  # noqa: F401

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        if request.message == "boom":
            cntl.set_failed(errors.EINTERNAL, "requested failure")
            return None
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def traced():
    """Sampling wide open: ratio 1.0 and the collector cap disabled, so
    every span in the test is recorded deterministically."""
    from brpc_tpu.metrics.collector import global_collector

    _flags.set_flag("rpcz_sample_ratio", "1.0")
    _flags.set_flag("collector_max_samples_per_second", "0")
    global_collector()._deny_until = 0.0
    _span.reset_for_test()
    yield
    _flags.set_flag("collector_max_samples_per_second", "1000")


@pytest.fixture()
def tcp_server():
    server = Server().add_service(EchoImpl()).start("127.0.0.1:0")
    yield server
    server.stop()
    server.join(timeout=2)


def addr(server):
    return str(server.listen_endpoint())


def _wait_spans(predicate, timeout=3.0):
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        spans = _span.recent_spans(100)
        if predicate(spans):
            return spans
        time.sleep(0.01)
    return _span.recent_spans(100)


def _find(spans, kind, method="Echo"):
    for s in spans:
        if s.kind == kind and s.method == method:
            return s
    return None


# ------------------------------------------------------------------ span core
class TestSpanCore:
    def test_phase_accumulates_and_clamps(self):
        sp = _span.Span(1, 1, 0, _span.KIND_CLIENT, "S", "M")
        sp.add_phase("send_us", 10.0)
        sp.add_phase("send_us", 5.0)
        sp.add_phase("queue_us", -3.0)  # negative clamps to zero
        assert sp.phases["send_us"] == 15.0
        assert sp.phases["queue_us"] == 0.0

    def test_event_cap_counts_drops(self):
        sp = _span.Span(1, 1, 0, _span.KIND_CLIENT, "S", "M")
        for i in range(_span.MAX_EVENTS_PER_SPAN + 10):
            sp.event("e", i=i)
        assert len(sp.events) == _span.MAX_EVENTS_PER_SPAN
        assert sp.events_dropped == 10
        assert "events dropped" in sp.render()

    def test_durations_ride_monotonic_clock(self, monkeypatch):
        """Wall-clock skew (NTP step) between start and end must not
        corrupt the reported latency — the regression the monotonic
        migration exists to prevent."""
        sp = _span.Span(1, 1, 0, _span.KIND_SERVER, "S", "M")
        real = time.time
        monkeypatch.setattr(time, "time", lambda: real() - 3600.0)
        time.sleep(0.01)
        sp.end(0)
        assert 5_000 < sp.latency_us < 5_000_000

    def test_json_round_trip(self, traced):
        sp = _span.Span(0xabc, 0xdef, 0x123, _span.KIND_SERVER,
                        "EchoService", "Echo", peer="1.2.3.4:5")
        sp.request_size = 64
        sp.add_phase("parse_us", 12.5)
        sp.event("credit_stall", wait_us=8.0, need=4, got=0)
        sp.annotate("hello")
        sp.end(0)
        d = json.loads(json.dumps(sp.to_dict()))
        assert d["trace_id"] == f"{0xabc:016x}"
        assert d["parent_span_id"] == f"{0x123:016x}"
        assert d["phases"]["parse_us"] == 12.5
        assert d["events"][0]["name"] == "credit_stall"
        assert d["events"][0]["need"] == 4
        assert d["annotations"][0]["text"] == "hello"
        td = json.loads(json.dumps(_span.trace_to_dict(0xabc)))
        assert [s["span_id"] for s in td["spans"]] == [f"{0xdef:016x}"]

    def test_recent_spans_filters(self, traced):
        for method, code, us in (("Fast", 0, 10), ("Slow", 0, 90_000),
                                 ("Bad", 7, 20)):
            sp = _span.Span(1, 1, 0, _span.KIND_SERVER, "Svc", method)
            sp.start_mono_us -= us  # synthesize latency
            sp.end(code)
        assert [s.method for s in _span.recent_spans(10)] == \
            ["Bad", "Slow", "Fast"]  # newest first
        assert [s.method for s in _span.recent_spans(10, method="Svc.S")] \
            == ["Slow"]
        assert [s.method for s in
                _span.recent_spans(10, min_latency_us=50_000)] == ["Slow"]
        assert [s.method for s in _span.recent_spans(10, error_only=True)] \
            == ["Bad"]


# ------------------------------------------------------------- generic path
class TestGenericPathPhases:
    def test_server_span_carries_dispatch_phases(self, tcp_server, traced):
        ch = Channel().init(addr(tcp_server))
        Stub(ch, ECHO).Echo(echo_pb2.EchoRequest(message="hi"))
        spans = _wait_spans(lambda ss: _find(ss, "server") is not None)
        srv = _find(spans, "server")
        assert srv is not None
        for name in ("queue_us", "parse_us", "execute_us", "respond_us"):
            assert name in srv.phases, f"missing {name}: {srv.phases}"
        # additivity: the marks never explain more than the span's latency
        assert sum(srv.phases.values()) <= srv.latency_us * 1.05
        client = _find(spans, "client")
        assert client is not None and "parse_us" in client.phases

    def test_phase_aggregates_exposed(self, tcp_server, traced):
        from brpc_tpu.metrics import dump_exposed

        # the per-phase Adders are created lazily and cached; another
        # test file's clear_registry() may have dropped their exposure —
        # drop the cache so this trace re-creates (and re-exposes) them
        _span._phase_adders.clear()
        ch = Channel().init(addr(tcp_server))
        Stub(ch, ECHO).Echo(echo_pb2.EchoRequest(message="agg"))
        _wait_spans(lambda ss: _find(ss, "server") is not None)
        snap = dump_exposed()
        assert "g_span_phase_execute_us" in snap


# -------------------------------------------------------------- tunnel path
class TestTunnelPathPhases:
    def test_block_path_echo_phases(self, tpu_server, traced):
        stub = _stub_for(tpu_server, timeout_ms=30000)
        payload = b"\xa5" * (1 << 20)
        r = stub.Echo(echo_pb2.EchoRequest(message="m", payload=payload))
        assert r.payload == payload
        spans = _wait_spans(
            lambda ss: _find(ss, "client") is not None
            and _find(ss, "server") is not None)
        client = _find(spans, "client")
        srv = _find(spans, "server")
        assert client.trace_id == srv.trace_id
        # 1MB rides the block path: the client span must carry send
        # timing, the server span the dispatch phases
        assert client.phases.get("send_us", 0.0) > 0.0
        assert "credit_wait_us" in client.phases
        for name in ("parse_us", "execute_us", "respond_us"):
            assert name in srv.phases
        # the pipelined send stamps one event per posted quantum
        assert any(name == "send_quantum"
                   for _, name, _ in client.events)

    def test_streaming_echo_phases_explain_latency(self, tpu_server,
                                                   traced):
        """Acceptance: a sampled 16MB streaming echo's phase breakdown
        sums to ~the measured trace latency (credit_wait/send on the
        client + queue/parse/execute/respond/send on the server)."""
        stub = _stub_for(tpu_server, timeout_ms=60000)
        payload = bytes(range(256)) * (16 * 1024 * 1024 // 256)
        r = stub.Echo(echo_pb2.EchoRequest(message="big", payload=payload))
        assert r.payload == payload
        spans = _wait_spans(
            lambda ss: _find(ss, "client") is not None
            and _find(ss, "server") is not None, timeout=10.0)
        client = _find(spans, "client")
        srv = _find(spans, "server")
        assert srv.trace_id == client.trace_id
        accounted = sum(client.phases.values()) + sum(srv.phases.values())
        total = client.latency_us
        # the timeline must explain the latency — a large unattributed
        # remainder means a layer stopped stamping its marks (bounded
        # above too: double-counted phases would overshoot the wall time)
        assert accounted >= 0.85 * total, \
            f"phases {accounted:.0f}us explain too little of {total:.0f}us"
        assert accounted <= 1.15 * total, \
            f"phases {accounted:.0f}us overshoot wall time {total:.0f}us"

    def test_credit_stall_measured_under_shrunken_window(self, tpu_server,
                                                         traced):
        from brpc_tpu.tpu import transport

        stub = _stub_for(tpu_server, timeout_ms=30000)
        payload = b"\x42" * (1 << 20)
        stub.Echo(echo_pb2.EchoRequest(message="warm", payload=payload))
        ep = tpu_server.listen_endpoint()
        vs = transport._remote_sockets[
            (ep.host, ep.port, ep.device_ordinal)]
        win = vs.endpoint.window
        time.sleep(0.1)  # let in-flight ACKs settle before seizing
        stolen = []
        while win._free:  # shrink the window to zero credits
            stolen.extend(win.acquire(len(win._free)))
        stalls0 = transport.g_tunnel_credit_stalls.get_value()
        result = []
        t = threading.Thread(target=lambda: result.append(
            stub.Echo(echo_pb2.EchoRequest(message="stalled",
                                           payload=payload))))
        t.start()
        time.sleep(0.25)  # the sender is parked on acquire() now
        win.release(stolen)
        t.join(20)
        assert result and result[0].payload == payload
        assert transport.g_tunnel_credit_stalls.get_value() > stalls0
        spans = _wait_spans(lambda ss: any(
            s.kind == "client" and s.phases.get("credit_wait_us", 0) >
            100_000 for s in ss))
        stalled = next(s for s in spans if s.kind == "client"
                       and s.phases.get("credit_wait_us", 0) > 100_000)
        assert any(name == "credit_stall"
                   for _, name, _ in stalled.events)


# -------------------------------------------------------------- batched path
class TestBatchedPathPhases:
    def test_batch_riders_get_wait_and_execute(self, traced):
        from brpc_tpu.batch import make_batched

        def vec(batch):
            time.sleep(0.02)
            return ["ok"] * batch.size

        bm = make_batched("t.phases", vec, max_batch_size=2, max_delay_us=0,
                          flush_on_poll_batch=False)
        done = []
        spans = []
        for i in range(2):
            cntl = Controller()
            cntl.span = _span.Span(i + 1, i + 1, 0, _span.KIND_SERVER,
                                   "B", "V")
            spans.append(cntl.span)
            bm(cntl, f"req{i}", lambda resp=None: done.append(resp))
        deadline = time.monotonic() + 3
        while len(done) < 2 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert len(done) == 2
        for sp in spans:
            assert "batch_wait_us" in sp.phases
            assert sp.phases["execute_us"] >= 15_000  # the 20ms vec call
            ev = next(f for _, n, f in sp.events if n == "batch")
            assert ev["size"] == 2 and "pad" in ev and "bucket" in ev


# ------------------------------------------------------------- http surface
class TestRpczHttp:
    def _traffic(self, server):
        ch = Channel().init(addr(server))
        stub = Stub(ch, ECHO)
        stub.Echo(echo_pb2.EchoRequest(message="ok"))
        cntl = Controller()
        with pytest.raises(RpcError):
            stub.Echo(echo_pb2.EchoRequest(message="boom"),
                      controller=cntl)
        _wait_spans(lambda ss: any(s.error_code for s in ss
                                   if s.kind == "server"))

    def test_filters(self, tcp_server, traced):
        self._traffic(tcp_server)
        a = addr(tcp_server)
        assert b"EchoService.Echo" in http_fetch(a, "GET", "/rpcz").body
        assert b"EchoService.Echo" in http_fetch(
            a, "GET", "/rpcz?method=EchoService").body
        body = http_fetch(a, "GET", "/rpcz?method=NoSuchService").body
        assert b"EchoService.Echo" not in body
        body = http_fetch(a, "GET", "/rpcz?min_latency_us=999999999").body
        assert b"EchoService.Echo" not in body
        doc = json.loads(http_fetch(
            a, "GET", "/rpcz?error_only=1&format=json").body)
        assert doc["spans"] and all(s["error_code"] for s in doc["spans"])
        resp = http_fetch(a, "GET", "/rpcz?count=notanumber")
        assert resp.status == 400

    def test_json_export_and_trace_fetch(self, tcp_server, traced):
        self._traffic(tcp_server)
        a = addr(tcp_server)
        doc = json.loads(http_fetch(a, "GET", "/rpcz?format=json").body)
        span = next(s for s in doc["spans"]
                    if s["method"] == "Echo" and not s["error_code"])
        assert "phases" in span and "events" in span
        trace = json.loads(http_fetch(
            a, "GET", f"/rpcz/{span['trace_id']}?format=json").body)
        assert trace["trace_id"] == span["trace_id"]
        assert any(s["span_id"] == span["span_id"]
                   for s in trace["spans"])

    def test_tpu_builtin(self, tpu_server, traced):
        from brpc_tpu.builtin import services

        stub = _stub_for(tpu_server)
        stub.Echo(echo_pb2.EchoRequest(message="x",
                                       payload=b"\x01" * (1 << 20)))

        class _Http:
            path = "/tpu"
            query = {"format": "json"}

            def header(self, k, default=""):
                return default

        status, ctype, body = services.tpu_service(tpu_server, _Http())
        assert status == 200
        state = json.loads(body)
        assert state["client_endpoints"], "tunnel client endpoint missing"
        cl = state["client_endpoints"][0]
        assert cl["window_total"] > 0 and "credit_stalls" in cl
        assert state["server_endpoints"], "server endpoint missing"
        assert state["borrowed_peak_blocks"] >= 0
        _Http.query = {}
        status, ctype, body = services.tpu_service(tpu_server, _Http())
        assert status == 200 and "window:" in body

    def test_status_percentiles_and_method_vars(self, tcp_server, traced):
        from brpc_tpu.metrics import dump_exposed

        ch = Channel().init(addr(tcp_server))
        Stub(ch, ECHO).Echo(echo_pb2.EchoRequest(message="p"))
        body = http_fetch(addr(tcp_server), "GET", "/status").body
        assert b"p50=" in body and b"p90=" in body and b"p99=" in body
        # first dispatch auto-exposed the per-method recorder on /vars
        snap = dump_exposed()
        assert "rpc_method_echoservice_echo_latency_p50" in snap
        assert "rpc_method_echoservice_echo_count" in snap

    def test_prometheus_counter_type_lines(self):
        from brpc_tpu.fault import core as _fault_core
        from brpc_tpu.metrics import prometheus_text

        # re-expose (overwrites in the registry — robust against another
        # test file's clear_registry()): the TYPE line must say counter,
        # carried by the prometheus_type attribute through expose_as
        _fault_core.g_fault_hits.expose_as("g_fault_hits")
        txt = prometheus_text()
        assert "# TYPE g_fault_hits counter" in txt


# ------------------------------------------------------------- trace_view
class TestTraceView:
    def test_waterfall_renders_phases_and_events(self, traced):
        root = _span.Span(0x77, 0x77, 0, _span.KIND_CLIENT,
                          "EchoService", "Echo")
        root.add_phase("send_us", 600.0)
        root.add_phase("credit_wait_us", 200.0)
        root.event("credit_stall", wait_us=200.0, need=4, got=0)
        child = _span.Span(0x77, 0x78, 0x77, _span.KIND_SERVER,
                           "EchoService", "Echo")
        child.add_phase("execute_us", 100.0)
        time.sleep(0.002)
        child.end(0)
        root.end(0)
        from tools import trace_view

        out = io.StringIO()
        trace_view.render(_span.trace_to_dict(0x77), out=out)
        text = out.getvalue()
        assert "EchoService.Echo" in text
        assert "phase legend" in text
        assert "[credit_stall]" in text
        assert "client" in text and "server" in text


# ------------------------------------------------------- probabilistic fault
class TestProbabilisticFault:
    def test_p_draw_rides_collector_budget(self, traced):
        from brpc_tpu.fault.core import g_fault_p_skipped

        _flags.set_flag("fault_injection_enabled", True)
        try:
            fault.arm("x.prob", mode="always", p=0.5)
            fired = sum(1 for _ in range(300)
                        if fault.hit("x.prob") is not None)
            # binomial(300, .5): a miss of this bound is ~1e-9
            assert 75 <= fired <= 225
            assert g_fault_p_skipped.get_value() > 0
        finally:
            fault.disarm_all()
            _flags.set_flag("fault_injection_enabled", False)

    def test_p_validated(self):
        with pytest.raises(ValueError):
            fault.arm("x.badp", p=0.0)
        with pytest.raises(ValueError):
            fault.arm("x.badp", p=1.5)


# ------------------------------------------------------------- sampling off
class TestSamplingOff:
    def test_hot_path_is_span_free(self, tcp_server):
        _flags.set_flag("rpcz_sample_ratio", "0.0")
        try:
            _span.reset_for_test()
            ch = Channel().init(addr(tcp_server))
            stub = Stub(ch, ECHO)
            cntl = Controller()
            stub.Echo(echo_pb2.EchoRequest(message="dark"),
                      controller=cntl)
            assert cntl.span is None
            time.sleep(0.1)
            assert _span.recent_spans(10) == []
        finally:
            _flags.set_flag("rpcz_sample_ratio", "1.0")
