"""Disaggregated prefill/decode: KV block-chain migration over the
tpu:// record lane (brpc_tpu/serving/migration.py).

Four layers, cheapest first:

* the ledger's migration surface — quiesce/export/release-on-ACK on the
  source, adopt-from-staging on the destination, the export gate that
  refuses un-quiesced chains, and write-clears-quiesce semantics;
* the wire protocol — manifest validation (geometry, block_bytes,
  capacity), staging ownership for the whole transfer, and the
  commit-as-ACK contract;
* the disaggregated serving plane end to end — a prefill-role engine
  hands every just-prefilled chain to a decode-role engine over a real
  loopback server, the two-stage ShardedLlmChannel dispatch stitches the
  replies, and the migrated generation is BIT-IDENTICAL to a co-located
  run on the committed corpus schedule (zero re-prefilled tokens, both
  armed pools idle at teardown);
* chaos — serving.migrate.drop kills the destination tunnel
  mid-transfer (source retains the chain and decodes locally, zero
  leaked blocks on either pool), and shard death drains live sequences
  onto a survivor where the client's retry resumes without re-prefill.
"""

import threading
import time
import types

import numpy as np
import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.proto import serving_pb2
from brpc_tpu.rpc import ChannelOptions, Server, errors
from brpc_tpu.serving import (EngineConfig, KVCacheConfig, ModelConfig,
                              PagedKVCache, ServingEngine,
                              ShardedLlmChannel, TinyTransformer)
from brpc_tpu.serving.migration import (KVMigrator, MigrationReceiver,
                                        chain_block_bytes,
                                        g_serving_migrate_failed,
                                        g_serving_migrate_seqs,
                                        read_chain_blocks,
                                        write_chain_blocks)
from brpc_tpu.serving.service import LlmServingService

# the committed replay corpus's schedule (synth prompts, greedy argmax
# decode -> bit-replayable token streams)
from tools.record_serving_corpus import SCHEDULE

CFG = dict(vocab=256, d_model=32, n_heads=2, n_layers=2)


def _kv(num_blocks=128, block_size=16, layers=2, kv_dim=16):
    kv = PagedKVCache(KVCacheConfig(block_size=block_size,
                                    num_blocks=num_blocks),
                      layers, kv_dim)
    kv._check = True  # armed ledger: audit every mutation
    return kv


def _build_engine(role="both", num_blocks=128):
    cfg = ModelConfig(**CFG)
    kv = PagedKVCache(KVCacheConfig(block_size=16, num_blocks=num_blocks),
                      cfg.n_layers, cfg.kv_dim)
    kv._check = True
    model = TinyTransformer(cfg, kv)
    engine = ServingEngine(model, kv, EngineConfig(
        max_batch=8, token_budget=512, idle_wait_s=0.002, role=role),
        prefix_cache=False).start()
    return engine, kv, model


def _teardown(engine, kv, model):
    engine.stop()
    kv.assert_idle()
    model.close()


def _submit(engine, prompt, max_new, resume=0, cntl=None):
    ev = threading.Event()
    box = {}
    code, seq = engine.submit(
        prompt, max_new, cntl=cntl,
        done=lambda r, box=box, ev=ev: (box.update(r=r), ev.set()),
        resume_seq_id=resume)
    return code, seq, ev, box


@pytest.fixture
def fault_enabled():
    _flags.set_flag("fault_injection_enabled", True)
    yield
    fault.disarm_all()
    _flags.set_flag("fault_injection_enabled", False)


# ------------------------------------------------- ledger migration surface
class TestLedgerMigrationSurface:
    def test_quiesce_export_release_roundtrip(self):
        kv = _kv()
        t = kv.alloc_sequence(1, 40)  # 3 blocks
        assert kv.quiesce_sequence(1) == 40
        table, ntokens = kv.export_chain(1)
        assert list(table) == list(t) and ntokens == 40
        assert kv.release_exported(1) == 3
        kv.assert_idle("after release_exported")

    def test_export_without_quiesce_refused(self):
        kv = _kv()
        kv.alloc_sequence(1, 16)
        with pytest.raises(AssertionError, match="without quiesce"):
            kv.export_chain(1)
        kv.free_sequence(1)
        kv.assert_idle()

    def test_write_clears_the_quiesce_mark(self):
        # any ledger write between quiesce and export re-arms the gate:
        # the exported table must be the table the destination adopts
        kv = _kv()
        kv.alloc_sequence(1, 16)
        kv.quiesce_sequence(1)
        kv.extend_sequence(1, 17)
        with pytest.raises(AssertionError, match="without quiesce"):
            kv.export_chain(1)
        kv.unquiesce_sequence(1)
        kv.free_sequence(1)
        kv.assert_idle()

    def test_unquiesce_restores_local_fallback(self):
        kv = _kv()
        kv.alloc_sequence(1, 16)
        kv.quiesce_sequence(1)
        kv.unquiesce_sequence(1)
        with pytest.raises(AssertionError):
            kv.export_chain(1)  # gate re-armed: not exportable
        kv.extend_sequence(1, 32)  # and the chain still grows locally
        kv.free_sequence(1)
        kv.assert_idle()

    def test_staging_adopt_handoff_keeps_single_ownership(self):
        """The receiver-side choreography: staging id owns the blocks
        through the transfer, adoption bumps to 2, freeing the staging
        id leaves the destination sequence as the sole owner."""
        kv = _kv()
        staging = -(1 + 1)
        t = kv.alloc_sequence(staging, 40)
        for b in t:
            assert kv.block_ref(b) == 1
        kv.adopt_sequence(7, t, 40)
        for b in t:
            assert kv.block_ref(b) == 2
        kv.free_sequence(staging)
        for b in t:
            assert kv.block_ref(b) == 1
        assert list(kv.block_table(7)) == list(t)
        kv.extend_sequence(7, 41)  # adopted chain decodes normally
        kv.free_sequence(7)
        kv.assert_idle("after staging handoff")

    def test_chain_bytes_roundtrip_through_pools(self):
        """read_chain_blocks ∘ write_chain_blocks is the identity on the
        chain's slots: what the source serializes is exactly what the
        destination's pools hold after the fused scatter."""
        src = _kv()
        dst = _kv()
        t = src.alloc_sequence(1, 40)
        bb = chain_block_bytes(src)
        assert bb == chain_block_bytes(dst)
        # write a recognizable pattern through the source pools
        k = np.asarray(src.k_pool).copy()
        v = np.asarray(src.v_pool).copy()
        for i, b in enumerate(t):
            sl = slice(b * src.block_size, (b + 1) * src.block_size)
            k[:, sl, :] = float(i + 1)
            v[:, sl, :] = -float(i + 1)
        import jax.numpy as jnp

        src.update_pools(jnp.asarray(k), jnp.asarray(v))
        payloads = read_chain_blocks(src, t, bb)
        assert len(payloads) == 3 and all(len(p) == bb for p in payloads)
        st = dst.alloc_sequence(-2, 40)
        write_chain_blocks(dst, st, payloads, 40)
        got_k = np.asarray(dst.k_pool)
        got_v = np.asarray(dst.v_pool)
        for i, b in enumerate(st):
            sl = slice(b * dst.block_size, (b + 1) * dst.block_size)
            assert np.all(got_k[:, sl, :] == float(i + 1))
            assert np.all(got_v[:, sl, :] == -float(i + 1))
        src.free_sequence(1)
        dst.free_sequence(-2)
        src.assert_idle()
        dst.assert_idle()


# --------------------------------------------------------- wire validation
class TestManifestValidation:
    def _receiver_reject(self, engine, **overrides):
        rx = MigrationReceiver(engine)
        kv = engine.kv
        fields = dict(seq_id=5, prompt_tokens=[1, 2, 3], out_tokens=[4],
                      max_new_tokens=8, stop_token=0, ntokens=4,
                      n_blocks=1, block_size=kv.block_size,
                      layers=kv.layers, kv_dim=kv.kv_dim,
                      block_bytes=chain_block_bytes(kv), recovery=False)
        fields.update(overrides)
        req = serving_pb2.MigrateRequest(**fields)
        # a controller with no stream settings at all
        cntl = types.SimpleNamespace(_srv_meta=None)
        return rx.open(cntl, req)

    def test_open_without_stream_rejected(self):
        engine, kv, model = _build_engine()
        try:
            ack = self._receiver_reject(engine)
            assert not ack.accepted and "stream" in ack.message
        finally:
            _teardown(engine, kv, model)

    def test_geometry_and_capacity_mismatches_rejected(self):
        engine, kv, model = _build_engine()
        meta = types.SimpleNamespace(
            stream_settings=types.SimpleNamespace(stream_id=1))

        def open_with(**overrides):
            rx = MigrationReceiver(engine)
            fields = dict(seq_id=5, prompt_tokens=[1, 2, 3],
                          out_tokens=[4], max_new_tokens=8, stop_token=0,
                          ntokens=4, n_blocks=1,
                          block_size=kv.block_size, layers=kv.layers,
                          kv_dim=kv.kv_dim,
                          block_bytes=chain_block_bytes(kv),
                          recovery=False)
            fields.update(overrides)
            cntl = types.SimpleNamespace(_srv_meta=meta)
            return rx.open(cntl, serving_pb2.MigrateRequest(**fields))

        try:
            ack = open_with(block_size=8)
            assert not ack.accepted and "geometry" in ack.message
            ack = open_with(kv_dim=kv.kv_dim * 2)
            assert not ack.accepted and "geometry" in ack.message
            ack = open_with(block_bytes=1)
            assert not ack.accepted and "block_bytes" in ack.message
            # 1 block cannot carry 40 tokens at block_size 16
            ack = open_with(ntokens=40)
            assert not ack.accepted and "cannot carry" in ack.message
            kv.assert_idle("rejects must not leak staging chains")
        finally:
            _teardown(engine, kv, model)

    def test_commit_unknown_sequence_rejected(self):
        engine, kv, model = _build_engine()
        try:
            rx = MigrationReceiver(engine)
            ack = rx.commit(None,
                            serving_pb2.MigrateCommitRequest(seq_id=99))
            assert not ack.accepted and "no open migration" in ack.message
        finally:
            _teardown(engine, kv, model)


# ---------------------------------------------------- disaggregated plane
@pytest.fixture
def disagg_pair():
    """prefill-role engine + decode-role engine behind a real loopback
    LlmService, wired with a KVMigrator — the minimal disaggregated
    deployment."""
    dec, dec_kv, dec_model = _build_engine(role="decode")
    srv = Server().add_service(
        LlmServingService(dec)).start("127.0.0.1:0")
    pre, pre_kv, pre_model = _build_engine(role="prefill")
    pre.set_migrator(KVMigrator(f"{srv.listen_endpoint()}"))
    yield pre, dec, srv
    pre.stop()
    srv.stop()
    srv.join(timeout=2)
    dec.stop()
    # the acceptance gate: zero leaked blocks on BOTH armed pools
    pre_kv.assert_idle("prefill pool after disaggregated run")
    dec_kv.assert_idle("decode pool after disaggregated run")
    pre_model.close()
    dec_model.close()


class TestDisaggregatedServing:
    def test_corpus_schedule_bit_identical_to_colocated(self, disagg_pair):
        """The correctness oracle: every sequence of the committed corpus
        schedule, prefill on one engine + migrate + decode on the other,
        produces EXACTLY the co-located engine's greedy tokens — and the
        decode engine never prefills a single token."""
        pre, dec, _srv = disagg_pair
        ref_engine, ref_kv, ref_model = _build_engine()
        try:
            ref = []
            for plen, max_new in SCHEDULE:
                code, seq, ev, _ = _submit(
                    ref_engine, ref_model.synth_prompt(plen), max_new)
                assert code == 0
                assert ev.wait(300), "reference run stalled"
                ref.append(list(seq.out_tokens))
        finally:
            _teardown(ref_engine, ref_kv, ref_model)

        assert dec.prefill_tokens == 0
        got = []
        for plen, max_new in SCHEDULE:
            code, _seq, ev, box = _submit(
                pre, pre.model.synth_prompt(plen), max_new)
            assert code == 0
            assert ev.wait(300), "prefill stage stalled"
            h = box["r"]
            assert h.finish_reason == "handoff"
            assert h.handoff_shard == pre.migrator.dest_shard
            assert len(h.tokens) >= 1  # prefill emitted the first token
            code, _seq2, ev2, box2 = _submit(
                dec, np.zeros(0, dtype=np.int32), 0, resume=h.seq_id)
            assert code == 0
            assert ev2.wait(300), "decode stage stalled"
            a = box2["r"]
            got.append(list(h.tokens) + list(a.tokens))
        assert got == ref
        # zero re-prefilled tokens: the decode engine only ever decoded
        assert dec.prefill_tokens == 0
        assert pre.migrator.seqs == len(SCHEDULE)
        assert pre.migrator.failed == 0

    def test_resume_attach_is_single_use(self, disagg_pair):
        pre, dec, _srv = disagg_pair
        code, _s, ev, box = _submit(pre, pre.model.synth_prompt(16), 4)
        assert code == 0 and ev.wait(300)
        h = box["r"]
        code, _s2, ev2, _b2 = _submit(
            dec, np.zeros(0, dtype=np.int32), 0, resume=h.seq_id)
        assert code == 0 and ev2.wait(300)
        # the sequence finished and detached: a second attach is EREQUEST
        code, _s3, _ev3, _b3 = _submit(
            dec, np.zeros(0, dtype=np.int32), 0, resume=h.seq_id)
        assert code == errors.EREQUEST

    def test_unknown_resume_id_is_erequest(self, disagg_pair):
        _pre, dec, _srv = disagg_pair
        code, _s, _ev, _b = _submit(
            dec, np.zeros(0, dtype=np.int32), 0, resume=424242)
        assert code == errors.EREQUEST

    def test_migrate_metrics_and_snapshot(self, disagg_pair):
        pre, dec, _srv = disagg_pair
        seqs0 = g_serving_migrate_seqs.get_value()
        code, _s, ev, box = _submit(pre, pre.model.synth_prompt(16), 4)
        assert code == 0 and ev.wait(300)
        h = box["r"]
        code, _s2, ev2, _b2 = _submit(
            dec, np.zeros(0, dtype=np.int32), 0, resume=h.seq_id)
        assert code == 0 and ev2.wait(300)
        assert g_serving_migrate_seqs.get_value() == seqs0 + 1
        out = pre.snapshot()["migration"]
        assert out["parked"] == 0
        assert out["out"]["seqs"] >= 1 and out["out"]["bytes"] > 0
        assert out["out"]["gbps"] > 0
        inn = dec.snapshot()["migration"]
        assert inn["in"]["seqs_in"] >= 1
        assert inn["in"]["pending_in"] == 0


class TestTwoStageRouter:
    def test_two_stage_dispatch_stitches_the_generation(self):
        """Client-side contract: a ShardedLlmChannel over [prefill shard
        0, decode shard 1] with prefill_partitions=[0] issues stage 1 to
        the prefill shard, follows the handoff to shard 1, and returns
        ONE stitched response equal to the co-located generation."""
        ref_engine, ref_kv, ref_model = _build_engine()
        try:
            code, seq, ev, _ = _submit(ref_engine,
                                       ref_model.synth_prompt(24), 6)
            assert code == 0 and ev.wait(300)
            ref_toks = list(seq.out_tokens)
        finally:
            _teardown(ref_engine, ref_kv, ref_model)

        pre, pre_kv, pre_model = _build_engine(role="prefill")
        dec, dec_kv, dec_model = _build_engine(role="decode")
        srv0 = Server().add_service(
            LlmServingService(pre)).start("127.0.0.1:0")
        srv1 = Server().add_service(
            LlmServingService(dec)).start("127.0.0.1:0")
        pre.set_migrator(
            KVMigrator(f"{srv1.listen_endpoint()}", dest_shard=1))
        try:
            url = (f"list://{srv0.listen_endpoint()} 0/2,"
                   f"{srv1.listen_endpoint()} 1/2")
            ch = ShardedLlmChannel(
                url, 2,
                options=ChannelOptions(protocol="trpc_std",
                                       timeout_ms=60000),
                prefill_partitions=[0])
            req = serving_pb2.GenerateRequest(prompt_len=24,
                                              max_new_tokens=6)
            assert ch.shard_of(req) == 0  # fresh prompts -> prefill shard
            resp = ch.generate(req)
            assert list(resp.tokens) == ref_toks
            assert resp.prompt_len == 24
            assert resp.steps == len(ref_toks)
            assert resp.finish_reason != "handoff"  # fully stitched
            # resume requests route by the handoff meta, not the hash
            follow = serving_pb2.GenerateRequest(resume_seq_id=7,
                                                 resume_shard=1)
            assert ch.shard_of(follow) == 1
        finally:
            srv0.stop()
            srv0.join(timeout=2)
            srv1.stop()
            srv1.join(timeout=2)
            pre.stop()
            dec.stop()
            pre_kv.assert_idle("prefill pool after two-stage dispatch")
            dec_kv.assert_idle("decode pool after two-stage dispatch")
            pre_model.close()
            dec_model.close()


# ------------------------------------------------------------------ chaos
@pytest.mark.chaos
class TestMigrationChaos:
    def test_drop_fault_falls_back_to_local_decode(self, fault_enabled,
                                                   disagg_pair):
        """serving.migrate.drop kills the destination tunnel on every
        transfer: the source must retain the chain and decode the
        sequence LOCALLY to the same greedy tokens — no stranded
        ownership, zero leaked blocks on either armed pool (the fixture
        teardown proves it)."""
        pre, dec, _srv = disagg_pair
        ref_engine, ref_kv, ref_model = _build_engine()
        try:
            code, seq, ev, _ = _submit(ref_engine,
                                       ref_model.synth_prompt(16), 6)
            assert code == 0 and ev.wait(300)
            ref_toks = list(seq.out_tokens)
        finally:
            _teardown(ref_engine, ref_kv, ref_model)

        failed0 = g_serving_migrate_failed.get_value()
        fault.arm("serving.migrate.drop", mode="always")
        try:
            code, _s, ev, box = _submit(pre, pre.model.synth_prompt(16), 6)
            assert code == 0
            assert ev.wait(300), "local-fallback decode stalled"
        finally:
            fault.disarm_all()
        r = box["r"]
        # NOT a handoff: the prefill engine finished the whole generation
        assert r.finish_reason == "length"
        assert list(r.tokens) == ref_toks
        assert pre.migrator.failed >= 1
        assert g_serving_migrate_failed.get_value() > failed0
        # the decode engine adopted nothing
        assert dec.snapshot()["migration"]["in"]["seqs_in"] == 0
        assert dec.snapshot()["migration"]["in"]["pending_in"] == 0

    def test_stall_fault_delays_but_completes(self, fault_enabled,
                                              disagg_pair):
        pre, dec, _srv = disagg_pair
        fault.arm("serving.migrate.stall", mode="oneshot", delay_ms=50)
        try:
            t0 = time.monotonic()
            code, _s, ev, box = _submit(pre, pre.model.synth_prompt(16), 4)
            assert code == 0 and ev.wait(300)
            h = box["r"]
            assert h.finish_reason == "handoff"
            assert time.monotonic() - t0 >= 0.05
        finally:
            fault.disarm_all()
        code, _s2, ev2, _b2 = _submit(
            dec, np.zeros(0, dtype=np.int32), 0, resume=h.seq_id)
        assert code == 0 and ev2.wait(300)

    def test_shard_death_drains_onto_survivor_without_reprefill(self):
        """Kill a shard mid-generation: stop() drains its live chains to
        the survivor (recovery migration), the client's retry of the SAME
        request attaches to the migrated sequence by prompt match, and
        the full generation comes back bit-identical to an uninterrupted
        run — with the survivor having prefilled ZERO tokens."""
        ref_engine, ref_kv, ref_model = _build_engine()
        try:
            code, seq, ev, _ = _submit(ref_engine,
                                       ref_model.synth_prompt(24), 32)
            assert code == 0 and ev.wait(300)
            ref_toks = list(seq.out_tokens)
        finally:
            _teardown(ref_engine, ref_kv, ref_model)

        dying, dying_kv, dying_model = _build_engine()
        surv, surv_kv, surv_model = _build_engine()
        srv = Server().add_service(
            LlmServingService(surv)).start("127.0.0.1:0")
        dying.set_migrator(KVMigrator(f"{srv.listen_endpoint()}"))
        try:
            cntl = types.SimpleNamespace(
                failed_code=0,
                set_failed=lambda c, m, _s=None: None)
            box = {}
            ev = threading.Event()

            def set_failed(code, msg):
                cntl.failed_code = code
                cntl.failed_msg = msg

            cntl.set_failed = set_failed
            code, seq = dying.submit(
                dying_model.synth_prompt(24), 32, cntl=cntl,
                done=lambda r, box=box, ev=ev: (box.update(r=r),
                                                ev.set()))
            assert code == 0
            # let it decode a few tokens, then kill the shard
            deadline = time.monotonic() + 60
            while len(seq.out_tokens) < 4:
                assert time.monotonic() < deadline, "decode never started"
                time.sleep(0.005)
            dying.stop()
            assert ev.wait(60), "doomed RPC never completed"
            # the client saw a RETRIABLE failure naming the drain
            assert box["r"] is None
            assert cntl.failed_code == errors.EFAILEDSOCKET
            assert "migrated to survivor" in cntl.failed_msg
            assert dying.migrator.seqs == 1
            # the retry: same prompt/max_new on the survivor attaches to
            # the live migrated sequence — full token list, no prefill
            pf0 = surv.prefill_tokens
            code, _s2, ev2, box2 = _submit(
                surv, surv_model.synth_prompt(24), 32)
            assert code == 0
            assert ev2.wait(300), "recovered generation stalled"
            r = box2["r"]
            assert list(r.tokens) == ref_toks
            assert surv.prefill_tokens == pf0  # zero re-prefilled tokens
        finally:
            srv.stop()
            srv.join(timeout=2)
            surv.stop()
            dying_kv.assert_idle("dying pool after drain")
            surv_kv.assert_idle("survivor pool after recovery")
            dying_model.close()
            surv_model.close()


# ------------------------------------------------------------ observability
class TestMigrationObservability:
    def test_backlog_watch_rule_installed_and_reloadable(self):
        from brpc_tpu.metrics.watch import global_watch, install_default_rules

        install_default_rules()
        rules = {r.name: r for r in global_watch().rules()}
        assert "serving_migrate_backlog" in rules
        rule = rules["serving_migrate_backlog"]
        assert rule.var == "g_serving_migrate_inflight"
        assert rule.kind == "threshold"
        assert rule.bound() == float(_flags.get("serving_migrate_backlog_max"))
        old = _flags.get("serving_migrate_backlog_max")
        try:
            _flags.set_flag("serving_migrate_backlog_max", "2")
            assert rule.bound() == 2.0  # reloadable, no restart
        finally:
            _flags.set_flag("serving_migrate_backlog_max", str(old))

    def test_serving_builtin_reports_migration(self, disagg_pair):
        import json as _json

        from brpc_tpu.builtin.services import serving_service

        pre, dec, _srv = disagg_pair
        code, _s, ev, box = _submit(pre, pre.model.synth_prompt(16), 4)
        assert code == 0 and ev.wait(300)
        h = box["r"]
        code, _s2, ev2, _b2 = _submit(
            dec, np.zeros(0, dtype=np.int32), 0, resume=h.seq_id)
        assert code == 0 and ev2.wait(300)

        http = types.SimpleNamespace(query={}, path="/serving")
        _st, _ct, body = serving_service(None, http)
        mig_lines = [l for l in body.splitlines()
                     if l.strip().startswith("migrate:")]
        assert mig_lines, body
        joined = "\n".join(mig_lines)
        assert "role=prefill" in joined and "role=decode" in joined
        assert "out ->" in joined and "in seqs" in joined

        http = types.SimpleNamespace(query={"format": "json"},
                                     path="/serving")
        _st, ct, body = serving_service(None, http)
        assert "json" in ct
        snaps = _json.loads(body)["engines"]
        migs = [s["migration"] for s in snaps if s.get("migration")]
        assert any(m.get("out", {}).get("seqs", 0) >= 1 for m in migs)
        assert any(m.get("in", {}).get("seqs_in", 0) >= 1 for m in migs)

    def test_migration_vars_exposed(self):
        from brpc_tpu.metrics.variable import get_exposed
        from brpc_tpu.serving import migration as _mig

        # earlier test files may clear_registry(); re-expose the
        # import-time vars so the /vars contract stays checkable
        for name in ("g_serving_migrate_seqs", "g_serving_migrate_blocks",
                     "g_serving_migrate_bytes", "g_serving_migrate_failed",
                     "g_serving_migrate_inflight"):
            if get_exposed(name) is None:
                var = getattr(_mig, name)
                (var.expose_as if hasattr(var, "expose_as")
                 else var.expose)(name)
            assert get_exposed(name) is not None, name
