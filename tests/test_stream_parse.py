"""Streaming parse pipeline tests — mid-message credit return.

The tentpole contract: once a protocol cracks a header it registers a
pending-body cursor on the socket, the cut loop feeds arriving bytes into
it without re-running parse, and on the tpu:// tunnel each borrowed block's
FT_ACK credit returns as soon as ITS bytes are claimed — mid-message. These
tests pin that behavior at three levels: the cursor/cut-loop unit level,
the endpoint level (generic and native cut paths), and end-to-end over a
loopback tunnel where a message LARGER than the whole credit window must
flow borrowed-only — impossible unless credits return mid-message.
"""

import time

import pytest

from brpc_tpu import flags as _flags
from brpc_tpu.butil.iobuf import IOBuf, supports_block_ownership
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    Service,
    Stub,
)

from test_tpu_transport import (
    _acked_indices,
    _data_frame_body,
    _make_endpoint,
    _trpc_response_packet,
)

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoServiceImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def tpu_server():
    server = Server(ServerOptions())
    server.add_service(EchoServiceImpl())
    server.start("tpu://127.0.0.1:0/0")
    yield server
    server.stop()
    server.join()


def _stub_for(server, timeout_ms=30000):
    channel = Channel(ChannelOptions(protocol="trpc_std",
                                     timeout_ms=timeout_ms))
    channel.init(str(server.listen_endpoint()))
    return Stub(channel, ECHO)


@pytest.fixture()
def small_stream_min():
    """Lower the streaming threshold so unit tests can use small bodies."""
    old = _flags.get("stream_body_min_bytes")
    _flags.set_flag("stream_body_min_bytes", "4096")
    yield 4096
    _flags.set_flag("stream_body_min_bytes", str(old))


# ---------------------------------------------------------------------------
# cursor unit level
# ---------------------------------------------------------------------------
class _FakeParseSock:
    """Just enough socket surface for InputMessenger.cut_messages."""

    def __init__(self):
        self.read_buf = IOBuf()
        self.preferred_protocol = None
        self.pending_body = None
        self.failed = False
        self.in_messages = 0
        self.owner_server = None
        self.user_data = None

    def remove_pending_id(self, cid):
        return False

    def set_failed(self, code, reason=""):
        self.failed = True
        self.pending_body = None


class TestCursorUnit:
    def test_cutn_into_buffer_copies_and_pops(self):
        buf = IOBuf()
        buf.append(b"abcdef")
        buf.append(b"ghij")
        dest = bytearray(7)
        assert buf.cutn_into_buffer(7, memoryview(dest)) == 7
        assert bytes(dest) == b"abcdefg"
        assert buf.tobytes() == b"hij"

    def test_cutn_into_buffer_fires_release_hooks(self):
        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        released = []
        src = bytearray(b"x" * 100)
        buf = IOBuf()
        buf.append_user_data(memoryview(src),
                            release=lambda: released.append(1))
        dest = bytearray(100)
        buf.cutn_into_buffer(40, memoryview(dest)[:40])
        assert released == []          # 60 bytes of the block still queued
        buf.cutn_into_buffer(60, memoryview(dest)[40:])
        assert released == [1]         # last ref died AT claim time
        assert bytes(dest) == b"x" * 100

    def test_cursor_survives_not_enough_rounds(self, small_stream_min):
        """A trpc_std body drip-fed through many PARSE_NOT_ENOUGH_DATA-sized
        pieces keeps ONE cursor alive across rounds, never re-parses the
        header, and completes into a normally-dispatched message."""
        from brpc_tpu.policy import ensure_registered
        from brpc_tpu.rpc.input_messenger import InputMessenger

        ensure_registered()
        pkt = _trpc_response_packet(b"\x5c" * 16384)
        sock = _FakeParseSock()
        messenger = InputMessenger()
        cursor_seen = set()
        remaining_trace = []
        step = 7
        for off in range(0, len(pkt), step):
            sock.read_buf.append(pkt[off:off + step])
            messenger.cut_messages(sock)
            if sock.pending_body is not None:
                cursor_seen.add(id(sock.pending_body))
                remaining_trace.append(sock.pending_body.remaining)
        assert not sock.failed, (sock.failed,)
        assert len(cursor_seen) == 1          # one cursor, surviving rounds
        assert remaining_trace == sorted(remaining_trace, reverse=True)
        assert sock.pending_body is None      # completed and dispatched
        assert sock.in_messages == 1
        assert len(sock.read_buf) == 0

    def test_small_bodies_never_register_a_cursor(self):
        from brpc_tpu.policy import ensure_registered
        from brpc_tpu.rpc.input_messenger import InputMessenger

        ensure_registered()
        pkt = _trpc_response_packet(b"s" * 512)  # far below the threshold
        sock = _FakeParseSock()
        messenger = InputMessenger()
        sock.read_buf.append(pkt[:40])
        messenger.cut_messages(sock)
        assert sock.pending_body is None
        sock.read_buf.append(pkt[40:])
        messenger.cut_messages(sock)
        assert sock.in_messages == 1

    def test_tpuc_frame_streams_through_cursor(self, small_stream_min):
        """TPUC DATA frames (DCN inline fallback) stage large bodies through
        a ref-moving cursor instead of re-probing a growing read_buf."""
        import struct

        from brpc_tpu.tpu import transport as tr

        proto = tr.TpuCtrlProtocol()
        body = b"\xa5" * 8192
        frame = struct.pack(tr.CTRL_HDR, tr.CTRL_MAGIC, tr.FT_DATA,
                            len(body)) + body
        sock = _FakeParseSock()
        buf = sock.read_buf
        buf.append(frame[:2000])
        rc, msg = proto.parse(buf, sock)
        assert rc == 1 and msg is None        # PARSE_NOT_ENOUGH_DATA
        cursor = sock.pending_body
        assert cursor is not None and cursor.total == len(body)
        assert len(buf) == 0                  # arrived bytes already claimed
        buf.append(frame[2000:])
        cursor.feed(buf)
        assert cursor.done
        done = cursor.finish()
        assert done.meta == tr.FT_DATA
        assert done.body.tobytes() == body

    def test_http_content_length_body_streams(self, small_stream_min):
        from brpc_tpu.policy.http_protocol import HttpProtocol

        body = b"Z" * 10000
        raw = (b"POST /svc/m HTTP/1.1\r\nHost: h\r\n"
               b"Content-Length: 10000\r\n\r\n") + body
        proto = HttpProtocol()
        sock = _FakeParseSock()
        sock.read_buf.append(raw[:100])
        rc, msg = proto.parse(sock.read_buf, sock)
        assert rc == 1 and sock.pending_body is not None
        sock.read_buf.append(raw[100:])
        cursor = sock.pending_body
        cursor.feed(sock.read_buf)
        assert cursor.done
        parsed = cursor.finish()
        assert parsed.meta.body == body
        assert parsed.body.tobytes() == body

    def test_http_fetch_path_keeps_whole_message_semantics(self):
        # standalone parse (no sock/proto) must never register a cursor
        from brpc_tpu.policy.http_protocol import parse_http_message

        raw = (b"HTTP/1.1 200 OK\r\nContent-Length: 10\r\n\r\n" + b"x" * 4)
        buf = IOBuf(raw)
        rc, msg = parse_http_message(buf)
        assert rc == 1 and msg is None
        assert len(buf) == len(raw)           # nothing consumed


# ---------------------------------------------------------------------------
# endpoint level: the mid-message ACK itself
# ---------------------------------------------------------------------------
class TestMidMessageCreditReturn:
    def _stream_packet_through(self, tr, fake, ep, pkt):
        """Write pkt across pool blocks and deliver one DATA frame per
        block, returning the list of (acked_so_far, message_done) after
        each frame."""
        pool = ep.recv_pool
        bs = pool.block_size
        trace = []
        nblocks = -(-len(pkt) // bs)
        for b in range(nblocks):
            chunk = pkt[b * bs:(b + 1) * bs]
            pool._shm.buf[b * bs:b * bs + len(chunk)] = chunk
            ep.on_data(IOBuf(_data_frame_body([(b, len(chunk))])))
            acked = [i for fr in _acked_indices(fake) for i in fr]
            trace.append((list(acked),
                          ep.vsock.pending_body is None))
        return trace

    def test_ack_returns_before_message_completes_generic_path(self):
        """THE tentpole regression: with the generic (_cut_one) cut path,
        at least one credit is ACKed while the message is still mid-body."""
        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        tr, fake, ep = _make_endpoint()
        try:
            # 300KB body ≥ stream_body_min (256KB), 64KB blocks → 5 frames
            pkt = _trpc_response_packet(b"\xcd" * (300 * 1024))
            trace = self._stream_packet_through(tr, fake, ep, pkt)
            # after the FIRST frame the message is incomplete (cursor
            # registered) yet its block's credit is already on the wire
            first_acked, first_done = trace[0]
            assert not first_done, "message must still be mid-body"
            assert 0 in first_acked, \
                f"block 0 credit not returned mid-message: {trace}"
            # message eventually completes and every block ACKs exactly once
            assert trace[-1][1], trace
            final = sorted(trace[-1][0])
            assert final == list(range(len(trace))), trace
        finally:
            ep.fail(0, "test done")

    def test_ack_returns_mid_message_native_cut_path(self):
        """Same contract with the native batch scanner active on the vsock
        (preferred protocol TRPC + complete plain frames batch-scanned):
        the scanner must neither swallow the cursor nor re-copy borrowed
        bytes, and credits still return mid-message."""
        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        from brpc_tpu.rpc.protocol import find_protocol

        tr, fake, ep = _make_endpoint()
        try:
            ep.vsock.preferred_protocol = find_protocol("trpc_std")
            # stage 1: two complete small messages arrive INLINE (plain
            # refs — the native scanner's territory) in one frame
            small = _trpc_response_packet(b"a" * 64)
            inline = small + small
            import struct

            body = struct.pack(tr.DATA_BODY_HDR, 0, len(inline), 0) + inline
            ep.on_data(IOBuf(body))
            assert ep.vsock.in_messages == 2
            # stage 2: a large blocked message streams through the SAME
            # socket — the scanner bails (owned blocks / incomplete head),
            # the generic path registers the cursor, credits flow mid-body
            pkt = _trpc_response_packet(b"\x77" * (300 * 1024))
            trace = self._stream_packet_through(tr, fake, ep, pkt)
            first_acked, first_done = trace[0]
            assert not first_done
            assert 0 in first_acked, trace
            assert trace[-1][1]
            assert sorted(trace[-1][0]) == list(range(len(trace)))
        finally:
            ep.fail(0, "test done")

    def test_native_batcher_defers_to_pending_cursor(self):
        from brpc_tpu.rpc.protocol import find_protocol

        tr, fake, ep = _make_endpoint()
        try:
            sock = _FakeParseSock()
            sock.preferred_protocol = find_protocol("trpc_std")
            sock.pending_body = object()  # any live cursor
            sock.read_buf.append(_trpc_response_packet(b"y" * 64))
            assert ep._messenger._cut_batch_native(sock) is None
        finally:
            ep.fail(0, "test done")

    def test_borrowed_outstanding_stays_low_while_streaming(self):
        """The whole point of the shrunken window: claiming at arrival
        keeps the in-flight borrow footprint at one frame's worth, not one
        message's worth."""
        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        tr, fake, ep = _make_endpoint()
        try:
            pkt = _trpc_response_packet(b"\x11" * (300 * 1024))
            pool = ep.recv_pool
            bs = pool.block_size
            peak = 0
            for b in range(-(-len(pkt) // bs)):
                chunk = pkt[b * bs:(b + 1) * bs]
                pool._shm.buf[b * bs:b * bs + len(chunk)] = chunk
                ep.on_data(IOBuf(_data_frame_body([(b, len(chunk))])))
                peak = max(peak, ep._borrowed_outstanding)
            # 5-block message, but never more than one block outstanding
            # after a cut (the cursor claims each arrival inside the cut)
            assert peak <= 1, peak
        finally:
            ep.fail(0, "test done")


# ---------------------------------------------------------------------------
# send side: pipelined two-stage loop with exact acquire
# ---------------------------------------------------------------------------
class TestSendPipelining:
    def _frames_of(self, tr, fake, ftype):
        import struct

        out = []
        for raw in fake.frames:
            magic, ft, blen = struct.unpack_from(tr.CTRL_HDR, raw)
            if ft == ftype:
                out.append(raw[tr.CTRL_HDR_SIZE:tr.CTRL_HDR_SIZE + blen])
        return out

    def test_exact_acquire_and_frame_quantum(self):
        import struct

        tr, fake, ep = _make_endpoint()
        try:
            # attach a window over our own pool: 8 blocks of 64KB
            ep.window = tr.PeerWindow(ep.recv_pool.name,
                                      ep.recv_pool.block_size,
                                      ep.recv_pool.block_count)
            payload = b"\x3c" * (300 * 1024)  # 5 blocks
            rc = ep.send_packet(IOBuf(payload))
            assert rc == 0
            datas = self._frames_of(tr, fake, tr.FT_DATA)
            seg_lens = []
            for body in datas:
                epoch, inline_len, nsegs = struct.unpack_from(
                    tr.DATA_BODY_HDR, body)
                assert inline_len == 0
                assert 1 <= nsegs <= tr.SEND_PIPELINE_SEGS
                for k in range(nsegs):
                    idx, ln = struct.unpack_from(
                        tr.SEG_FMT, body, tr.DATA_BODY_HDR_SIZE + 8 * k)
                    assert ln > 0          # exact acquire: no empty segs
                    seg_lens.append(ln)
            assert sum(seg_lens) == len(payload)
            # 5 blocks at a 4-block quantum → 2 frames: the peer starts
            # parsing frame 1 while frame 2's blocks are being filled
            assert len(datas) == 2, [len(d) for d in datas]
            # every acquired credit is spoken for: 8 - 5 remain free
            with ep.window._cond:
                assert len(ep.window._free) == 3
        finally:
            ep.fail(0, "test done")


# ---------------------------------------------------------------------------
# end to end: a message larger than the WHOLE window flows borrowed-only
# ---------------------------------------------------------------------------
class TestShrunkWindowEndToEnd:
    def test_negotiated_window_is_64_blocks(self, tpu_server):
        from brpc_tpu.tpu import transport as tr

        assert tr.DEFAULT_BLOCK_COUNT == 64
        stub = _stub_for(tpu_server)
        stub.Echo(echo_pb2.EchoRequest(message="hello"))
        with tr._remote_lock:
            vs = next(s for s in tr._remote_sockets.values() if not s.failed)
        assert vs.endpoint.window.block_count == 64

    def test_16mb_sweep_regression_copied_fraction(self, tpu_server):
        """The PR-2 guard at the SHRUNKEN window: a 16MB echo (16MB request
        + 16MB response = 128 blocks against a 64-block window) must stay
        ≤10% copied. Only mid-message credit return makes this possible —
        without it the borrow budget overflows and bytes fall back to
        copy-and-ACK."""
        from brpc_tpu.tpu import transport as tr

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        stub = _stub_for(tpu_server, timeout_ms=60000)
        payload = b"\x42" * (16 * 1024 * 1024)
        stub.Echo(echo_pb2.EchoRequest(message="warm", payload=payload))
        borrowed0 = tr.g_tunnel_borrowed_bytes.get_value()
        copied0 = tr.g_tunnel_copied_bytes.get_value()
        r = stub.Echo(echo_pb2.EchoRequest(message="sweep", payload=payload))
        assert r.payload == payload
        borrowed = tr.g_tunnel_borrowed_bytes.get_value() - borrowed0
        copied = tr.g_tunnel_copied_bytes.get_value() - copied0
        assert borrowed > 0
        frac = copied / max(1, borrowed + copied)
        assert frac <= 0.10, (borrowed, copied, frac)
        # ... and at no point did the borrow footprint approach the window
        assert tr.borrowed_peak_blocks() < tr.DEFAULT_BLOCK_COUNT, \
            tr.borrowed_peak_blocks()

    def test_sender_reuses_credits_mid_message(self, tpu_server):
        """E2E mid-message proof from the SENDER's side: a 24MB payload is
        96 blocks — more than the whole 64-block window — so the send can
        only complete if credits the receiver returned MID-message were
        re-acquired. copied==0 rules out the copy-and-ACK fallback having
        supplied them."""
        from brpc_tpu.tpu import transport as tr

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        stub = _stub_for(tpu_server, timeout_ms=60000)
        payload = b"\x99" * (24 * 1024 * 1024)
        copied0 = tr.g_tunnel_copied_bytes.get_value()
        r = stub.Echo(echo_pb2.EchoRequest(message="wrap", payload=payload))
        assert r.payload == payload
        assert tr.g_tunnel_copied_bytes.get_value() - copied0 == 0

    def test_offloaded_cut_path_streams_mid_message(self, tpu_server):
        """Force the bootstrap socket's cut loop onto the offloaded fiber
        cutter (tiny inline_cut_max_bytes) and re-prove the window-wrap:
        > 64 blocks of payload with zero copied bytes means credits
        returned mid-message on the offloaded path too."""
        from brpc_tpu.tpu import transport as tr

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        old = _flags.get("inline_cut_max_bytes")
        _flags.set_flag("inline_cut_max_bytes", "8192")
        try:
            stub = _stub_for(tpu_server, timeout_ms=60000)
            payload = b"\x77" * (20 * 1024 * 1024)  # 80 blocks > 64 window
            copied0 = tr.g_tunnel_copied_bytes.get_value()
            r = stub.Echo(echo_pb2.EchoRequest(message="off",
                                               payload=payload))
            assert r.payload == payload
            assert tr.g_tunnel_copied_bytes.get_value() - copied0 == 0
        finally:
            _flags.set_flag("inline_cut_max_bytes", str(old))


# ---------------------------------------------------------------------------
# teardown semantics
# ---------------------------------------------------------------------------
class TestCursorTeardown:
    def test_socket_failure_drops_cursor(self, small_stream_min):
        from brpc_tpu.policy import ensure_registered
        from brpc_tpu.rpc.input_messenger import InputMessenger

        ensure_registered()
        pkt = _trpc_response_packet(b"\xdd" * 16384)
        sock = _FakeParseSock()
        messenger = InputMessenger()
        sock.read_buf.append(pkt[:8000])
        messenger.cut_messages(sock)
        assert sock.pending_body is not None
        sock.set_failed(1001, "teardown")
        assert sock.pending_body is None

    def test_endpoint_fail_mid_cursor_releases_everything(self):
        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        tr, fake, ep = _make_endpoint()
        pkt = _trpc_response_packet(b"\xee" * (300 * 1024))
        pool = ep.recv_pool
        bs = pool.block_size
        # deliver only the first two of five blocks, then kill the tunnel
        for b in range(2):
            chunk = pkt[b * bs:(b + 1) * bs]
            pool._shm.buf[b * bs:b * bs + len(chunk)] = chunk
            ep.on_data(IOBuf(_data_frame_body([(b, len(chunk))])))
        assert ep.vsock.pending_body is not None
        ep.fail(999, "mid-cursor teardown")
        assert ep.vsock.pending_body is None
        # the claimed bytes' source blocks were already released at feed
        # time; teardown leaves no exports pinning the pool
        deadline = time.monotonic() + 5
        while pool.exports and time.monotonic() < deadline:
            time.sleep(0.01)
        assert pool.exports == 0
        tr._sweep_deferred_pools()
        assert pool._closed
