"""TPU-layer tests on the virtual 8-device CPU mesh (SURVEY §4: the fake
cluster substrate — N virtual chips stand in for a pod the way N loopback
channels stand in for N servers in the reference)."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from brpc_tpu.tpu import collective, mesh as meshlib
from brpc_tpu.tpu.ring import full_attention_reference, ring_attention


@pytest.fixture(scope="module")
def mesh8():
    return meshlib.make_mesh({"x": -1})


class TestMesh:
    def test_device_count(self):
        assert meshlib.device_count() == 8

    def test_make_mesh_infer(self):
        m = meshlib.make_mesh({"dp": 2, "tp": -1})
        assert m.shape == {"dp": 2, "tp": 4}

    def test_bad_mesh(self):
        with pytest.raises(ValueError):
            meshlib.make_mesh({"dp": 3})

    def test_endpoints(self):
        eps = meshlib.list_device_endpoints()
        assert len(eps) == 8
        assert all(e.is_tpu() for e in eps)
        assert meshlib.resolve_device(eps[3]).id == eps[3].device_ordinal


class TestCollectives:
    def test_all_reduce_matches_sum(self, mesh8):
        x = jnp.arange(16.0)
        out = collective.all_reduce(x, mesh8, "x")
        # each shard of 2 gets the sum over the axis of its position-mates
        expected = x.reshape(8, 2).sum(0)
        np.testing.assert_allclose(np.asarray(out).reshape(8, 2)[0], expected)

    def test_all_gather_identity(self, mesh8):
        x = jnp.arange(8.0)
        out = collective.all_gather(x, mesh8, "x")
        assert out.shape == (64,)
        np.testing.assert_allclose(np.asarray(out)[:8], np.arange(8.0))

    def test_reduce_scatter(self, mesh8):
        # 8 devices each contribute a [16] row; result = row-sum, scattered
        x = jnp.ones((8, 16))
        out = collective.reduce_scatter(x, mesh8, "x")
        assert out.shape == (16,)
        np.testing.assert_allclose(np.asarray(out), 8.0 * np.ones(16))

    def test_shift_rotates(self, mesh8):
        x = jnp.arange(8.0)
        out = collective.shift(x, mesh8, "x", offset=1)
        np.testing.assert_allclose(np.asarray(out), np.roll(np.arange(8.0), 1))

    def test_ring_all_reduce_equals_sum(self, mesh8):
        rng = np.random.default_rng(0)
        x = jnp.asarray(rng.normal(size=(8, 32)), dtype=jnp.float32)
        ring = np.asarray(collective.ring_all_reduce(x, mesh8, "x"))
        expected = np.asarray(x).sum(0)
        for row in ring:  # every device ends with the full sum
            np.testing.assert_allclose(row, expected, rtol=1e-5, atol=1e-6)

    def test_fanout_sum_merge(self, mesh8):
        fn = collective.fanout(lambda s: s * 2.0, mesh8, "x", merge="sum")
        x = jnp.ones((8,))
        out = fn(x)
        np.testing.assert_allclose(np.asarray(out), 16.0 * np.ones(8))

    def test_partition_stays_sharded(self, mesh8):
        fn = collective.partition(lambda s: s + 1.0, mesh8, "x")
        x = jnp.zeros((8,))
        np.testing.assert_allclose(np.asarray(fn(x)), np.ones(8))

    def test_all_to_all(self, mesh8):
        # [8, 8] sharded on dim0; swap shard ownership to dim1
        x = jnp.arange(64.0).reshape(8, 8)
        out = collective.all_to_all(x, mesh8, "x", split_axis=1, concat_axis=0)
        assert out.shape == (64, 1)


class TestRingAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_full_attention(self, mesh8, causal):
        rng = np.random.default_rng(1)
        B, S, H, D = 2, 32, 4, 16
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        out_ring = ring_attention(q, k, v, mesh8, "x", causal=causal)
        out_full = full_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out_ring), np.asarray(out_full),
                                   rtol=2e-4, atol=2e-5)

    def test_composes_with_dp_tp(self):
        m = meshlib.make_mesh({"dp": 2, "sp": 2, "tp": 2})
        rng = np.random.default_rng(2)
        B, S, H, D = 2, 16, 4, 8
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        out = ring_attention(q, k, v, m, "sp", causal=True,
                             batch_axis="dp", head_axis="tp")
        ref = full_attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_kernel_inside_ring(self, mesh8, causal):
        # VERDICT r2 #5: the carry-form Pallas kernel accumulates ACROSS
        # hops; the lax path is the oracle
        rng = np.random.default_rng(3)
        B, S, H, D = 2, 32, 4, 16
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        out_flash = ring_attention(q, k, v, mesh8, "x", causal=causal,
                                   use_flash=True)
        out_lax = ring_attention(q, k, v, mesh8, "x", causal=causal)
        out_full = full_attention_reference(q, k, v, causal=causal)
        np.testing.assert_allclose(np.asarray(out_flash),
                                   np.asarray(out_lax),
                                   rtol=1e-5, atol=1e-6)
        np.testing.assert_allclose(np.asarray(out_flash),
                                   np.asarray(out_full),
                                   rtol=2e-4, atol=2e-5)

    def test_flash_ring_composes_with_dp_tp(self):
        m = meshlib.make_mesh({"dp": 2, "sp": 2, "tp": 2})
        rng = np.random.default_rng(4)
        B, S, H, D = 2, 16, 4, 8
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        out = ring_attention(q, k, v, m, "sp", causal=True,
                             batch_axis="dp", head_axis="tp",
                             use_flash=True)
        ref = full_attention_reference(q, k, v, causal=True)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=2e-4, atol=2e-5)

    @pytest.mark.parametrize("causal", [False, True])
    def test_flash_ring_gradients_match_reference(self, causal):
        # VERDICT r3 #3: the ring-flash path must be trainable — its
        # custom VJP runs the Pallas flash-backward kernels per hop and
        # rotates dk/dv home around the ring
        m = meshlib.make_mesh({"dp": 2, "sp": 2, "tp": 2})
        rng = np.random.default_rng(7)
        B, S, H, D = 2, 32, 4, 16
        q = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        k = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)
        v = jnp.asarray(rng.normal(size=(B, S, H, D)), dtype=jnp.float32)

        def loss_flash(q, k, v):
            o = ring_attention(q, k, v, m, "sp", causal=causal,
                               batch_axis="dp", head_axis="tp",
                               use_flash=True, block_q=16, block_k=16)
            return jnp.sum(jnp.sin(o))

        def loss_ref(q, k, v):
            return jnp.sum(jnp.sin(
                full_attention_reference(q, k, v, causal=causal)))

        g = jax.grad(loss_flash, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_ref, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)


class TestPallasOps:
    def test_rmsnorm_matches_reference(self):
        from brpc_tpu.tpu.pallas_ops import rmsnorm, rmsnorm_reference

        rng = np.random.default_rng(3)
        x = jnp.asarray(rng.normal(size=(4, 32, 128)), dtype=jnp.float32)
        w = jnp.asarray(rng.normal(size=(128,)), dtype=jnp.float32)
        out = rmsnorm(x, w)
        ref = rmsnorm_reference(x, w)
        np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                                   rtol=1e-5, atol=1e-6)

    def test_rmsnorm_ragged_rows(self):
        from brpc_tpu.tpu.pallas_ops import rmsnorm, rmsnorm_reference

        x = jnp.ones((7, 64))  # N not divisible by block_rows
        w = jnp.ones((64,))
        np.testing.assert_allclose(
            np.asarray(rmsnorm(x, w, block_rows=4)),
            np.asarray(rmsnorm_reference(x, w)), rtol=1e-5)

    def test_rmsnorm_gradients_match_reference(self):
        from brpc_tpu.tpu.pallas_ops import rmsnorm, rmsnorm_reference

        rng = np.random.default_rng(9)
        x = jnp.asarray(rng.normal(size=(4, 32, 128)), dtype=jnp.float32)
        w = jnp.asarray(rng.normal(size=(128,)), dtype=jnp.float32)
        gx, gw = jax.grad(
            lambda x, w: jnp.sum(jnp.sin(rmsnorm(x, w))),
            argnums=(0, 1))(x, w)
        rx, rw = jax.grad(
            lambda x, w: jnp.sum(jnp.sin(rmsnorm_reference(x, w))),
            argnums=(0, 1))(x, w)
        np.testing.assert_allclose(np.asarray(gx), np.asarray(rx),
                                   rtol=1e-4, atol=1e-5)
        np.testing.assert_allclose(np.asarray(gw), np.asarray(rw),
                                   rtol=1e-4, atol=1e-5)


class TestTpuSocket:
    """The transport graft: RPC whose wire is the device DMA engine."""

    def test_echo_through_device(self):
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, Stub

        ch = Channel().init("tpu://localhost/0")
        stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
        payload = bytes(range(256)) * 64
        resp = stub.Echo(echo_pb2.EchoRequest(message="via-hbm",
                                              payload=payload))
        assert resp.message == "via-hbm"
        assert resp.payload == payload

    def test_attachment_rides_device(self):
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, Controller, Stub

        ch = Channel().init("tpu://localhost/1")
        stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
        cntl = Controller()
        cntl.request_attachment = b"DEVICE-ATTACH"
        stub.Echo(echo_pb2.EchoRequest(message="a"), controller=cntl)
        assert cntl.response_attachment == b"DEVICE-ATTACH"

    def test_unknown_device_method(self):
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, MethodDescriptor, RpcError, errors

        ch = Channel().init("tpu://localhost/0")
        md = MethodDescriptor("NoSvc", "NoMeth",
                              echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        with pytest.raises(RpcError) as ei:
            ch.call_method(md, echo_pb2.EchoRequest(message="x"))
        assert ei.value.error_code == errors.ENOMETHOD

    def test_custom_device_method(self):
        import jax.numpy as jnp

        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, MethodDescriptor
        from brpc_tpu.tpu.tpusocket import register_device_method
        from brpc_tpu.rpc import errors as err

        def reverse_handler(device, meta, payload, attachment):
            req = echo_pb2.EchoRequest()
            req.ParseFromString(payload)
            arr = jnp.asarray(bytearray(req.payload), dtype=jnp.uint8)
            rev = bytes(np.asarray(arr[::-1]))
            resp = echo_pb2.EchoResponse(message=req.message[::-1], payload=rev)
            return err.OK, resp.SerializeToString(), b""

        register_device_method("RevService", "Reverse", reverse_handler)
        ch = Channel().init("tpu://localhost/2")
        md = MethodDescriptor("RevService", "Reverse",
                              echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        resp = ch.call_method(
            md, echo_pb2.EchoRequest(message="abc", payload=b"1234"))
        assert resp.message == "cba" and resp.payload == b"4321"


class TestTrain:
    def test_single_device_forward(self):
        from brpc_tpu.tpu import train

        cfg = train.ModelConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                                d_ff=64, max_seq=16)
        params = train.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jnp.zeros((2, 16), dtype=jnp.int32)
        logits = train.forward(params, tokens, cfg)
        assert logits.shape == (2, 16, 64)

    def test_sharded_train_step_runs_and_learns(self):
        from brpc_tpu.tpu import train

        m = meshlib.make_mesh({"dp": 2, "sp": 2, "tp": 2})
        cfg = train.ModelConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                                d_ff=64, max_seq=16)
        params = train.init_params(jax.random.PRNGKey(0), cfg)
        step, pshard, bshard = train.make_train_step(cfg, m, lr=1e-2)
        params = jax.device_put(params, pshard)
        batch = train.demo_batch(jax.random.PRNGKey(1), cfg, batch=4, seq=16)
        batch = jax.device_put(batch, bshard)
        losses = []
        for _ in range(5):
            params, loss = step(params, batch)
            losses.append(float(loss))
        assert losses[-1] < losses[0]  # actually learning

    def test_sharded_forward_matches_unsharded(self):
        from brpc_tpu.tpu import train

        m = meshlib.make_mesh({"dp": 2, "sp": 2, "tp": 2})
        cfg = train.ModelConfig(vocab=64, d_model=32, n_heads=4, n_layers=2,
                                d_ff=64, max_seq=16)
        params = train.init_params(jax.random.PRNGKey(0), cfg)
        tokens = jax.random.randint(jax.random.PRNGKey(2), (2, 16), 0, 64)
        ref = train.forward(params, tokens, cfg)

        with m:
            sharded = jax.jit(
                lambda p, t: train.forward(p, t, cfg, mesh=m))(params, tokens)
        np.testing.assert_allclose(np.asarray(sharded), np.asarray(ref),
                                   rtol=5e-4, atol=5e-5)


class TestFlashAttention:
    @pytest.mark.parametrize("causal", [False, True])
    def test_matches_reference(self, causal):
        import jax

        from brpc_tpu.tpu.pallas_ops import (attention_reference,
                                             flash_attention)

        key = jax.random.PRNGKey(0)
        kq, kk, kv = jax.random.split(key, 3)
        S, D = 256, 64
        q = jax.random.normal(kq, (S, D), dtype=jnp.float32)
        k = jax.random.normal(kk, (S, D), dtype=jnp.float32)
        v = jax.random.normal(kv, (S, D), dtype=jnp.float32)
        out = flash_attention(q, k, v, causal=causal, block_q=64,
                              block_k=64, interpret=True)
        ref = attention_reference(q, k, v, causal=causal)
        assert jnp.allclose(out, ref, atol=2e-3), float(
            jnp.abs(out - ref).max())

    def test_multi_head(self):
        import jax

        from brpc_tpu.tpu.pallas_ops import (attention_reference,
                                             flash_attention_mha)

        key = jax.random.PRNGKey(1)
        B, H, S, D = 2, 4, 128, 32
        q, k, v = (jax.random.normal(kk, (B, H, S, D), dtype=jnp.float32)
                   for kk in jax.random.split(key, 3))
        out = flash_attention_mha(q, k, v, causal=True, block_q=64,
                                  block_k=64, interpret=True)
        for b in range(B):
            for h in range(H):
                ref = attention_reference(q[b, h], k[b, h], v[b, h],
                                          causal=True)
                assert jnp.allclose(out[b, h], ref, atol=2e-3)

    def test_block_misalignment_rejected(self):
        import jax

        from brpc_tpu.tpu.pallas_ops import flash_attention

        q = jnp.zeros((100, 32))
        with pytest.raises(ValueError):
            flash_attention(q, q, q, block_q=64, block_k=64,
                            interpret=True)

    @pytest.mark.parametrize("causal", [False, True])
    def test_mha_gradients_match_reference(self, causal):
        # the Pallas backward kernels (dq / dkv) against AD through the
        # O(S^2) reference
        import jax

        from brpc_tpu.tpu.pallas_ops import (attention_reference,
                                             flash_attention_mha)

        key = jax.random.PRNGKey(5)
        B, H, S, D = 2, 3, 128, 32
        q, k, v = (jax.random.normal(kk, (B, H, S, D), dtype=jnp.float32)
                   for kk in jax.random.split(key, 3))

        def ref(q, k, v):
            f = lambda q1, k1, v1: attention_reference(q1, k1, v1,
                                                       causal=causal)
            return jax.vmap(jax.vmap(f))(q, k, v)

        def loss_f(q, k, v):
            return jnp.sum(jnp.sin(flash_attention_mha(
                q, k, v, causal=causal, block_q=64, block_k=64,
                interpret=True)))

        def loss_r(q, k, v):
            return jnp.sum(jnp.sin(ref(q, k, v)))

        g = jax.grad(loss_f, argnums=(0, 1, 2))(q, k, v)
        gr = jax.grad(loss_r, argnums=(0, 1, 2))(q, k, v)
        for a, b in zip(g, gr):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-4, atol=5e-5)

    def test_flash_attention_on_hardware(self):
        """Exercise the NATIVE Mosaic lowering (scratch shapes, tiling) —
        interpret mode can hide hardware constraints. bf16 MXU matmuls
        give ~1e-2 error vs the fp32 reference at D=128."""
        import jax

        if jax.default_backend() != "tpu":
            pytest.skip("no TPU backend")
        from brpc_tpu.tpu.pallas_ops import (attention_reference,
                                             flash_attention)

        key = jax.random.PRNGKey(2)
        q, k, v = (jax.random.normal(kk, (256, 128), dtype=jnp.float32)
                   for kk in jax.random.split(key, 3))
        out = flash_attention(q, k, v, causal=True, interpret=False)
        ref = attention_reference(q, k, v, causal=True)
        assert jnp.allclose(out, ref, atol=2e-2), float(
            jnp.abs(out - ref).max())


class TestFlashInModel:
    def test_forward_matches_reference_attention(self):
        import jax

        from brpc_tpu.tpu import train

        cfg_ref = train.ModelConfig(vocab=64, d_model=64, n_heads=2,
                                    n_layers=2, d_ff=128, max_seq=128,
                                    use_flash_attention=False)
        cfg_flash = train.ModelConfig(vocab=64, d_model=64, n_heads=2,
                                      n_layers=2, d_ff=128, max_seq=128,
                                      use_flash_attention=True)
        params = train.init_params(jax.random.PRNGKey(0), cfg_ref)
        tokens = jax.random.randint(jax.random.PRNGKey(1), (2, 128), 0, 64)
        ref = train.forward(params, tokens, cfg_ref)
        out = train.forward(params, tokens, cfg_flash)
        assert jnp.allclose(out, ref, atol=3e-3), float(
            jnp.abs(out - ref).max())

    def test_train_step_grads_through_flash(self):
        # the default config is kernels-on (VERDICT r3 #3): a full
        # value_and_grad train step must flow through the Pallas custom
        # VJPs and match the XLA-attention baseline's gradients
        import jax

        from brpc_tpu.tpu import train

        base = dict(vocab=64, d_model=64, n_heads=2, n_layers=2,
                    d_ff=128, max_seq=128)
        cfg_on = train.ModelConfig(**base, use_flash_attention=True)
        cfg_off = train.ModelConfig(**base, use_flash_attention=False)
        params = train.init_params(jax.random.PRNGKey(0), cfg_on)
        batch = train.demo_batch(jax.random.PRNGKey(1), cfg_on, 2, 128)
        loss_on, g_on = jax.value_and_grad(train.loss_fn)(params, batch,
                                                          cfg_on)
        loss_off, g_off = jax.value_and_grad(train.loss_fn)(params, batch,
                                                            cfg_off)
        assert jnp.allclose(loss_on, loss_off, rtol=1e-4)
        flat_on = jax.tree_util.tree_leaves(g_on)
        flat_off = jax.tree_util.tree_leaves(g_off)
        for a, b in zip(flat_on, flat_off):
            np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                       rtol=5e-3, atol=5e-4)


class TestFusedXent:
    def test_matches_reference(self):
        import jax

        from brpc_tpu.tpu.pallas_ops import (softmax_xent,
                                             softmax_xent_reference)

        key = jax.random.PRNGKey(3)
        logits = jax.random.normal(key, (512, 1024), dtype=jnp.float32) * 3
        targets = jax.random.randint(jax.random.PRNGKey(4), (512,), 0, 1024)
        out = softmax_xent(logits, targets, interpret=True)
        ref = softmax_xent_reference(logits, targets)
        assert jnp.allclose(out, ref, atol=1e-4), (float(out), float(ref))

    def test_odd_row_counts_supported(self):
        import jax

        from brpc_tpu.tpu.pallas_ops import (softmax_xent,
                                             softmax_xent_reference)

        logits = jax.random.normal(jax.random.PRNGKey(5), (100, 64)) * 2
        targets = jax.random.randint(jax.random.PRNGKey(6), (100,), 0, 64)
        out = softmax_xent(logits, targets, block_rows=64, interpret=True)
        assert jnp.allclose(out, softmax_xent_reference(logits, targets),
                            atol=1e-4)

    def test_fused_xent_in_loss(self):
        import jax

        from brpc_tpu.tpu import train

        cfg = train.ModelConfig(vocab=64, d_model=32, n_heads=2, n_layers=1,
                                d_ff=64, max_seq=32)
        cfg_fused = train.ModelConfig(vocab=64, d_model=32, n_heads=2,
                                      n_layers=1, d_ff=64, max_seq=32,
                                      use_fused_xent=True)
        params = train.init_params(jax.random.PRNGKey(0), cfg)
        batch = train.demo_batch(jax.random.PRNGKey(1), cfg, 2, 32)
        ref = train.loss_fn(params, batch, cfg)
        out = train.loss_fn(params, batch, cfg_fused)
        assert jnp.allclose(out, ref, atol=1e-5), (float(out), float(ref))

    def test_fused_xent_gradients_match(self):
        import jax

        from brpc_tpu.tpu.pallas_ops import (softmax_xent,
                                             softmax_xent_reference)

        logits = jax.random.normal(jax.random.PRNGKey(7), (64, 128)) * 2
        targets = jax.random.randint(jax.random.PRNGKey(8), (64,), 0, 128)
        g_fused = jax.grad(lambda x: softmax_xent(x, targets))(logits)
        g_ref = jax.grad(
            lambda x: softmax_xent_reference(x, targets))(logits)
        assert jnp.allclose(g_fused, g_ref, atol=1e-5), float(
            jnp.abs(g_fused - g_ref).max())

    def test_fused_xent_train_step(self):
        import jax

        from brpc_tpu.tpu import train

        cfg = train.ModelConfig(vocab=64, d_model=32, n_heads=2, n_layers=1,
                                d_ff=64, max_seq=32, use_fused_xent=True)
        params = train.init_params(jax.random.PRNGKey(0), cfg)
        batch = train.demo_batch(jax.random.PRNGKey(1), cfg, 2, 32)
        params2, loss = train.sgd_train_step(params, batch, cfg)
        assert jnp.isfinite(loss)  # grad through the kernel works
