"""Authenticator, retry/backup policies, and the CLI tools driven
in-process against loopback servers (reference pattern: tools are built on
the public API only)."""

import os
import shutil
import sys
import threading
import time

import pytest

from brpc_tpu import flags as _flags
from brpc_tpu.policy.auth import (
    AuthContext,
    Authenticator,
    SharedSecretAuthenticator,
)
from brpc_tpu.policy.retry import BackupRequestPolicy, RetryOnCodes, RetryPolicy
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    RpcError,
    Server,
    ServerOptions,
    Service,
    Stub,
    errors,
)

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoServiceImpl(Service):
    DESCRIPTOR = ECHO_DESC

    def __init__(self):
        super().__init__()
        self.calls = 0
        self.seen_users = []
        self.close_next_connection = False

    def Echo(self, cntl, request, done):
        self.calls += 1
        if cntl.auth_context is not None:
            self.seen_users.append(cntl.auth_context.user)
        if self.close_next_connection:
            self.close_next_connection = False
            cntl._srv_socket.set_failed(errors.EFAILEDSOCKET, "injected")
            return None
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        return echo_pb2.EchoResponse(message=request.message)


def start_server(**opts):
    impl = EchoServiceImpl()
    server = Server(ServerOptions(**opts)).add_service(impl)
    server.start("127.0.0.1:0")
    return server, impl


# ------------------------------------------------------------------------ auth
class TestAuth:
    def test_shared_secret_ok(self):
        auth = SharedSecretAuthenticator(b"s3cret", user="alice")
        server, impl = start_server(auth=SharedSecretAuthenticator(b"s3cret"))
        try:
            ch = Channel(ChannelOptions(auth=auth)).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            assert stub.Echo(echo_pb2.EchoRequest(message="m")).message == "m"
            assert impl.seen_users == ["alice"]
        finally:
            server.stop(); server.join(timeout=2)

    def test_wrong_secret_rejected(self):
        server, _ = start_server(auth=SharedSecretAuthenticator(b"right"))
        try:
            ch = Channel(ChannelOptions(
                auth=SharedSecretAuthenticator(b"wrong"),
                max_retry=0)).init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            with pytest.raises(RpcError) as ei:
                stub.Echo(echo_pb2.EchoRequest(message="m"))
            assert ei.value.error_code == errors.EAUTH
        finally:
            server.stop(); server.join(timeout=2)

    def test_missing_credential_rejected(self):
        server, _ = start_server(auth=SharedSecretAuthenticator(b"k"))
        try:
            ch = Channel(ChannelOptions(max_retry=0)).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            with pytest.raises(RpcError) as ei:
                stub.Echo(echo_pb2.EchoRequest(message="m"))
            assert ei.value.error_code == errors.EAUTH
        finally:
            server.stop(); server.join(timeout=2)

    def test_auth_over_http(self):
        auth = SharedSecretAuthenticator(b"k", user="bob")
        server, impl = start_server(auth=SharedSecretAuthenticator(b"k"))
        try:
            ch = Channel(ChannelOptions(auth=auth, protocol="http")).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            assert stub.Echo(echo_pb2.EchoRequest(message="h")).message == "h"
            assert impl.seen_users == ["bob"]
        finally:
            server.stop(); server.join(timeout=2)

    def test_custom_authenticator(self):
        class AllowEven(Authenticator):
            def __init__(self):
                self.n = 0

            def generate_credential(self):
                self.n += 1
                return str(self.n)

            def verify_credential(self, token, peer):
                try:
                    return (AuthContext(user=f"u{token}")
                            if int(token) % 2 == 0 else None)
                except ValueError:
                    return None

        server, _ = start_server(auth=AllowEven())
        try:
            ch = Channel(ChannelOptions(auth=AllowEven(), max_retry=0)).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            with pytest.raises(RpcError):  # first credential "1" is odd
                stub.Echo(echo_pb2.EchoRequest(message="m"))
            assert stub.Echo(echo_pb2.EchoRequest(message="m")).message == "m"
        finally:
            server.stop(); server.join(timeout=2)


# ---------------------------------------------------------------- retry policy
class TestRetryPolicy:
    def test_no_retry_policy_fails_fast(self):
        class NeverRetry(RetryPolicy):
            def do_retry(self, cntl):
                return False

        server, impl = start_server()
        try:
            ch = Channel(ChannelOptions(
                max_retry=3, retry_policy=NeverRetry())).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            impl.close_next_connection = True
            with pytest.raises(RpcError) as ei:
                stub.Echo(echo_pb2.EchoRequest(message="m"))
            assert ei.value.error_code == errors.EFAILEDSOCKET
            assert impl.calls == 1  # no second attempt
        finally:
            server.stop(); server.join(timeout=2)

    def test_default_policy_retries_socket_failure(self):
        server, impl = start_server()
        try:
            ch = Channel(ChannelOptions(max_retry=3)).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            impl.close_next_connection = True
            assert stub.Echo(echo_pb2.EchoRequest(message="m")).message == "m"
            assert impl.calls == 2
        finally:
            server.stop(); server.join(timeout=2)

    def test_retry_on_codes_set(self):
        policy = RetryOnCodes({errors.EINTERNAL}, include_default=False)

        class FakeCntl:
            error_code = errors.EINTERNAL

        assert policy.do_retry(FakeCntl())
        FakeCntl.error_code = errors.EFAILEDSOCKET
        assert not policy.do_retry(FakeCntl())

    def test_backup_policy_vetoes_hedge(self):
        class NoBackup(BackupRequestPolicy):
            def do_backup(self, cntl):
                return False

        server, impl = start_server()
        try:
            ch = Channel(ChannelOptions(
                backup_request_ms=20,
                backup_request_policy=NoBackup(),
                timeout_ms=2000)).init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            resp = stub.Echo(echo_pb2.EchoRequest(message="m", sleep_us=100_000))
            assert resp.message == "m"
            time.sleep(0.05)
            assert impl.calls == 1  # hedge suppressed
        finally:
            server.stop(); server.join(timeout=2)

    def test_backup_fires_by_default(self):
        server, impl = start_server()
        try:
            ch = Channel(ChannelOptions(
                backup_request_ms=20, timeout_ms=2000)).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            resp = stub.Echo(echo_pb2.EchoRequest(message="m", sleep_us=100_000))
            assert resp.message == "m"
            time.sleep(0.2)
            assert impl.calls == 2  # original + hedge
        finally:
            server.stop(); server.join(timeout=2)


# ------------------------------------------------------------- trace stitching
class TestTraceStitching:
    def test_two_hop_trace_shares_trace_id(self):
        from brpc_tpu.trace import span as _span

        _span.reset_for_test()
        backend, _ = start_server()

        class ProxyService(Service):
            DESCRIPTOR = ECHO_DESC

            def __init__(self, downstream):
                super().__init__()
                self._stub = Stub(downstream, ECHO_DESC)

            def Echo(self, cntl, request, done):
                # downstream call inside the handler must join the trace
                return self._stub.Echo(request)

        down = Channel().init(str(backend.listen_endpoint()))
        proxy = Server().add_service(ProxyService(down)).start("127.0.0.1:0")
        try:
            stub = Stub(Channel().init(str(proxy.listen_endpoint())), ECHO_DESC)
            assert stub.Echo(echo_pb2.EchoRequest(message="hop")).message == "hop"
            deadline = time.time() + 2
            while time.time() < deadline:
                spans = _span.recent_spans(20)
                if len(spans) >= 4:
                    break
                time.sleep(0.01)
            trace_ids = {s.trace_id for s in spans}
            assert len(spans) >= 4  # client, proxy-server, proxy-client, backend
            assert len(trace_ids) == 1, "all hops share one trace"
        finally:
            proxy.stop(); proxy.join(timeout=2)
            backend.stop(); backend.join(timeout=2)


# ---------------------------------------------------------------------- tools
class TestTools:
    def test_rpc_press(self, capsys):
        sys.path.insert(0, "tools")
        from tools import rpc_press  # noqa

        server, impl = start_server()
        try:
            rc = rpc_press.main([
                "--server", str(server.listen_endpoint()),
                "--qps", "200", "--duration", "0.5", "--quiet"])
            assert rc == 0
            out = capsys.readouterr().out
            assert "latency_p99_us" in out
            assert impl.calls > 10
        finally:
            server.stop(); server.join(timeout=2)

    def test_rpc_press_proto_json_io(self, tmp_path, capsys):
        """Reference rpc_press parity: runtime .proto compilation
        (--proto/--inc via protoc — or the vendored pre-compiled descriptor
        set on hosts without protoc), JSON request input, JSON response
        output, lb over a naming url, pooled connections, attachments."""
        sys.path.insert(0, "tools")
        from tools import rpc_press  # noqa

        if shutil.which("protoc") is not None:
            proto = tmp_path / "press_echo.proto"
            proto.write_text(
                'syntax = "proto3";\n'
                "package press.test;\n"
                "message Req { string message = 1; bytes payload = 2;\n"
                "  int32 sleep_us = 3; }\n"
                "message Resp { string message = 1; bytes payload = 2; }\n"
                "service EchoService { rpc Echo(Req) returns (Resp); }\n")
            method_args = ["--proto", str(proto)]
        else:
            desc = os.path.join(os.path.dirname(__file__), "data",
                                "press_echo.desc")
            method_args = ["--descriptor-set", desc]
        inp = tmp_path / "reqs.json"
        inp.write_text('{"message": "a"}\n{"message": "b"}\n')
        outp = tmp_path / "resps.json"
        server, impl = start_server()
        try:
            rc = rpc_press.main([
                "--server", f"list://{server.listen_endpoint()}",
                "--lb-policy", "rr",
                *method_args,
                "--full-method", "press.test.EchoService.Echo",
                "--input", str(inp), "--output", str(outp),
                "--connection-type", "pooled",
                "--attachment-size", "64",
                "--qps", "200", "--duration", "0.5", "--quiet"])
            assert rc == 0
            out = capsys.readouterr().out
            assert "latency_p99_us" in out
            lines = [l for l in outp.read_text().splitlines() if l.strip()]
            assert len(lines) > 10
            import json as _json

            msgs = {_json.loads(l)["message"] for l in lines[:20]}
            assert msgs <= {"a", "b"} and msgs
        finally:
            server.stop(); server.join(timeout=2)

    def test_rpc_dump_then_replay(self, tmp_path, capsys):
        from tools import rpc_replay

        _flags.set_flag("rpc_dump_ratio", "1.0")
        try:
            server, impl = start_server(rpc_dump_dir=str(tmp_path))
            try:
                ch = Channel().init(str(server.listen_endpoint()))
                stub = Stub(ch, ECHO_DESC)
                for i in range(3):
                    stub.Echo(echo_pb2.EchoRequest(message=f"r{i}"))
                deadline = time.time() + 2
                while (server.rpc_dumper.sampled_count < 3
                       and time.time() < deadline):
                    time.sleep(0.01)
                server.rpc_dumper.close()
            finally:
                server.stop(); server.join(timeout=2)
            _flags.set_flag("rpc_dump_ratio", "0.0")

            # replay the dump into a fresh server
            server2, impl2 = start_server()
            try:
                rc = rpc_replay.main([
                    "--dump", str(tmp_path),
                    "--server", str(server2.listen_endpoint())])
                assert rc == 0
                assert impl2.calls == 3
                assert "replayed ok 3 failed 0" in capsys.readouterr().out
            finally:
                server2.stop(); server2.join(timeout=2)
        finally:
            _flags.set_flag("rpc_dump_ratio", "0.0")

    def test_rpc_view(self, capsys):
        # one-shot fetch goes over the BINARY protocol now
        from tools import rpc_view

        server, _ = start_server()
        try:
            rc = rpc_view.main([str(server.listen_endpoint()), "status"])
            assert rc == 0
            assert "EchoService" in capsys.readouterr().out
            # --http fallback still works
            rc = rpc_view.main([str(server.listen_endpoint()), "status",
                                "--http"])
            assert rc == 0
            assert "EchoService" in capsys.readouterr().out
        finally:
            server.stop(); server.join(timeout=2)

    def test_rpc_view_proxy(self):
        # the reference tools/rpc_view shape: a standalone HTTP proxy that
        # speaks the binary protocol to the target — builtin pages of the
        # TARGET render through the PROXY's HTTP port
        from brpc_tpu.policy.http_protocol import http_fetch
        from tools import rpc_view

        server, _ = start_server()
        proxy = None
        try:
            proxy = rpc_view.serve("127.0.0.1:0",
                                   str(server.listen_endpoint()),
                                   block=False)
            pep = str(proxy.listen_endpoint())
            resp = http_fetch(pep, "GET", "/status", timeout=5)
            assert resp.status == 200
            body = resp.body.decode()
            assert "EchoService" in body
            # the proxied page reports the TARGET's endpoint, not the proxy
            assert str(server.listen_endpoint()) in body
            resp = http_fetch(pep, "GET", "/vars", timeout=5)
            assert resp.status == 200 and resp.body
            resp = http_fetch(pep, "GET", "/index", timeout=5)
            assert resp.status == 200 and b"/status" in resp.body
        finally:
            if proxy is not None:
                proxy.stop(); proxy.join(timeout=2)
            server.stop(); server.join(timeout=2)
