"""HTTP protocol family: parser conformance (the reference's per-protocol
wire-byte unittests, test/brpc_http_rpc_protocol_unittest.cpp), json2pb,
flags, builtin services served off the same RPC port, rpcz, rpc_dump."""

import json
import time

import pytest

from brpc_tpu import flags as _flags
from brpc_tpu import json2pb
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy.http_protocol import (
    CONTENT_JSON,
    CONTENT_PROTO,
    HttpProtocol,
    http_fetch,
    parse_http_message,
    render_request,
    render_response,
)
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    Service,
    Stub,
    errors,
)
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    PARSE_TRY_OTHERS,
)

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoServiceImpl(Service):
    DESCRIPTOR = ECHO_DESC

    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def http_server():
    server = Server().add_service(EchoServiceImpl()).start("127.0.0.1:0")
    yield server
    server.stop()
    server.join(timeout=2)


def addr(server):
    return str(server.listen_endpoint())


# ---------------------------------------------------------------- wire parser
class TestHttpParser:
    def test_request_roundtrip(self):
        raw = render_request("POST", "/Svc/M?x=1&y=b", "h", b"body",
                             extra_headers={"X-Foo": "bar"})
        buf = IOBuf(raw)
        rc, msg = parse_http_message(buf)
        assert rc == 0
        assert msg.method == "POST" and msg.path == "/Svc/M"
        assert msg.query == {"x": "1", "y": "b"}
        assert msg.header("x-foo") == "bar"
        assert msg.body == b"body"
        assert len(buf) == 0

    def test_response_roundtrip(self):
        raw = render_response(404, "text/plain", "nope")
        rc, msg = parse_http_message(IOBuf(raw))
        assert rc == 0
        assert not msg.is_request
        assert msg.status == 404
        assert msg.body == b"nope"

    def test_incremental_feed(self):
        raw = render_request("GET", "/vars", "h")
        for cut in (1, 10, len(raw) - 1):
            buf = IOBuf(raw[:cut])
            rc, _ = parse_http_message(buf)
            assert rc == PARSE_NOT_ENOUGH_DATA
        rc, msg = parse_http_message(IOBuf(raw))
        assert rc == 0 and msg.method == "GET"

    def test_other_protocol_bytes(self):
        rc, _ = parse_http_message(IOBuf(b"TRPC\x00\x00\x00\x01"))
        assert rc == PARSE_TRY_OTHERS
        # TRAC could still become TRACE -> not enough data yet
        rc, _ = parse_http_message(IOBuf(b"TRAC"))
        assert rc == PARSE_NOT_ENOUGH_DATA

    def test_bad_header(self):
        rc, _ = parse_http_message(
            IOBuf(b"GET /x HTTP/1.1\r\nbroken line\r\n\r\n"))
        assert rc == PARSE_BAD

    def test_chunked_body(self):
        raw = (b"POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
               b"4\r\nWiki\r\n5\r\npedia\r\n0\r\n\r\n")
        rc, msg = parse_http_message(IOBuf(raw))
        assert rc == 0
        assert msg.body == b"Wikipedia"

    def test_chunked_incomplete(self):
        raw = (b"POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"
               b"4\r\nWi")
        rc, _ = parse_http_message(IOBuf(raw))
        assert rc == PARSE_NOT_ENOUGH_DATA

    def test_pipelined_requests(self):
        raw = render_request("GET", "/a", "h") + render_request("GET", "/b", "h")
        buf = IOBuf(raw)
        rc, m1 = parse_http_message(buf)
        rc2, m2 = parse_http_message(buf)
        assert (rc, rc2) == (0, 0)
        assert m1.path == "/a" and m2.path == "/b"


# -------------------------------------------------------------------- json2pb
class TestJson2Pb:
    def test_roundtrip(self):
        req = echo_pb2.EchoRequest(message="hi", payload=b"\x01\x02")
        text = json2pb.pb_to_json(req)
        back = json2pb.json_to_pb(text, echo_pb2.EchoRequest)
        assert back == req

    def test_bad_json(self):
        with pytest.raises(json2pb.Json2PbError):
            json2pb.json_to_pb("{not json", echo_pb2.EchoRequest)

    def test_unknown_fields_ignored(self):
        msg = json2pb.json_to_pb('{"message": "x", "bogus": 1}',
                                 echo_pb2.EchoRequest)
        assert msg.message == "x"


# ---------------------------------------------------------------------- flags
class TestFlags:
    def test_define_get_set(self):
        f = _flags.define("test_flag_xyz", 5, "help", reloadable=True)
        assert _flags.get("test_flag_xyz") == 5
        _flags.set_flag("test_flag_xyz", "7")
        assert f.value == 7

    def test_validator_rejects(self):
        _flags.define("test_flag_pos", 1.0, validator=lambda v: v > 0)
        with pytest.raises(_flags.FlagError):
            _flags.set_flag("test_flag_pos", "-2.0")
        assert _flags.get("test_flag_pos") == 1.0

    def test_non_reloadable(self):
        _flags.define("test_flag_frozen", "a")
        with pytest.raises(_flags.FlagError):
            _flags.set_flag("test_flag_frozen", "b")

    def test_bool_parsing(self):
        f = _flags.define("test_flag_bool", False, reloadable=True)
        _flags.set_flag("test_flag_bool", "true")
        assert f.value is True
        _flags.set_flag("test_flag_bool", "0")
        assert f.value is False


# ----------------------------------------------------------- builtin services
class TestBuiltinServices:
    def test_index_lists_services(self, http_server):
        resp = http_fetch(addr(http_server), "GET", "/")
        assert resp.status == 200
        assert b"/status" in resp.body and b"/vars" in resp.body

    def test_status(self, http_server):
        resp = http_fetch(addr(http_server), "GET", "/status")
        assert resp.status == 200
        assert b"EchoService" in resp.body

    def test_health_version(self, http_server):
        assert http_fetch(addr(http_server), "GET", "/health").body == b"OK\n"
        assert b"brpc_tpu" in http_fetch(addr(http_server), "GET",
                                         "/version").body

    def test_vars(self, http_server):
        from brpc_tpu.metrics import Status

        Status(42).expose("test_http_var")
        resp = http_fetch(addr(http_server), "GET", "/vars")
        assert b"test_http_var : 42" in resp.body
        resp = http_fetch(addr(http_server), "GET", "/vars/test_http_var")
        assert resp.body == b"test_http_var : 42\n"

    def test_flags_list_and_set(self, http_server):
        resp = http_fetch(addr(http_server), "GET", "/flags")
        assert b"circuit_breaker_enabled" in resp.body
        resp = http_fetch(addr(http_server), "GET",
                          "/flags/idle_timeout_s?setvalue=30")
        assert resp.status == 200
        assert _flags.get("idle_timeout_s") == 30.0
        _flags.set_flag("idle_timeout_s", "-1")

    def test_flags_set_rejected(self, http_server):
        resp = http_fetch(addr(http_server), "GET",
                          "/flags/rpcz_sample_ratio?setvalue=2.0")
        assert resp.status == 403

    def test_connections_and_sockets(self, http_server):
        resp = http_fetch(addr(http_server), "GET", "/connections")
        assert resp.status == 200
        resp = http_fetch(addr(http_server), "GET", "/sockets")
        assert resp.status == 200

    def test_prometheus(self, http_server):
        resp = http_fetch(addr(http_server), "GET", "/brpc_metrics")
        assert resp.status == 200

    def test_protobufs(self, http_server):
        resp = http_fetch(addr(http_server), "GET", "/protobufs")
        assert b"EchoService.Echo" in resp.body

    def test_unknown_builtin_falls_through_to_404(self, http_server):
        resp = http_fetch(addr(http_server), "GET", "/no_such_thing")
        assert resp.status == 404


# ------------------------------------------------------------------- JSON RPC
class TestJsonRpc:
    def test_json_call(self, http_server):
        body = json.dumps({"message": "json hello"}).encode()
        resp = http_fetch(addr(http_server), "POST", "/EchoService/Echo",
                          body=body, content_type=CONTENT_JSON)
        assert resp.status == 200
        data = json.loads(resp.body)
        assert data["message"] == "json hello"

    def test_json_call_bad_body(self, http_server):
        resp = http_fetch(addr(http_server), "POST", "/EchoService/Echo",
                          body=b"{oops", content_type=CONTENT_JSON)
        assert resp.status == 400
        assert json.loads(resp.body)["error_code"] == errors.EREQUEST

    def test_no_such_method(self, http_server):
        resp = http_fetch(addr(http_server), "POST", "/EchoService/Nope",
                          body=b"{}", content_type=CONTENT_JSON)
        assert resp.status == 404

    def test_no_such_service(self, http_server):
        resp = http_fetch(addr(http_server), "POST", "/Nope/Echo",
                          body=b"{}", content_type=CONTENT_JSON)
        assert resp.status == 404


# --------------------------------------------------------------- pb-over-http
class TestPbOverHttp:
    def test_channel_http_protocol(self, http_server):
        ch = Channel(ChannelOptions(protocol="http")).init(addr(http_server))
        stub = Stub(ch, ECHO_DESC)
        resp = stub.Echo(echo_pb2.EchoRequest(message="over http"))
        assert resp.message == "over http"

    def test_attachment_over_http(self, http_server):
        from brpc_tpu.rpc import Controller, MethodDescriptor

        ch = Channel(ChannelOptions(protocol="http")).init(addr(http_server))
        md = MethodDescriptor.from_pb(ECHO_DESC.methods_by_name["Echo"])
        cntl = Controller()
        cntl.request_attachment = b"side-channel"
        resp = ch.call_method(md, echo_pb2.EchoRequest(message="x"),
                              controller=cntl)
        assert resp.message == "x"
        assert cntl.response_attachment == b"side-channel"

    def test_many_sequential_calls_one_connection(self, http_server):
        ch = Channel(ChannelOptions(protocol="http")).init(addr(http_server))
        stub = Stub(ch, ECHO_DESC)
        for i in range(20):
            assert stub.Echo(echo_pb2.EchoRequest(message=str(i))).message == str(i)


# ----------------------------------------------------------------------- rpcz
class TestRpcz:
    def test_spans_recorded_and_rendered(self, http_server):
        from brpc_tpu.trace import span as _span

        _span.reset_for_test()
        ch = Channel().init(addr(http_server))
        stub = Stub(ch, ECHO_DESC)
        stub.Echo(echo_pb2.EchoRequest(message="traced"))
        # the server span is recorded just after the response is written —
        # wait for it
        deadline = time.time() + 2
        while time.time() < deadline:
            spans = _span.recent_spans(10)
            if {s.kind for s in spans} >= {"client", "server"}:
                break
            time.sleep(0.01)
        kinds = {s.kind for s in spans}
        assert "client" in kinds and "server" in kinds
        client = next(s for s in spans if s.kind == "client")
        server_span = next(s for s in spans if s.kind == "server")
        # propagation: same trace, parent chain intact
        assert client.trace_id == server_span.trace_id
        assert server_span.parent_span_id == client.span_id
        resp = http_fetch(addr(http_server), "GET", "/rpcz")
        assert b"EchoService.Echo" in resp.body
        resp = http_fetch(addr(http_server), "GET",
                          f"/rpcz/{client.trace_id:x}")
        assert resp.status == 200


# ------------------------------------------------------------------- rpc_dump
class TestRpcDump:
    def test_dump_and_load(self, tmp_path):
        from brpc_tpu.trace.rpc_dump import RpcDumpLoader

        _flags.set_flag("rpc_dump_ratio", "1.0")
        try:
            server = (Server(ServerOptions(rpc_dump_dir=str(tmp_path)))
                      .add_service(EchoServiceImpl()).start("127.0.0.1:0"))
            try:
                ch = Channel().init(str(server.listen_endpoint()))
                stub = Stub(ch, ECHO_DESC)
                for i in range(5):
                    stub.Echo(echo_pb2.EchoRequest(message=f"dump{i}"))
                deadline = time.time() + 2
                while server.rpc_dumper.sampled_count < 5 and time.time() < deadline:
                    time.sleep(0.01)
                server.rpc_dumper.close()
                records = list(RpcDumpLoader(str(tmp_path)))
                assert len(records) == 5
                meta, body = records[0]
                assert meta.request.service_name == "EchoService"
                req = echo_pb2.EchoRequest()
                req.ParseFromString(body)
                assert req.message.startswith("dump")
            finally:
                server.stop()
                server.join(timeout=2)
        finally:
            _flags.set_flag("rpc_dump_ratio", "0.0")


class TestProgressiveAttachment:
    def test_chunked_streaming_download(self):
        """Handler finishes the RPC, then streams body chunks from another
        thread (reference progressive_attachment.cpp); the client sees the
        assembled chunked body and the connection stays keep-alive."""
        import socket as _socket
        import threading

        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Server, Service

        chunks = [b"alpha-", b"beta-", b"g" * 5000, b"-end"]
        started = threading.Event()

        class Downloader(Service):
            DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

            def Echo(self, cntl, request, done):
                pa = cntl.create_progressive_attachment()
                assert pa.write(chunks[0]) == 0  # buffered pre-headers

                def pump():
                    started.wait(5)
                    for c in chunks[1:]:
                        assert pa.write(c) == 0
                    assert pa.close() == 0

                threading.Thread(target=pump, daemon=True).start()
                return echo_pb2.EchoResponse(message="ignored")

        server = Server().add_service(Downloader()).start("127.0.0.1:0")
        try:
            ep = server.listen_endpoint()
            with _socket.create_connection((ep.host, ep.port),
                                           timeout=5) as s:
                s.sendall(b"POST /EchoService/Echo HTTP/1.1\r\n"
                          b"Host: t\r\nContent-Type: application/json\r\n"
                          b"Content-Length: 2\r\n\r\n{}")
                s.settimeout(5)
                data = b""
                while b"\r\n\r\n" not in data:
                    data += s.recv(4096)
                head, _, rest = data.partition(b"\r\n\r\n")
                assert b"Transfer-Encoding: chunked" in head
                started.set()  # let the pump stream the remaining chunks
                while b"0\r\n\r\n" not in rest:
                    chunk = s.recv(4096)
                    if not chunk:
                        break
                    rest += chunk
                # decode chunked framing
                body = b""
                pos = 0
                while True:
                    nl = rest.index(b"\r\n", pos)
                    size = int(rest[pos:nl], 16)
                    if size == 0:
                        break
                    body += rest[nl + 2:nl + 2 + size]
                    pos = nl + 2 + size + 2
                assert body == b"".join(chunks)
                # keep-alive: the SAME connection serves another request
                s.sendall(b"GET /health HTTP/1.1\r\nHost: t\r\n\r\n")
                more = s.recv(4096)
                assert more.startswith(b"HTTP/1.1 200")
        finally:
            server.stop()
            server.join(timeout=2)

    def test_write_after_close_rejected(self):
        from brpc_tpu.rpc import errors
        from brpc_tpu.rpc.progressive import ProgressiveAttachment

        pa = ProgressiveAttachment()
        pa.write(b"x")
        pa.close()
        assert pa.write(b"y") == errors.ESTREAMCLOSED

    def test_progressive_rejected_on_binary_protocol(self):
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import (Channel, ChannelOptions, Server, Service,
                                  Stub)

        seen = {}

        class Svc(Service):
            DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

            def Echo(self, cntl, request, done):
                try:
                    cntl.create_progressive_attachment()
                    seen["raised"] = False
                except ValueError:
                    seen["raised"] = True
                return echo_pb2.EchoResponse(message="ok")

        server = Server().add_service(Svc()).start("127.0.0.1:0")
        try:
            ch = Channel(ChannelOptions(timeout_ms=3000))
            ch.init(str(server.listen_endpoint()))
            stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
            assert stub.Echo(echo_pb2.EchoRequest(message="x")).message == "ok"
            assert seen["raised"] is True
        finally:
            server.stop()
            server.join(timeout=2)
