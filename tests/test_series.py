"""Time-series metrics plane (ISSUE 12) — series tiers under a fake clock,
the sampler-tick sweep with opt-outs, watch rules firing/clearing, the
/vars series+SVG contract, fleet merge (unit + workers=2 e2e), and the
Prometheus exposition round-trip."""

import json
import time

import pytest

from brpc_tpu import flags
from brpc_tpu.metrics import clear_registry, prometheus_text
from brpc_tpu.metrics.reducer import Adder, Maxer
from brpc_tpu.metrics.series import (
    HOUR_SAMPLES,
    MINUTE_SAMPLES,
    SECOND_SAMPLES,
    SeriesRegistry,
    VarSeries,
    global_series,
)
from brpc_tpu.metrics.status import PassiveStatus, Status
from brpc_tpu.metrics.watch import (
    STATE_FIRING,
    STATE_NO_DATA,
    STATE_OK,
    WatchRegistry,
    WatchRule,
)
from tests.test_shard import shard_flags  # noqa: F401 (fixture reuse)


@pytest.fixture(autouse=True)
def _clean_state():
    clear_registry()
    global_series().clear()
    yield
    clear_registry()
    global_series().clear()


class _Http:
    """Minimal HttpMessage stand-in for invoking builtin handlers."""

    def __init__(self, path, query=None, headers=None):
        self.path = path
        self.query = query or {}
        self.headers = headers or {}

    def header(self, name, default=""):
        return self.headers.get(name, default)


# ------------------------------------------------------------- tier rings
class TestVarSeriesTiers:
    def test_identity_prefill_and_shapes(self):
        s = VarSeries()
        d = s.to_dict()
        assert d["second"] == [0] * SECOND_SAMPLES
        assert d["minute"] == [0] * MINUTE_SAMPLES
        assert d["hour"] == [0] * HOUR_SAMPLES
        assert d["count"] == 0

    def test_second_ring_wrap_keeps_newest_60(self):
        s = VarSeries()
        for i in range(70):
            s.append(i)
        assert s.to_dict()["second"] == list(range(10, 70))

    def test_minute_rollup_exact_avg(self):
        s = VarSeries()
        for i in range(1, 61):          # 1..60, avg = 30.5 -> int floor 30
            s.append(i)
        d = s.to_dict()
        assert d["minute"][-1] == 30
        assert d["minute"][:-1] == [0] * (MINUTE_SAMPLES - 1)

    def test_minute_rollup_float_keeps_fraction(self):
        s = VarSeries()
        for i in range(1, 61):
            s.append(float(i))
        assert s.to_dict()["minute"][-1] == pytest.approx(30.5)
        assert s.to_dict()["float"] is True

    def test_hour_rollup_exact(self):
        s = VarSeries()
        for _ in range(SECOND_SAMPLES * MINUTE_SAMPLES):
            s.append(7)
        d = s.to_dict()
        assert d["hour"][-1] == 7
        assert d["minute"] == [7] * MINUTE_SAMPLES
        assert d["count"] == 3600

    def test_max_reduce_op(self):
        s = VarSeries(reduce_op="max")
        for i in range(60):
            s.append(i)
        assert s.to_dict()["minute"][-1] == 59

    def test_unknown_reduce_falls_back_to_avg(self):
        assert VarSeries(reduce_op="bogus").reduce_op == "avg"


# ------------------------------------------------------------- the sweep
class TestSeriesRegistry:
    def test_sweep_appends_numeric_exposed_vars(self):
        a = Adder("t_series_adder")
        reg = SeriesRegistry()
        for i in range(5):
            a.put(2)
            reg.tick()
        d = reg.dump("t_series_*")["t_series_adder"]
        assert d["count"] == 5
        assert d["second"][-5:] == [2, 4, 6, 8, 10]
        assert d["last"] == 10

    def test_non_numeric_and_bool_vars_skipped(self):
        Status("hello").expose("t_series_str")
        Status(True).expose("t_series_bool")
        Status(3).expose("t_series_int")
        reg = SeriesRegistry()
        reg.tick()
        names = reg.names()
        assert "t_series_int" in names
        assert "t_series_str" not in names
        assert "t_series_bool" not in names

    def test_var_attr_opt_out_honored(self):
        v = Status(1)
        v.series_opt_out = True
        v.expose("t_series_optout_attr")
        reg = SeriesRegistry()
        reg.tick()
        assert "t_series_optout_attr" not in reg.names()

    def test_programmatic_glob_opt_out_drops_existing(self):
        Status(1).expose("worker0_t_x")
        Status(1).expose("t_series_kept")
        reg = SeriesRegistry()
        reg.tick()
        assert "worker0_t_x" in reg.names()
        reg.opt_out("worker*_*")
        assert "worker0_t_x" not in reg.names()
        reg.tick()
        assert "worker0_t_x" not in reg.names()
        assert "t_series_kept" in reg.names()

    def test_flag_glob_opt_out(self):
        Status(1).expose("t_highcard_x")
        flags.set_flag("var_series_optout", "t_highcard_*")
        try:
            reg = SeriesRegistry()
            reg.tick()
            assert "t_highcard_x" not in reg.names()
        finally:
            flags.set_flag("var_series_optout", "")

    def test_enabled_flag_gates_sweep(self):
        Status(1).expose("t_series_gated")
        reg = SeriesRegistry()
        flags.set_flag("var_series_enabled", False)
        try:
            reg.tick()
            assert reg.names() == []
            assert reg.ticks == 0
        finally:
            flags.set_flag("var_series_enabled", True)
        reg.tick()
        assert "t_series_gated" in reg.names()

    def test_hidden_var_series_gced(self):
        v = Status(1).expose("t_series_gc")
        reg = SeriesRegistry()
        reg.tick()
        assert "t_series_gc" in reg.names()
        v.hide()
        reg.tick()
        assert "t_series_gc" not in reg.names()

    def test_series_reduce_attr_picked_up(self):
        m = Maxer()
        v = PassiveStatus(m.get_value)
        v.series_reduce = "max"
        v.expose("t_series_maxer")
        reg = SeriesRegistry()
        for i in range(60):
            m.put(i)
            reg.tick()
        assert reg.dump("t_series_maxer")["t_series_maxer"]["minute"][-1] == 59


# ------------------------------------------------------------ watch rules
class TestWatchRules:
    def _reg_with_var(self, name="t_watch_v"):
        self.status = Status(0)
        self.status.expose(name)
        return SeriesRegistry()

    def test_threshold_fires_and_clears_on_spike(self):
        reg = self._reg_with_var()
        w = WatchRegistry()
        r = w.add(WatchRule("spike", "t_watch_v", "threshold", ">", 10,
                            for_ticks=2, clear_ticks=3))
        reg.tick()
        w.evaluate_all(reg)
        assert r.state == STATE_OK
        self.status.set_value(50)            # the spike
        reg.tick()
        w.evaluate_all(reg)
        assert r.state == STATE_OK           # debounce: 1 of 2 ticks
        reg.tick()
        w.evaluate_all(reg)
        assert r.state == STATE_FIRING
        self.status.set_value(0)             # drain
        for _ in range(2):
            reg.tick()
            w.evaluate_all(reg)
            assert r.state == STATE_FIRING   # 2 of 3 clear ticks
        reg.tick()
        w.evaluate_all(reg)
        assert r.state == STATE_OK
        assert r.transitions == 2

    def test_delta_kind(self):
        reg = self._reg_with_var()
        w = WatchRegistry()
        r = w.add(WatchRule("jump", "t_watch_v", "delta", ">=", 5,
                            window_s=10))
        for i in range(3):
            self.status.set_value(i)         # +1/tick: delta below 5
            reg.tick()
            w.evaluate_all(reg)
        assert r.state == STATE_OK
        self.status.set_value(100)
        reg.tick()
        w.evaluate_all(reg)
        assert r.state == STATE_FIRING
        assert r.observed >= 5

    def test_rate_kind_normalizes_per_second(self):
        reg = self._reg_with_var()
        w = WatchRegistry()
        r = w.add(WatchRule("fast", "t_watch_v", "rate", ">", 3,
                            window_s=4))
        value = 0
        for _ in range(6):
            value += 10                      # 10/s >= 3/s
            self.status.set_value(value)
            reg.tick()
            w.evaluate_all(reg)
        assert r.state == STATE_FIRING
        assert r.observed == pytest.approx(10.0)

    def test_no_data_until_var_appears(self):
        reg = SeriesRegistry()
        w = WatchRegistry()
        r = w.add(WatchRule("ghost", "t_watch_missing", "threshold", ">", 0))
        reg.tick()
        w.evaluate_all(reg)
        assert r.state == STATE_NO_DATA

    def test_firing_emits_structured_span(self):
        from brpc_tpu.trace import span as _span

        _span.reset_for_test()
        reg = self._reg_with_var()
        w = WatchRegistry()
        w.add(WatchRule("spanful", "t_watch_v", "threshold", ">", 10,
                        for_ticks=1))
        self.status.set_value(99)
        reg.tick()
        w.evaluate_all(reg)
        spans = _span.recent_spans(10, method="spanful")
        assert spans, "watch transition must land in the span DB"
        _off, ev_name, fields = spans[0].events[0]
        assert ev_name == "watch_firing"
        assert fields["rule"] == "spanful"
        assert fields["state"] == STATE_FIRING

    def test_bad_rule_params_rejected(self):
        with pytest.raises(ValueError):
            WatchRule("x", "v", "nope", ">", 1)
        with pytest.raises(ValueError):
            WatchRule("x", "v", "threshold", "~", 1)
        with pytest.raises(ValueError):
            WatchRule("x", "v", "threshold", ">", 1, for_ticks=0)

    def test_post_tick_hook_runs_watch_in_sampler_tick(self):
        reg = self._reg_with_var()
        w = WatchRegistry()
        r = w.add(WatchRule("hooked", "t_watch_v", "threshold", ">", 10,
                            for_ticks=1))
        reg.post_tick_hooks.append(w.evaluate_all)
        self.status.set_value(42)
        reg.tick()                            # one tick: sweep + evaluate
        assert r.state == STATE_FIRING


# ----------------------------------------------------- /vars + /watch http
class TestVarsServiceContract:
    def test_series_json_glob(self):
        from brpc_tpu.builtin.services import vars_service

        a = Adder("t_http_qps")
        for i in range(3):
            a.put(5)
            global_series().tick()
        st, ct, body = vars_service(
            None, _Http("/vars", {"series": "json", "name": "t_http_*"}))
        assert st == 200 and "json" in ct
        doc = json.loads(body)
        assert doc["workers"] == 0
        sd = doc["series"]["t_http_qps"]
        # >=: the bvar-sampler daemon (started by earlier server tests in
        # the same process) may interleave extra ticks with ours
        assert sd["count"] >= 3
        assert sd["second"][-1] == 15
        assert len(sd["second"]) == SECOND_SAMPLES

    def test_detail_series_json_and_404(self):
        from brpc_tpu.builtin.services import vars_service

        Adder("t_http_one").put(1)
        global_series().tick()
        st, _, body = vars_service(
            None, _Http("/vars/t_http_one", {"series": "json"}))
        assert st == 200
        assert json.loads(body)["t_http_one"]["count"] >= 1
        st, _, _ = vars_service(
            None, _Http("/vars/t_http_missing", {"series": "json"}))
        assert st == 404

    def test_detail_svg_contract(self):
        from brpc_tpu.builtin.services import vars_service

        Adder("t_http_svg").put(3)
        global_series().tick()
        st, ct, body = vars_service(
            None, _Http("/vars/t_http_svg", {"format": "svg"}))
        assert st == 200 and ct == "image/svg+xml"
        assert body.startswith("<svg") and body.endswith("</svg>")
        for tier in ("second", "minute", "hour"):
            assert tier in body
        assert "polyline" in body

    def test_detail_html_page(self):
        from brpc_tpu.builtin.services import vars_service

        Adder("t_http_page").put(9)
        global_series().tick()
        st, ct, body = vars_service(
            None, _Http("/vars/t_http_page", {},
                        {"accept": "text/html"}))
        assert st == 200 and "html" in ct
        assert "<svg" in body and "t_http_page" in body

    def test_plain_text_mentions_series(self):
        from brpc_tpu.builtin.services import vars_service

        Adder("t_http_txt").put(2)
        global_series().tick()
        st, ct, body = vars_service(None, _Http("/vars/t_http_txt"))
        assert st == 200 and "text" in ct
        assert "t_http_txt : 2" in body
        assert "series" in body

    def test_watch_builtin_text_and_json(self):
        from brpc_tpu.builtin.services import watch_service
        from brpc_tpu.metrics.watch import global_watch

        rule = WatchRule("t_watch_http", "t_nope", "threshold", ">", 1)
        global_watch().add(rule)
        try:
            st, ct, body = watch_service(None, _Http("/watch"))
            assert st == 200 and "t_watch_http" in body
            st, ct, body = watch_service(
                None, _Http("/watch", {"format": "json"}))
            doc = json.loads(body)
            mine = [r for r in doc["rules"] if r["name"] == "t_watch_http"]
            assert mine and mine[0]["state"] == STATE_NO_DATA
            assert mine[0]["var"] == "t_nope"
        finally:
            global_watch().remove("t_watch_http")


# ------------------------------------------------------------- fleet merge
class TestFleetMergeUnit:
    def _snap(self, index, vars_):
        return json.dumps({"index": index, "vars": vars_}).encode()

    def test_sum_max_and_worker_namespacing(self):
        from brpc_tpu.metrics.variable import get_exposed
        from brpc_tpu.shard.fleet import FleetVars

        fv = FleetVars()
        try:
            fv.on_snapshot(0, self._snap(0, {
                "g_reqs": ["sum", "counter", 7],
                "peak": ["max", "gauge", 10]}))
            fv.on_snapshot(1, self._snap(1, {
                "g_reqs": ["sum", "counter", 5],
                "peak": ["max", "gauge", 30]}))
            assert get_exposed("fleet_g_reqs").get_value() == 12
            assert get_exposed("fleet_peak").get_value() == 30
            assert get_exposed("worker0_g_reqs").get_value() == 7
            assert get_exposed("worker1_g_reqs").get_value() == 5
            assert get_exposed("fleet_shard_workers").get_value() == 2
            # fleet == sum of per-worker vars for Adder-backed counters
            assert get_exposed("fleet_g_reqs").get_value() == \
                get_exposed("worker0_g_reqs").get_value() + \
                get_exposed("worker1_g_reqs").get_value()
        finally:
            fv.hide_all()

    def test_latency_merges_qps_weighted(self):
        from brpc_tpu.metrics.variable import get_exposed
        from brpc_tpu.shard.fleet import FleetVars

        fv = FleetVars()
        try:
            fv.on_snapshot(0, self._snap(0, {
                "m_latency": ["wavg_qps", "gauge", 100],
                "m_qps": ["sum", "gauge", 30]}))
            fv.on_snapshot(1, self._snap(1, {
                "m_latency": ["wavg_qps", "gauge", 200],
                "m_qps": ["sum", "gauge", 10]}))
            # (100*30 + 200*10) / 40 = 125
            assert get_exposed("fleet_m_latency").get_value() == \
                pytest.approx(125.0)
            assert get_exposed("fleet_m_qps").get_value() == 40
        finally:
            fv.hide_all()

    def test_worker_vars_opted_out_of_series(self):
        from brpc_tpu.shard.fleet import FleetVars

        fv = FleetVars()
        try:
            fv.on_snapshot(0, self._snap(0, {"g_x": ["sum", "counter", 1]}))
            reg = SeriesRegistry()
            reg.tick()
            assert "worker0_g_x" not in reg.names()   # high-cardinality
            assert "fleet_g_x" in reg.names()          # aggregate keeps series
        finally:
            fv.hide_all()

    def test_fleet_vars_carry_help_and_merge_op_derivation(self):
        from brpc_tpu.metrics.variable import get_exposed
        from brpc_tpu.shard.fleet import FleetVars, _merge_op

        fv = FleetVars()
        try:
            fv.on_snapshot(0, self._snap(0, {"g_x": ["sum", "counter", 1]}))
            var = get_exposed("fleet_g_x")
            assert "W_VARS" in var.prometheus_help
            assert var.prometheus_type == "counter"
        finally:
            fv.hide_all()
        a = Adder()
        assert _merge_op("g_anything", a) == "sum"
        assert _merge_op("x_latency", Status(0)) == "wavg_qps"
        assert _merge_op("x_latency_p99", Status(0)) == "max"
        assert _merge_op("x_max_latency", Status(0)) == "max"
        assert _merge_op("x_qps", Status(0)) == "sum"

    def test_malformed_snapshot_ignored(self):
        from brpc_tpu.shard.fleet import FleetVars

        fv = FleetVars()
        try:
            fv.on_snapshot(0, b"not json")
            fv.on_snapshot(0, b'{"index": 0, "vars": {"x": "bad"}}')
            assert fv.workers_reporting() <= 1
        finally:
            fv.hide_all()

    def test_worker_snapshot_numeric_only(self):
        from brpc_tpu.shard.fleet import worker_snapshot

        Adder("t_fleet_counter").put(3)
        Status("text").expose("t_fleet_text")
        doc = json.loads(worker_snapshot(4).decode())
        assert doc["index"] == 4
        assert doc["vars"]["t_fleet_counter"] == ["sum", "counter", 3]
        assert "t_fleet_text" not in doc["vars"]


# ------------------------------------------------- prometheus round-trip
def _parse_exposition(text):
    """A deliberately real scrape parse: TYPE/HELP comments + samples."""
    types, helps, samples = {}, {}, {}
    for line in text.splitlines():
        if not line or line.isspace():
            continue
        if line.startswith("# TYPE "):
            _, _, rest = line.partition("# TYPE ")
            name, _, mtype = rest.partition(" ")
            assert mtype in ("gauge", "counter"), line
            types[name] = mtype
        elif line.startswith("# HELP "):
            _, _, rest = line.partition("# HELP ")
            name, _, h = rest.partition(" ")
            helps[name] = h
        elif line.startswith("#"):
            raise AssertionError(f"unknown comment: {line}")
        else:
            name_part, _, value = line.rpartition(" ")
            name = name_part.partition("{")[0]
            samples[name] = float(value)
    return types, helps, samples


class TestPrometheusRoundTrip:
    def test_window_persecond_passive_are_gauges(self):
        from brpc_tpu.metrics import PerSecond, SamplerCollector, Window

        coll = SamplerCollector(interval_s=3600)
        a = Adder("t_prom_total")
        win = Window(a, window_size=10, collector=coll)
        win.expose("t_prom_window")
        ps = PerSecond(a, window_size=10, collector=coll)
        ps.expose("t_prom_qps")
        PassiveStatus(lambda: 5).expose("t_prom_passive")
        a.put(3)
        coll.tick_all()
        types, _helps, samples = _parse_exposition(prometheus_text())
        assert types["t_prom_total"] == "counter"
        assert types["t_prom_window"] == "gauge"
        assert types["t_prom_qps"] == "gauge"
        assert types["t_prom_passive"] == "gauge"
        assert samples["t_prom_total"] == 3.0

    def test_latency_recorder_count_is_counter_rest_gauge(self):
        from brpc_tpu.metrics import LatencyRecorder

        rec = LatencyRecorder(window_size=10)
        rec.expose("t_prom_m")
        rec.record(100)
        types, _helps, _samples = _parse_exposition(prometheus_text())
        assert types["t_prom_m_count"] == "counter"
        assert types["t_prom_m_latency"] == "gauge"
        assert types["t_prom_m_qps"] == "gauge"
        assert types["t_prom_m_max_latency"] == "gauge"

    def test_fleet_vars_round_trip_with_help(self):
        from brpc_tpu.shard.fleet import FleetVars

        fv = FleetVars()
        try:
            fv.on_snapshot(0, json.dumps({
                "index": 0,
                "vars": {"g_fleet_rt": ["sum", "counter", 2]}}).encode())
            fv.on_snapshot(1, json.dumps({
                "index": 1,
                "vars": {"g_fleet_rt": ["sum", "counter", 3]}}).encode())
            types, helps, samples = _parse_exposition(prometheus_text())
            assert types["fleet_g_fleet_rt"] == "counter"
            assert "W_VARS merge" in helps["fleet_g_fleet_rt"]
            assert samples["fleet_g_fleet_rt"] == 5.0
            assert samples["worker0_g_fleet_rt"] == 2.0
            assert types["fleet_shard_workers"] == "gauge"
        finally:
            fv.hide_all()

    def test_cluster_vars_round_trip_with_merge_help(self):
        from brpc_tpu.fleet import FleetObserver

        def fetch(addr, path):
            if path != "/vars?series=json":
                return {"engines": [], "rules": []}
            n = 2 if addr == "a:1" else 3
            return {"workers": 0, "series": {},
                    "vars": {"g_cluster_rt": ["sum", "counter", n]}}

        obs = FleetObserver("list://a:1,b:2", fetch=fetch)
        try:
            assert obs.scrape_once() == 2
            types, helps, samples = _parse_exposition(prometheus_text())
            assert types["cluster_g_cluster_rt"] == "counter"
            assert "sum" in helps["cluster_g_cluster_rt"]
            assert samples["cluster_g_cluster_rt"] == 5.0
            assert types["cluster_fleet_members_live"] == "gauge"
            assert samples["cluster_fleet_members_live"] == 2.0
        finally:
            obs.hide_all()


# ------------------------------------------------------- vars_view smoke
class TestVarsViewTool:
    def test_render_from_dump(self, capsys):
        import importlib

        vars_view = importlib.import_module("tools.vars_view")
        s = VarSeries()
        for i in range(10):
            s.append(i)
        doc = {"workers": 2, "series": {"qps_a": s.to_dict()}}
        out = vars_view.render(doc, "*", "second")
        assert "qps_a" in out
        assert "workers=2" in out
        assert "min=0" in out and "last=9" in out
        # sparkline uses the unicode ramp
        assert any(ch in out for ch in "▁▂▃▄▅▆▇█")

    def test_main_reads_file(self, tmp_path, capsys):
        import importlib

        vars_view = importlib.import_module("tools.vars_view")
        s = VarSeries()
        s.append(4)
        p = tmp_path / "snap.json"
        p.write_text(json.dumps({"series": {"x": s.to_dict()}}))
        assert vars_view.main([str(p), "--name", "x"]) == 0
        out = capsys.readouterr().out
        assert "x" in out and "last=4" in out

    def test_no_match(self, tmp_path):
        import importlib

        vars_view = importlib.import_module("tools.vars_view")
        assert "no vars match" in vars_view.render({"series": {}}, "*",
                                                   "second")

    def test_render_fleet_merges_op_correctly(self):
        import importlib

        vars_view = importlib.import_module("tools.vars_view")

        def member_doc(values, op="sum", ptype="counter"):
            s = VarSeries()
            for v in values:
                s.append(v)
            return {"series": {"g_reqs": s.to_dict()},
                    "vars": {"g_reqs": [op, ptype, values[-1]]}}

        docs = {"hosta:1": member_doc([1, 2, 3]),
                "hostb:2": member_doc([10, 20, 30])}
        out = vars_view.render_fleet(docs, "g_reqs", "second")
        assert "hosta:1" in out and "hostb:2" in out
        assert "[sum]" in out
        # merged row: element-wise sum, so last = 3 + 30
        assert "=merged" in out
        assert "last=33" in out

    def test_render_fleet_max_op(self):
        import importlib

        vars_view = importlib.import_module("tools.vars_view")
        mk = lambda v: {"series": {"p99": dict(VarSeries().to_dict(),
                                               second=[v], last=v)},
                        "vars": {"p99": ["max", "gauge", v]}}
        out = vars_view.render_fleet({"a:1": mk(900.0), "b:2": mk(100.0)},
                                     "p99", "second")
        assert "[max]" in out
        assert "last=900" in out


# ----------------------------------------------------------- workers=2 e2e
@pytest.mark.slow
class TestFleetE2E:
    def test_w_vars_merge_and_series(self, shard_flags):
        """The ISSUE 12 acceptance path: 2 shard workers ship W_VARS
        snapshots; the parent's fleet aggregates are op-correct and the
        per-method qps var accumulates >=30 one-second series samples
        (ticks driven manually — count-based rollups need no wall clock)."""
        from brpc_tpu.metrics import global_collector
        from brpc_tpu.metrics.variable import get_exposed
        from tests.test_shard import _echo_server, _stub_for
        from brpc_tpu.proto import echo_pb2

        srv = _echo_server()
        try:
            assert srv._shard_plane.wait_ready(15.0)
            stub = _stub_for(srv)
            for i in range(40):
                req = echo_pb2.EchoRequest(message=f"fleet-{i}")
                resp = stub.Echo(req)
                assert resp.message == f"fleet-{i}"
            # wait for both workers' W_VARS snapshots to land
            deadline = time.monotonic() + 15.0
            count_name = "fleet_rpc_method_echoservice_echo_count"
            while time.monotonic() < deadline:
                fleet_count = get_exposed(count_name)
                if (srv._shard_plane.fleet.workers_reporting() == 2
                        and fleet_count is not None
                        and fleet_count.get_value() >= 40):
                    break
                time.sleep(0.1)
            assert srv._shard_plane.fleet.workers_reporting() == 2
            w0 = get_exposed("worker0_rpc_method_echoservice_echo_count")
            w1 = get_exposed("worker1_rpc_method_echoservice_echo_count")
            fleet = get_exposed(count_name)
            assert fleet is not None and w0 is not None and w1 is not None
            assert fleet.get_value() == w0.get_value() + w1.get_value()
            assert fleet.get_value() >= 40
            # per-method qps visible fleet-wide
            assert get_exposed(
                "fleet_rpc_method_echoservice_echo_qps") is not None
            # >=30 one-second series samples for a per-method qps var via
            # the parent's sampler tick (manual — no 30 s of wall clock)
            for _ in range(31):
                global_collector().tick_all()
            from brpc_tpu.builtin.services import vars_service

            st, _, body = vars_service(
                srv, _Http("/vars", {
                    "series": "json",
                    "name": "fleet_rpc_method_*_qps"}))
            doc = json.loads(body)
            assert doc["workers"] == 2
            qps_series = doc["series"][
                "fleet_rpc_method_echoservice_echo_qps"]
            assert qps_series["count"] >= 30
            # workerN_* mirrors stay out of the series plane (opt-out)
            assert not [n for n in doc["series"] if n.startswith("worker")]
        finally:
            srv.stop()
            srv.join()

    def test_seeded_deadline_spike_flips_watch_rule(self, shard_flags):
        """Acceptance: a seeded deadline-expiry spike flips the pre-wired
        rule to firing on /watch, then back to ok once the window drains."""
        from brpc_tpu.builtin.services import watch_service
        from brpc_tpu.metrics import global_collector
        from brpc_tpu.metrics.watch import global_watch
        from brpc_tpu.rpc import server_processing as sp
        from tests.test_shard import _echo_server

        srv = _echo_server()   # Server.start installs the default rules
        try:
            rule = {r.name: r for r in global_watch().rules()}[
                "deadline_expiry_rate"]
            # the autouse registry clean may have hidden the module Adder's
            # wrapper; re-expose so the series sweep sees it again
            if sp.g_server_deadline_expired._var.name is None:
                sp.g_server_deadline_expired._var.expose(
                    "g_server_deadline_expired")

            def state_on_watch():
                _, _, body = watch_service(
                    srv, _Http("/watch", {"format": "json"}))
                rules = json.loads(body)["rules"]
                return {r["name"]: r["state"] for r in rules}[
                    "deadline_expiry_rate"]

            for _ in range(3):
                global_collector().tick_all()     # baseline samples
            # seed the spike: way past 0.5 expiries/s over the 10 s window
            for _ in range(rule.for_ticks + 1):
                sp.g_server_deadline_expired.put(50)
                global_collector().tick_all()
            assert rule.state == STATE_FIRING
            assert state_on_watch() == STATE_FIRING
            # drain: rate falls back to 0 once the spike leaves the window
            for _ in range(rule.window_s + rule.clear_ticks + 2):
                global_collector().tick_all()
            assert rule.state == STATE_OK
            assert state_on_watch() == STATE_OK
        finally:
            srv.stop()
            srv.join()
