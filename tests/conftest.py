"""Test substrate: a virtual 8-device CPU mesh (SURVEY §4 takeaway).

The reference tests simulate a cluster with N channels to loopback servers;
we likewise simulate a TPU pod with 8 virtual CPU devices.

The axon sitecustomize registers the real-TPU PJRT plugin at interpreter
start and forces jax_platforms='axon,...' via jax.config — env vars set here
are too late. Backend *initialization* is lazy though, so overriding the
config before any jax.devices() call still wins.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ["JAX_PLATFORMS"] = "cpu"

try:
    import jax  # noqa: E402

    jax.config.update("jax_platforms", "cpu")
except ImportError:  # non-jax environments still run the pure-RPC tests
    pass

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))


def pytest_configure(config):
    config.addinivalue_line(
        "markers", "slow: long-running tests excluded from the tier-1 run")
    config.addinivalue_line(
        "markers", "chaos: fault-injection tests (in the tier-1 budget)")


import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _brpc_tpu_check_ledger():
    """With BRPC_TPU_CHECK=1 in the environment, assert at session exit
    that every tracked credit window is whole and no borrowed block view
    is still alive. A no-op in normal runs."""
    yield
    from brpc_tpu.analysis import runtime_check as _rc

    if not _rc.ACTIVE:
        return
    try:
        from brpc_tpu.tpu.transport import _sweep_deferred_pools as _drain
    except Exception:
        _drain = None
    _rc.ledger.assert_balanced(drain=_drain)
