"""Test substrate: a virtual 8-device CPU mesh (SURVEY §4 takeaway).

The reference tests simulate a cluster with N channels to loopback servers;
we likewise simulate a TPU pod with 8 virtual CPU devices via
--xla_force_host_platform_device_count, set before jax is imported anywhere.
"""

import os
import sys

flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()
os.environ.setdefault("JAX_PLATFORMS", "cpu")

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
