"""Profiler builtin tests — /hotspots/*, /pprof/*, /vlog (reference
builtin/hotspots_service + pprof_service + vlog_service)."""

import logging

import pytest

from brpc_tpu.policy.http_protocol import http_fetch
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, ChannelOptions, Server, Service, Stub


class Echo(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message)


@pytest.fixture()
def server():
    srv = Server().add_service(Echo()).start("127.0.0.1:0")
    yield srv
    srv.stop()
    srv.join(timeout=2)


class TestProfiling:
    def test_cpu_profile(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots/cpu?seconds=0.2", timeout=10)
        assert r.status == 200
        assert b"cumulative" in r.body

    def test_heap_snapshot_and_growth(self, server):
        ep = str(server.listen_endpoint())
        http_fetch(ep, path="/hotspots/heap")  # may just start tracing
        r = http_fetch(ep, path="/hotspots/heap")
        assert r.status == 200 and b"allocation sites" in r.body
        http_fetch(ep, path="/hotspots/growth")
        # allocate between the two growth snapshots
        blob = [bytearray(1024) for _ in range(100)]
        r = http_fetch(ep, path="/hotspots/growth")
        assert r.status == 200 and b"growth since" in r.body
        del blob

    def test_contention_endpoint(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots/contention")
        assert r.status == 200 and b"contention" in r.body

    def test_hotspots_index(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots")
        assert b"/hotspots/cpu" in r.body
        assert b"/hotspots/flame" in r.body

    def test_flame_view(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots/flame?seconds=0.3", timeout=10)
        assert r.status == 200
        assert b"samples over" in r.body
        assert b'class="f"' in r.body  # nested frame divs rendered

    def test_pprof_endpoints(self, server):
        ep = str(server.listen_endpoint())
        stub = Stub(Channel(ChannelOptions()).init(ep), Echo.DESCRIPTOR)
        for _ in range(10):
            stub.Echo(echo_pb2.EchoRequest(message="load"))
        r = http_fetch(ep, path="/pprof/profile?seconds=0.2", timeout=10)
        assert r.status == 200
        assert b";" in r.body or b" " in r.body  # collapsed stacks
        assert b"num_symbols" in http_fetch(ep, path="/pprof/symbol").body
        assert http_fetch(ep, path="/pprof/cmdline").status == 200
        assert http_fetch(ep, path="/pprof/nope").status == 404

    def test_vlog_list_and_set(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/vlog")
        assert r.status == 200 and b"loggers" in r.body
        r = http_fetch(ep, path="/vlog?logger=brpc_tpu.test&level=DEBUG")
        assert b"DEBUG" in r.body
        assert logging.getLogger("brpc_tpu.test").level == logging.DEBUG
        r = http_fetch(ep, path="/vlog?logger=brpc_tpu.test&level=BOGUS")
        assert r.status == 400

    def test_contention_records_real_waits(self, server):
        from brpc_tpu.fiber.butex import Butex, contention_stats
        import threading
        import time

        bx = Butex(0, site="test.site")

        def waiter():
            bx.wait(0, timeout=2)

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        bx.wake(1)
        t.join()
        rows = {site: (w, ns) for site, w, ns in contention_stats()}
        assert "test.site" in rows
        waits, wait_ns = rows["test.site"]
        assert waits >= 1 and wait_ns > 0
