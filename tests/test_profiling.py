"""Profiler tests — the whole-process sampler (/hotspots/*, /pprof/*),
phase attribution, the continuous ring, contention waiter stacks, the
folded differ, flame_view SVG rendering, and /vlog (reference
builtin/hotspots_service + pprof_service + vlog_service)."""

import logging
import os
import sys
import threading
import time

import pytest

from brpc_tpu import flags as _flags
from brpc_tpu.policy.http_protocol import http_fetch
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Channel, ChannelOptions, Server, Service, Stub

TOOLS = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "tools")


class Echo(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message)


@pytest.fixture()
def server():
    srv = Server().add_service(Echo()).start("127.0.0.1:0")
    yield srv
    srv.stop()
    srv.join(timeout=2)


def _hot_spin(stop_ev):
    """The known-hot function: pure-python arithmetic, no wait leaves."""
    x = 1
    while not stop_ev.is_set():
        for i in range(2000):
            x = (x * 31 + i) % 1000003
    return x


@pytest.fixture()
def busy_thread():
    stop = threading.Event()
    t = threading.Thread(target=_hot_spin, args=(stop,),
                         name="test-busy-spin")
    t.start()
    yield t
    stop.set()
    t.join(timeout=5)


class TestSamplerDominance:
    """The acceptance check: a busy worker thread dominates the sampler's
    cpu-classified output — and cProfile provably misses it.

    Threads leaked by OTHER test modules parked in C-level socket reads
    have no Python wait leaf and classify as on-cpu, so both tests take a
    baseline profile before the spin starts and discount those leaves."""

    def test_busy_worker_dominates_cpu_samples(self):
        from brpc_tpu.profiling.sampler import run_profile

        noise = {f for f, _ in run_profile(0.25, hz=100.0, budget=False)
                 .top_self(100, cpu_only=True)}
        stop = threading.Event()
        t = threading.Thread(target=_hot_spin, args=(stop,),
                             name="test-busy-spin")
        t.start()
        try:
            prof = run_profile(0.5, hz=200.0, budget=False)
        finally:
            stop.set()
            t.join(timeout=5)
        top = dict(prof.top_self(100, cpu_only=True))
        hot = sum(n for f, n in top.items()
                  if f.endswith("test_profiling.py:_hot_spin"))
        denom = sum(n for f, n in top.items() if f not in noise)
        assert hot > 20  # the spin thread must actually be sampled
        assert hot >= 0.8 * denom, sorted(top.items(), key=lambda kv:
                                          -kv[1])[:5]

    def test_hotspots_cpu_attributes_hot_function(self, server):
        import json as _json

        ep = str(server.listen_endpoint())

        def fetch():
            r = http_fetch(ep,
                           path="/hotspots/cpu?seconds=0.5&format=json",
                           timeout=10)
            assert r.status == 200
            return _json.loads(r.body)

        noise = {f for f, _ in fetch()["top_self_cpu"]}
        stop = threading.Event()
        t = threading.Thread(target=_hot_spin, args=(stop,),
                             name="test-busy-spin")
        t.start()
        try:
            d = fetch()
        finally:
            stop.set()
            t.join(timeout=5)
        assert d["samples"] > 0 and d["cpu_samples"] > 0
        hot = sum(n for f, n in d["top_self_cpu"]
                  if f.endswith("test_profiling.py:_hot_spin"))
        denom = sum(n for f, n in d["top_self_cpu"] if f not in noise)
        assert hot > 20, d["top_self_cpu"][:5]
        assert hot >= 0.8 * denom, d["top_self_cpu"][:5]

    def test_cprofile_engine_misses_other_threads(self, server,
                                                  busy_thread):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots/cpu?seconds=0.3&engine=cprofile",
                       timeout=10)
        assert r.status == 200
        # the legacy engine instruments ONLY the handler thread (which
        # sleeps) — the spinning thread is invisible, and the output says so
        assert b"_hot_spin" not in r.body
        assert b"calling thread ONLY" in r.body
        assert b"cumulative" in r.body


class TestPhaseAttribution:
    def test_phases_on_live_tpu_echo(self):
        """Span phases stamped by the server datapath show up keyed in the
        sampler aggregate during a live tpu:// echo run."""
        from brpc_tpu.profiling.sampler import ProfileSession

        srv = Server().add_service(Echo()).start("tpu://127.0.0.1:0/0")
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=30000))
            ch.init(str(srv.listen_endpoint()))
            stub = Stub(ch, Echo.DESCRIPTOR)
            stub.Echo(echo_pb2.EchoRequest(message="warm"))
            sess = ProfileSession(hz=400.0, budget=False).start()
            deadline = time.monotonic() + 1.5
            while time.monotonic() < deadline:
                stub.Echo(echo_pb2.EchoRequest(message="x" * 512))
            prof = sess.stop()
        finally:
            srv.stop()
            srv.join(timeout=2)
        phases = set(prof.by_phase()) - {"-"}
        known = {"parse", "execute", "respond", "send", "credit_wait"}
        assert phases <= known | phases  # sanity: by_phase returns strings
        assert len(phases & known) >= 2, (
            f"expected >=2 marked phases in {sorted(phases)}")

    def test_folded_lines_carry_role_and_phase_roots(self):
        from brpc_tpu.profiling.sampler import FoldedProfile

        prof = FoldedProfile(hz=100.0)
        prof.add("worker", "execute", ("a.py:f", "b.py:g"), 3)
        lines = prof.folded_lines()
        assert lines == ["role=worker;phase=execute;a.py:f;b.py:g 3"]
        assert prof.folded_lines(tag_role=False, tag_phase=False) == \
            ["a.py:f;b.py:g 3"]


class TestContinuousRing:
    def test_ring_retention_and_eviction(self):
        """A dedicated ContinuousProfiler honors the (reloadable) window
        and ring-capacity flags: more windows than capacity are produced,
        only the newest `cap` are retained."""
        from brpc_tpu.profiling.sampler import ContinuousProfiler

        _flags.set_flag("collector_max_samples_per_second", "100000")
        from brpc_tpu.metrics.collector import global_collector
        global_collector()._deny_until = 0.0
        _flags.set_flag("tpu_prof_continuous_hz", "100")
        _flags.set_flag("tpu_prof_window_s", "0.15")
        _flags.set_flag("tpu_prof_ring_windows", "3")
        cont = ContinuousProfiler()
        t0 = time.monotonic()
        cont.start()
        try:
            time.sleep(1.2)
            wins = cont.windows()
            produced = (time.monotonic() - t0) / 0.15
            assert produced > 4  # enough windows elapsed to force eviction
            assert 1 <= len(wins) <= 3
            # retained windows are the NEWEST ones: oldest retained window
            # started well after the profiler itself did
            assert wins[0].start_ts > time.time() - 1.0
            assert all(w.ticks > 0 for w in wins)
            merged = cont.query(None, None)
            assert merged.ticks == sum(w.ticks for w in wins)
            # a range before every window merges nothing
            empty = cont.query(time.time() - 3600, time.time() - 1800)
            assert empty.samples == 0
        finally:
            cont.stop()
            cont.join(timeout=5)
            _flags.set_flag("tpu_prof_continuous_hz", "5")
            _flags.set_flag("tpu_prof_window_s", "15")
            _flags.set_flag("tpu_prof_ring_windows", "24")
            _flags.set_flag("collector_max_samples_per_second", "1000")

    def test_continuous_endpoint_lists_ring(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots/continuous", timeout=10)
        assert r.status == 200
        assert b"continuous profiler ring" in r.body
        r = http_fetch(ep, path="/hotspots/continuous?from=-60&to=0",
                       timeout=10)
        assert r.status == 200


class TestContentionStacks:
    def test_waiter_stacks_under_seized_lock(self, server):
        """Threads blocked on a seized TrackedLock leave sampled waiter
        STACKS (not just wait totals) at the site, and the /hotspots/
        contention endpoint renders them."""
        from brpc_tpu.analysis.runtime_check import TrackedLock
        from brpc_tpu.fiber import butex

        _flags.set_flag("collector_max_samples_per_second", "100000")
        from brpc_tpu.metrics.collector import global_collector
        global_collector()._deny_until = 0.0
        lk = TrackedLock("test.seized", threading.Lock())
        try:
            lk.acquire()

            def waiter():
                lk.acquire()
                lk.release()

            ts = [threading.Thread(target=waiter, name=f"test-waiter-{i}")
                  for i in range(3)]
            for t in ts:
                t.start()
            time.sleep(0.15)
            lk.release()
            for t in ts:
                t.join(timeout=5)
            stacks = butex.contention_stacks()
            assert "lock:test.seized" in stacks
            folded, waits, wait_ns = stacks["lock:test.seized"][0]
            assert "test_profiling.py:waiter" in folded
            assert waits >= 1 and wait_ns > 0
            ep = str(server.listen_endpoint())
            r = http_fetch(ep, path="/hotspots/contention", timeout=10)
            assert r.status == 200
            assert b"lock:test.seized" in r.body
            assert b"stack x" in r.body
        finally:
            _flags.set_flag("collector_max_samples_per_second", "1000")

    def test_contention_records_real_waits(self, server):
        from brpc_tpu.fiber.butex import Butex, contention_stats

        bx = Butex(0, site="test.site")

        def waiter():
            bx.wait(0, timeout=2)

        t = threading.Thread(target=waiter, name="test-butex-waiter")
        t.start()
        time.sleep(0.05)
        bx.wake(1)
        t.join()
        rows = {site: (w, ns) for site, w, ns in contention_stats()}
        assert "test.site" in rows
        waits, wait_ns = rows["test.site"]
        assert waits >= 1 and wait_ns > 0


class TestDiff:
    BASE = "role=w;phase=-;a.py:f;b.py:g 90\nrole=w;phase=-;a.py:f;c.py:h 10\n"
    NEW = "role=w;phase=-;a.py:f;b.py:g 50\nrole=w;phase=-;a.py:f;c.py:h 50\n"

    def test_self_movers_and_threshold(self):
        from brpc_tpu.profiling import diff as d

        rep = d.diff_folded(self.BASE, self.NEW, min_delta_pct=5.0)
        movers = {m["frame"]: m["delta_pct"] for m in rep["movers"]}
        assert movers["c.py:h"] == pytest.approx(40.0)
        assert movers["b.py:g"] == pytest.approx(-40.0)
        # below-threshold movers disappear entirely
        rep = d.diff_folded(self.BASE, self.NEW, min_delta_pct=45.0)
        assert rep["movers"] == []
        # a non-leaf frame never moves in self mode, but does in total mode
        assert "a.py:f" not in movers
        rep = d.diff_folded(
            "a.py:f;b.py:g 100", "c.py:h;b.py:g 100",
            min_delta_pct=5.0, mode="total")
        total_movers = {m["frame"] for m in rep["movers"]}
        assert {"a.py:f", "c.py:h"} <= total_movers

    def test_top_truncation_reports_suppressed(self):
        from brpc_tpu.profiling import diff as d

        base = "\n".join(f"f{i}.py:x 1" for i in range(30)) + "\nz.py:z 70"
        rep = d.diff_folded(base, "z.py:z 100", top=5, min_delta_pct=0.1)
        assert len(rep["movers"]) == 5
        assert rep["suppressed"] > 0
        assert "truncated" in d.render_text(rep)

    def test_prof_diff_cli_gate(self, tmp_path):
        sys.path.insert(0, TOOLS)
        try:
            import prof_diff
        finally:
            sys.path.remove(TOOLS)
        base = tmp_path / "base.folded"
        new = tmp_path / "new.folded"
        base.write_text(self.BASE)
        new.write_text(self.NEW)
        assert prof_diff.main([str(base), str(new)]) == 0
        assert prof_diff.main([str(base), str(new),
                               "--fail-above-pct", "10"]) == 1
        assert prof_diff.main([str(base), str(new),
                               "--fail-above-pct", "90"]) == 0
        assert prof_diff.main([str(tmp_path / "missing.folded"),
                               str(new)]) == 2


class TestFlameView:
    FOLDED = ("role=w;phase=execute;main.py:run;hot.py:spin 80\n"
              "role=w;phase=-;main.py:run;idle.py:park 20\n")

    def test_render_svg(self):
        sys.path.insert(0, TOOLS)
        try:
            import flame_view
        finally:
            sys.path.remove(TOOLS)
        counts = flame_view.parse_folded(self.FOLDED)
        assert sum(counts.values()) == 100
        svg = flame_view.render_svg(counts, width=800, title="t")
        assert svg.startswith("<svg")
        assert "hot.py:spin" in svg
        assert "80 samples" in svg
        # same frame renders the same color across runs (diff stability)
        assert flame_view._color("hot.py:spin") == \
            flame_view._color("hot.py:spin")

    def test_cli_smoke(self, tmp_path, capsys):
        sys.path.insert(0, TOOLS)
        try:
            import flame_view
        finally:
            sys.path.remove(TOOLS)
        src = tmp_path / "p.folded"
        out = tmp_path / "p.svg"
        src.write_text(self.FOLDED)
        assert flame_view.main([str(src), "-o", str(out)]) == 0
        assert out.read_text().startswith("<svg")
        assert "2 unique stacks, 100 samples" in capsys.readouterr().out
        assert flame_view.main([str(tmp_path / "empty"), "-o",
                                str(out)]) == 2


class TestProfiling:
    def test_cpu_profile_sampler_default(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots/cpu?seconds=0.2", timeout=10)
        assert r.status == 200
        assert b"whole process, all threads" in r.body
        assert b"by role (wall samples)" in r.body
        assert b"folded stacks" in r.body
        r = http_fetch(ep, path="/hotspots/cpu?seconds=0.2&format=folded",
                       timeout=10)
        assert r.status == 200
        assert b"role=" in r.body and b"phase=" in r.body

    def test_concurrent_profile_runs_rejected(self, server):
        ep = str(server.listen_endpoint())
        results = []

        def long_run():
            results.append(http_fetch(
                ep, path="/hotspots/cpu?seconds=1.2", timeout=15))

        t = threading.Thread(target=long_run, name="test-prof-long")
        t.start()
        time.sleep(0.3)
        r = http_fetch(ep, path="/hotspots/cpu?seconds=0.1", timeout=10)
        t.join(timeout=15)
        assert r.status == 503
        assert b"another profile is running" in r.body
        assert results and results[0].status == 200

    def test_heap_snapshot_and_growth(self, server):
        ep = str(server.listen_endpoint())
        http_fetch(ep, path="/hotspots/heap")  # may just start tracing
        r = http_fetch(ep, path="/hotspots/heap")
        assert r.status == 200 and b"allocation sites" in r.body
        http_fetch(ep, path="/hotspots/growth")
        # allocate between the two growth snapshots
        blob = [bytearray(1024) for _ in range(100)]
        r = http_fetch(ep, path="/hotspots/growth")
        assert r.status == 200 and b"growth since" in r.body
        del blob

    def test_contention_endpoint(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots/contention")
        assert r.status == 200 and b"contention" in r.body

    def test_hotspots_index(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots")
        assert b"/hotspots/cpu" in r.body
        assert b"/hotspots/flame" in r.body
        assert b"/hotspots/continuous" in r.body

    def test_flame_view(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/hotspots/flame?seconds=0.3", timeout=10)
        assert r.status == 200
        assert b"samples over" in r.body
        assert b'class="f"' in r.body  # nested frame divs rendered

    def test_pprof_endpoints(self, server):
        ep = str(server.listen_endpoint())
        stub = Stub(Channel(ChannelOptions()).init(ep), Echo.DESCRIPTOR)
        for _ in range(10):
            stub.Echo(echo_pb2.EchoRequest(message="load"))
        r = http_fetch(ep, path="/pprof/profile?seconds=0.2", timeout=10)
        assert r.status == 200
        assert b";" in r.body or b" " in r.body  # collapsed stacks
        r = http_fetch(ep, path="/pprof/profile?seconds=0.2&engine=cprofile",
                       timeout=10)
        assert r.status == 200
        assert b"instruments ONLY the thread" in r.body
        assert b"num_symbols" in http_fetch(ep, path="/pprof/symbol").body
        assert http_fetch(ep, path="/pprof/cmdline").status == 200
        assert http_fetch(ep, path="/pprof/nope").status == 404

    def test_status_vitals_and_prof_vars(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/status")
        assert r.status == 200
        for needle in (b"rss_kb:", b"threads:", b"tracemalloc:",
                       b"continuous_profiler:", b"/hotspots/cpu"):
            assert needle in r.body, needle
        from brpc_tpu.metrics.variable import get_exposed
        from brpc_tpu.profiling import sampler as _sampler

        # earlier tests may clear_registry(); re-expose the import-time
        # Adders so the /vars contract stays checkable
        for name in ("g_prof_samples", "g_prof_dropped",
                     "g_prof_overruns"):
            if get_exposed(name) is None:
                getattr(_sampler, name).expose_as(name)
        r = http_fetch(ep, path="/vars")
        assert b"g_prof_samples" in r.body
        assert b"g_prof_dropped" in r.body
        assert b"g_prof_overruns" in r.body

    def test_vlog_list_and_set(self, server):
        ep = str(server.listen_endpoint())
        r = http_fetch(ep, path="/vlog")
        assert r.status == 200 and b"loggers" in r.body
        r = http_fetch(ep, path="/vlog?logger=brpc_tpu.test&level=DEBUG")
        assert b"DEBUG" in r.body
        assert logging.getLogger("brpc_tpu.test").level == logging.DEBUG
        r = http_fetch(ep, path="/vlog?logger=brpc_tpu.test&level=BOGUS")
        assert r.status == 400
