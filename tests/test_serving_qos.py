"""Multi-tenant QoS: weighted fair share, the closed loop, overload survival.

Five layers, cheapest first:

* the TenantScheduler as a pure ledger — stride accounting converging to
  the weight ratio, idle-share redistribution and one-step reclaim,
  per-lane queue caps, deadline death at the admission boundary, the
  protected carve-out, and best-effort-first shed ordering;
* the QosLimiter gradient — multiplicative shrink under rising queue
  wait, additive recovery gated on inflight, both clamps;
* the governor's tick against a stub engine — queued best-effort work
  shed EOVERCROWDED down to the ceiling, the protected lane untouched,
  every block back in the pool;
* identity on the wire — Controller ``tenant_id``/``priority`` through
  RequestMeta to the engine's lanes, the committed overload corpus
  carrying it, and rpc_replay's --tenant-override restamping it;
* the acceptance gate — the diurnal-overload corpus replayed at 2x the
  recorded rate: the protected tenant's p99 holds within 1.5x its
  unloaded baseline while best-effort sheds EOVERCROWDED, and the same
  wave with QoS off violates the bound.
"""

import collections
import json
import os
import threading
import time
import types

import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.rpc import errors
from brpc_tpu.serving import EngineConfig, LlmServingService, ServingEngine
from brpc_tpu.serving.qos import (DEFAULT_TENANT, QosConfig, QosLimiter,
                                  TenantScheduler)
from test_serving import _Cntl, _small_kv, _stub_engine, _StubModel

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS_OVERLOAD = os.path.join(REPO, "tests", "data",
                               "serving_corpus_overload")


def _seq(tenant, priority=0, cost=16, t_submit=None):
    """A scheduler-shaped sequence: just the fields the ledger reads."""
    return types.SimpleNamespace(
        tenant_id=tenant, priority=priority, cntl=None,
        t_submit=time.monotonic() if t_submit is None else t_submit,
        cost=cost)


def _cost(s):
    return s.cost


# ------------------------------------------------- fair share (pure ledger)
class TestFairShare:
    def _run_steps(self, sched, lanes, steps, budget):
        """Admission rounds with every listed lane kept saturated."""
        for _ in range(steps):
            for tenant, prio in lanes:
                while sched.tenant_depth(tenant) < 4:
                    assert sched.enqueue(_seq(tenant, prio)) == 0
            b = budget
            while True:
                head = sched.peek(b, _cost)
                if head is None:
                    break
                sched.commit(head, head.cost)
                b -= head.cost

    def test_equal_weights_split_tokens_evenly(self):
        sched = TenantScheduler(QosConfig(tenants={"a": 1.0, "b": 1.0}))
        self._run_steps(sched, [("a", 0), ("b", 0)], steps=100, budget=32)
        snap = sched.snapshot()["tenants"]
        total = snap["a"]["admitted_tokens"] + snap["b"]["admitted_tokens"]
        assert total == 100 * 32
        assert abs(snap["a"]["token_share"] - 0.5) <= 0.05  # <=10% skew

    def test_weighted_share_converges_to_weight_ratio(self):
        sched = TenantScheduler(QosConfig(tenants={"heavy": 3.0,
                                                   "light": 1.0}))
        self._run_steps(sched, [("heavy", 0), ("light", 0)],
                        steps=100, budget=64)
        snap = sched.snapshot()["tenants"]
        assert abs(snap["heavy"]["token_share"] - 0.75) <= 0.05

    def test_idle_share_redistributes_and_is_reclaimed_within_one_step(self):
        sched = TenantScheduler(QosConfig(tenants={"a": 1.0, "b": 1.0}))
        self._run_steps(sched, [("a", 0), ("b", 0)], steps=10, budget=32)
        # b goes idle: drain its lane, keep a saturated
        for s in list(sched.iter_waiting()):
            if s.tenant_id == "b":
                sched.drop(s)
        before = sched.snapshot()["tenants"]["a"]["admitted_tokens"]
        self._run_steps(sched, [("a", 0)], steps=10, budget=32)
        after = sched.snapshot()["tenants"]["a"]["admitted_tokens"]
        assert after - before == 10 * 32  # the idle share redistributed
        # b returns: its clamped clock competes again within ONE step —
        # no catch-up burst, but no lockout either
        assert sched.enqueue(_seq("b")) == 0
        admitted, b = [], 32
        while True:
            head = sched.peek(b, _cost)
            if head is None:
                break
            sched.commit(head, head.cost)
            b -= head.cost
            admitted.append(head.tenant_id)
        assert "b" in admitted

    def test_queue_cap_sheds_retriable_per_lane(self):
        sched = TenantScheduler(QosConfig(queue_cap=2))
        assert sched.enqueue(_seq("bulk")) == 0
        assert sched.enqueue(_seq("bulk")) == 0
        assert sched.enqueue(_seq("bulk")) == errors.EOVERCROWDED
        assert sched.snapshot()["tenants"]["bulk"]["shed"] == 1
        assert sched.enqueue(_seq("other")) == 0  # the cap is per lane

    def test_deadline_rechecked_at_admission_boundary(self):
        sched = TenantScheduler(QosConfig())
        dead = time.monotonic() - 0.1
        assert sched.admission_check("t", 0, deadline_mono=dead) \
            == errors.ERPCTIMEDOUT

    def test_protected_carveout_above_ceiling(self):
        sched = TenantScheduler(QosConfig(ceiling_start=4.0,
                                          ceiling_min=2.0,
                                          protected_priority=1))
        for _ in range(4):
            assert sched.enqueue(_seq("bulk", 0)) == 0
        # best-effort load sits at the ceiling: bulk sheds, protected rides
        assert sched.admission_check("bulk", 0) == errors.EOVERCROWDED
        assert sched.admission_check("prod", 1) == 0
        for _ in range(4):
            assert sched.enqueue(_seq("prod", 1)) == 0
        # the protected lane ALONE now exceeds the ceiling: it sheds too
        assert sched.admission_check("prod", 1) == errors.EOVERCROWDED

    def test_shed_victims_best_effort_oldest_first(self):
        sched = TenantScheduler(QosConfig(protected_priority=1))
        now = time.monotonic()
        old = _seq("bulk", 0, t_submit=now - 2.0)
        mid = _seq("bulk", 0, t_submit=now - 1.0)
        prod = _seq("prod", 1, t_submit=now - 3.0)
        for s in (prod, mid, old):
            assert sched.enqueue(s) == 0
        assert sched.shed_victims(2) == [old, mid]  # age order, p0 first
        # protected is never shed while it fits under the ceiling
        assert sched.shed_victims(5) == []
        assert sched.tenant_depth("prod") == 1


# -------------------------------------------------------- gradient limiter
class TestLimiter:
    def test_rising_wait_shrinks_multiplicatively(self):
        lim = QosLimiter(QosConfig(ceiling_start=8.0, ceiling_min=2.0))
        # first sample IS the floor: gradient 1, additive probe
        assert lim.observe(1000.0, inflight=0) == pytest.approx(9.0)
        # avg EMA 5000, min drifted to 1010 -> gradient clamps at 0.5
        assert lim.observe(9000.0, inflight=0) == pytest.approx(5.5)

    def test_floor_and_recovery_gated_by_inflight(self):
        lim = QosLimiter(QosConfig(ceiling_start=4.0, ceiling_min=2.0,
                                   ceiling_max=6.0))
        for _ in range(50):
            lim.observe(lim._avg_wait_us * 10 + 1000.0, inflight=0)
        assert lim.ceiling == pytest.approx(2.0)  # clamped at the floor
        # an empty sample under saturation is NOT evidence of headroom
        assert lim.observe(0.0, inflight=10) == pytest.approx(2.0)
        for _ in range(50):
            lim.observe(0.0, inflight=0)
        assert lim.ceiling == 6.0  # additive recovery up to the max


# ------------------------------------------------ governor (stub engine)
class TestGovernor:
    def test_tick_sheds_queued_best_effort_down_to_ceiling(self):
        qos = QosConfig(ceiling_start=8.0, ceiling_min=2.0, queue_cap=32)
        eng = _stub_engine(start=False, qos=qos)
        eng.running = True
        subs = []

        def submit(tenant, prio):
            cntl = _Cntl()
            ev = threading.Event()
            code, seq = eng.submit(eng.model.synth_prompt(4), 2,
                                   cntl=cntl, tenant_id=tenant,
                                   priority=prio,
                                   done=lambda r, e=ev: e.set())
            assert code == 0
            subs.append((cntl, ev, seq))
            return seq

        try:
            submit("prod", 1)
            bulk = [submit("bulk", 0) for _ in range(5)]
            gov = eng._qos_governor
            assert gov is not None
            assert eng.queue_depth == 6
            gov.tick(sample_us=1000.0)  # warms the floor: no shed
            assert eng.queue_depth == 6
            gov.tick(sample_us=30000.0)  # 30x the floor: shrink + shed
            ceiling = eng.qos.limiter.ceiling
            assert ceiling < 6.0
            shed = [s for (c, e, s) in subs
                    if c.code == errors.EOVERCROWDED]
            assert len(shed) == 6 - int(ceiling)
            assert all(s.tenant_id == "bulk" for s in shed)
            assert shed[0] is bulk[0]  # oldest best-effort went first
            assert subs[0][0].code == 0  # the protected request survived
            assert gov.sheds == len(shed)
            # the shed done-callbacks already fired (retriable contract)
            for (c, e, s) in subs:
                if c.code == errors.EOVERCROWDED:
                    assert e.wait(5.0)
        finally:
            eng.running = False
            eng._abort_all_locked_out(errors.ELOGOFF, "teardown")
        eng.kv.assert_idle("governor teardown")  # zero leaked KV blocks

    def test_governor_rides_the_sampler_hook_lifecycle(self):
        from brpc_tpu.metrics.series import global_series

        eng = _stub_engine(qos=QosConfig())
        try:
            assert eng._qos_governor in global_series().post_tick_hooks
        finally:
            eng.stop()
        assert eng._qos_governor not in global_series().post_tick_hooks
        eng.kv.assert_idle("hook lifecycle teardown")


# ------------------------------------------------------------------- chaos
@pytest.fixture()
def fault_enabled():
    _flags.set_flag("fault_injection_enabled", True)
    yield
    fault.disarm_all()
    _flags.set_flag("fault_injection_enabled", False)


@pytest.mark.chaos
class TestQosChaos:
    def test_burst_fault_sheds_bulk_protects_prod_and_recovers(
            self, fault_enabled):
        qos = QosConfig(tenants={"prod": 8.0, "bulk": 1.0}, queue_cap=4,
                        protected_priority=1)
        eng = _stub_engine(step_s=0.002, max_batch=4, token_budget=64,
                           num_blocks=64, qos=qos)
        try:
            def prod_once():
                cntl = _Cntl()
                ev = threading.Event()
                t0 = time.monotonic()
                code, _ = eng.submit(eng.model.synth_prompt(8), 4,
                                     cntl=cntl, tenant_id="prod",
                                     priority=1,
                                     done=lambda r, e=ev: e.set())
                assert code == 0
                assert ev.wait(30)
                assert cntl.code == 0
                return time.monotonic() - t0

            unloaded = sorted(prod_once() for _ in range(8))[-1]

            # each real bulk submit fans out 7 synthetic clones: 96
            # offered against a lane capped at 4
            fault.arm("serving.qos.burst", mode="always", factor=8,
                      match={"tenant": "bulk"})
            for _ in range(12):
                eng.submit(eng.model.synth_prompt(8), 4, tenant_id="bulk",
                           priority=0, done=lambda r: None)
            burst_p99 = sorted(prod_once() for _ in range(8))[-1]
            snap = eng.qos.snapshot()["tenants"]
            assert snap["bulk"]["shed"] > 0  # the flood shed EOVERCROWDED
            assert snap["prod"]["shed"] == 0  # the protected lane never did
            # protected p99 holds within bound under the armed burst
            assert burst_p99 <= unloaded * 4 + 0.05, (burst_p99, unloaded)

            fault.disarm_all()
            # recovery: the lane drains and a plain bulk request completes
            deadline = time.monotonic() + 30
            while (eng.queue_depth or eng.running_count) \
                    and time.monotonic() < deadline:
                time.sleep(0.005)
            cntl = _Cntl()
            ev = threading.Event()
            code, _ = eng.submit(eng.model.synth_prompt(8), 4, cntl=cntl,
                                 tenant_id="bulk", priority=0,
                                 done=lambda r, e=ev: e.set())
            assert code == 0 and ev.wait(30) and cntl.code == 0
        finally:
            eng.stop()
        eng.kv.assert_idle("post burst fault")  # zero leaked KV blocks


# ----------------------------------------------------- identity on the wire
def _serving_server(eng):
    from brpc_tpu.rpc import Server

    return Server().add_service(LlmServingService(eng)).start("127.0.0.1:0")


class TestWireIdentity:
    def test_tenant_and_priority_ride_request_meta(self):
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.rpc import Channel, ChannelOptions, Controller, Stub

        eng = _stub_engine(qos=QosConfig(tenants={"prod": 2.0}))
        server = _serving_server(eng)
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=30000))
            ch.init(str(server.listen_endpoint()))
            stub = Stub(ch, serving_pb2.DESCRIPTOR
                        .services_by_name["LlmService"])
            cntl = Controller()
            cntl.tenant_id = "prod"
            cntl.priority = 1
            resp = stub.Generate(serving_pb2.GenerateRequest(
                prompt_len=8, max_new_tokens=2), controller=cntl)
            assert not cntl.failed() and len(resp.tokens) == 2
            # no identity -> the default lane bills it
            resp = stub.Generate(serving_pb2.GenerateRequest(
                prompt_len=8, max_new_tokens=2), controller=Controller())
            assert len(resp.tokens) == 2
            snap = eng.qos.snapshot()["tenants"]
            assert snap["prod"]["admitted"] == 1
            assert snap[DEFAULT_TENANT]["admitted"] == 1
        finally:
            server.stop()
            server.join(timeout=2)
            eng.stop()
        eng.kv.assert_idle("wire identity teardown")

    def test_overload_corpus_records_identity(self):
        from tools import record_serving_corpus_overload as recorder
        from tools.rpc_replay import load_items

        items, skipped = load_items(CORPUS_OVERLOAD)
        assert skipped == 0 and len(items) == len(recorder.SCHEDULE)
        got = collections.Counter((i.tenant, i.priority) for i in items)
        want = collections.Counter(
            (t, p) for _, t, p, _, _ in recorder.SCHEDULE)
        assert got == want

    def test_replay_overrides_restamp_every_record(self, tmp_path):
        from tools import rpc_replay

        eng = _stub_engine(max_batch=8, token_budget=512, num_blocks=256,
                           qos=QosConfig(queue_cap=64))
        server = _serving_server(eng)
        try:
            out = tmp_path / "replay.json"
            rc = rpc_replay.main([
                "--dump", CORPUS_OVERLOAD,
                "--server", str(server.listen_endpoint()),
                "--rate-mult", "20", "--timeout-ms", "30000",
                "--report-interval", "0",
                "--tenant-override", "probe", "--priority-override", "1",
                "--json-out", str(out)])
            assert rc == 0
            data = json.loads(out.read_text())
            assert list(data["tenants"]) == ["probe"]
            assert data["tenants"]["probe"]["ok"] == data["sent"]
            snap = eng.qos.snapshot()["tenants"]
            assert snap["probe"]["admitted"] == data["sent"]
        finally:
            server.stop()
            server.join(timeout=2)
            eng.stop()
        eng.kv.assert_idle("override replay teardown")


# ----------------------------------------------------------- observability
class TestObservability:
    def test_snapshot_and_builtin_page_render_qos(self):
        eng = _stub_engine(qos=QosConfig(tenants={"prod": 2.0}))
        try:
            cntl = _Cntl()
            ev = threading.Event()
            code, _ = eng.submit(eng.model.synth_prompt(8), 2, cntl=cntl,
                                 tenant_id="prod", priority=1,
                                 done=lambda r, e=ev: e.set())
            assert code == 0 and ev.wait(30)
            snap = eng.snapshot()["qos"]
            assert snap["tenants"]["prod"]["admitted"] >= 1
            assert {"ceiling", "min_wait_us", "avg_wait_us", "updates"} \
                <= set(snap["limiter"])

            from brpc_tpu.builtin.services import serving_service
            http = types.SimpleNamespace(query={}, path="/serving")
            _st, _ct, body = serving_service(None, http)
            assert "qos: ceiling=" in body
            assert "[tenant prod]" in body
            http = types.SimpleNamespace(query={"format": "json"},
                                         path="/serving")
            _st, ct, body = serving_service(None, http)
            assert "json" in ct
            snaps = json.loads(body)["engines"]
            assert any(s.get("qos") for s in snaps)
        finally:
            eng.stop()
        eng.kv.assert_idle("qos page teardown")

    def test_qos_vars_and_gauges_track_live_engines(self):
        from brpc_tpu.serving import qos as qos_mod

        qos = QosConfig(tenants={"prod": 2.0}, ceiling_start=6.0,
                        ceiling_min=2.0, ceiling_max=6.0)
        eng = _stub_engine(step_s=0.02, max_batch=1, qos=qos)
        tvars = qos_mod._vars_for_tenant("prod")
        a0 = tvars["admitted"].get_value()
        s0 = tvars["shed"].get_value()
        evs = []
        try:
            sheds = 0
            for _ in range(10):
                ev = threading.Event()
                code, _ = eng.submit(eng.model.synth_prompt(4), 4,
                                     tenant_id="prod", priority=0,
                                     done=lambda r, e=ev: e.set())
                if code == errors.EOVERCROWDED:
                    sheds += 1
                else:
                    evs.append(ev)
            assert sheds >= 4  # 10 offered vs a ceiling of 6
            with eng._cv:  # atomic vs the step loop
                assert tvars["depth"].get_value() \
                    == eng.qos.tenant_depth("prod")
                assert qos_mod.g_serving_qos_occupancy.get_value() > 0.0
                assert qos_mod.g_serving_qos_max_wait_ms.get_value() >= 0.0
            for ev in evs:
                assert ev.wait(30)
            assert tvars["admitted"].get_value() - a0 == len(evs)
            assert tvars["shed"].get_value() - s0 == sheds
        finally:
            eng.stop()
        eng.kv.assert_idle("qos vars teardown")


def test_qos_starvation_rule_installed_with_reloadable_bound():
    from brpc_tpu.metrics.watch import (KIND_THRESHOLD, global_watch,
                                        install_default_rules)

    install_default_rules()
    rule = {r.name: r
            for r in global_watch().rules()}["serving_qos_starvation"]
    assert rule.var == "g_serving_qos_max_wait_ms"
    assert rule.kind == KIND_THRESHOLD and rule.op == ">"
    assert rule.value_fn is not None
    assert rule.value_fn() == pytest.approx(
        _flags.get("serving_qos_starvation_ms"))
    _flags.set_flag("serving_qos_starvation_ms", "500")
    try:
        assert rule.value_fn() == pytest.approx(500.0)
    finally:
        _flags.set_flag("serving_qos_starvation_ms", "2000")


# ------------------------------------- corpus sweep through the tier-1 gate
def test_overload_corpus_replays_clean_through_qos_at_recorded_rate(
        tmp_path):
    """The committed overload corpus at the RECORDED rate against the
    real model WITH QoS armed: inside capacity nothing sheds, the replay
    restamps both tenants onto their lanes, and trace_diff finds no
    phase regression at p50 with a 50ms floor — the same tier-1 gate the
    base serving corpus rides."""
    from brpc_tpu.metrics.collector import global_collector
    from brpc_tpu.trace import span as _span
    from tools import record_serving_corpus_overload as recorder
    from tools import rpc_replay, trace_diff

    dumps = [f for f in os.listdir(CORPUS_OVERLOAD)
             if f.endswith(".dump")]
    assert dumps, ("committed overload corpus missing; run "
                   "tools/record_serving_corpus_overload.py")

    _flags.set_flag("rpcz_sample_ratio", "1.0")
    _flags.set_flag("collector_max_samples_per_second", "0")
    global_collector()._deny_until = 0.0
    # ceiling floor above the corpus's 40-request worst case: this test
    # gates identity restamp + trace parity at the recorded rate, not
    # the closed loop (the overload test owns that) — queue waits here
    # run ~1s by construction, and on a contended CI box enough 1 Hz
    # governor ticks land inside the replay to crush an unfloored
    # ceiling below peak inflight and shed work that IS inside capacity
    engine = recorder.build_engine(qos=QosConfig(
        tenants={"prod": 4.0, "batch": 1.0}, queue_cap=64,
        ceiling_min=48.0))
    try:
        recorder.warm_engine(engine)
        _span.reset_for_test()
        server = _serving_server(engine)
        try:
            rc = rpc_replay.main([
                "--dump", CORPUS_OVERLOAD,
                "--server", str(server.listen_endpoint()),
                "--rate-mult", "1", "--timeout-ms", "30000",
                "--report-interval", "0"])
            assert rc == 0  # inside capacity: nothing shed, nothing failed
            deadline = time.monotonic() + 5.0
            while (len([s for s in _span.recent_spans(200)
                        if s.kind == _span.KIND_SERVER])
                   < len(recorder.SCHEDULE)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        finally:
            server.stop()
            server.join(timeout=2)
        # the replay restamped the recorded identity: both lanes billed
        snap = engine.qos.snapshot()["tenants"]
        n_prod = sum(1 for r in recorder.SCHEDULE
                     if r[1] == recorder.PROD)
        assert snap["prod"]["admitted"] == n_prod
        assert snap["batch"]["admitted"] == len(recorder.SCHEDULE) - n_prod
        replayed = tmp_path / "replayed.json"
        replayed.write_text(json.dumps(
            {"spans": [s.to_dict() for s in _span.recent_spans(200)]}))
        rc = trace_diff.main([CORPUS_OVERLOAD, str(replayed),
                              "--percentile", "50",
                              "--min-delta-us", "50000"])
        assert rc == 0
    finally:
        engine.stop()
        engine.kv.assert_idle("overload corpus gate teardown")
        engine.model.close()
        _flags.set_flag("rpcz_sample_ratio", "1.0")
        _flags.set_flag("collector_max_samples_per_second", "1000")


# --------------------------------------- closed-loop overload (acceptance)
class _QosStubModel(_StubModel):
    """Decode-dominated stub: prefill compute is negligible next to the
    decode steps, so latency ratios measure admission scheduling (the
    thing QoS controls), not model speed."""

    def prefill(self, prompt, table):
        self.prefills += 1
        time.sleep(0.0002)
        return 1


def _overload_engine(qos):
    kv = _small_kv(num_blocks=256)
    # max_batch one above the ceiling+protected worst case: the pinned
    # ceiling holds best-effort inflight at 3, so a protected arrival
    # always finds a slot instead of waiting out a batch residual
    eng = ServingEngine(
        _QosStubModel(0.005), kv,
        EngineConfig(max_batch=5, token_budget=64, max_queue=256,
                     idle_wait_s=0.002, qos=qos))
    eng.start()
    return eng


def _replay_corpus(server, tmp_path, name, rate_mult):
    from tools import rpc_replay

    out = tmp_path / f"{name}.json"
    rpc_replay.main([
        "--dump", CORPUS_OVERLOAD,
        "--server", str(server.listen_endpoint()),
        "--rate-mult", str(rate_mult), "--timeout-ms", "30000",
        "--report-interval", "0", "--json-out", str(out)])
    return json.loads(out.read_text())


def test_closed_loop_overload_protects_prod_and_sheds_batch(tmp_path):
    """The acceptance gate: the diurnal-overload corpus replayed at 2x
    the recorded rate against a saturable engine. With QoS armed the
    protected tenant's p99 stays within 1.5x its unloaded baseline while
    best-effort sheds EOVERCROWDED; the identical wave against the same
    engine with QoS off violates the bound."""
    # ceiling pinned one below max_batch: best-effort can never occupy
    # every slot, so the protected lane always has admission headroom —
    # the closed-loop's dynamic version of this is exercised above
    qos_cfg = QosConfig(tenants={"prod": 8.0, "batch": 1.0}, queue_cap=8,
                        protected_priority=1, ceiling_start=3.0,
                        ceiling_min=2.0, ceiling_max=3.0)

    eng = _overload_engine(qos_cfg)
    server = _serving_server(eng)
    try:
        # warmup pass (discarded): sockets, threads, and the step loop
        # pay their cold-start costs outside the measured baseline
        _replay_corpus(server, tmp_path, "warmup", 2)
        # unloaded baseline: a quarter of the recorded rate leaves every
        # request effectively alone on the engine
        base = _replay_corpus(server, tmp_path, "unloaded", 0.25)
        assert base["tenants"]["prod"]["fail"] == 0, base
        p99_unloaded = base["tenants"]["prod"]["p99_us"]
        assert p99_unloaded > 0

        # 2x the recorded rate: the batch burst pushes past saturation
        over = _replay_corpus(server, tmp_path, "overload", 2)
        prod, batch = over["tenants"]["prod"], over["tenants"]["batch"]
        assert prod["fail"] == 0  # the protected lane never shed
        assert batch["shed"] > 0  # best-effort shed EOVERCROWDED
        assert batch["shed"] == batch["fail"]  # sheds, not errors
        assert prod["p99_us"] <= 1.5 * p99_unloaded, (prod, p99_unloaded)
        snap = eng.qos.snapshot()["tenants"]
        assert snap["batch"]["shed"] >= batch["shed"]
    finally:
        server.stop()
        server.join(timeout=2)
        eng.stop()
    eng.kv.assert_idle("overload qos teardown")

    # the control arm: same engine shape, same wave, QoS off — the
    # burst queues ahead of the protected traffic and the bound breaks
    eng = _overload_engine(None)
    server = _serving_server(eng)
    try:
        fifo = _replay_corpus(server, tmp_path, "fifo", 2)
        assert fifo["tenants"]["prod"]["p99_us"] > 1.5 * p99_unloaded, fifo
    finally:
        server.stop()
        server.join(timeout=2)
        eng.stop()
    eng.kv.assert_idle("overload fifo teardown")
