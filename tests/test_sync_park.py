"""Engine-parked sync calls (dp_call_sync) — round-4 fast-path contract.

A sync fast call blocks INSIDE the engine (GIL released); the parse
thread completes it directly. These tests pin the completion matrix:
engine-native completion, the Python fallback (compressed responses via
dp_sync_complete_py), the zero-copy buffer steal for big responses,
deadline behavior, and waiter wakeup on shutdown. Reference analog: a
bthread blocking on its CallId butex (brpc/controller.cpp Join).
"""

from __future__ import annotations

import threading
import time

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (Channel, ChannelOptions, Controller, Server,
                          ServerOptions, Service, Stub, errors)
from brpc_tpu.rpc.channel import RpcError

pytestmark = pytest.mark.skipif(
    not __import__("brpc_tpu.rpc.native_transport",
                   fromlist=["dataplane_available"]).dataplane_available(),
    reason="native engine unavailable")


class _Echo(Service):
    DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    def Echo(self, cntl, request, done):
        if request.message == "compress":
            cntl.compress_type = 1  # gzip response -> Python fallback
        if request.message == "slow":
            time.sleep(0.5)
        if request.message == "big":
            cntl.response_attachment = b"\xcd" * (1 << 20)
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def native_server():
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(_Echo())
    srv.start("127.0.0.1:0")
    yield srv
    srv.stop()
    srv.join(timeout=5)


def _stub(ep, **kw):
    kw.setdefault("timeout_ms", 5000)
    opts = ChannelOptions(protocol="trpc_std", native_transport=True, **kw)
    ch = Channel(opts)
    ch.init(str(ep))
    return Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])


class TestEngineParkedSync:
    def test_engine_completed_roundtrip(self, native_server):
        stub = _stub(native_server.listen_endpoint())
        r = stub.Echo(echo_pb2.EchoRequest(message="hi", payload=b"p" * 100))
        assert r.message == "hi" and r.payload == b"p" * 100

    def test_compressed_response_python_fallback(self, native_server):
        # server compresses -> frame needs Python policy -> the parked
        # waiter completes via dp_sync_complete_py
        stub = _stub(native_server.listen_endpoint())
        r = stub.Echo(echo_pb2.EchoRequest(message="compress",
                                           payload=b"z" * 5000))
        assert r.message == "compress" and r.payload == b"z" * 5000

    def test_big_response_buffer_steal(self, native_server):
        stub = _stub(native_server.listen_endpoint())
        c = Controller()
        stub.Echo(echo_pb2.EchoRequest(message="big"), controller=c)
        assert len(c.response_attachment) == (1 << 20)
        assert c.response_attachment[:3] == b"\xcd\xcd\xcd"

    def test_deadline_maps_to_rpc_timeout(self, native_server):
        stub = _stub(native_server.listen_endpoint(), timeout_ms=100,
                     max_retry=0)
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="slow"))
        assert ei.value.error_code == errors.ERPCTIMEDOUT

    def test_concurrent_parked_callers(self, native_server):
        stub = _stub(native_server.listen_endpoint())
        fails = []
        barrier = threading.Barrier(5)

        def worker(i):
            barrier.wait()
            try:
                for k in range(30):
                    msg = f"t{i}-{k}"
                    r = stub.Echo(echo_pb2.EchoRequest(message=msg))
                    assert r.message == msg
            except BaseException as e:  # noqa: BLE001 — re-raised below
                fails.append(e)

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(5)]
        for t in ts:
            t.start()
        for t in ts:
            t.join(30)
        if fails:
            raise fails[0]

    def test_server_stop_wakes_parked_caller(self, native_server):
        stub = _stub(native_server.listen_endpoint(), timeout_ms=10000)
        out = {}

        def call():
            try:
                stub.Echo(echo_pb2.EchoRequest(message="slow"))
                out["r"] = "ok"
            except RpcError as e:
                out["r"] = e.error_code

        w = threading.Thread(target=call)
        w.start()
        time.sleep(0.1)
        native_server.stop()
        w.join(15)
        assert not w.is_alive(), "parked caller never woke"
        # graceful drain may complete it OR it errors — never hangs
        assert out.get("r") is not None
