"""Sharded dispatch plane (brpc_tpu/shard) — ISSUE 11 acceptance tests.

Unit level: the shm SPSC ring survives wrap/full/reattach, the flat-bytes
ring codecs round-trip, the pre-parse RpcMeta scanner reads routing facts
from real protobuf bytes, and cid->worker routing is stable and spread.
Lease level (CreditLedger armed): grant/take/fill/post, stale-epoch
drops, explicit returns, and worker-death reclaim all leave the parent's
PeerWindow balanced. Integration level (the 1-core CI acceptance): echo
equivalence workers=0 vs workers=2, a 2-worker soak with zero
lost/duplicated responses and the ledger balancing at teardown, the
W_RESP_SEGS bulk path, `worker.crash` chaos recovering via respawn with a
generation bump — and the shm sweeper leaving no stale segments behind.
"""

import glob
import os
import struct
import threading
import time

import pytest

from brpc_tpu import fault, flags
from brpc_tpu.analysis import runtime_check as rc
from brpc_tpu.proto import echo_pb2, rpc_meta_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    Server,
    ServerOptions,
    Stub,
)
from brpc_tpu.shard import wire
from brpc_tpu.shard.plane import shard_for
from brpc_tpu.shard.ring import ShardRing
from brpc_tpu.shard.subwindow import LeaseManager, SubWindow

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]
FACTORY = "brpc_tpu.shard.testing:echo_services"


def _shard_shm_segments():
    return {os.path.basename(p)
            for p in glob.glob("/dev/shm/brpctpu_shard_*")
            + glob.glob("/dev/shm/brpctpu_spill_*")}


@pytest.fixture()
def shard_flags():
    """tpu_shard_workers=2 for one test; always back to the 0 default."""
    flags.set_flag("tpu_shard_workers", 2)
    before = _shard_shm_segments()
    try:
        yield
    finally:
        flags.set_flag("tpu_shard_workers", 0)
        leaked = _shard_shm_segments() - before
        assert not leaked, f"stale shard shm segments: {sorted(leaked)}"


@pytest.fixture()
def checker():
    was_active = rc.ACTIVE
    rc.activate()
    try:
        yield rc
    finally:
        if was_active:
            rc.activate()
        else:
            rc.deactivate()


def _echo_server():
    from brpc_tpu.shard.testing import ShardEchoService

    srv = Server(ServerOptions(shard_factory=FACTORY))
    srv.add_service(ShardEchoService())
    srv.start("tpu://127.0.0.1:0/0")
    return srv


def _stub_for(srv, timeout_ms=20000, max_retry=0):
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=timeout_ms,
                                max_retry=max_retry))
    ch.init(str(srv.listen_endpoint()))
    return Stub(ch, ECHO)


# ------------------------------------------------------------------- ring
class TestShardRing:
    def _name(self, tag):
        return f"test_shardring_{os.getpid():x}_{tag}"

    def test_push_pop_roundtrip_in_order(self):
        r = ShardRing.create(self._name("rt"), 64 * 1024)
        try:
            recs = [(i % 7 + 1, bytes([i & 0xFF]) * i) for i in range(40)]
            for t, p in recs:
                assert r.push(t, p)
            assert r.pop(max_records=100) == recs
            assert r.empty
            assert r.pushed == 40 and r.popped == 40
        finally:
            r.close()

    def test_full_ring_rejects_then_recovers(self):
        r = ShardRing.create(self._name("full"), 64 * 1024)
        try:
            payload = b"\xaa" * 4096
            n = 0
            while r.push(1, payload):
                n += 1
            assert n > 0
            assert r.push_full >= 1          # bounded: never blocks, never grows
            assert r.pop(max_records=1000) == [(1, payload)] * n
            assert r.push(2, b"again")       # space reclaimed after pop
            assert r.pop() == [(2, b"again")]
        finally:
            r.close()

    def test_wraparound_preserves_payloads(self):
        r = ShardRing.create(self._name("wrap"), 64 * 1024)
        try:
            # shove several capacities' worth through in odd-sized records
            # so the write cursor crosses the end many times
            for i in range(400):
                p = bytes([(i * 37) & 0xFF]) * (1000 + (i * 311) % 3000)
                assert r.push(3, p)
                got = r.pop()
                assert got == [(3, p)], f"record {i} corrupted"
        finally:
            r.close()

    def test_attach_by_name_sees_producer_records(self):
        name = self._name("attach")
        prod = ShardRing.create(name, 64 * 1024)
        try:
            cons = ShardRing.attach(name)
            try:
                assert prod.push(9, b"cross-process bytes")
                assert cons.pop() == [(9, b"cross-process bytes")]
                # consumer's head advance is visible to the producer
                assert prod.free_bytes() == prod.capacity
            finally:
                cons.close()
        finally:
            prod.close()

    def test_owner_close_unlinks(self):
        name = self._name("unlink")
        r = ShardRing.create(name, 64 * 1024)
        r.close()
        with pytest.raises(FileNotFoundError):
            ShardRing.attach(name)


# ------------------------------------------------------------------ codecs
class TestWireCodecs:
    def test_msg_roundtrip(self):
        assert wire.decode_msg(wire.encode_msg(7, b"FRAME")) == (7, b"FRAME")

    def test_indices_roundtrip(self):
        b = wire.encode_indices(3, 12, [0, 5, 63, 17])
        assert wire.decode_indices(b) == (3, 12, [0, 5, 63, 17])

    def test_want_roundtrip(self):
        assert wire.decode_want(wire.encode_want(4, 16)) == (4, 16)

    def test_resp_roundtrip(self):
        b = wire.encode_resp(2, 1 << 40, b"\x00packet")
        assert wire.decode_resp(b) == (2, 1 << 40, b"\x00packet")

    def test_resp_segs_roundtrip(self):
        segs = [(0, 262144), (63, 17)]
        b = wire.encode_resp_segs(1, 2, 99, segs)
        assert wire.decode_resp_segs(b) == (1, 2, 99, segs)

    def test_scan_request_meta_reads_real_protobuf(self):
        meta = rpc_meta_pb2.RpcMeta()
        meta.request.service_name = "EchoService"
        meta.request.method_name = "Echo"
        meta.correlation_id = 0xDEADBEEF
        meta.attempt_version = 2
        info = wire.scan_request_meta(meta.SerializeToString())
        assert info == (True, 0xDEADBEEF, 2, False)

    def test_scan_flags_streams_and_responses(self):
        meta = rpc_meta_pb2.RpcMeta()
        meta.request.service_name = "S"
        meta.stream_settings.stream_id = 5
        has_req, _, _, has_stream = wire.scan_request_meta(
            meta.SerializeToString())
        assert has_req and has_stream     # streams stay on the parent path
        resp = rpc_meta_pb2.RpcMeta()
        resp.response.error_code = 0
        resp.correlation_id = 11
        info = wire.scan_request_meta(resp.SerializeToString())
        assert info == (False, 11, 0, False)

    def test_scanner_rejects_garbage(self):
        assert wire.scan_request_meta(b"\xff\xff\xff\xff") is None

    def test_response_cid_from_packed_response(self):
        from brpc_tpu.policy import ensure_registered
        from brpc_tpu.rpc.protocol import find_protocol

        ensure_registered()
        meta = rpc_meta_pb2.RpcMeta()
        meta.correlation_id = 424242
        meta.response.error_code = 0
        pkt = bytes(find_protocol("trpc_std").pack_response(meta, b"body"))
        _, meta_size, _ = struct.unpack_from("!4sII", pkt)
        assert wire.response_cid(pkt, meta_size) == 424242


# ----------------------------------------------------------------- routing
class TestRouting:
    def test_stable(self):
        for cid in (1, 2, 1 << 31, 0xFFFFFFFF):
            assert shard_for(cid, 4) == shard_for(cid, 4)

    def test_sequential_cids_spread_over_two_workers(self):
        hits = [0, 0]
        for cid in range(1, 2001):
            hits[shard_for(cid, 2)] += 1
        assert 0.35 < hits[0] / 2000 < 0.65, hits

    def test_versioned_cids_spread_over_two_workers(self):
        """Real cids from a low-concurrency channel are ``version << 32``:
        VersionedPool reuses slot 0 and only the odd version advances.
        The original Knuth hash mapped ALL of these to worker 0."""
        hits = [0, 0]
        for v in range(1, 4001, 2):
            hits[shard_for(v << 32, 2)] += 1
        assert 0.35 < hits[0] / 2000 < 0.65, hits

    def test_every_worker_reached(self):
        for n in (2, 3, 4, 7):
            seen = {shard_for(cid, n) for cid in range(1, 512)}
            assert seen == set(range(n)), (n, seen)
            seen = {shard_for(v << 32, n) for v in range(1, 129, 2)}
            assert seen == set(range(n)), ("versioned", n, seen)


# ------------------------------------------------------------------ leases
class TestCreditSubWindows:
    """LeaseManager/SubWindow against a real shm pool + PeerWindow with the
    CreditLedger armed: every path hands the credits home."""

    BS, BC = 4096, 16

    @pytest.fixture()
    def window(self, checker):
        from multiprocessing import shared_memory as _shm

        from brpc_tpu.tpu.transport import PeerWindow

        name = f"test_shardlease_{os.getpid():x}"
        seg = _shm.SharedMemory(create=True, size=self.BS * self.BC,
                                name=name)
        win = PeerWindow(name, self.BS, self.BC)
        try:
            yield name, seg, win
        finally:
            win.close()
            try:
                seg.close()
                seg.unlink()
            except FileNotFoundError:
                pass

    def test_grant_take_fill_post_balances(self, checker, window):
        name, seg, win = window
        lm = LeaseManager(win, epoch=0)
        sub = SubWindow(name, self.BS, self.BC, epoch=0)
        try:
            got = lm.grant(widx=0, want=4)
            assert got and len(got) == 4
            assert lm.leased_count(0) == 4
            assert sub.grant(got, epoch=0)
            taken = sub.take_now(2)
            assert taken is not None and len(taken) == 2
            sub.fill(taken[0], b"\xcd" * 100, 100)
            # the single copy lands directly in the client-visible pool
            base = taken[0] * self.BS
            assert bytes(seg.buf[base:base + 100]) == b"\xcd" * 100
            # parent posts the segs frame: credits ride to the client and
            # come home through the normal FT_ACK -> window.release path
            lm.note_posted(0, taken)
            win.release(taken)
            # idle shrink returns the rest explicitly
            back = sub.give_back(self.BC)
            assert sorted(back) == sorted(set(got) - set(taken))
            lm.note_returned(0, back)
            assert lm.leased_count(0) == 0
            rc.ledger.assert_balanced()
        finally:
            sub.close()

    def test_take_now_never_blocks_or_splits(self, checker, window):
        name, _, win = window
        lm = LeaseManager(win, epoch=0)
        sub = SubWindow(name, self.BS, self.BC, epoch=0)
        try:
            got = lm.grant(0, 3)
            sub.grant(got, 0)
            t0 = time.monotonic()
            assert sub.take_now(5) is None          # all-or-nothing
            assert time.monotonic() - t0 < 0.05     # and never parks
            assert sub.take_misses == 1
            assert sub.free_count() == 3            # nothing was split off
            lm.note_returned(0, sub.give_back(3))
            rc.ledger.assert_balanced()
        finally:
            sub.close()

    def test_stale_epoch_grant_dropped(self, checker, window):
        name, _, win = window
        sub = SubWindow(name, self.BS, self.BC, epoch=3)
        try:
            assert not sub.grant([1, 2], epoch=2)
            assert sub.free_count() == 0
        finally:
            sub.close()

    def test_reclaim_on_worker_death_rebalances_to_sibling(self, checker,
                                                           window):
        _, _, win = window
        lm = LeaseManager(win, epoch=0)
        dead = lm.grant(widx=1, want=self.BC)       # whole window leased out
        assert len(dead) == self.BC
        # sibling can't grow: bounded acquire misses instead of parking
        assert lm.grant(widx=0, want=4, timeout=0.01) is None
        assert lm.grant_misses == 1
        assert lm.reclaim_worker(1) == self.BC      # death reclaims wholesale
        assert lm.leased_count(1) == 0
        moved = lm.grant(widx=0, want=4)            # and the sibling can grow
        assert len(moved) == 4
        lm.release_all()
        rc.ledger.assert_balanced()

    def test_ungrant_returns_undelivered_credits(self, checker, window):
        _, _, win = window
        lm = LeaseManager(win, epoch=0)
        got = lm.grant(0, 4)
        lm.ungrant(0, got)                          # ring-full push failure
        assert lm.leased_count(0) == 0
        assert len(lm.grant(0, self.BC)) == self.BC
        lm.release_all()
        rc.ledger.assert_balanced()


# ------------------------------------------------------------- integration
class TestShardPlaneEndToEnd:
    """The ISSUE's 1-core CI acceptance: equivalence, soak, bulk, chaos."""

    def _wait_ledger_clean(self, timeout=5.0):
        from brpc_tpu.tpu.transport import _sweep_deferred_pools

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            snap = rc.ledger.snapshot()
            if (not snap["violations"] and not snap["borrowed"]
                    and not any(snap["windows"].values())):
                break
            time.sleep(0.02)
        rc.ledger.assert_balanced(drain=_sweep_deferred_pools)

    def test_echo_equivalence_workers0_vs_2(self, shard_flags):
        """Same requests, byte-identical answers, shard plane on or off."""
        cases = [(f"m{i}", bytes([i]) * (i * 97)) for i in range(12)]

        def run(workers):
            flags.set_flag("tpu_shard_workers", workers)
            srv = _echo_server()
            try:
                plane = srv._shard_plane
                if workers:
                    assert plane is not None and plane.wait_ready(30.0)
                else:
                    assert plane is None    # the 0 default is a strict no-op
                stub = _stub_for(srv)
                out = []
                for msg, payload in cases:
                    cntl = Controller()
                    cntl.request_attachment = payload
                    r = stub.Echo(echo_pb2.EchoRequest(message=msg,
                                                       payload=payload),
                                  controller=cntl)
                    out.append((r.message, r.payload,
                                bytes(cntl.response_attachment)))
                if workers:
                    assert plane.forwarded > 0
                return out
            finally:
                srv.stop()
                srv.join()

        assert run(0) == run(2)

    def test_two_worker_soak_no_lost_or_dup(self, shard_flags, checker):
        """4 client threads x 40 unique calls over 2 workers: every reply
        matches its request, both workers dispatched, zero fallbacks, and
        the armed CreditLedger balances at teardown."""
        srv = _echo_server()
        try:
            plane = srv._shard_plane
            assert plane.wait_ready(30.0)
            stub = _stub_for(srv)
            errors_ = []

            def client(tid):
                try:
                    for i in range(40):
                        msg = f"t{tid}-{i}"
                        body = (msg.encode() * 9)[:200]
                        cntl = Controller()
                        cntl.request_attachment = body
                        r = stub.Echo(echo_pb2.EchoRequest(message=msg),
                                      controller=cntl)
                        assert r.message == msg, (r.message, msg)
                        assert bytes(cntl.response_attachment) == body
                except BaseException as e:  # noqa: BLE001
                    errors_.append(e)

            ts = [threading.Thread(target=client, args=(i,),
                                   name=f"soak-client-{i}")
                  for i in range(4)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            assert not errors_, errors_[:3]
            assert plane.forwarded == 160
            assert plane.fallback == 0
            deadline = time.monotonic() + 5.0    # W_STATS lags ~0.5s
            while time.monotonic() < deadline:
                per_worker = [w["dispatched"]
                              for w in plane.state_dict()["workers"]]
                if all(d > 0 for d in per_worker) \
                        and sum(per_worker) >= 160:
                    break
                time.sleep(0.05)
            assert all(d > 0 for d in per_worker), per_worker
            assert sum(per_worker) >= 160, per_worker
        finally:
            srv.stop()
            srv.join()
        # workers hold leased credits while the plane is up — balance is
        # demanded at teardown: shutdown returns every outstanding lease
        # before the endpoints' graceful window_teardown audits the whole
        # window, so any stranded sub-window credit is a violation here
        self._wait_ledger_clean()

    def test_bulk_response_uses_leased_segments(self, shard_flags):
        """A 64KB echo flows back as W_RESP_SEGS: the worker fills leased
        client-pool blocks directly and the parent only posts indices."""
        srv = _echo_server()
        try:
            plane = srv._shard_plane
            assert plane.wait_ready(30.0)
            stub = _stub_for(srv)
            payload = bytes(range(256)) * 256
            r = stub.Echo(echo_pb2.EchoRequest(message="bulk",
                                               payload=payload))
            assert r.payload == payload
            deadline = time.monotonic() + 5.0    # W_STATS lags ~0.5s
            while time.monotonic() < deadline:
                segs = sum(w["resp_segs"]
                           for w in plane.state_dict()["workers"])
                if segs:
                    break
                time.sleep(0.05)
            assert segs > 0, plane.state_dict()["workers"]
        finally:
            srv.stop()
            srv.join()

    @pytest.mark.chaos
    def test_worker_crash_respawns_with_generation_bump(self, shard_flags):
        """`worker.crash` chaos: the monitor reaps the corpse, fans
        retriable errors to its in-flight cids, reclaims its leases, and
        respawns it under a bumped generation — traffic keeps flowing."""
        flags.set_flag("fault_injection_enabled", True)
        srv = _echo_server()
        try:
            plane = srv._shard_plane
            assert plane.wait_ready(30.0)
            stub = _stub_for(srv, max_retry=3)
            for i in range(10):
                assert stub.Echo(
                    echo_pb2.EchoRequest(message=f"a{i}")).message == f"a{i}"
            pid0 = plane.workers[1].pid
            gen0 = plane.generation
            fault.arm("worker.crash", mode="oneshot", match={"worker": 1})
            deadline = time.monotonic() + 15.0
            while time.monotonic() < deadline and plane.generation == gen0:
                time.sleep(0.05)
            assert plane.generation > gen0, "worker death never observed"
            assert plane.wait_ready(30.0), "respawn did not come back READY"
            w1 = plane.workers[1]
            assert w1.pid != pid0 and w1.gen == 1 and w1.respawns == 1
            # retriable fan-out + respawn: the same stub keeps working
            for i in range(20):
                assert stub.Echo(
                    echo_pb2.EchoRequest(message=f"b{i}")).message == f"b{i}"
            assert plane.state_dict()["workers"][1]["inflight_cids"] == 0
        finally:
            fault.disarm_all()
            flags.set_flag("fault_injection_enabled", False)
            srv.stop()
            srv.join()

    def test_shutdown_leaves_no_stale_shm(self, shard_flags):
        before = _shard_shm_segments()
        srv = _echo_server()
        plane = srv._shard_plane
        assert plane.wait_ready(30.0)
        stub = _stub_for(srv)
        assert stub.Echo(echo_pb2.EchoRequest(message="x")).message == "x"
        mid = _shard_shm_segments()
        assert len(mid - before) >= 4    # 2 rings per worker exist while up
        srv.stop()
        srv.join()
        assert _shard_shm_segments() - before == set()

    def test_tpu_builtin_reports_shard_section(self, shard_flags):
        """/tpu?format=json carries the plane: per-worker pid/role/lease
        occupancy/respawn generation (the ISSUE's observability surface)."""
        import json as _json

        from brpc_tpu.builtin import services as _builtin

        srv = _echo_server()
        try:
            plane = srv._shard_plane
            assert plane.wait_ready(30.0)
            stub = _stub_for(srv)
            assert stub.Echo(echo_pb2.EchoRequest(message="s")).message == "s"

            class _Http:
                path = "/tpu"
                query = {"format": "json"}

                def header(self, k, default=""):
                    return default

            status, _, body = _builtin.tpu_service(srv, _Http())
            assert status == 200
            shard = _json.loads(body)["shard"]
            assert shard["workers_configured"] == 2
            assert len(shard["workers"]) == 2
            for i, w in enumerate(shard["workers"]):
                assert w["index"] == i and w["alive"]
                assert w["pid"] > 0 and w["role"] == f"worker:{i}"
        finally:
            srv.stop()
            srv.join()
