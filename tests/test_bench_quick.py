"""CI smoke lane for bench.py (BENCH_QUICK + BENCH_PHASES=shm).

Runs the benchmark's CPU-only shm-sweep phase end to end in a subprocess —
real client/server process pair over the tpu:// tunnel — and asserts the
contract the perf tooling depends on: a machine-readable headline JSON line
on stdout, and the zero-copy receive counters (borrowed vs copied bytes,
ACK batching ratio) on stderr.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench_run():
    env = dict(os.environ,
               BENCH_QUICK="1",
               BENCH_PHASES="shm",
               BENCH_SKIP_DEVICE="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, timeout=240,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, \
        f"bench.py failed rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return proc


def test_headline_json(bench_run):
    lines = [l for l in bench_run.stdout.splitlines()
             if l.startswith("{")]
    assert len(lines) == 1, bench_run.stdout
    headline = json.loads(lines[0])
    assert headline["metric"] == "echo_1mb_framework_bandwidth"
    assert headline["unit"] == "GB/s"
    assert headline["value"] > 0, headline


def test_only_shm_phase_ran(bench_run):
    err = bench_run.stderr
    assert "# tpu:// sweep" in err
    # the skipped phases must not have produced their reports
    assert "# multi_threaded_echo" not in err
    assert "# hybrid lane" not in err
    assert "# device lane" not in err


@pytest.fixture(scope="module")
def batch_bench_run():
    env = dict(os.environ,
               BENCH_QUICK="1",
               BENCH_PHASES="batch",
               BENCH_SKIP_DEVICE="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, timeout=300,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, \
        f"bench.py failed rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return proc


def test_batch_lane_report(batch_bench_run):
    lanes = [l for l in batch_bench_run.stderr.splitlines()
             if l.startswith("# batch lane (")]
    assert len(lanes) == 1, batch_bench_run.stderr
    line = lanes[0]
    assert "per-request qps=" in line and "batched qps=" in line, line
    ratio = float(line.split("batched/per-request = ")[1].split("x")[0])
    # the acceptance floor: coalesced dispatch amortizes per-call jit
    # dispatch + interpreter overhead across the batch
    assert ratio >= 2.0, line
    assert "OK 2x floor" in line, line


def test_batch_lane_vars_counters(batch_bench_run):
    err = batch_bench_run.stderr
    for var in ("g_batch_size", "g_batch_queue_delay_us"):
        lines = [l for l in err.splitlines()
                 if l.startswith(f"# batch lane /vars: {var}")]
        assert lines, f"missing {var} in:\n{err[-2000:]}"
        # a live average: "name : avg (count=N)" with N > 0
        assert "(count=" in lines[0], lines[0]
        count = int(lines[0].split("(count=")[1].split(")")[0])
        assert count > 0, lines[0]


def test_batch_phase_skips_others(batch_bench_run):
    err = batch_bench_run.stderr
    assert "# tpu:// sweep" not in err
    assert "# multi_threaded_echo" not in err
    assert "# device lane" not in err


def test_zero_copy_counters_emitted(bench_run):
    err = bench_run.stderr
    zc = [l for l in err.splitlines()
          if l.startswith("# tpu:// zero-copy receive")]
    assert zc, err
    from brpc_tpu.butil.iobuf import supports_block_ownership

    if not supports_block_ownership():
        return  # degraded environment: counters exist but all-copied
    assert "borrowed=" in zc[0] and "copied=" in zc[0], zc[0]
    borrowed = int(zc[0].split("borrowed=")[1].split("B")[0].replace(",", ""))
    assert borrowed > 0, zc[0]
    assert any(l.startswith("# tpu:// ack batching") for l in err.splitlines())


def test_shrunken_window_peak_report(bench_run):
    """The streaming-parse sweep lane: bench_tpu_sweep reports (and guards)
    peak borrowed-outstanding against the shrunken 64-block window."""
    err = bench_run.stderr
    peaks = [l for l in err.splitlines()
             if l.startswith("# tpu:// borrowed peak:")]
    assert peaks, err[-2000:]
    line = peaks[0]
    peak = int(line.split("borrowed peak:")[1].split("blocks")[0])
    window = int(line.split("(window")[1].split(")")[0])
    assert window == 64, line
    from brpc_tpu.butil.iobuf import supports_block_ownership

    if supports_block_ownership():
        # the whole point of streaming claims: the footprint never
        # approaches the window even with 16MB messages in the sweep
        assert peak < window, line


def test_tunnel_counters_on_vars(bench_run):
    """The zero-copy counters must be queryable through the /vars surface
    (expose registry), not just printed by bench.py."""
    from brpc_tpu.metrics.variable import get_exposed
    from brpc_tpu.tpu import transport  # noqa: F401  (registers on import)

    for name in ("g_tunnel_borrowed_bytes", "g_tunnel_copied_bytes",
                 "g_tunnel_borrowed_peak_blocks"):
        assert get_exposed(name) is not None, name
