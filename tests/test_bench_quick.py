"""CI smoke lane for bench.py (BENCH_QUICK + BENCH_PHASES=shm).

Runs the benchmark's CPU-only shm-sweep phase end to end in a subprocess —
real client/server process pair over the tpu:// tunnel — and asserts the
contract the perf tooling depends on: a machine-readable headline JSON line
on stdout, and the zero-copy receive counters (borrowed vs copied bytes,
ACK batching ratio) on stderr.
"""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


@pytest.fixture(scope="module")
def bench_run():
    env = dict(os.environ,
               BENCH_QUICK="1",
               BENCH_PHASES="shm",
               BENCH_SKIP_DEVICE="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, timeout=240,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, \
        f"bench.py failed rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return proc


def test_headline_json(bench_run):
    lines = [l for l in bench_run.stdout.splitlines()
             if l.startswith("{")]
    # headline + 64B qps + vars series overhead
    assert len(lines) == 3, bench_run.stdout
    headline = json.loads(lines[0])
    assert headline["metric"] == "echo_1mb_framework_bandwidth"
    assert headline["unit"] == "GB/s"
    assert headline["value"] > 0, headline


def test_small_message_qps_json(bench_run):
    """The shm sweep must emit the 64B small-message summary line."""
    rows = [json.loads(l) for l in bench_run.stdout.splitlines()
            if l.startswith("{")]
    small = [r for r in rows if r["metric"] == "echo_64b_qps"]
    assert len(small) == 1, bench_run.stdout
    assert small[0]["unit"] == "qps"
    assert small[0]["value"] > 0, small[0]
    assert small[0]["vs_baseline"] > 0, small[0]


def test_vars_series_overhead_metric(bench_run):
    """The shm sweep must emit the series-ring overhead metric, and one
    ring sweep must stay far inside the sampler's 1s tick budget."""
    rows = [json.loads(l) for l in bench_run.stdout.splitlines()
            if l.startswith("{")]
    m = [r for r in rows if r["metric"] == "vars_series_overhead_pct"]
    assert len(m) == 1, bench_run.stdout
    assert m[0]["unit"] == "%"
    assert 0 <= m[0]["value"] < 2.0, m[0]


def test_method_qps_series_nonempty_after_sweep(bench_run):
    """By the end of the shm sweep the bench server's per-method qps var
    must have accumulated live 1-second series samples (the sampler
    daemon sweeps rings while traffic flows)."""
    lines = [l for l in bench_run.stderr.splitlines()
             if l.startswith(
                 "# vars series rpc_method_echoservice_echo_qps")]
    assert lines, bench_run.stderr[-2000:]
    line = lines[0]
    count = int(line.split("count=")[1].split(" ")[0])
    nonzero = int(line.split("nonzero_1s=")[1].split(" ")[0])
    assert count >= 1, line
    assert nonzero >= 1, line


def test_rtc_lane_activates_on_shm_sweep(bench_run):
    """The run-to-completion lane must engage for the sweep's small
    echoes: the bench server's exit report shows inline hits on Echo."""
    rtc = [l for l in bench_run.stderr.splitlines()
           if l.startswith("# rtc ")]
    assert rtc, bench_run.stderr[-2000:]
    line = rtc[0]
    assert "EchoService.Echo" in line, line
    hits = int(line.split("EchoService.Echo:hits=")[1].split(",")[0])
    assert hits > 0, line
    assert "demoted=0" in line, line


def test_only_shm_phase_ran(bench_run):
    err = bench_run.stderr
    assert "# tpu:// sweep" in err
    # the skipped phases must not have produced their reports
    assert "# multi_threaded_echo" not in err
    assert "# hybrid lane" not in err
    assert "# device lane" not in err


@pytest.fixture(scope="module")
def batch_bench_run():
    env = dict(os.environ,
               BENCH_QUICK="1",
               BENCH_PHASES="batch",
               BENCH_SKIP_DEVICE="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, timeout=300,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, \
        f"bench.py failed rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return proc


def test_batch_lane_report(batch_bench_run):
    lanes = [l for l in batch_bench_run.stderr.splitlines()
             if l.startswith("# batch lane (")]
    assert len(lanes) == 1, batch_bench_run.stderr
    line = lanes[0]
    assert "per-request qps=" in line and "batched qps=" in line, line
    ratio = float(line.split("batched/per-request = ")[1].split("x")[0])
    # the acceptance floor: coalesced dispatch amortizes per-call jit
    # dispatch + interpreter overhead across the batch
    assert ratio >= 2.0, line
    assert "OK 2x floor" in line, line


def test_batch_lane_vars_counters(batch_bench_run):
    err = batch_bench_run.stderr
    for var in ("g_batch_size", "g_batch_queue_delay_us"):
        lines = [l for l in err.splitlines()
                 if l.startswith(f"# batch lane /vars: {var}")]
        assert lines, f"missing {var} in:\n{err[-2000:]}"
        # a live average: "name : avg (count=N)" with N > 0
        assert "(count=" in lines[0], lines[0]
        count = int(lines[0].split("(count=")[1].split(")")[0])
        assert count > 0, lines[0]


def test_batch_phase_skips_others(batch_bench_run):
    err = batch_bench_run.stderr
    assert "# tpu:// sweep" not in err
    assert "# multi_threaded_echo" not in err
    assert "# device lane" not in err


@pytest.fixture(scope="module")
def serving_bench_run():
    # 8 virtual CPU devices so the sharded A/B runs the real dp=2/sp=2/tp=2
    # serving mesh (matches tests/conftest.py) instead of the 1x1x1
    # degenerate
    env = dict(os.environ,
               BENCH_QUICK="1",
               BENCH_PHASES="serving",
               BENCH_SKIP_DEVICE="1",
               JAX_PLATFORMS="cpu",
               XLA_FLAGS="--xla_force_host_platform_device_count=8")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, timeout=420,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, \
        f"bench.py failed rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return proc


def test_serving_lane_json_metrics(serving_bench_run):
    """The serving phase emits exactly its ten machine-readable lines:
    streamed tokens/sec, TTFT percentiles measured at stream-frame
    arrival, the continuous-vs-static scheduling ratio (sharded stack),
    the sharded engine's tokens/sec, the prefix-cache hit-TTFT A/B pair,
    the disaggregated prefill/decode interference A/B pair plus the
    migration lane's GB/s, and the coalesced device dispatch rate vs the
    BENCH_r05 isolated-dispatch baseline."""
    rows = [json.loads(l) for l in serving_bench_run.stdout.splitlines()
            if l.startswith("{")]
    by = {r["metric"]: r for r in rows}
    assert set(by) == {"serving_tokens_per_sec", "serving_ttft_ms",
                       "serving_continuous_vs_static",
                       "serving_sharded_tokens_per_s",
                       "serving_prefix_hit_ttft_ms",
                       "serving_prefix_hit_ratio",
                       "serving_disagg_decode_jitter",
                       "serving_disagg_ttft_ms",
                       "serving_migrate_gbps",
                       "device_op_rate"}, \
        serving_bench_run.stdout
    assert by["serving_tokens_per_sec"]["unit"] == "tokens/s"
    assert by["serving_tokens_per_sec"]["value"] > 0
    ttft = by["serving_ttft_ms"]
    assert ttft["unit"] == "ms" and ttft["value"] > 0
    assert ttft["p99"] >= ttft["value"], ttft
    sharded = by["serving_sharded_tokens_per_s"]
    assert sharded["unit"] == "tokens/s" and sharded["value"] > 0, sharded
    # the fixture forces 8 virtual devices -> the dp=2/sp=2/tp=2 mesh
    assert sharded["devices"] == 8, sharded
    ops = by["device_op_rate"]
    assert ops["unit"] == "op/s" and ops["value"] > 0, ops
    assert ops["vs_baseline"] == 7222.0, ops
    # coalesced dispatch must beat the isolated per-RPC baseline even on
    # the CPU sim (the fused-program path skips per-op Python dispatch)
    assert ops["value"] > ops["vs_baseline"], ops


def test_serving_continuous_beats_static_by_1_5x(serving_bench_run):
    """The acceptance floor: iteration-level admission must clear 1.5x the
    static-gang QPS on the mixed-length A/B (3:1 short:long, so every
    static gang drains behind one straggler) — with sharding on: the A/B
    runs MeshTransformer + ShardedKVCache over the 8-virtual-device
    mesh."""
    rows = [json.loads(l) for l in serving_bench_run.stdout.splitlines()
            if l.startswith("{")]
    ab = [r for r in rows
          if r["metric"] == "serving_continuous_vs_static"][0]
    assert ab["continuous_qps"] > 0 and ab["static_qps"] > 0, ab
    assert ab["value"] >= 1.5, ab
    lane = [l for l in serving_bench_run.stderr.splitlines()
            if l.startswith("# serving lane:")]
    assert lane and "OK 1.5x floor" in lane[0], \
        serving_bench_run.stderr[-2000:]


def test_serving_prefix_hit_ttft_floor(serving_bench_run):
    """The prefix-cache acceptance floor: on the shared-prefix corpus a
    warm (cache-hit) generation's TTFT must come in at no more than half
    the cold engine's — the radix fork replaces O(prompt) prefill with
    one decode-shaped suffix launch."""
    rows = [json.loads(l) for l in serving_bench_run.stdout.splitlines()
            if l.startswith("{")]
    hit = [r for r in rows
           if r["metric"] == "serving_prefix_hit_ttft_ms"][0]
    assert hit["unit"] == "ms" and hit["value"] > 0, hit
    assert hit["cold_ms"] > 0, hit
    assert hit["value"] <= 0.5 * hit["cold_ms"], hit
    assert hit["ratio"] <= 0.5, hit
    ratio = [r for r in rows
             if r["metric"] == "serving_prefix_hit_ratio"][0]
    # warmup primes the tree: all but the very first request hit
    assert ratio["unit"] == "ratio" and ratio["value"] >= 0.5, ratio
    lane = [l for l in serving_bench_run.stderr.splitlines()
            if l.startswith("# serving prefix:")]
    assert lane and "OK 0.5x ceiling" in lane[0], \
        serving_bench_run.stderr[-2000:]


def test_serving_disagg_interference_floor(serving_bench_run):
    """The disaggregation acceptance floor: on the 3:1 mixed corpus the
    decode engine of the disaggregated pair must show strictly less
    inter-token jitter (p99-p50 ITL) than the co-located engine whose
    decode steps share a loop with the long prefill launches — and the
    migration lane must have actually moved bytes (GB/s > 0)."""
    rows = [json.loads(l) for l in serving_bench_run.stdout.splitlines()
            if l.startswith("{")]
    jit = [r for r in rows
           if r["metric"] == "serving_disagg_decode_jitter"][0]
    assert jit["unit"] == "ms", jit
    assert jit["coloc_ms"] > 0, jit
    assert jit["value"] < jit["coloc_ms"], jit
    ttft = [r for r in rows if r["metric"] == "serving_disagg_ttft_ms"][0]
    assert ttft["value"] > 0 and ttft["coloc_ms"] > 0, ttft
    gbps = [r for r in rows if r["metric"] == "serving_migrate_gbps"][0]
    assert gbps["unit"] == "GB/s" and gbps["value"] > 0, gbps
    assert gbps["seqs"] > 0 and gbps["blocks"] > 0, gbps
    lane = [l for l in serving_bench_run.stderr.splitlines()
            if l.startswith("# serving disagg:")]
    assert lane and "OK interference floor" in lane[0], \
        serving_bench_run.stderr[-2000:]


def test_serving_phase_skips_others(serving_bench_run):
    err = serving_bench_run.stderr
    assert "# tpu:// sweep" not in err
    assert "# batch lane (" not in err
    assert "# device lane" not in err
    assert "# serving spec:" not in err


@pytest.fixture(scope="module")
def spec_bench_run():
    env = dict(os.environ,
               BENCH_QUICK="1",
               BENCH_PHASES="spec",
               BENCH_SKIP_DEVICE="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, timeout=300,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, \
        f"bench.py failed rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return proc


def test_spec_lane_json_metrics(spec_bench_run):
    """The spec phase emits exactly its three machine-readable lines:
    the speculative-vs-baseline tokens/s A/B, the run's accept rate, and
    the per-user decode latency pair."""
    rows = [json.loads(l) for l in spec_bench_run.stdout.splitlines()
            if l.startswith("{")]
    by = {r["metric"]: r for r in rows}
    assert set(by) == {"serving_spec_tokens_per_s",
                       "serving_spec_accept_rate",
                       "serving_spec_itl_ms"}, spec_bench_run.stdout
    tps = by["serving_spec_tokens_per_s"]
    assert tps["unit"] == "tokens/s" and tps["value"] > 0, tps
    assert tps["baseline"] > 0, tps
    itl = by["serving_spec_itl_ms"]
    assert itl["unit"] == "ms" and itl["value"] > 0, itl
    assert itl["baseline_ms"] > 0, itl


def test_spec_beats_baseline_by_1_3x(spec_bench_run):
    """The acceptance floor: on the repetition-heavy corpus the
    draft+verify lane must clear 1.3x the non-speculative engine's
    tokens/s — k accepted drafts plus the bonus token ride one fused
    verify launch, so committed tokens per dispatch goes up while the
    bit-identity oracle (checked inside the lane, gated exactly in
    test_serving_spec.py) pins correctness."""
    rows = [json.loads(l) for l in spec_bench_run.stdout.splitlines()
            if l.startswith("{")]
    tps = [r for r in rows if r["metric"] == "serving_spec_tokens_per_s"][0]
    assert tps["ratio"] >= 1.3, tps
    lane = [l for l in spec_bench_run.stderr.splitlines()
            if l.startswith("# serving spec:")]
    assert lane and "OK 1.3x floor" in lane[0], \
        spec_bench_run.stderr[-2000:]


def test_spec_accept_rate_on_repetitive_corpus(spec_bench_run):
    """Prompt-lookup must actually hit on the motif corpus — an accept
    rate near zero means the lane is winning (or losing) for the wrong
    reason."""
    rows = [json.loads(l) for l in spec_bench_run.stdout.splitlines()
            if l.startswith("{")]
    ar = [r for r in rows if r["metric"] == "serving_spec_accept_rate"][0]
    assert ar["unit"] == "ratio", ar
    assert ar["drafted"] > 0 and ar["accepted"] > 0, ar
    assert ar["value"] >= 0.5, ar


def test_spec_phase_skips_others(spec_bench_run):
    err = spec_bench_run.stderr
    assert "# serving lane:" not in err
    assert "# tpu:// sweep" not in err
    assert "# batch lane (" not in err
    assert "# device lane" not in err


@pytest.fixture(scope="module")
def qos_bench_run():
    env = dict(os.environ,
               BENCH_QUICK="1",
               BENCH_PHASES="qos",
               BENCH_SKIP_DEVICE="1",
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py")],
                          capture_output=True, text=True, timeout=300,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, \
        f"bench.py failed rc={proc.returncode}:\n{proc.stderr[-2000:]}"
    return proc


def test_qos_lane_json_metrics(qos_bench_run):
    """The qos phase emits exactly its two machine-readable lines: the
    protected tenant's p99 under the best-effort flood (with its
    unloaded and FIFO-engine comparators) and the flood's shed rate."""
    rows = [json.loads(l) for l in qos_bench_run.stdout.splitlines()
            if l.startswith("{")]
    by = {r["metric"]: r for r in rows}
    assert set(by) == {"serving_qos_protected_p99_ms",
                       "serving_qos_shed_rate"}, qos_bench_run.stdout
    p99 = by["serving_qos_protected_p99_ms"]
    assert p99["unit"] == "ms" and p99["value"] > 0, p99
    assert p99["unloaded_ms"] > 0 and p99["fifo_ms"] > 0, p99


def test_qos_protects_p99_vs_fifo(qos_bench_run):
    """The acceptance floor: under the same flood the fair-share engine
    must hold the protected tenant's p99 to a fraction of the FIFO
    engine's — on FIFO, prod queues behind the whole best-effort wave;
    with QoS, weighted admission interleaves it ahead."""
    rows = [json.loads(l) for l in qos_bench_run.stdout.splitlines()
            if l.startswith("{")]
    p99 = [r for r in rows
           if r["metric"] == "serving_qos_protected_p99_ms"][0]
    assert p99["fifo_ratio"] >= 1.5, p99
    lane = [l for l in qos_bench_run.stderr.splitlines()
            if l.startswith("# serving qos:")]
    assert lane, qos_bench_run.stderr[-2000:]


def test_qos_sheds_best_effort_flood(qos_bench_run):
    """The flood past the batch tenant's queue cap must shed
    EOVERCROWDED at admission (the FIFO engine, with no per-tenant cap,
    absorbs the whole wave into its queue)."""
    rows = [json.loads(l) for l in qos_bench_run.stdout.splitlines()
            if l.startswith("{")]
    shed = [r for r in rows if r["metric"] == "serving_qos_shed_rate"][0]
    assert shed["unit"] == "ratio", shed
    assert shed["shed"] > 0 and shed["sent"] > 0, shed
    assert shed["value"] >= 0.3, shed
    assert shed["fifo_shed"] == 0, shed


def test_qos_phase_skips_others(qos_bench_run):
    err = qos_bench_run.stderr
    assert "# serving lane:" not in err
    assert "# serving spec:" not in err
    assert "# tpu:// sweep" not in err
    assert "# batch lane (" not in err


def test_zero_copy_counters_emitted(bench_run):
    err = bench_run.stderr
    zc = [l for l in err.splitlines()
          if l.startswith("# tpu:// zero-copy receive")]
    assert zc, err
    from brpc_tpu.butil.iobuf import supports_block_ownership

    if not supports_block_ownership():
        return  # degraded environment: counters exist but all-copied
    assert "borrowed=" in zc[0] and "copied=" in zc[0], zc[0]
    borrowed = int(zc[0].split("borrowed=")[1].split("B")[0].replace(",", ""))
    assert borrowed > 0, zc[0]
    assert any(l.startswith("# tpu:// ack batching") for l in err.splitlines())


def test_shrunken_window_peak_report(bench_run):
    """The streaming-parse sweep lane: bench_tpu_sweep reports (and guards)
    peak borrowed-outstanding against the shrunken 64-block window."""
    err = bench_run.stderr
    peaks = [l for l in err.splitlines()
             if l.startswith("# tpu:// borrowed peak:")]
    assert peaks, err[-2000:]
    line = peaks[0]
    peak = int(line.split("borrowed peak:")[1].split("blocks")[0])
    window = int(line.split("(window")[1].split(")")[0])
    assert window == 64, line
    from brpc_tpu.butil.iobuf import supports_block_ownership

    if supports_block_ownership():
        # the whole point of streaming claims: the footprint never
        # approaches the window even with 16MB messages in the sweep
        assert peak < window, line


def test_tunnel_counters_on_vars(bench_run):
    """The zero-copy counters must be queryable through the /vars surface
    (expose registry), not just printed by bench.py."""
    from brpc_tpu.metrics.variable import get_exposed
    from brpc_tpu.tpu import transport  # noqa: F401  (registers on import)

    for name in ("g_tunnel_borrowed_bytes", "g_tunnel_copied_bytes",
                 "g_tunnel_borrowed_peak_blocks"):
        assert get_exposed(name) is not None, name


@pytest.fixture(scope="module")
def profile_bench_run(tmp_path_factory):
    out = tmp_path_factory.mktemp("prof") / "bench.folded"
    env = dict(os.environ,
               BENCH_QUICK="1",
               BENCH_PROFILE_OUT=str(out),
               JAX_PLATFORMS="cpu")
    proc = subprocess.run([sys.executable, os.path.join(REPO, "bench.py"),
                           "--profile"],
                          capture_output=True, text=True, timeout=240,
                          cwd=REPO, env=env)
    assert proc.returncode == 0, \
        f"bench.py --profile failed rc={proc.returncode}:\n" \
        f"{proc.stderr[-2000:]}"
    return proc, out


def test_profile_folded_artifact(profile_bench_run):
    """--profile must leave a non-empty folded-stacks artifact the flame
    and diff tools can consume."""
    proc, out = profile_bench_run
    text = out.read_text()
    stacks = [l for l in text.splitlines()
              if l and not l.startswith("#")]
    assert stacks, text[:500]
    for line in stacks:
        stack, _, weight = line.rpartition(" ")
        assert int(weight) > 0, line
        assert stack.startswith("role="), line
        assert ";phase=" in stack, line


def test_profile_budget_table_and_ratio(profile_bench_run):
    """The per-call CPU budget table must print per-phase us/call rows and
    an attributed-vs-measured sum within the +-25% acceptance band."""
    proc, _ = profile_bench_run
    err = proc.stderr
    assert "# per-call CPU budget by phase" in err
    phase_rows = [l for l in err.splitlines()
                  if l.startswith("#   ") and "us/call" in l]
    assert len(phase_rows) >= 2, err[-2000:]
    budget = [l for l in err.splitlines()
              if l.startswith("# profile budget:")]
    assert budget, err[-2000:]
    ratio = float(budget[0].split("ratio=")[1])
    assert 0.75 <= ratio <= 1.25, budget[0]
    # and the machine-readable line on stdout agrees
    rows = [json.loads(l) for l in proc.stdout.splitlines()
            if l.startswith("{")]
    metric = [r for r in rows
              if r["metric"] == "profile_attributed_cpu_ratio"]
    assert len(metric) == 1, proc.stdout
    assert 0.75 <= metric[0]["value"] <= 1.25, metric[0]


def test_sampler_overhead_under_two_pct_at_default_hz():
    """The always-on rate must be affordable: sampling a live 64B echo
    lane at the default continuous hz costs <2% of wall time — with a live
    serving engine folded in, so the guard also prices the engine's
    registered step-loop thread and the g_serving_* series rings."""
    import time

    from brpc_tpu import flags as _flags
    from brpc_tpu.profiling.sampler import ProfileSession
    from brpc_tpu.proto import echo_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Server, Service, Stub
    from test_serving import _stub_engine

    ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    class EchoImpl(Service):
        DESCRIPTOR = ECHO

        def Echo(self, cntl, request, done):
            return echo_pb2.EchoResponse(message=request.message,
                                         payload=request.payload)

    hz = float(_flags.get("tpu_prof_continuous_hz"))
    assert hz > 0
    # the guard must cover the series plane: Server.start installs the
    # ring sweep on the same 1s sampler daemon the guard exercises
    from brpc_tpu.metrics.series import global_series

    assert _flags.get("var_series_enabled")
    ticks_before = global_series().ticks
    srv = Server().add_service(EchoImpl()).start("tpu://127.0.0.1:0/0")
    engine = _stub_engine(step_s=0.002)
    try:
        # decode activity spanning the whole sampled window: the engine's
        # "serving" thread is profiler-registered, so its stacks are in
        # every tick the guard prices
        for _ in range(3):
            assert engine.submit(engine.model.synth_prompt(4), 500)[0] == 0
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=10000))
        ch.init(str(srv.listen_endpoint()))
        stub = Stub(ch, ECHO)
        req = echo_pb2.EchoRequest(message="x", payload=b"\xab" * 64)
        stub.Echo(req)  # warmup
        sess = ProfileSession(hz=hz, budget=False).start()
        t0 = time.monotonic()
        deadline = t0 + 1.5
        while time.monotonic() < deadline:
            stub.Echo(req)
        wall = time.monotonic() - t0
        prof = sess.stop()
        assert engine.steps > 0, "serving engine never stepped in-window"
    finally:
        srv.stop()
        srv.join(timeout=2)
        engine.stop()
    overhead = prof.sample_time_s / wall
    assert overhead < 0.02, (
        f"sampler self-time {overhead:.2%} of wall at {hz:g}hz "
        f"({prof.ticks} ticks, sample_time={prof.sample_time_s:.4f}s)")
    # the series sweep ran during the window and its own cost stays far
    # inside the 1s tick budget (same <2% bar as the profiler)
    series = global_series()
    assert series.ticks > ticks_before, "series rings never ticked"
    avg_tick = series.total_tick_s / max(series.ticks, 1)
    assert avg_tick < 0.02, (
        f"series ring sweep averages {avg_tick * 1e3:.2f}ms per 1s tick")


def test_record_replay_diff_smoke(tmp_path):
    """The record -> replay -> diff loop on the shm lane, end to end
    through the CLI tools: ~2s of recorded echo traffic over tpu://, a 2x
    open-loop replay via tools/rpc_replay, and tools/trace_diff comparing
    the recorded phase timelines against the replayed ones — exit 0, no
    regression flagged on an unchanged server."""
    import json as _json
    import time

    from brpc_tpu import flags as _flags
    from brpc_tpu.metrics.collector import global_collector
    from brpc_tpu.proto import echo_pb2
    from brpc_tpu.rpc import (Channel, ChannelOptions, Server,
                              ServerOptions, Service, Stub)
    from brpc_tpu.trace import span as _span
    from tools import rpc_replay, trace_diff

    ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    class EchoImpl(Service):
        DESCRIPTOR = ECHO

        def Echo(self, cntl, request, done):
            return echo_pb2.EchoResponse(message=request.message)

    record_dir = tmp_path / "dumps"
    _flags.set_flag("rpcz_sample_ratio", "1.0")
    _flags.set_flag("rpc_dump_ratio", "1.0")
    _flags.set_flag("collector_max_samples_per_second", "0")
    global_collector()._deny_until = 0.0
    _span.reset_for_test()
    try:
        server = (Server(ServerOptions(rpc_dump_dir=str(record_dir)))
                  .add_service(EchoImpl()).start("tpu://127.0.0.1:0/0"))
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=10000))
            ch.init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO)
            deadline = time.monotonic() + 2.0
            sent = 0
            while time.monotonic() < deadline and sent < 60:
                stub.Echo(echo_pb2.EchoRequest(message=f"s{sent}"))
                sent += 1
                time.sleep(0.01)  # real inter-arrival gaps to halve
            t = time.monotonic() + 2.0
            while (server.rpc_dumper.sampled_count < sent
                   and time.monotonic() < t):
                time.sleep(0.01)
            assert server.rpc_dumper.sampled_count >= sent
            server.rpc_dumper.close()
        finally:
            server.stop()
            server.join(timeout=2)
        _flags.set_flag("rpc_dump_ratio", "0.0")

        _span.reset_for_test()
        server2 = Server().add_service(EchoImpl()).start("tpu://127.0.0.1:0/0")
        try:
            t0 = time.monotonic()
            rc = rpc_replay.main([
                "--dump", str(record_dir),
                "--server", str(server2.listen_endpoint()),
                "--rate-mult", "2", "--timeout-ms", "10000",
                "--report-interval", "0"])
            replay_s = time.monotonic() - t0
            assert rc == 0
            # 2x rate-mult: the ~1.5s+ recorded schedule replays in ~half
            assert replay_s < 1.5, f"2x replay took {replay_s:.2f}s"
            t = time.monotonic() + 2.0
            while (len([s for s in _span.recent_spans(200)
                        if s.kind == _span.KIND_SERVER]) < sent
                   and time.monotonic() < t):
                time.sleep(0.01)
        finally:
            server2.stop()
            server2.join(timeout=2)
        replayed = tmp_path / "replayed.json"
        replayed.write_text(_json.dumps({"spans": [
            s.to_dict() for s in _span.recent_spans(200)]}))
        # p50 + 10ms floor: quiet on an unchanged server even on a noisy box
        rc = trace_diff.main([str(record_dir), str(replayed),
                              "--percentile", "50",
                              "--min-delta-us", "10000"])
        assert rc == 0
    finally:
        _flags.set_flag("rpc_dump_ratio", "0.0")
        _flags.set_flag("collector_max_samples_per_second", "1000")


BASELINE_FOLDED = os.path.join(REPO, "tests", "data",
                               "bench_profile_baseline.folded")


def test_per_phase_cpu_ratchet_vs_baseline(profile_bench_run, capsys):
    """The committed folded baseline gates per-phase CPU share: a live
    --profile run must not move any phase=* synthetic root frame by more
    than 5 percentage points of whole-process samples (measured run-to-run
    noise on this lane is <1pp; a phase whose per-call CPU blows up shows
    here with the phase named)."""
    from tools import prof_diff

    _, out = profile_bench_run
    rc = prof_diff.main([BASELINE_FOLDED, str(out), "--total",
                         "--only-prefix", "phase=",
                         "--fail-above-pct", "5"])
    captured = capsys.readouterr()
    assert rc == 0, f"per-phase CPU ratchet tripped:\n{captured.out}"


def test_per_phase_ratchet_names_moved_phase(tmp_path, capsys):
    """Sensitivity check, no live run needed: inflate the baseline's
    phase=parse stacks 9x and the ratchet must exit 1 with the moved
    phase ranked as the top mover."""
    from tools import prof_diff

    doctored = []
    for line in open(BASELINE_FOLDED, encoding="utf-8"):
        stack, _, weight = line.rstrip("\n").rpartition(" ")
        if ";phase=parse;" in stack:
            weight = str(int(weight) * 9)
        doctored.append(f"{stack} {weight}")
    bad = tmp_path / "doctored.folded"
    bad.write_text("\n".join(doctored) + "\n")
    rc = prof_diff.main([BASELINE_FOLDED, str(bad), "--total",
                         "--only-prefix", "phase=",
                         "--fail-above-pct", "5", "--json"])
    assert rc == 1
    report = json.loads(capsys.readouterr().out)
    assert report["movers"], report
    assert report["movers"][0]["frame"] == "phase=parse", report["movers"]
    assert report["movers"][0]["delta_pct"] > 5, report["movers"][0]
    # the filter keeps the ratchet to the synthetic phase frames only
    assert all(m["frame"].startswith("phase=") for m in report["movers"])
