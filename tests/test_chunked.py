"""Chunked transfer-encoding streaming parse (ROADMAP carry-over).

The PendingBodyCursor machinery handled only declared-length bodies;
ChunkedBodyCursor extends streaming consumption to Transfer-Encoding:
chunked, where the total is unknown until the 0-size chunk. Three levels:
the cursor state machine fed adversarially fragmented bytes, cursor
registration through parse_http_message, and an end-to-end chunked POST
against a live server with the body dripped across many writes."""

import json
import socket
import time
import types

import pytest

from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.policy.http_protocol import HttpProtocol, parse_http_message
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Server, Service
from brpc_tpu.rpc.protocol import (
    PARSE_BAD,
    PARSE_NOT_ENOUGH_DATA,
    ChunkedBodyCursor,
)

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoServiceImpl(Service):
    DESCRIPTOR = ECHO_DESC

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def http_server():
    server = Server().add_service(EchoServiceImpl()).start("127.0.0.1:0")
    yield server
    server.stop()
    server.join(timeout=2)


def _chunked(*parts, trailers=b""):
    out = b""
    for p in parts:
        out += f"{len(p):x}".encode() + b"\r\n" + p + b"\r\n"
    return out + b"0\r\n" + trailers + b"\r\n"


def _cursor(collected):
    return ChunkedBodyCursor(
        types.SimpleNamespace(name="http"),
        finish=lambda cur: collected.append(cur.body()))


# ------------------------------------------------------------- state machine
class TestCursorStateMachine:
    def test_whole_body_single_feed(self):
        got = []
        cur = _cursor(got)
        buf = IOBuf(_chunked(b"Wiki", b"pedia"))
        cur.feed(buf)
        assert cur.done and not cur.failed
        assert len(buf) == 0
        cur.finish()
        assert got == [b"Wikipedia"]

    def test_byte_by_byte_feed(self):
        got = []
        cur = _cursor(got)
        wire = _chunked(b"hello ", b"chunked", b" world")
        for i in range(len(wire)):
            assert not cur.done
            cur.feed(IOBuf(wire[i:i + 1]))
        assert cur.done
        cur.finish()
        assert got == [b"hello chunked world"]

    def test_split_inside_size_line_and_chunk(self):
        got = []
        cur = _cursor(got)
        body = b"\xaa" * 1000
        wire = _chunked(body)
        # split mid size-line, mid data, mid trailing CRLF
        for cutpoints in ((1, 500, len(wire) - 1),):
            prev = 0
            for cp in cutpoints + (len(wire),):
                cur.feed(IOBuf(wire[prev:cp]))
                prev = cp
        assert cur.done
        cur.finish()
        assert got == [body]

    def test_chunk_extension_ignored(self):
        got = []
        cur = _cursor(got)
        cur.feed(IOBuf(b"4;ext=1\r\nWiki\r\n0\r\n\r\n"))
        assert cur.done
        cur.finish()
        assert got == [b"Wiki"]

    def test_trailer_headers_consumed(self):
        got = []
        cur = _cursor(got)
        wire = _chunked(b"data", trailers=b"X-Sum: 1\r\nX-N: 2\r\n")
        cur.feed(IOBuf(wire))
        assert cur.done
        cur.finish()
        assert got == [b"data"]

    def test_consumed_counts_framing_and_payload(self):
        cur = _cursor([])
        wire = _chunked(b"abcd")
        extra = b"GET / HTTP/1.1\r\n"   # next pipelined message stays put
        buf = IOBuf(wire + extra)
        cur.feed(buf)
        assert cur.done
        assert cur.consumed == len(wire)
        assert buf.tobytes() == extra

    def test_malformed_size_fails(self):
        cur = _cursor([])
        cur.feed(IOBuf(b"zz\r\nWiki\r\n"))
        assert cur.failed and "size" in cur.error
        assert not cur.done

    def test_missing_chunk_terminator_fails(self):
        cur = _cursor([])
        cur.feed(IOBuf(b"4\r\nWikiXX\r\n"))
        assert cur.failed and "terminator" in cur.error

    def test_oversized_framing_line_fails(self):
        cur = _cursor([])
        cur.feed(IOBuf(b"1" * 400))
        assert cur.failed and "oversized" in cur.error

    def test_bare_lf_fails(self):
        cur = _cursor([])
        cur.feed(IOBuf(b"4\nWiki\r\n"))
        assert cur.failed


# -------------------------------------------------- parse-level registration
class TestParseRegistration:
    HEAD = b"POST /x HTTP/1.1\r\nTransfer-Encoding: chunked\r\n\r\n"

    def _sock(self):
        return types.SimpleNamespace(pending_body=None)

    def test_incomplete_body_registers_cursor(self):
        sock = self._sock()
        buf = IOBuf(self.HEAD + b"4\r\nWi")
        rc, _ = parse_http_message(buf, sock=sock, proto=HttpProtocol())
        assert rc == PARSE_NOT_ENOUGH_DATA
        cur = sock.pending_body
        assert isinstance(cur, ChunkedBodyCursor)
        assert len(buf) == 0                    # partial chunk claimed
        # drip the rest; finish() produces the message
        cur.feed(IOBuf(b"ki\r\n5\r\npedia\r\n0\r\n\r\n"))
        assert cur.done
        msg = cur.finish()
        assert msg.meta.body == b"Wikipedia"
        assert msg.meta.path == "/x"

    def test_complete_body_keeps_whole_message_path(self):
        sock = self._sock()
        buf = IOBuf(self.HEAD + _chunked(b"Wiki", b"pedia"))
        rc, msg = parse_http_message(buf, sock=sock, proto=HttpProtocol())
        assert rc == 0 and msg.body == b"Wikipedia"
        assert sock.pending_body is None

    def test_no_sock_keeps_whole_message_semantics(self):
        # standalone callers (http_fetch) never get a cursor
        rc, _ = parse_http_message(IOBuf(self.HEAD + b"4\r\nWi"))
        assert rc == PARSE_NOT_ENOUGH_DATA

    def test_busy_socket_not_double_registered(self):
        sock = types.SimpleNamespace(pending_body=object())
        rc, _ = parse_http_message(IOBuf(self.HEAD + b"4\r\nWi"),
                                   sock=sock, proto=HttpProtocol())
        assert rc == PARSE_NOT_ENOUGH_DATA

    def test_malformed_mid_stream_fails_socket_via_cut_loop(self):
        from test_stream_parse import _FakeParseSock

        from brpc_tpu.policy import ensure_registered
        from brpc_tpu.rpc.input_messenger import InputMessenger

        ensure_registered()
        sock = _FakeParseSock()
        messenger = InputMessenger()
        sock.read_buf.append(self.HEAD + b"4\r\nWi")
        messenger.cut_messages(sock)
        assert isinstance(sock.pending_body, ChunkedBodyCursor)
        sock.read_buf.append(b"ki\r\nNOT-HEX\r\n")
        messenger.cut_messages(sock)
        assert sock.failed
        assert sock.pending_body is None


# ------------------------------------------------------------------ e2e wire
class TestEndToEnd:
    def test_chunked_json_post_dripped_across_writes(self, http_server):
        """A chunked POST whose frames arrive over many separate writes:
        the server's cut loop must stream them through the cursor and
        dispatch one complete JSON-RPC call."""
        body = json.dumps({"message": "chunky",
                           "payload": "QUJD" * 2000}).encode()
        step = 97
        chunks = [body[i:i + step] for i in range(0, len(body), step)]
        wire = (b"POST /EchoService/Echo HTTP/1.1\r\n"
                b"Content-Type: application/json\r\n"
                b"Transfer-Encoding: chunked\r\n\r\n")
        ep = http_server.listen_endpoint()
        with socket.create_connection((ep.host, ep.port), timeout=10) as s:
            s.sendall(wire)
            for c in chunks:
                s.sendall(f"{len(c):x}".encode() + b"\r\n")
                s.sendall(c + b"\r\n")
                time.sleep(0.002)           # force separate read bursts
            s.sendall(b"0\r\n\r\n")
            s.settimeout(10)
            resp = b""
            while b"\r\n\r\n" not in resp:
                resp += s.recv(65536)
            head, _, rest = resp.partition(b"\r\n\r\n")
            assert b"200" in head.split(b"\r\n")[0]
            clen = int([h for h in head.split(b"\r\n")
                        if h.lower().startswith(b"content-length")][0]
                       .split(b":")[1])
            while len(rest) < clen:
                rest += s.recv(65536)
        data = json.loads(rest)
        assert data["message"] == "chunky"
        assert data["payload"] == "QUJD" * 2000
