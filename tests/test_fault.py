"""Chaos suite: the fault-injection framework and the self-healing tunnel.

Three layers, mirroring how the framework is meant to be used:

* registry semantics (arm/disarm, triggers, the master gate) — pure units;
* each injection point observably fires at its call site — fake-ctrl
  endpoints and wire-frame assertions;
* the tunnel survives what the points break — real servers, real shm
  windows: a vsock killed mid-16MB message heals under a new epoch and the
  retried call still crosses zero-copy, stale frames of the dead epoch
  bounce off the guard, and an endpoint that keeps refusing re-handshake
  is isolated by the healer's circuit breaker.
"""

import json
import struct
import threading
import time

import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.butil.iobuf import IOBuf
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    Server,
    ServerOptions,
    Stub,
)

from test_tpu_transport import (  # noqa: F401  (fixture reuse)
    EchoServiceImpl,
    _acked_indices,
    _data_frame_body,
    _make_endpoint,
    _stub_for,
    _trpc_response_packet,
    tpu_server,
)

pytestmark = pytest.mark.chaos

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


@pytest.fixture()
def fault_enabled():
    _flags.set_flag("fault_injection_enabled", True)
    yield
    fault.disarm_all()
    _flags.set_flag("fault_injection_enabled", False)


# ---------------------------------------------------------------- registry
class TestRegistry:
    def test_master_gate_defaults_off(self):
        fault.arm("x.gated", mode="always")
        try:
            assert fault.hit("x.gated") is None
        finally:
            fault.disarm("x.gated")

    def test_oneshot_after_n(self, fault_enabled):
        fault.arm("x.shot", after=2, k=7)
        assert fault.hit("x.shot") is None
        assert fault.hit("x.shot") is None
        fired = fault.hit("x.shot")
        assert fired == {"k": 7}
        # oneshot: consumed and auto-disarmed
        assert fault.hit("x.shot") is None
        assert not fault.disarm("x.shot")

    def test_always_with_count_and_match(self, fault_enabled):
        fault.arm("x.many", mode="always", count=2, match={"ftype": 3})
        # mismatch neither fires nor consumes
        assert fault.hit("x.many", ftype=4) is None
        assert fault.hit("x.many", ftype=3) is not None
        assert fault.hit("x.many", ftype=3) is not None
        assert fault.hit("x.many", ftype=3) is None  # count exhausted

    def test_parse_spec_kv_coercion(self, fault_enabled):
        fault.parse_spec_kv("x.kv", {"mode": "always", "after": "1",
                                     "match_role": "client",
                                     "delay_ms": "25", "flag": "true"})
        assert fault.hit("x.kv", role="server") is None
        assert fault.hit("x.kv", role="client") is None       # after=1 skip
        fired = fault.hit("x.kv", role="client")
        assert fired == {"delay_ms": 25, "flag": True}
        fault.disarm("x.kv")

    def test_snapshot_reports_armed_state(self, fault_enabled):
        fault.arm("x.snap", mode="always", q=1)
        try:
            fault.hit("x.snap")
            rows = {r["point"]: r for r in fault.snapshot()}
            row = rows["x.snap"]
            assert row["fired"] >= 1
            assert row["armed"]["mode"] == "always"
            assert row["armed"]["p"] == 1.0  # p is a trigger, not a param
            assert row["armed"]["params"] == {"q": 1}
        finally:
            fault.disarm("x.snap")


# ------------------------------------------------------- points fire (unit)
class TestInjectionPointsFire:
    def test_send_delay(self, fault_enabled):
        tr, fake, ep = _make_endpoint()
        try:
            fault.arm("tpu.send.delay", delay_ms=60)
            t0 = time.monotonic()
            assert ep.send_packet(IOBuf(b"tiny")) == 0
            assert time.monotonic() - t0 >= 0.05
        finally:
            ep.fail(0, "test done")

    def test_frame_corrupt_flips_a_byte(self, fault_enabled):
        tr, fake, ep = _make_endpoint()
        try:
            assert ep.send_packet(IOBuf(b"payload!")) == 0
            clean = fake.frames[-1]
            fault.arm("tpu.frame.corrupt", offset=len(clean) - 1)
            assert ep.send_packet(IOBuf(b"payload!")) == 0
            dirty = fake.frames[-1]
            assert len(dirty) == len(clean)
            assert dirty[-1] == clean[-1] ^ 0xFF
            assert dirty[:-1] == clean[:-1]
        finally:
            ep.fail(0, "test done")

    def test_frame_truncate_cuts_the_tail(self, fault_enabled):
        tr, fake, ep = _make_endpoint()
        try:
            assert ep.send_packet(IOBuf(b"payload!")) == 0
            clean = fake.frames[-1]
            fault.arm("tpu.frame.truncate", bytes=3)
            assert ep.send_packet(IOBuf(b"payload!")) == 0
            assert fake.frames[-1] == clean[:-3]
        finally:
            ep.fail(0, "test done")

    def test_frame_drop_posts_nothing(self, fault_enabled):
        tr, fake, ep = _make_endpoint()
        try:
            n0 = len(fake.frames)
            fault.arm("tpu.frame.drop")
            assert ep.send_packet(IOBuf(b"gone")) == 0    # "posted" ok
            assert len(fake.frames) == n0                 # ...but no frame
            assert ep.send_packet(IOBuf(b"kept")) == 0
            assert len(fake.frames) == n0 + 1
        finally:
            ep.fail(0, "test done")

    def test_tunnel_kill_fails_the_vsock(self, fault_enabled):
        tr, fake, ep = _make_endpoint()
        fault.arm("tpu.tunnel.kill")
        assert ep.send_packet(IOBuf(b"boom")) != 0
        assert fake.failed
        assert ep.vsock.failed

    def test_ack_drop_swallows_credits(self, fault_enabled):
        tr, fake, ep = _make_endpoint()
        try:
            fault.arm("tpu.ack.drop")
            ep._queue_acks((1, 2))
            assert _acked_indices(fake) == []       # credits vanished
            ep._queue_acks((3,))
            assert _acked_indices(fake) == [[3]]    # oneshot consumed
        finally:
            ep.fail(0, "test done")


# --------------------------------------------------------- epoch discipline
class _RecorderWindow:
    def __init__(self):
        self.released = []

    def release(self, indices):
        self.released.extend(indices)

    def close(self):
        pass


class TestEpochGuards:
    def test_stale_ack_is_discarded(self):
        tr, fake, ep = _make_endpoint()
        try:
            ep.window = _RecorderWindow()
            ep.epoch = 3
            stale0 = tr.g_tunnel_stale_epoch_frames.get_value()
            ep.on_ack(struct.pack("!4I", 2, 2, 0, 1))     # old epoch
            assert ep.window.released == []
            assert tr.g_tunnel_stale_epoch_frames.get_value() == stale0 + 1
            ep.on_ack(struct.pack("!4I", 3, 2, 0, 1))     # current epoch
            assert ep.window.released == [0, 1]
        finally:
            ep.window = None
            ep.fail(0, "test done")

    def test_stale_data_is_discarded(self):
        tr, fake, ep = _make_endpoint()
        try:
            ep.epoch = 3
            stale0 = tr.g_tunnel_stale_epoch_frames.get_value()
            ep.on_data(IOBuf(_data_frame_body([(0, 64)], epoch=2)))
            assert len(ep.vsock.read_buf) == 0
            assert ep._borrowed_outstanding == 0          # nothing borrowed
            assert tr.g_tunnel_stale_epoch_frames.get_value() == stale0 + 1
        finally:
            ep.fail(0, "test done")

    def test_server_in_band_rehandshake(self):
        from test_tpu_transport import _FakeCtrl

        tr, _, client_ep = _make_endpoint()   # donates a real shm pool
        fake = _FakeCtrl()
        srv = tr.TpuEndpoint(fake, role="server")
        try:
            pool = client_ep.recv_pool
            hello = {"v": tr.HANDSHAKE_VERSION, "pool": pool.name,
                     "bs": pool.block_size, "bc": pool.block_count,
                     "ordinal": 0, "pid": 1, "gen": 1}
            srv.on_hello(json.dumps(hello).encode())
            assert srv.ready.is_set() and srv.epoch == 1
            first_pool = srv.recv_pool
            assert first_pool is not None

            # the dialer comes back under generation 2 on the SAME socket
            hello["gen"] = 2
            srv.on_hello(json.dumps(hello).encode())
            assert srv.epoch == 2
            assert srv.recv_pool is not None
            assert srv.recv_pool is not first_pool        # rebuilt fresh
            acks = [f for f in fake.frames
                    if struct.unpack_from(tr.CTRL_HDR, f)[1]
                    == tr.FT_HELLO_ACK]
            assert len(acks) == 2
            last = json.loads(acks[-1][tr.CTRL_HDR_SIZE:].decode())
            assert last["gen"] == 2 and "err" not in last

            # a stale duplicate HELLO from the dead epoch is pure noise
            stale0 = tr.g_tunnel_stale_epoch_frames.get_value()
            hello["gen"] = 1
            srv.on_hello(json.dumps(hello).encode())
            assert srv.epoch == 2
            assert tr.g_tunnel_stale_epoch_frames.get_value() == stale0 + 1
        finally:
            srv.fail(0, "test done")
            client_ep.fail(0, "test done")


# ------------------------------------------------------------- EOB wakeup
class TestEndOfBodyWakeup:
    def test_flush_bypasses_cut_batch_hold(self):
        tr, fake, ep = _make_endpoint()
        try:
            ep.cut_batch_begin()
            ep._queue_acks((4, 5))
            assert _acked_indices(fake) == []         # banked by the hold
            eob0 = tr.g_tunnel_eob_wakeups.get_value()
            ep.cut_body_complete()
            assert _acked_indices(fake) == [[4, 5]]   # flushed NOW
            assert tr.g_tunnel_eob_wakeups.get_value() == eob0 + 1
            ep.cut_batch_end()                        # nothing left to send
            assert _acked_indices(fake) == [[4, 5]]
        finally:
            ep.fail(0, "test done")


# ----------------------------------------------------- self-healing tunnel
class TestSelfHealingTunnel:
    def test_kill_mid_16mb_message_recovers(self, tpu_server, fault_enabled):
        from brpc_tpu.tpu import transport as tr

        stub = _stub_for(tpu_server, timeout_ms=60000)
        payload = b"\xc7" * (16 * 1024 * 1024)
        # warm the tunnel so the kill hits an established epoch
        assert stub.Echo(echo_pb2.EchoRequest(message="warm")).message \
            == "warm"
        ep = tpu_server.listen_endpoint()
        key = (ep.host, ep.port, ep.device_ordinal)
        vs0 = tr._remote_sockets.get(key)
        assert vs0 is not None and not vs0.failed
        tr.reset_borrowed_peak()
        copied0 = tr.g_tunnel_copied_bytes.get_value()
        reconnects0 = tr.g_tunnel_reconnects.get_value()

        # the 9th DATA frame of the streaming send kills the vsock; the
        # retried attempt must land on a healed tunnel under a new epoch
        fault.arm("tpu.tunnel.kill", after=8)
        r = stub.Echo(echo_pb2.EchoRequest(message="big", payload=payload))
        assert r.payload == payload
        assert vs0.failed                              # the kill was real
        vs1 = tr._remote_sockets.get(key)
        assert vs1 is not None and vs1 is not vs0 and not vs1.failed
        assert vs1.endpoint.epoch >= 2                 # fresh generation
        assert tr.g_tunnel_reconnects.get_value() > reconnects0
        from brpc_tpu.butil.iobuf import supports_block_ownership

        if supports_block_ownership():
            # the RETRIED 16MB attempt still crossed zero-copy
            assert tr.g_tunnel_copied_bytes.get_value() == copied0

        # teardown-leak check: every borrow of both the dead and the live
        # endpoints drains back to zero once the dust settles
        endpoints = [vs0.endpoint, vs1.endpoint] \
            + [e for e in tpu_server._tpu_endpoints]
        deadline = time.monotonic() + 5.0
        while time.monotonic() < deadline:
            if all(e._borrowed_outstanding == 0 for e in endpoints):
                break
            time.sleep(0.02)
        assert all(e._borrowed_outstanding == 0 for e in endpoints)

    def test_handshake_refusals_trip_the_breaker(self, tpu_server,
                                                 fault_enabled):
        from brpc_tpu.tpu import transport as tr

        ep = tpu_server.listen_endpoint()
        key = (ep.host, ep.port, ep.device_ordinal)
        healer = tr._healer_for(key)
        healer.breaker.reset()
        fault.arm("tpu.handshake.fail", mode="always",
                  reason="chaos says no")
        try:
            # no cached socket for this key yet: every dial re-handshakes
            for _ in range(3):
                with pytest.raises(ConnectionError):
                    tr.connect_tpu(ep, connect_timeout=5.0)
            assert healer.breaker.isolated
            # the breaker now fails fast, without dialing at all
            with pytest.raises(ConnectionError, match="circuit breaker"):
                tr.connect_tpu(ep, connect_timeout=5.0)
        finally:
            fault.disarm("tpu.handshake.fail")
            healer.breaker.reset()
        # pardoned + disarmed: the same endpoint dials clean
        vs = tr.connect_tpu(ep, connect_timeout=5.0)
        assert not vs.failed

    def test_tpu_probe_follows_scheme(self, tpu_server):
        from brpc_tpu.rpc.health_check import (probe_for_endpoint,
                                               tcp_probe, tpu_probe)

        ep = tpu_server.listen_endpoint()
        assert probe_for_endpoint(ep) is tpu_probe
        assert tpu_probe(ep) is True
        assert tcp_probe(ep) is True                  # delegates by scheme


# --------------------------------------------------------- server deadlines
class _CaptureSock:
    remote = "chaos://client"

    def __init__(self):
        self.written = []

    def write(self, packet, id_wait=None):
        self.written.append(packet.tobytes()
                            if hasattr(packet, "tobytes") else bytes(packet))
        return 0


class TestServerDeadline:
    def _request_meta(self, timeout_ms):
        from brpc_tpu.proto import rpc_meta_pb2

        meta = rpc_meta_pb2.RpcMeta()
        meta.correlation_id = 77
        meta.request.service_name = "EchoService"
        meta.request.method_name = "Echo"
        meta.request.timeout_ms = timeout_ms
        return meta

    def test_expired_budget_rejected_before_handler(self):
        from brpc_tpu.policy import ensure_registered
        from brpc_tpu.rpc import errors, server_processing as sp
        from brpc_tpu.rpc.protocol import ParsedMessage, find_protocol

        ensure_registered()
        proto = find_protocol("trpc_std")
        server = Server(ServerOptions())
        server.add_service(EchoServiceImpl())
        server.start("127.0.0.1:0")
        try:
            msg = ParsedMessage(proto, self._request_meta(100), IOBuf())
            sock = _CaptureSock()
            msg.socket = sock
            msg.arrival = time.monotonic() - 1.0      # budget long gone
            n0 = sp.g_server_deadline_expired.get_value()
            sp.process_rpc_request(proto, msg, server)
            assert sp.g_server_deadline_expired.get_value() == n0 + 1
            assert len(sock.written) == 1
            rc, resp = proto.parse(IOBuf(sock.written[0]))
            assert resp.meta.response.error_code == errors.ERPCTIMEDOUT
            assert server.concurrency == 0            # settled, not leaked
        finally:
            server.stop()
            server.join(timeout=2)

    def test_fresh_budget_sets_deadline_and_dispatches(self):
        from brpc_tpu.policy import ensure_registered
        from brpc_tpu.rpc import errors, server_processing as sp
        from brpc_tpu.rpc.protocol import ParsedMessage, find_protocol
        from brpc_tpu.proto import echo_pb2 as _echo

        ensure_registered()
        proto = find_protocol("trpc_std")
        server = Server(ServerOptions())
        server.add_service(EchoServiceImpl())
        server.start("127.0.0.1:0")
        try:
            meta = self._request_meta(30000)
            req = _echo.EchoRequest(message="hi")
            msg = ParsedMessage(proto, meta, IOBuf(req.SerializeToString()))
            sock = _CaptureSock()
            msg.socket = sock
            sp.process_rpc_request(proto, msg, server)
            deadline = time.monotonic() + 2.0
            while not sock.written and time.monotonic() < deadline:
                time.sleep(0.01)
            assert sock.written, "handler never answered"
            rc, resp = proto.parse(IOBuf(sock.written[0]))
            assert resp.meta.response.error_code == errors.OK
        finally:
            server.stop()
            server.join(timeout=2)

    def test_batch_admit_rejects_spent_deadline(self):
        from brpc_tpu.batch.runtime import make_batched
        from brpc_tpu.rpc import errors

        calls = []
        bound = make_batched("chaos.batch",
                             lambda ctx: calls.append(ctx) or
                             [None] * ctx.size)
        cntl = Controller()
        cntl.deadline_mono = time.monotonic() - 0.5
        done_called = []
        bound(cntl, object(), lambda resp: done_called.append(resp))
        assert cntl.error_code == errors.ERPCTIMEDOUT
        assert not calls and not done_called

    def test_handler_crash_point_is_isolated(self, tpu_server,
                                             fault_enabled):
        from brpc_tpu.rpc import errors
        from brpc_tpu.rpc.channel import RpcError

        stub = _stub_for(tpu_server)
        fault.arm("rpc.handler.crash")
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="die"))
        assert ei.value.error_code == errors.EINTERNAL
        # the crash consumed the oneshot; the server survived it
        assert stub.Echo(echo_pb2.EchoRequest(message="ok")).message == "ok"


# ----------------------------------------------------- /fault + chaos_run
class TestFaultServiceAndChaosRun:
    @pytest.fixture()
    def http_server(self):
        server = Server(ServerOptions())
        server.add_service(EchoServiceImpl())
        server.start("127.0.0.1:0")
        yield server
        server.stop()
        server.join(timeout=2)
        fault.disarm_all()
        _flags.set_flag("fault_injection_enabled", False)

    def test_fault_http_surface(self, http_server):
        from brpc_tpu.policy.http_protocol import http_fetch

        addr = str(http_server.listen_endpoint())
        resp = http_fetch(addr, "GET", "/fault")
        assert resp.status == 200
        state = json.loads(resp.body)
        assert state["enabled"] is False
        points = {r["point"] for r in state["points"]}
        assert "tpu.tunnel.kill" in points
        assert "rpc.handler.crash" in points

        resp = http_fetch(addr, "GET",
                          "/fault/arm?point=x.http&mode=always&delay_ms=5")
        assert resp.status == 200
        rows = {r["point"]: r for r in fault.snapshot()}
        assert rows["x.http"]["armed"]["params"] == {"delay_ms": 5}
        assert http_fetch(addr, "GET",
                          "/fault/disarm?point=x.http").status == 200
        assert http_fetch(addr, "GET",
                          "/fault/disarm?point=x.http").status == 404
        assert http_fetch(addr, "GET", "/fault/arm").status == 400
        assert http_fetch(addr, "GET", "/fault/nonsense").status == 404

    def test_chaos_run_scenario_replay(self, http_server, tmp_path):
        import sys

        sys.path.insert(0, "tools")
        try:
            import chaos_run
        finally:
            sys.path.pop(0)

        fault.register("x.scenario", "chaos_run e2e target")
        scenario = {
            "steps": [
                {"op": "flag", "name": "fault_injection_enabled",
                 "value": "true"},
                {"op": "arm", "point": "x.scenario", "mode": "always",
                 "delay_ms": 1},
                {"op": "sleep", "seconds": 0.01},
                {"op": "expect_fired", "point": "x.scenario", "min": 0},
            ]
        }
        path = tmp_path / "scenario.json"
        path.write_text(json.dumps(scenario))
        addr = str(http_server.listen_endpoint())
        summary = chaos_run.run_scenario(addr, str(path))
        assert summary["steps"] == 4
        assert _flags.get("fault_injection_enabled") is True
        assert fault.hit("x.scenario") == {"delay_ms": 1}   # really armed
        # and an unmet expectation fails the run
        scenario["steps"].append({"op": "expect_fired",
                                  "point": "x.never", "min": 1})
        path.write_text(json.dumps(scenario))
        with pytest.raises(chaos_run.ScenarioError):
            chaos_run.run_scenario(addr, str(path))
