"""Metrics tests (pattern: reference test/bvar_*_unittest.cpp — real threads
hammering reducers, manual sampler ticks instead of 1 s sleeps)."""

import threading

import pytest

from brpc_tpu.metrics import (
    Adder,
    Maxer,
    Miner,
    IntRecorder,
    LatencyRecorder,
    Percentile,
    PerSecond,
    SamplerCollector,
    Status,
    PassiveStatus,
    MultiDimension,
    Window,
    clear_registry,
    dump_exposed,
    get_exposed,
    prometheus_text,
)


@pytest.fixture(autouse=True)
def _clean_registry():
    clear_registry()
    yield
    clear_registry()


class TestReducers:
    def test_adder_single_thread(self):
        a = Adder()
        a << 1 << 2 << 3
        assert a.get_value() == 6

    def test_adder_many_threads(self):
        a = Adder()
        n_threads, per_thread = 8, 10_000

        def worker():
            for _ in range(per_thread):
                a.put(1)

        ts = [threading.Thread(target=worker) for _ in range(n_threads)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert a.get_value() == n_threads * per_thread

    def test_maxer_miner(self):
        m, mi = Maxer(), Miner()
        for v in [3, 9, 1]:
            m.put(v)
            mi.put(v)
        assert m.get_value() == 9
        assert mi.get_value() == 1

    def test_reset_zeroes(self):
        a = Adder()
        a.put(5)
        assert a.reset() == 5
        assert a.get_value() == 0


class TestWindow:
    def test_window_delta_partial_series(self):
        col = SamplerCollector(interval_s=3600)  # never auto-ticks in test
        a = Adder()
        w = Window(a, window_size=3, collector=col)
        a.put(10)
        col.tick_all()  # sample: 10
        a.put(5)
        col.tick_all()  # sample: 15
        # series started inside the window: everything counts
        assert w.get_value() == 15

    def test_window_delta_full_ring(self):
        col = SamplerCollector(interval_s=3600)
        a = Adder()
        w = Window(a, window_size=2, collector=col)
        for v in (10, 5, 2):
            a.put(v)
            col.tick_all()  # cumulative samples: 10, 15, 17
        # last 2 seconds saw +5 and +2
        assert w.get_value() == 7

    def test_per_second(self):
        col = SamplerCollector(interval_s=3600)
        a = Adder()
        qps = PerSecond(a, window_size=10, collector=col)
        for _ in range(3):
            a.put(100)
            col.tick_all()
        assert qps.get_value() == pytest.approx(100, rel=0.5)


class TestWindowNonInvertible:
    def test_windowed_miner(self):
        from brpc_tpu.metrics import Miner

        col = SamplerCollector(interval_s=3600)
        mi = Miner()
        w = Window(mi, window_size=3, collector=col)
        mi.put(5)
        col.tick_all()
        assert w.get_value() == 5  # not clamped to 0 by the empty identity

    def test_windowed_maxer_negative(self):
        from brpc_tpu.metrics import Maxer

        col = SamplerCollector(interval_s=3600)
        m = Maxer()
        w = Window(m, window_size=3, collector=col)
        m.put(-7)
        col.tick_all()
        assert w.get_value() == -7


class TestThreadDeathRetirement:
    def test_adder_survives_thread_death(self):
        import gc

        a = Adder()

        def worker():
            a.put(10)

        for _ in range(5):
            t = threading.Thread(target=worker)
            t.start()
            t.join()
        del t
        gc.collect()
        assert a.get_value() == 50
        # dead-thread agents folded into _retired, not leaked in the list
        assert len(a._agents) <= 1

    def test_percentile_survives_thread_death(self):
        import gc

        p = Percentile()

        def worker():
            for i in range(100):
                p.put(i)

        t = threading.Thread(target=worker)
        t.start()
        t.join()
        del t
        gc.collect()
        assert p.get_value().count == 100


class TestPercentile:
    def test_count_weighted_merge(self):
        from brpc_tpu.metrics import PercentileSamples

        hot = PercentileSamples()
        hot.add_group([100.0] * 1000, 1_000_000)  # 1M fast events
        cold = PercentileSamples()
        cold.add_group([5000.0] * 1000, 2_000)    # 2k slow events
        hot.merge(cold)
        # p50 must reflect the 500x traffic imbalance, not 50/50 samples
        assert hot.get_number(0.5) == 100.0
        assert hot.get_number(0.999) == 5000.0

    def test_basic_distribution(self):
        p = Percentile()
        for i in range(1000):
            p.put(i)
        samples = p.get_value()
        assert samples.count == 1000
        assert 450 <= samples.get_number(0.5) <= 550
        assert samples.get_number(0.99) >= 900

    def test_multithread_counts(self):
        p = Percentile()

        def worker():
            for i in range(5000):
                p.put(i)

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert p.get_value().count == 20_000


class TestLatencyRecorder:
    def test_bundle(self):
        col = SamplerCollector(interval_s=3600)
        rec = LatencyRecorder(window_size=10, collector=col)
        for v in range(1, 101):
            rec.record(v * 10.0)
        col.tick_all()
        assert rec.count() == 100
        assert rec.latency() == pytest.approx(505.0, rel=0.01)
        assert rec.max_latency() == 1000.0
        assert rec.latency_percentile(0.99) >= 950
        assert rec.qps() > 0

    def test_describe(self):
        rec = LatencyRecorder(collector=SamplerCollector(interval_s=3600))
        rec.record(100)
        d = rec.describe()
        assert "qps" in d and "p99" in d


class TestRegistry:
    def test_expose_and_dump(self):
        s = Status(42)
        s.expose("my_status")
        assert get_exposed("my_status") is s
        assert dump_exposed()["my_status"] == "42"
        s.hide()
        assert get_exposed("my_status") is None

    def test_passive_status(self):
        calls = []
        p = PassiveStatus(lambda: len(calls))
        p.expose("passive")
        calls.append(1)
        assert p.get_value() == 1

    def test_expose_name_normalization(self):
        Status(1).expose("Foo::Bar baz")
        assert get_exposed("foo_bar_baz") is not None

    def test_adder_expose(self):
        a = Adder("requests_total")
        a.put(3)
        assert dump_exposed()["requests_total"] == "3"


class TestMultiDimension:
    def test_labels(self):
        md = MultiDimension(("method", "code"))
        md.get_stats(("echo", "200")).set_value(5)
        md.get_stats(("echo", "500")).set_value(1)
        assert md.count_stats() == 2
        assert md.get_stats(("echo", "200")).get_value() == 5
        assert md.has_stats(("echo", "500"))
        md.delete_stats(("echo", "500"))
        assert md.count_stats() == 1

    def test_factory_form_and_prometheus_labels(self):
        from brpc_tpu.metrics import Adder
        from brpc_tpu.metrics.status import prometheus_text

        md = MultiDimension(Adder, ["svc"]).expose("md_prom_test")
        md.stats(["a"]).put(2)
        md.stats(["b"]).put(7)
        text = prometheus_text()
        assert 'md_prom_test{svc="a"} 2' in text
        assert 'md_prom_test{svc="b"} 7' in text

    def test_arity_check(self):
        md = MultiDimension(("a",))
        with pytest.raises(ValueError):
            md.get_stats(("x", "y"))


class TestPrometheus:
    def test_text_format(self):
        Status(7).expose("numeric_var")
        Status("hello").expose("string_var")
        text = prometheus_text()
        assert "# TYPE numeric_var gauge" in text
        assert "numeric_var 7" in text
        assert "string_var" not in text  # non-numeric excluded
