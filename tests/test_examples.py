"""Smoke-run every example (the reference treats examples as living docs +
perf harnesses; ours must stay runnable). Single-file examples run in-proc
via their main(); server+client pairs run as subprocesses on random ports."""

import os
import socket
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
ENV = {**os.environ, "PYTHONPATH": REPO}


def free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def run_pair(server_rel, client_rel, client_args, port, timeout=40):
    server = subprocess.Popen(
        [sys.executable, os.path.join(REPO, server_rel),
         "--port", str(port), "--run_seconds", "30"],
        env=ENV, stdout=subprocess.PIPE, stderr=subprocess.STDOUT, text=True)
    try:
        deadline = time.time() + 15
        while time.time() < deadline:  # wait for the listen line
            line = server.stdout.readline()
            if "listening" in line.lower() or "server on" in line.lower():
                break
        else:
            pytest.fail("server never came up")
        client = subprocess.run(
            [sys.executable, os.path.join(REPO, client_rel), *client_args],
            env=ENV, capture_output=True, text=True, timeout=timeout)
        assert client.returncode == 0, client.stdout + client.stderr
        return client.stdout
    finally:
        server.kill()
        server.wait()


def run_single(rel, args=(), timeout=60):
    proc = subprocess.run(
        [sys.executable, os.path.join(REPO, rel), *args],
        env=ENV, capture_output=True, text=True, timeout=timeout)
    assert proc.returncode == 0, proc.stdout + proc.stderr
    return proc.stdout


class TestExamplePairs:
    def test_echo(self):
        port = free_port()
        out = run_pair("examples/echo/server.py", "examples/echo/client.py",
                       ["--server", f"127.0.0.1:{port}", "-n", "3"], port)
        assert "hello 2" in out and "attachment" in out

    def test_streaming_echo(self):
        port = free_port()
        out = run_pair("examples/streaming_echo/server.py",
                       "examples/streaming_echo/client.py",
                       ["--server", f"127.0.0.1:{port}", "-n", "30"], port)
        assert "echoed 30 messages" in out

    def test_grpc_echo(self):
        port = free_port()
        out = run_pair("examples/grpc_echo/server.py",
                       "examples/grpc_echo/client.py",
                       ["--server", f"127.0.0.1:{port}", "-n", "3"], port)
        assert "grpc 2" in out and "SERVING" in out

    def test_multi_threaded_echo(self):
        port = free_port()
        out = run_pair("examples/echo/server.py",
                       "examples/multi_threaded_echo/client.py",
                       ["--server", f"127.0.0.1:{port}",
                        "--threads", "4", "--seconds", "2"], port)
        assert "qps=" in out and "final:" in out


class TestSingleFileExamples:
    def test_parallel_echo(self):
        out = run_single("examples/parallel_echo/client.py", ["-n", "2"])
        assert "[srv0]" in out and "[srv1]" in out and "[srv2]" in out

    def test_selective_echo(self):
        out = run_single("examples/selective_echo/client.py", ["-n", "6"])
        assert "killed srv0" in out

    def test_collective_fanout(self):
        out = run_single("examples/collective_fanout/client.py", [])
        assert "mesh detected: True" in out and "OK" in out

    def test_dashboard_proxy(self):
        out = run_single("examples/dashboard_proxy/client.py", [])
        assert "over trpc_std OK" in out

    def test_partition_echo(self):
        out = run_single("examples/partition_echo/client.py", ["-n", "2"])
        assert "p0" in out and "p2" in out

    def test_backup_request(self):
        out = run_single("examples/backup_request/client.py", ["-n", "4"])
        assert "backup=yes" in out and "fast" in out

    def test_tpu_transfer(self):
        out = run_single("examples/tpu_transfer/client.py",
                         ["--sizes", "4096,65536", "-n", "4"])
        assert "MB/s" in out

    def test_device_stream(self):
        srv = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "examples",
                                          "device_stream", "server.py"),
             "--listen", "127.0.0.1:0"],
            env=ENV, stdout=subprocess.PIPE, text=True)
        try:
            line = srv.stdout.readline()
            addr = line.split(" on ", 1)[1].strip()
            client = subprocess.run(
                [sys.executable, os.path.join(REPO, "examples",
                                              "device_stream",
                                              "client.py"),
                 "--server", addr, "-n", "4", "--block-kb", "64",
                 "--window-kb", "128"],
                env=ENV, capture_output=True, text=True, timeout=120)
            assert client.returncode == 0, client.stdout + client.stderr
            assert "consumed on-device" in client.stdout
        finally:
            srv.terminate()
            srv.wait()

    def test_device_data(self):
        srv = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "examples", "device_data",
                                          "server.py"),
             "--listen", "tpu://127.0.0.1:0/0"],
            env=ENV, stdout=subprocess.PIPE, text=True)
        try:
            line = srv.stdout.readline()
            addr = line.split(" on ", 1)[1].split(" ")[0].strip()
            client = subprocess.run(
                [sys.executable, os.path.join(REPO, "examples",
                                              "device_data", "client.py"),
                 "--server", addr, "--mb", "1", "--copies", "3",
                 "--pump-rounds", "2"],
                env=ENV, capture_output=True, text=True, timeout=120)
            assert client.returncode == 0, client.stdout + client.stderr
            assert "content verified" in client.stdout
            assert "checksum=" in client.stdout
        finally:
            srv.terminate()
            try:
                srv.wait(timeout=10)
            except subprocess.TimeoutExpired:
                srv.kill()

    def test_transport_sweep(self):
        # bench_server prints LISTEN and serves until stdin closes
        srv = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "bench_server.py"),
             "--listen", "127.0.0.1:0", "--native"],
            env=ENV, stdin=subprocess.PIPE, stdout=subprocess.PIPE, text=True)
        try:
            addr = srv.stdout.readline().split(" ", 1)[1].strip()
            client = subprocess.run(
                [sys.executable,
                 os.path.join(REPO, "examples", "transport_sweep",
                              "client.py"),
                 "--server", addr, "--sizes", "64,65536", "--threads", "2",
                 "--seconds", "0.5", "--attachment", "--native"],
                env=ENV, capture_output=True, text=True, timeout=60)
            assert client.returncode == 0, client.stdout + client.stderr
            assert "MB/s" in client.stdout and "p99=" in client.stdout
        finally:
            srv.stdin.close()
            try:
                srv.wait(timeout=10)
            except subprocess.TimeoutExpired:
                srv.kill()
                srv.wait()

    def test_rtmp_live(self):
        out = run_single("examples/rtmp_live/client.py", ["-n", "6"])
        assert "relayed" in out and "OK" in out

    def test_mongo_kv(self):
        out = run_single("examples/mongo_kv/client.py", ["-n", "3"])
        assert "find key2" in out and "OK" in out
