"""Event dispatcher pool + off-loop cutting tests (VERDICT r1 weak #4;
reference event_dispatcher.cpp:32,59-78 multi-loop + socket.cpp:2256
ProcessEvent handoff)."""

import os
import socket as _socket
import threading
import time

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    Controller,
    MethodDescriptor,
    Server,
    Service,
    Stub,
)
from brpc_tpu.rpc.event_dispatcher import (
    EventDispatcher,
    all_dispatchers,
    pick_dispatcher,
)

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]
ECHO_MD = MethodDescriptor("EchoService", "Echo",
                           echo_pb2.EchoRequest, echo_pb2.EchoResponse)


class EchoImpl(Service):
    DESCRIPTOR = ECHO_DESC

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message)


class TestDispatcherPool:
    def test_pool_has_multiple_loops(self):
        assert len(all_dispatchers()) >= 2

    def test_pick_rotates(self):
        picks = {id(pick_dispatcher()) for _ in range(8)}
        assert len(picks) >= 2


class TestSuspendResume:
    def test_suspend_blocks_delivery_resume_restores(self):
        d = EventDispatcher(name="test-susp")
        r, w = _socket.socketpair()
        r.setblocking(False)
        hits = []
        d.add_consumer(r.fileno(), on_readable=lambda: hits.append(
            r.recv(4096)))
        try:
            w.send(b"a")
            deadline = time.monotonic() + 2
            while not hits and time.monotonic() < deadline:
                time.sleep(0.01)
            assert hits, "baseline delivery failed"
            d.suspend_read(r.fileno())
            time.sleep(0.05)
            hits.clear()
            w.send(b"b")
            time.sleep(0.2)
            assert not hits, "suspended fd still delivered"
            d.resume_read(r.fileno())
            deadline = time.monotonic() + 2
            while not hits and time.monotonic() < deadline:
                time.sleep(0.01)
            assert hits, "resume did not restore delivery"
        finally:
            d.stop()
            r.close()
            w.close()

    def test_enable_write_respects_suspension(self):
        d = EventDispatcher(name="test-susp2")
        r, w = _socket.socketpair()
        r.setblocking(False)
        hits = []
        d.add_consumer(r.fileno(), on_readable=lambda: hits.append(
            r.recv(4096)))
        try:
            d.suspend_read(r.fileno())
            # poking the write side must not resurrect read interest
            d.enable_write(r.fileno(), lambda: None)
            d.disable_write(r.fileno())
            w.send(b"x")
            time.sleep(0.2)
            assert not hits
        finally:
            d.stop()
            r.close()
            w.close()


class TestCloseAfterSend:
    def test_request_parsed_when_client_closes_immediately(self):
        """Bytes arriving in the same drain burst as the FIN must still be
        parsed (close-after-send): the server processes the request even
        though the client hung up right after writing it."""
        import socket as _s

        from brpc_tpu.policy.trpc_std import TrpcStdProtocol
        from brpc_tpu.proto import rpc_meta_pb2

        hits = []

        class Counting(Service):
            DESCRIPTOR = ECHO_DESC

            def Echo(self, cntl, request, done):
                hits.append(request.message)
                return echo_pb2.EchoResponse(message="ok")

        server = Server().add_service(Counting()).start("127.0.0.1:0")
        try:
            ep = server.listen_endpoint()
            meta = rpc_meta_pb2.RpcMeta()
            meta.request.service_name = "EchoService"
            meta.request.method_name = "Echo"
            meta.correlation_id = 7
            payload = echo_pb2.EchoRequest(
                message="fin-race").SerializeToString()
            wire = TrpcStdProtocol().pack_request(meta, payload)
            raw = _s.create_connection((ep.host, ep.port))
            raw.sendall(bytes(wire.fetch(len(wire))))
            raw.close()  # FIN lands in the same (or next) drain burst
            deadline = time.monotonic() + 5
            while not hits and time.monotonic() < deadline:
                time.sleep(0.01)
            assert hits == ["fin-race"]
        finally:
            server.stop()
            server.join(timeout=5)


class TestFloodIsolation:
    def test_small_rpc_latency_survives_16mb_flood(self, monkeypatch):
        """Two connections pinned to ONE dispatcher; one floods 16MB echoes,
        the other's small-RPC p99 must stay low because large bursts are
        cut off-loop (the whole point of the handoff)."""
        import brpc_tpu.rpc.server as server_mod
        from brpc_tpu.rpc.input_messenger import InputMessenger
        from brpc_tpu.rpc.socket_map import SocketMap

        shared = EventDispatcher(name="test-shared")
        monkeypatch.setattr(server_mod, "pick_dispatcher", lambda: shared)
        server = Server().add_service(EchoImpl()).start("127.0.0.1:0")
        try:
            addr = str(server.listen_endpoint())
            # per-channel socket maps pinned to the SAME dispatcher -> two
            # separate connections whose client-side reads also share one
            # loop; server-side accepts are pinned via the monkeypatch
            flood_ch = Channel().init(addr)
            small_ch = Channel().init(addr)
            flood_ch._socket_map = SocketMap(shared, InputMessenger())
            small_ch._socket_map = SocketMap(shared, InputMessenger())

            stop = threading.Event()
            flood_err = []

            def flood():
                stub = Stub(flood_ch, ECHO_DESC)
                payload = "x" * (16 << 20)
                while not stop.is_set():
                    try:
                        c = Controller()
                        c.timeout_ms = 30_000
                        stub.Echo(echo_pb2.EchoRequest(message=payload),
                                  controller=c)
                    except Exception as e:  # pragma: no cover
                        flood_err.append(e)
                        return

            t = threading.Thread(target=flood, daemon=True)
            t.start()
            time.sleep(0.3)  # let the flood get going
            stub = Stub(small_ch, ECHO_DESC)
            lat = []
            for _ in range(60):
                t0 = time.monotonic()
                c = Controller()
                c.timeout_ms = 10_000
                resp = stub.Echo(echo_pb2.EchoRequest(message="ping"),
                                 controller=c)
                lat.append(time.monotonic() - t0)
                assert resp.message == "ping"
            stop.set()
            t.join(timeout=40)
            assert not flood_err, flood_err
            lat.sort()
            # If cutting ran inline, the continuous 16MB parses would stall
            # essentially EVERY small RPC for >=100ms — so assert on p90
            # (immune to a stray scheduler hiccup) plus a loose tail bound,
            # not a tight absolute p99 that flakes on loaded CI machines.
            p90 = lat[int(len(lat) * 0.90) - 1]
            p99 = lat[int(len(lat) * 0.99) - 1]
            assert p90 < 0.25, f"small-RPC p90 {p90*1000:.1f}ms under flood"
            assert p99 < 1.0, f"small-RPC p99 {p99*1000:.1f}ms under flood"
        finally:
            server.stop()
            server.join(timeout=5)
            shared.stop()
