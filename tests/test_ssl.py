"""SSL/TLS transport tests (VERDICT r1 #9; reference details/ssl_helper.cpp,
ssl_options.h): TLS echo, single-port TLS+plaintext coexistence, ALPN-driven
h2 (grpc over TLS), and failure behavior."""

import socket as _socket
import subprocess

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    Service,
    Stub,
)
from brpc_tpu.rpc.ssl_helper import ClientSslOptions, ServerSslOptions

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture(scope="module")
def certpair(tmp_path_factory):
    d = tmp_path_factory.mktemp("certs")
    cert, key = str(d / "cert.pem"), str(d / "key.pem")
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "2",
             "-subj", "/CN=127.0.0.1",
             "-addext", "subjectAltName=IP:127.0.0.1"],
            check=True, capture_output=True, timeout=60)
    except (OSError, subprocess.SubprocessError) as e:
        pytest.skip(f"openssl unavailable: {e}")
    return cert, key


@pytest.fixture()
def tls_server(certpair):
    cert, key = certpair
    server = Server(ServerOptions(ssl=ServerSslOptions(certfile=cert,
                                                       keyfile=key)))
    server.add_service(EchoImpl())
    server.start("127.0.0.1:0")
    yield server
    server.stop()
    server.join()


class TestTlsEcho:
    def test_tls_trpc_echo(self, tls_server):
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=10000,
                                    ssl=ClientSslOptions()))
        ch.init(str(tls_server.listen_endpoint()))
        stub = Stub(ch, ECHO)
        r = stub.Echo(echo_pb2.EchoRequest(message="tls", payload=b"s" * 5000))
        assert r.message == "tls" and r.payload == b"s" * 5000

    def test_tls_with_ca_verification(self, tls_server, certpair):
        cert, _ = certpair
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=10000,
                                    ssl=ClientSslOptions(
                                        ca_file=cert,
                                        server_hostname="127.0.0.1")))
        ch.init(str(tls_server.listen_endpoint()))
        stub = Stub(ch, ECHO)
        assert stub.Echo(echo_pb2.EchoRequest(message="ca")).message == "ca"

    def test_plaintext_still_served_on_same_port(self, tls_server):
        """First-byte sniffing keeps the single-port multiprotocol story."""
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=10000))
        ch.init(str(tls_server.listen_endpoint()))
        stub = Stub(ch, ECHO)
        assert stub.Echo(echo_pb2.EchoRequest(message="plain")).message \
            == "plain"

    def test_http_dashboard_over_plaintext_on_tls_port(self, tls_server):
        ep = tls_server.listen_endpoint()
        with _socket.create_connection((ep.host, ep.port), timeout=5) as s:
            s.sendall(b"GET /health HTTP/1.1\r\nHost: t\r\n"
                      b"Connection: close\r\n\r\n")
            s.settimeout(5)
            data = b""
            while True:
                try:
                    chunk = s.recv(4096)
                except OSError:
                    break
                if not chunk:
                    break
                data += chunk
        assert data.startswith(b"HTTP/1.1 200")


class TestAlpn:
    def test_grpc_over_tls_negotiates_h2(self, tls_server):
        """grpc channels offer ALPN h2; the server context advertises it."""
        ch = Channel(ChannelOptions(
            protocol="grpc", timeout_ms=10000,
            ssl=ClientSslOptions(alpn_protocols=["h2"])))
        ch.init(str(tls_server.listen_endpoint()))
        stub = Stub(ch, ECHO)
        r = stub.Echo(echo_pb2.EchoRequest(message="alpn"))
        assert r.message == "alpn"
        sock = ch._select_socket(None)
        assert sock.ssl and sock.alpn == "h2"

    def test_alpn_no_overlap_selects_nothing(self, tls_server):
        """No common ALPN protocol: OpenSSL completes the handshake with no
        protocol selected (the alert is optional per RFC 7301) — the
        channel still works and the socket records alpn=None."""
        ch = Channel(ChannelOptions(
            protocol="trpc_std", timeout_ms=3000,
            ssl=ClientSslOptions(alpn_protocols=["bogus/9"])))
        ch.init(str(tls_server.listen_endpoint()))
        stub = Stub(ch, ECHO)
        assert stub.Echo(echo_pb2.EchoRequest(message="x")).message == "x"
        sock = ch._select_socket(None)
        assert sock.ssl and sock.alpn is None
