"""RPC integration tests — client+server in one process over real loopback
sockets, no mock transport (the reference's own pattern:
test/brpc_channel_unittest.cpp:195 ChannelTest + fault injection via fd
close, brpc_server_unittest.cpp full-server tests)."""

import threading
import time

import pytest

from brpc_tpu.policy.compress import COMPRESS_GZIP
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    MethodDescriptor,
    RpcError,
    Server,
    ServerOptions,
    Service,
    Stub,
    errors,
)

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoServiceImpl(Service):
    DESCRIPTOR = ECHO_DESC

    def __init__(self):
        super().__init__()
        self.calls = 0
        self.close_next_connection = False

    def Echo(self, cntl, request, done):
        self.calls += 1
        if self.close_next_connection:
            self.close_next_connection = False
            # fault injection: kill the connection instead of responding
            # (reference _close_fd_once, brpc_channel_unittest.cpp:246-250)
            cntl._srv_socket.set_failed(errors.EFAILEDSOCKET, "test injection")
            return None
        if request.sleep_us:
            time.sleep(request.sleep_us / 1e6)
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(
            message=request.message, payload=request.payload
        )


@pytest.fixture()
def echo_server():
    impl = EchoServiceImpl()
    server = Server().add_service(impl).start("127.0.0.1:0")
    yield server, impl
    server.stop()
    server.join(timeout=2)


def make_stub(server, **opts):
    ch = Channel(ChannelOptions(**opts)).init(str(server.listen_endpoint()))
    return ch, Stub(ch, ECHO_DESC)


class TestEcho:
    def test_sync_echo(self, echo_server):
        server, _ = echo_server
        _, stub = make_stub(server)
        resp = stub.Echo(echo_pb2.EchoRequest(message="hello"))
        assert resp.message == "hello"

    def test_async_echo(self, echo_server):
        server, _ = echo_server
        _, stub = make_stub(server)
        ev = threading.Event()
        got = []

        def on_done(cntl):
            got.append((cntl.failed(), cntl.response.message))
            ev.set()

        stub.Echo(echo_pb2.EchoRequest(message="async"), done=on_done)
        assert ev.wait(5)
        assert got == [(False, "async")]

    def test_large_payload(self, echo_server):
        server, _ = echo_server
        _, stub = make_stub(server)
        payload = bytes(range(256)) * (4 * 4096)  # 4 MB
        resp = stub.Echo(echo_pb2.EchoRequest(message="big", payload=payload))
        assert resp.payload == payload

    def test_attachment_roundtrip(self, echo_server):
        server, _ = echo_server
        _, stub = make_stub(server)
        cntl = Controller()
        cntl.request_attachment = b"\x00\x01ATTACHMENT\xff"
        stub.Echo(echo_pb2.EchoRequest(message="a"), controller=cntl)
        assert cntl.response_attachment == b"\x00\x01ATTACHMENT\xff"

    def test_gzip_compression(self, echo_server):
        server, _ = echo_server
        _, stub = make_stub(server, compress_type=COMPRESS_GZIP)
        payload = b"z" * 100_000
        resp = stub.Echo(echo_pb2.EchoRequest(message="gz", payload=payload))
        assert resp.payload == payload

    def test_concurrent_clients(self, echo_server):
        server, _ = echo_server
        _, stub = make_stub(server)
        results = []
        lock = threading.Lock()

        def worker(n):
            for i in range(50):
                r = stub.Echo(echo_pb2.EchoRequest(message=f"{n}-{i}"))
                with lock:
                    results.append(r.message == f"{n}-{i}")

        ts = [threading.Thread(target=worker, args=(n,)) for n in range(8)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert len(results) == 400 and all(results)

    def test_two_channels_share_connection(self, echo_server):
        server, _ = echo_server
        ch1, stub1 = make_stub(server)
        ch2, stub2 = make_stub(server)
        stub1.Echo(echo_pb2.EchoRequest(message="a"))
        stub2.Echo(echo_pb2.EchoRequest(message="b"))
        assert server.connection_count() == 1  # SocketMap sharing


class TestErrors:
    def test_no_service(self, echo_server):
        server, _ = echo_server
        ch, _ = make_stub(server)
        bad = MethodDescriptor("Nope", "Echo",
                               echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        with pytest.raises(RpcError) as ei:
            ch.call_method(bad, echo_pb2.EchoRequest(message="x"))
        assert ei.value.error_code == errors.ENOSERVICE

    def test_no_method(self, echo_server):
        server, _ = echo_server
        ch, _ = make_stub(server)
        bad = MethodDescriptor("EchoService", "Nope",
                               echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        with pytest.raises(RpcError) as ei:
            ch.call_method(bad, echo_pb2.EchoRequest(message="x"))
        assert ei.value.error_code == errors.ENOMETHOD

    def test_timeout(self, echo_server):
        server, _ = echo_server
        _, stub = make_stub(server)
        cntl = Controller()
        cntl.timeout_ms = 80
        t0 = time.monotonic()
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="slow", sleep_us=400_000),
                      controller=cntl)
        assert ei.value.error_code == errors.ERPCTIMEDOUT
        assert time.monotonic() - t0 < 0.3

    def test_method_exception_is_einternal(self, echo_server):
        server, impl = echo_server

        def boom(cntl, request, done):
            raise RuntimeError("kaboom")

        impl.add_method("Boom", boom, echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        ch, _ = make_stub(server)
        bad = MethodDescriptor("EchoService", "Boom",
                               echo_pb2.EchoRequest, echo_pb2.EchoResponse)
        with pytest.raises(RpcError) as ei:
            ch.call_method(bad, echo_pb2.EchoRequest(message="x"))
        assert ei.value.error_code == errors.EINTERNAL
        assert "kaboom" in str(ei.value)

    def test_logoff_after_stop(self, echo_server):
        server, _ = echo_server
        _, stub = make_stub(server)
        stub.Echo(echo_pb2.EchoRequest(message="warm"))
        server.stop()
        cntl = Controller()
        cntl.max_retry = 0  # ELOGOFF is retryable; isolate the code
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="x"), controller=cntl)
        assert ei.value.error_code == errors.ELOGOFF

    def test_server_max_concurrency(self):
        impl = EchoServiceImpl()
        server = Server(ServerOptions(max_concurrency=1))
        server.add_service(impl).start("127.0.0.1:0")
        try:
            ch = Channel().init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            codes = []
            lock = threading.Lock()

            def call(sleep_us):
                cntl = Controller()
                try:
                    stub.Echo(echo_pb2.EchoRequest(message="c", sleep_us=sleep_us),
                              controller=cntl)
                    code = errors.OK
                except RpcError as e:
                    code = e.error_code
                with lock:
                    codes.append(code)

            t1 = threading.Thread(target=call, args=(300_000,))
            t1.start()
            time.sleep(0.1)  # ensure the slow call is in flight
            call(0)
            t1.join()
            assert sorted(codes) == [errors.OK, errors.ELIMIT]
        finally:
            server.stop()
            server.join(timeout=2)


class TestFaultTolerance:
    def test_retry_after_connection_close(self, echo_server):
        server, impl = echo_server
        _, stub = make_stub(server)
        stub.Echo(echo_pb2.EchoRequest(message="warm"))
        impl.close_next_connection = True
        # connection dies mid-call; channel must retry on a fresh socket
        resp = stub.Echo(echo_pb2.EchoRequest(message="retry-me"))
        assert resp.message == "retry-me"
        assert impl.calls == 3  # warm + killed attempt + successful retry

    def test_no_retry_when_disabled(self, echo_server):
        server, impl = echo_server
        _, stub = make_stub(server)
        stub.Echo(echo_pb2.EchoRequest(message="warm"))
        impl.close_next_connection = True
        cntl = Controller()
        cntl.max_retry = 0
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="x"), controller=cntl)
        assert ei.value.error_code == errors.EFAILEDSOCKET

    def test_backup_request_hedges_tail(self, echo_server):
        server, impl = echo_server
        _, stub = make_stub(server, backup_request_ms=50, timeout_ms=2000)

        # first call sleeps, backup (same attempt version) lands after the
        # sleep finishes server-side; both responses race, first wins.
        slow_once = {"armed": True}
        orig = impl.Echo

        def echo_with_one_slow(cntl, request, done):
            if slow_once["armed"]:
                slow_once["armed"] = False
                time.sleep(0.4)
            return orig(cntl, request, done)

        impl._methods["Echo"].fn = echo_with_one_slow
        t0 = time.monotonic()
        resp = stub.Echo(echo_pb2.EchoRequest(message="hedged"))
        dt = time.monotonic() - t0
        assert resp.message == "hedged"
        assert dt < 0.39  # finished before the slow attempt's sleep ended

    def test_connect_refused_fails_fast(self):
        ch = Channel(ChannelOptions(max_retry=1, connect_timeout_ms=300))
        ch.init("127.0.0.1:1")  # nothing listens there
        stub = Stub(ch, ECHO_DESC)
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="x"))
        assert ei.value.error_code == errors.EHOSTDOWN


class TestStats:
    def test_method_latency_recorded(self, echo_server):
        server, impl = echo_server
        _, stub = make_stub(server)
        for _ in range(10):
            stub.Echo(echo_pb2.EchoRequest(message="m"))
        entry = impl.find_method("Echo")
        # stats settle server-side after the response is written: poll
        deadline = time.monotonic() + 2
        while entry.latency.count() != 10 and time.monotonic() < deadline:
            time.sleep(0.01)
        assert entry.latency.count() == 10
        assert server.requests_processed.get_value() == 10

    def test_channel_latency_recorded(self, echo_server):
        server, _ = echo_server
        ch, stub = make_stub(server)
        stub.Echo(echo_pb2.EchoRequest(message="m"))
        assert ch.latency_recorder.count() == 1
