"""Native C++ core: build, correctness vs the pure-Python fallbacks, and
the wire-frame scanner's conformance with the trpc_std framing."""

import struct

import pytest

from brpc_tpu import native
from brpc_tpu.butil import misc


@pytest.fixture(scope="module")
def lib():
    lib = native.load()
    if lib is None:
        pytest.skip(f"native core unavailable: {native.build_error()}")
    return lib


class TestCrc32c:
    def test_matches_python(self, lib):
        native.install()
        try:
            for data in (b"", b"a", b"hello world", bytes(range(256)) * 33):
                got = misc.crc32c(data)
                misc._native_crc32c, saved = None, misc._native_crc32c
                try:
                    want = misc.crc32c(data)
                finally:
                    misc._native_crc32c = saved
                assert got == want, data[:16]
        finally:
            native.install()

    def test_known_vector(self, lib):
        native.install()
        # RFC 3720 test vector: crc32c of 32 zero bytes
        assert misc.crc32c(b"\x00" * 32) == 0x8A9136AA

    def test_chaining(self, lib):
        native.install()
        a = misc.crc32c(b"hello ")
        assert misc.crc32c(b"world", a) == misc.crc32c(b"hello world")


class TestFastRand:
    def test_distribution_sane(self, lib):
        native.install()
        vals = [misc.fast_rand() for _ in range(2000)]
        assert len(set(vals)) == 2000
        assert all(misc.fast_rand_less_than(7) < 7 for _ in range(500))
        assert misc.fast_rand_less_than(0) == 0


class TestFrameScanner:
    def make_frame(self, magic=b"TRPC", meta=b"m" * 5, body=b"b" * 9):
        return magic + struct.pack("!II", len(meta), len(body)) + meta + body

    def test_scan_complete_frames(self, lib):
        sc = native.FrameScanner()
        f1, f2 = self.make_frame(), self.make_frame(magic=b"TSTR", body=b"x")
        frames, consumed, bad = sc.scan(f1 + f2, 1 << 31)
        assert not bad
        assert frames == [(0, 5, 9), (len(f1), 5, 1)]
        assert consumed == len(f1) + len(f2)

    def test_incomplete_tail(self, lib):
        sc = native.FrameScanner()
        f1 = self.make_frame()
        frames, consumed, bad = sc.scan(f1 + f1[: len(f1) - 1], 1 << 31)
        assert not bad and len(frames) == 1 and consumed == len(f1)

    def test_bad_magic(self, lib):
        sc = native.FrameScanner()
        frames, consumed, bad = sc.scan(b"NOPE" + b"\x00" * 20, 1 << 31)
        assert bad and consumed == 0

    def test_oversized_frame_rejected(self, lib):
        sc = native.FrameScanner()
        f = self.make_frame(body=b"y" * 100)
        frames, consumed, bad = sc.scan(f, 50)
        assert bad

    def test_max_frames_cap(self, lib):
        sc = native.FrameScanner(max_frames=2)
        f = self.make_frame()
        frames, consumed, bad = sc.scan(f * 5, 1 << 31)
        assert len(frames) == 2 and consumed == 2 * len(f) and not bad
