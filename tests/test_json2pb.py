"""json2pb bridge tests (VERDICT r1 #10; reference src/json2pb
pb_to_json.cpp / json_to_pb.cpp conversion rules)."""

import json
import math

import pytest

from brpc_tpu.json2pb import (
    Json2PbError,
    Json2PbOptions,
    Pb2JsonOptions,
    json_to_pb,
    pb_to_json,
)
from brpc_tpu.proto import jsonpb_test_pb2 as tp


def full_msg():
    m = tp.JsonScratch(
        i32=-7, i64=-(1 << 40), u64=1 << 50, d=2.5, f=0.5, flag=True,
        text="héllo", blob=b"\x00\xffbin", color=tp.BLUE,
        inner=tp.Inner(name="n", nums=[1, 2, 3]),
        colors=[tp.RED, tp.BLUE],
    )
    m.items.add(name="a", nums=[4])
    m.items.add(name="b")
    m.counts["x"] = 1
    m.counts["y"] = 2
    m.registry[9].name = "nine"
    m.choice_a = "picked"
    return m


class TestRoundTrip:
    def test_full_roundtrip(self):
        m = full_msg()
        back = json_to_pb(pb_to_json(m), tp.JsonScratch)
        assert back == m

    def test_oneof(self):
        m = tp.JsonScratch()
        m.choice_b = 0  # default-valued but SET oneof must survive
        back = json_to_pb(pb_to_json(m), tp.JsonScratch)
        assert back.WhichOneof("choice") == "choice_b"

    def test_nan_inf(self):
        m = tp.JsonScratch(d=math.nan, f=math.inf)
        back = json_to_pb(pb_to_json(m), tp.JsonScratch)
        assert math.isnan(back.d) and math.isinf(back.f)

    def test_map_int_keys(self):
        m = tp.JsonScratch()
        m.registry[-3].name = "neg"
        d = json.loads(pb_to_json(m))
        assert d["registry"]["-3"]["name"] == "neg"
        back = json_to_pb(pb_to_json(m), tp.JsonScratch)
        assert back.registry[-3].name == "neg"


class TestPbToJsonOptions:
    def test_enum_as_number(self):
        m = tp.JsonScratch(color=tp.BLUE)
        d = json.loads(pb_to_json(m, options=Pb2JsonOptions(
            enum_as_name=False)))
        assert d["color"] == 2

    def test_int64_as_string(self):
        m = tp.JsonScratch(i64=1 << 40)
        d = json.loads(pb_to_json(m, options=Pb2JsonOptions(
            int64_as_string=True)))
        assert d["i64"] == str(1 << 40)

    def test_bytes_raw_passthrough(self):
        m = tp.JsonScratch(blob=b"\x01\x02raw")
        opts = Pb2JsonOptions(bytes_to_base64=False)
        d = json.loads(pb_to_json(m, options=opts))
        assert d["blob"] == "\x01\x02raw"
        back = json_to_pb(json.dumps(d), tp.JsonScratch,
                          options=Json2PbOptions(base64_to_bytes=False))
        assert back.blob == b"\x01\x02raw"

    def test_jsonify_empty_array(self):
        d = json.loads(pb_to_json(tp.JsonScratch(), options=Pb2JsonOptions(
            jsonify_empty_array=True)))
        assert d["items"] == [] and d["counts"] == {}

    def test_always_print_primitives(self):
        d = json.loads(pb_to_json(tp.JsonScratch(), options=Pb2JsonOptions(
            always_print_primitive_fields=True)))
        assert d["i32"] == 0 and d["flag"] is False and d["text"] == ""


class TestJsonToPbOptions:
    def test_unknown_field_tolerance(self):
        m = json_to_pb('{"nope": 1, "i32": 5}', tp.JsonScratch)
        assert m.i32 == 5
        with pytest.raises(Json2PbError):
            json_to_pb('{"nope": 1}', tp.JsonScratch,
                       ignore_unknown_fields=False)

    def test_unknown_enum(self):
        with pytest.raises(Json2PbError):
            json_to_pb('{"color": "MAGENTA"}', tp.JsonScratch)
        m = json_to_pb('{"color": "MAGENTA", "i32": 1}', tp.JsonScratch,
                       options=Json2PbOptions(allow_unknown_enum=True))
        assert m.i32 == 1 and m.color == tp.COLOR_UNSET

    def test_camel_case_json_names(self):
        m = json_to_pb('{"choiceA": "via-camel"}', tp.JsonScratch)
        assert m.choice_a == "via-camel"

    def test_type_errors_are_reported_with_path(self):
        with pytest.raises(Json2PbError) as ei:
            json_to_pb('{"inner": {"nums": ["NaN-ish"]}}', tp.JsonScratch)
        assert "inner.nums[0]" in str(ei.value)

    def test_int64_string_accepted(self):
        m = json_to_pb('{"i64": "-1099511627776"}', tp.JsonScratch)
        assert m.i64 == -(1 << 40)

    def test_malformed_json(self):
        with pytest.raises(Json2PbError):
            json_to_pb("{nope", tp.JsonScratch)

    def test_empty_body_default_message(self):
        assert json_to_pb("", tp.JsonScratch) == tp.JsonScratch()


class TestExplicitPresence:
    """Explicit-presence scalars follow the has-bit, not the value
    (ADVICE r2; reference pb_to_json.cpp checks has-bits)."""

    def test_proto2_optional_set_to_default_is_emitted(self):
        from brpc_tpu.proto import jsonpb_test2_pb2 as tp2
        m = tp2.Proto2Scratch(must=5)
        m.opt_i32 = 0
        d = json.loads(pb_to_json(m))
        assert d["opt_i32"] == 0 and "opt_text" not in d

    def test_proto2_roundtrip_preserves_presence(self):
        from brpc_tpu.proto import jsonpb_test2_pb2 as tp2
        m = tp2.Proto2Scratch(must=5)
        m.opt_i32 = 0
        back = json_to_pb(pb_to_json(m), tp2.Proto2Scratch)
        assert back.HasField("opt_i32")
        assert not back.HasField("opt_text")

    def test_set_to_default_is_emitted(self):
        m = tp.JsonScratch()
        m.maybe_i32 = 0
        assert json.loads(pb_to_json(m))["maybe_i32"] == 0

    def test_unset_is_omitted(self):
        assert "maybe_i32" not in json.loads(pb_to_json(tp.JsonScratch()))

    def test_roundtrip_preserves_presence(self):
        m = tp.JsonScratch()
        m.maybe_i32 = 0
        back = json_to_pb(pb_to_json(m), tp.JsonScratch)
        assert back.HasField("maybe_i32")
        back2 = json_to_pb(pb_to_json(tp.JsonScratch()), tp.JsonScratch)
        assert not back2.HasField("maybe_i32")
