"""Fiber-local keytables, /vlog kit, shared sampling Collector
(VERDICT r1 rows 19/6/27; reference bthread/key.cpp, builtin/
vlog_service.cpp, bvar/collector.h)."""

import threading
import time

from brpc_tpu.butil import vlog
from brpc_tpu.fiber import local as flocal
from brpc_tpu.fiber import runtime
from brpc_tpu.metrics.collector import Collector


class TestFiberLocal:
    def test_per_task_isolation(self):
        key = flocal.key_create()
        seen = {}

        def task(name):
            assert flocal.get_specific(key) is None  # fresh per task
            flocal.set_specific(key, name)
            time.sleep(0.01)
            seen[name] = flocal.get_specific(key)

        ts = [runtime.start_background(task, f"t{i}") for i in range(6)]
        for t in ts:
            assert t.join(5)
        assert seen == {f"t{i}": f"t{i}" for i in range(6)}

    def test_destructor_runs_at_task_end(self):
        freed = []
        key = flocal.key_create(destructor=freed.append)

        def task():
            flocal.set_specific(key, "resource")

        runtime.start_background(task).join(5)
        assert freed == ["resource"]

    def test_deleted_key_never_resolves(self):
        key = flocal.key_create()
        assert flocal.set_specific(key, 1)
        flocal.key_delete(key)
        assert not flocal.set_specific(key, 2)
        assert flocal.get_specific(key, default="gone") == "gone"
        # a new key reusing the slot must not see the old value
        key2 = flocal.key_create()
        assert flocal.get_specific(key2) is None

    def test_pthread_fallback(self):
        key = flocal.key_create()
        flocal.set_specific(key, "main-thread")
        assert flocal.get_specific(key) == "main-thread"
        other = {}

        def th():
            other["v"] = flocal.get_specific(key)

        t = threading.Thread(target=th)
        t.start()
        t.join()
        assert other["v"] is None  # thread-local, not process-global


class TestVlog:
    def test_default_off_and_runtime_enable(self):
        assert not vlog.vlog_is_on("testmod.alpha", 1)
        n = vlog.set_vlevel("testmod.*", 2)
        assert n >= 1
        assert vlog.vlog_is_on("testmod.alpha", 1)
        assert vlog.vlog_is_on("testmod.alpha", 2)
        assert not vlog.vlog_is_on("testmod.alpha", 3)
        # pattern applies to modules registered LATER too (--vmodule)
        assert vlog.vlog_is_on("testmod.beta", 2)
        vlog.set_vlevel("testmod.*", 0)

    def test_dump_lists_sites(self):
        vlog.vlog_is_on("dumpmod.x", 4)
        entries = {m: (lv, seen) for m, lv, seen in vlog.dump()}
        assert "dumpmod.x" in entries
        assert entries["dumpmod.x"][1] >= 4

    def test_vlog_endpoint(self):
        from brpc_tpu.builtin import dispatch
        from brpc_tpu.policy.http_protocol import HttpMessage

        vlog.vlog_is_on("endpointmod", 1)
        req = HttpMessage()
        req.path = "/vlog"
        status, _, body, *_ = dispatch(None, req)
        assert status == 200 and b"endpointmod" in bytes(
            body if isinstance(body, bytes) else body.encode())
        req.query = {"setlevel": "endpointmod=3"}
        status, _, body, *_ = dispatch(None, req)
        assert status == 200
        assert vlog.vlog_is_on("endpointmod", 3)
        vlog.set_vlevel("endpointmod", 0)


class TestCollector:
    def test_budget_caps_grants(self):
        col = Collector(max_per_second=50)
        col._tokens = 50.0  # start with a full bucket
        granted = sum(col.ask_to_be_sampled() for _ in range(500))
        # one bucket's worth (+ tiny refill during the loop)
        assert 45 <= granted <= 75, granted

    def test_refill_over_time(self):
        col = Collector(max_per_second=100)
        col._tokens = 0.0
        assert not col.ask_to_be_sampled()
        time.sleep(0.1)
        assert col.ask_to_be_sampled()  # ~10 tokens refilled

    def test_disabled_cap(self):
        col = Collector(max_per_second=0)
        assert all(col.ask_to_be_sampled() for _ in range(1000))

    def test_shared_budget_across_subsystems(self):
        """spans and rpc_dump draw from the same bucket: heavy tracing
        throttles dumping too (the reference Collector's whole point)."""
        import brpc_tpu.metrics.collector as cmod

        old = cmod._collector
        cmod._collector = Collector(max_per_second=30)
        cmod._collector._tokens = 30.0
        try:
            from brpc_tpu.trace.span import _sampled

            for _ in range(300):
                _sampled()  # spans burn the shared budget
            granted = sum(cmod._collector.ask_to_be_sampled()
                          for _ in range(50))
            assert granted <= 10  # dump-side asks find it drained
        finally:
            cmod._collector = old


class TestDebugKit:
    def test_dump_all_stacks(self):
        from brpc_tpu.butil.debug import dump_all_stacks

        out = dump_all_stacks()
        assert "MainThread" in out and "test_dump_all_stacks" in out

    def test_crash_handler_idempotent(self, tmp_path):
        import faulthandler

        from brpc_tpu.butil import debug

        debug.install_crash_handler(str(tmp_path / "crash.log"))
        debug.install_crash_handler()  # second call is a no-op
        assert faulthandler.is_enabled()

    def test_fibers_endpoint_shows_running_task(self):
        from brpc_tpu.builtin import dispatch
        from brpc_tpu.policy.http_protocol import HttpMessage

        gate = threading.Event()
        started = threading.Event()

        def busy_task():
            started.set()
            gate.wait(5)

        t = runtime.start_background(busy_task)
        try:
            assert started.wait(5)
            req = HttpMessage()
            req.path = "/fibers"
            status, _, body, *_ = dispatch(None, req)
            text = body if isinstance(body, str) else body.decode()
            assert status == 200 and "busy_task" in text
        finally:
            gate.set()
            t.join(5)
