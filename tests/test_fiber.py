"""Fiber runtime tests (pattern: reference test/bthread_unittest.cpp,
bthread_butex_unittest.cpp, bthread_id_unittest.cpp,
bthread_execution_queue_unittest.cpp — real threads, real contention)."""

import threading
import time

import pytest

from brpc_tpu.fiber import (
    Butex,
    ExecutionQueue,
    IdGone,
    TaskControl,
    TimerThread,
    id_bump_version,
    id_create,
    id_error,
    id_join,
    id_lock,
    id_lock_verify,
    id_unlock,
    id_unlock_and_destroy,
    start_background,
    start_urgent,
)


class TestRuntime:
    def test_background_runs(self):
        hits = []
        t = start_background(hits.append, 1)
        assert t.join(2)
        assert hits == [1]

    def test_many_tasks_all_run(self):
        counter = {"n": 0}
        lock = threading.Lock()

        def work():
            with lock:
                counter["n"] += 1

        tasks = [start_background(work) for _ in range(500)]
        for t in tasks:
            assert t.join(5)
        assert counter["n"] == 500

    def test_task_error_captured(self):
        def boom():
            raise ValueError("x")

        t = start_background(boom)
        assert t.join(2)
        assert isinstance(t.error, ValueError)

    def test_urgent_ordering_hint(self):
        # urgent tasks go to the head of a worker's queue
        control = TaskControl(concurrency=1)
        order = []
        gate = threading.Event()
        control.submit(lambda: gate.wait(2))  # block the single worker
        control.submit(order.append, (1,))
        control.submit(order.append, (2,), urgent=True)
        gate.set()
        time.sleep(0.3)
        assert order == [2, 1]
        control.stop()

    def test_work_stealing(self):
        control = TaskControl(concurrency=4)
        done = threading.Event()
        results = []
        lock = threading.Lock()

        def work(i):
            with lock:
                results.append(i)
                if len(results) == 200:
                    done.set()

        for i in range(200):
            control.submit(work, (i,))
        assert done.wait(5)
        control.stop()

    def test_idle_dispatch_latency_submillisecond(self):
        # VERDICT r1 #4: a submit to an idle pool must wake a parked worker
        # immediately (reference ParkingLot wakes on every signal,
        # task_control.cpp:565) — not on a 50ms poll tick.
        control = TaskControl(concurrency=4)
        # warm up: start the workers, let them park
        control.submit(lambda: None).join(2)
        time.sleep(0.1)
        lats = []
        for _ in range(50):
            t0 = time.perf_counter()
            done = threading.Event()
            control.submit(done.set)
            assert done.wait(2)
            lats.append(time.perf_counter() - t0)
            time.sleep(0.005)  # let the worker park again
        lats.sort()
        p50 = lats[len(lats) // 2]
        assert p50 < 0.001, f"idle dispatch p50 {p50*1e6:.0f}us >= 1ms"
        control.stop()

    def test_tagged_isolation(self):
        control = TaskControl(concurrency=2)
        seen = set()
        lock = threading.Lock()

        def work(tag):
            with lock:
                seen.add((tag, threading.current_thread().name.split("-")[2]))

        control.submit(work, (7,), tag=7)
        control.submit(work, (9,), tag=9)
        time.sleep(0.3)
        tags = {t for t, _ in seen}
        assert tags == {"7", "9"} or {int(t) for t, _ in seen} == {7, 9}
        control.stop()


class TestButex:
    def test_wait_returns_when_changed(self):
        b = Butex(0)
        woken = []

        def waiter():
            woken.append(b.wait(0, timeout=5))

        t = threading.Thread(target=waiter)
        t.start()
        time.sleep(0.05)
        b.wake(value=1)
        t.join(2)
        assert woken == [True]

    def test_no_lost_wakeup(self):
        b = Butex(0)
        b.set_value(1)
        # value already differs: wait must return immediately
        assert b.wait(0, timeout=0.01) is True

    def test_timeout(self):
        b = Butex(0)
        assert b.wait(0, timeout=0.05) is False


class TestTimer:
    def test_fires(self):
        timer = TimerThread()
        fired = threading.Event()
        timer.schedule(fired.set, 0.05)
        assert fired.wait(2)
        timer.stop()

    def test_unschedule(self):
        timer = TimerThread()
        fired = threading.Event()
        tid = timer.schedule(fired.set, 0.2)
        assert timer.unschedule(tid) is True
        assert not fired.wait(0.4)
        timer.stop()

    def test_ordering(self):
        timer = TimerThread()
        order = []
        done = threading.Event()
        timer.schedule(lambda: order.append(2), 0.10)
        timer.schedule(lambda: (order.append(1), done.set()), 0.15)
        timer.schedule(lambda: order.append(0), 0.05)
        assert done.wait(2)
        assert order == [0, 2, 1]
        timer.stop()

    def test_unschedule_fired_returns_false(self):
        timer = TimerThread()
        fired = threading.Event()
        tid = timer.schedule(fired.set, 0.01)
        assert fired.wait(2)
        time.sleep(0.05)
        assert timer.unschedule(tid) is False
        timer.stop()


class TestExecutionQueue:
    def test_ordered_delivery(self):
        got = []
        done = threading.Event()

        def consumer(batch):
            if batch is None:
                return
            got.extend(batch)
            if len(got) == 1000:
                done.set()

        q = ExecutionQueue(consumer)
        for i in range(1000):
            assert q.execute(i)
        assert done.wait(5)
        assert got == list(range(1000))

    def test_multi_producer_ordering_per_producer(self):
        got = []

        def consumer(batch):
            if batch:
                got.extend(batch)

        q = ExecutionQueue(consumer)

        def producer(pid):
            for i in range(200):
                q.execute((pid, i))

        ts = [threading.Thread(target=producer, args=(p,)) for p in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert q.join(5)
        assert len(got) == 800
        # per-producer FIFO preserved
        for p in range(4):
            seq = [i for (pid, i) in got if pid == p]
            assert seq == sorted(seq)

    def test_stop_delivers_none(self):
        batches = []
        q = ExecutionQueue(batches.append)
        q.execute("a")
        assert q.join(5)
        q.stop()
        time.sleep(0.2)
        assert batches[-1] is None
        assert q.execute("b") is False


class TestCallId:
    def test_lock_unlock_destroy_join(self):
        cid = id_create(data={"x": 1})
        data = id_lock(cid)
        assert data["x"] == 1
        id_unlock(cid)
        id_lock(cid)
        id_unlock_and_destroy(cid)
        assert id_join(cid, timeout=1)
        with pytest.raises(IdGone):
            id_lock(cid)

    def test_lock_mutual_exclusion(self):
        cid = id_create()
        active = {"n": 0, "max": 0}

        def worker():
            for _ in range(50):
                id_lock(cid)
                active["n"] += 1
                active["max"] = max(active["max"], active["n"])
                active["n"] -= 1
                id_unlock(cid)

        ts = [threading.Thread(target=worker) for _ in range(4)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert active["max"] == 1
        id_lock(cid)
        id_unlock_and_destroy(cid)

    def test_error_when_unlocked_runs_handler(self):
        calls = []

        def on_error(data, cid, code):
            calls.append((data, code))
            id_unlock_and_destroy(cid)

        cid = id_create(data="D", on_error=on_error)
        assert id_error(cid, 42) is True
        assert calls == [("D", 42)]
        assert id_join(cid, timeout=1)

    def test_error_deferred_until_unlock(self):
        calls = []

        def on_error(data, cid, code):
            calls.append(code)
            id_unlock_and_destroy(cid)

        cid = id_create(on_error=on_error)
        id_lock(cid)
        assert id_error(cid, 7) is True
        assert calls == []  # deferred: we hold the lock
        id_unlock(cid)      # delivery happens here
        assert calls == [7]

    def test_error_after_destroy_returns_false(self):
        cid = id_create()
        id_lock(cid)
        id_unlock_and_destroy(cid)
        assert id_error(cid, 1) is False

    def test_stale_version_rejected(self):
        cid = id_create(data="payload")
        id_lock(cid)
        v1 = 1
        id_bump_version(cid)  # retry issued: v2 now current
        id_unlock(cid)
        # response for attempt v1 arrives late:
        with pytest.raises(IdGone):
            id_lock_verify(cid, v1)
        # the id itself is still lockable at the current version
        assert id_lock_verify(cid, 2) == "payload"
        id_unlock_and_destroy(cid)

    def test_join_blocks_until_destroy(self):
        cid = id_create()
        done = []

        def joiner():
            done.append(id_join(cid, timeout=5))

        t = threading.Thread(target=joiner)
        t.start()
        time.sleep(0.05)
        assert not done
        id_lock(cid)
        id_unlock_and_destroy(cid)
        t.join(2)
        assert done == [True]
