"""Device-resident RPC payloads (tpu/device_lane.py) + the TpuSocket
two-phase overlap (tpu/tpusocket.py). Runs on the virtual CPU backend
(conftest forces JAX_PLATFORMS=cpu); the same code drives the real chip
in bench.py's device phase."""

import threading

import pytest

from brpc_tpu.proto import device_lane_pb2, echo_pb2
from brpc_tpu.rpc import (Channel, ChannelOptions, Controller, Server,
                          ServerOptions, Stub)
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.channel import RpcError
from brpc_tpu.tpu.device_lane import DeviceDataService, DeviceStore

DSVC = device_lane_pb2.DESCRIPTOR.services_by_name["DeviceDataService"]


def test_device_store_roundtrip():
    store = DeviceStore()
    blob = bytes(range(256)) * 64
    h, n = store.put(blob)
    assert n == len(blob)
    h2, n2 = store.copy(h)
    assert h2 != h and n2 == n
    assert store.get(h2) == blob
    assert store.free(h) and store.free(h2)
    assert not store.free(h)  # double free is a no-op
    assert store.get(h) is None


def test_device_store_copy_chain_stays_on_device():
    # repeated copies never touch the host until get(): content survives
    store = DeviceStore()
    blob = b"\xa5" * 4096
    h, _ = store.put(blob)
    for _ in range(8):
        h, _ = store.copy(h)
    store.fence()
    assert store.get(h) == blob
    count, resident, moved = store.stats()
    assert moved >= 2 * 8 * len(blob)


@pytest.fixture()
def device_server():
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(DeviceDataService(DeviceStore()))
    srv.start("127.0.0.1:0")
    yield srv
    srv.stop()
    srv.join()


def test_device_service_over_rpc(device_server):
    """The control plane crosses the wire; payload bytes cross exactly
    once each way (Put/Get) and Copy moves data purely device-side."""
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=10000,
                                native_transport=True))
    ch.init(str(device_server.listen_endpoint()))
    stub = Stub(ch, DSVC)
    blob = bytes(range(256)) * 1024  # 256KB
    cntl = Controller()
    cntl.request_attachment = blob
    put = stub.Put(device_lane_pb2.DeviceHandle(), controller=cntl)
    assert put.handle > 0 and put.nbytes == len(blob)
    # pipeline a few copies (server-side async dispatch)
    h = put.handle
    for _ in range(4):
        h = stub.Copy(device_lane_pb2.DeviceHandle(handle=h)).handle
    st = stub.Stats(device_lane_pb2.DeviceStatsRequest(fence=True))
    assert st.moved_bytes >= 2 * 4 * len(blob)
    cg = Controller()
    got = stub.Get(device_lane_pb2.DeviceHandle(handle=h), controller=cg)
    assert got.nbytes == len(blob)
    assert cg.response_attachment == blob
    with pytest.raises(RpcError) as ei:
        stub.Copy(device_lane_pb2.DeviceHandle(handle=999999))
    assert ei.value.error_code == errors.ENOMETHOD


def test_device_pump_verified_movement(device_server):
    # Pump runs the Pallas echo loop with a dependent checksum: same data
    # -> same scalar at any round count; moved_bytes reflects 4 passes/round
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=30000,
                                native_transport=True))
    ch.init(str(device_server.listen_endpoint()))
    stub = Stub(ch, DSVC)
    blob = bytes(range(256)) * 512  # 128KB = 16 rows of int32 lanes
    cntl = Controller()
    cntl.request_attachment = blob
    put = stub.Put(device_lane_pb2.DeviceHandle(), controller=cntl)
    r1 = stub.Pump(device_lane_pb2.PumpRequest(handle=put.handle, rounds=1))
    r3 = stub.Pump(device_lane_pb2.PumpRequest(handle=put.handle, rounds=3))
    assert r1.checksum == r3.checksum  # copies preserve the data
    assert r3.moved_bytes == 3 * r1.moved_bytes > 0
    with pytest.raises(RpcError):
        stub.Pump(device_lane_pb2.PumpRequest(handle=424242, rounds=1))


def test_device_service_over_tunnel():
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(DeviceDataService(DeviceStore()))
    srv.start("tpu://127.0.0.1:0/0")
    try:
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=20000,
                                    native_transport=True))
        ch.init(str(srv.listen_endpoint()))
        stub = Stub(ch, DSVC)
        blob = b"\x3c" * (1 << 20)
        cntl = Controller()
        cntl.request_attachment = blob
        put = stub.Put(device_lane_pb2.DeviceHandle(), controller=cntl)
        h = stub.Copy(device_lane_pb2.DeviceHandle(handle=put.handle)).handle
        cg = Controller()
        stub.Get(device_lane_pb2.DeviceHandle(handle=h), controller=cg)
        assert cg.response_attachment == blob
    finally:
        srv.stop()
        srv.join()


def test_tpusocket_device_service_inprocess():
    # tpu://host/ordinal (no port): device-program lane in process
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=10000))
    ch.init("tpu://localhost/0")
    stub = Stub(ch, DSVC)
    blob = b"\x42" * 8192
    cntl = Controller()
    cntl.request_attachment = blob
    put = stub.Put(device_lane_pb2.DeviceHandle(), controller=cntl)
    assert put.nbytes == len(blob)
    h = stub.Copy(device_lane_pb2.DeviceHandle(handle=put.handle)).handle
    cg = Controller()
    stub.Get(device_lane_pb2.DeviceHandle(handle=h), controller=cg)
    assert cg.response_attachment == blob


def test_tpusocket_pipelined_echo_overlap():
    """depth>1 on the device lane: async pipelined echoes batch through
    the two-phase executor and complete correctly. (True device-side
    overlap lives in device_lane's async Copy — the echo handler
    materializes synchronously; see its docstring for the teardown race
    that forbids deferred np.asarray here.)"""
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=10000))
    ch.init("tpu://localhost/0")
    stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
    N = 12
    done_ev = threading.Event()
    results = []
    lock = threading.Lock()

    def make_done(i):
        def done(cntl):
            with lock:
                results.append((i, cntl.error_code,
                                cntl.response.payload if cntl.response
                                else b""))
                if len(results) == N:
                    done_ev.set()
        return done

    for i in range(N):
        stub.Echo(echo_pb2.EchoRequest(message=str(i),
                                       payload=bytes([i]) * 4096),
                  done=make_done(i))
    assert done_ev.wait(30)
    assert len(results) == N
    for i, code, payload in results:
        assert code == errors.OK
        assert payload == bytes([i]) * 4096
