"""Fast-path lane tests: engine-parsed EV_REQUEST/EV_RESPONSE events,
native request/response packing (dp_call/dp_respond), fast-call records,
and native-service admission/status (VERDICT r2 #2).

The fast lane must be semantically indistinguishable from the full
Controller pipeline for plain unary RPCs; these tests pin that contract.
"""

import threading
import time

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (Channel, ChannelOptions, Controller, Server,
                          ServerOptions, Service, Stub)
from brpc_tpu.rpc.channel import MethodDescriptor, RpcError
from brpc_tpu.rpc import errors
from brpc_tpu.rpc.native_transport import dataplane_available

# applied per-test (not module-wide): the pure-Python fastpath tests at the
# bottom of this file run regardless of whether the native engine built
needs_native = pytest.mark.skipif(not dataplane_available(),
                                  reason="native engine unavailable")

SVC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = SVC

    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


def _fast_channel(ep, **kw):
    kw.setdefault("timeout_ms", 5000)
    ch = Channel(ChannelOptions(protocol="trpc_std",
                                native_transport=True, **kw))
    ch.init(str(ep))
    return ch


@pytest.fixture()
def native_server():
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(EchoImpl())
    srv.start("127.0.0.1:0")
    yield srv
    srv.stop()
    srv.join()


@needs_native
def test_fast_sync_echo(native_server):
    ch = _fast_channel(native_server.listen_endpoint())
    stub = Stub(ch, SVC)
    for i in range(10):
        r = stub.Echo(echo_pb2.EchoRequest(message=f"m{i}"))
        assert r.message == f"m{i}"


@needs_native
def test_fast_attachment_roundtrip(native_server):
    ch = _fast_channel(native_server.listen_endpoint())
    stub = Stub(ch, SVC)
    cntl = Controller()
    cntl.request_attachment = b"\x01\x02" * 500
    stub.Echo(echo_pb2.EchoRequest(message="a"), controller=cntl)
    assert cntl.response_attachment == b"\x01\x02" * 500
    assert cntl.latency_us > 0


@needs_native
def test_fast_big_response_via_donated_frame(native_server):
    # >=64KB responses arrive as donated EV_FRAME buffers; the fast record
    # must still complete through the frame path
    ch = _fast_channel(native_server.listen_endpoint())
    stub = Stub(ch, SVC)
    cntl = Controller()
    cntl.request_attachment = b"\xee" * (256 << 10)
    stub.Echo(echo_pb2.EchoRequest(message="big"), controller=cntl)
    assert cntl.response_attachment == b"\xee" * (256 << 10)


@needs_native
def test_fast_unknown_service_and_method(native_server):
    ch = _fast_channel(native_server.listen_endpoint())
    md = MethodDescriptor("NoSuchService", "Echo",
                          echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    with pytest.raises(RpcError) as ei:
        ch.call_method(md, echo_pb2.EchoRequest(message="x"))
    assert ei.value.error_code == errors.ENOSERVICE
    md2 = MethodDescriptor("EchoService", "Nope",
                           echo_pb2.EchoRequest, echo_pb2.EchoResponse)
    with pytest.raises(RpcError) as ei:
        ch.call_method(md2, echo_pb2.EchoRequest(message="x"))
    assert ei.value.error_code == errors.ENOMETHOD


@needs_native
def test_fast_async_done(native_server):
    ch = _fast_channel(native_server.listen_endpoint())
    stub = Stub(ch, SVC)
    ev = threading.Event()
    seen = {}

    def done(cntl):
        seen["code"] = cntl.error_code
        seen["resp"] = cntl.response
        seen["att"] = cntl.response_attachment
        ev.set()

    stub.Echo(echo_pb2.EchoRequest(message="async"), done=done)
    assert ev.wait(5)
    assert seen["code"] == errors.OK
    assert seen["resp"].message == "async"


@needs_native
def test_fast_async_big_response_pointer_record(native_server):
    # an ASYNC caller with a >=64KB response: the donated EV_FRAME rides
    # dp_poll_packed as a POINTER record (not inlined) and must complete
    # the rec through _process_frame; also pins join-after-done semantics
    ch = _fast_channel(native_server.listen_endpoint())
    stub = Stub(ch, SVC)
    ev = threading.Event()
    seen = {}

    def done(cntl):
        seen["att"] = cntl.response_attachment
        seen["cntl"] = cntl
        ev.set()

    cntl = Controller()
    cntl.request_attachment = b"\xa5" * (512 << 10)
    stub.Echo(echo_pb2.EchoRequest(message="big"), controller=cntl,
              done=done)
    assert ev.wait(10)
    assert seen["att"] == b"\xa5" * (512 << 10)
    assert seen["cntl"].join(1)  # post-completion join returns immediately


@needs_native
def test_fast_concurrent_joiners_share_one_event():
    # two threads joining one in-flight async call must BOTH wake (the
    # lazy join-event install is guarded; a lost event would hang one)
    held = []
    entered = threading.Event()

    class Holder(Service):
        DESCRIPTOR = SVC

        def Echo(self, cntl, request, done):
            held.append(done)  # answer later from another thread
            entered.set()

    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(Holder())
    srv.start("127.0.0.1:0")
    try:
        ch = _fast_channel(srv.listen_endpoint())
        stub = Stub(ch, SVC)
        cntl = Controller()
        stub.Echo(echo_pb2.EchoRequest(message="j"), controller=cntl,
                  done=lambda _c: None)
        assert entered.wait(5)
        results = []
        ts = [threading.Thread(target=lambda: results.append(
            cntl.join(10))) for _ in range(2)]
        for t in ts:
            t.start()
        time.sleep(0.2)  # both joiners parked on the lazy event
        held[0](echo_pb2.EchoResponse(message="late"))
        for t in ts:
            t.join(10)
        assert results == [True, True]
    finally:
        srv.stop()
        srv.join(timeout=5)


@needs_native
def test_fast_timeout_held_done(native_server):
    held = []

    class Holder(Service):
        DESCRIPTOR = SVC

        def Echo(self, cntl, request, done):
            held.append(done)  # never respond: client must time out
            return None

    srv2 = Server(ServerOptions(native_dataplane=True))
    srv2.add_service(Holder())
    srv2.start("127.0.0.1:0")
    try:
        ch = _fast_channel(srv2.listen_endpoint(), timeout_ms=300)
        stub = Stub(ch, SVC)
        t0 = time.monotonic()
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="never"))
        assert ei.value.error_code == errors.ERPCTIMEDOUT
        assert time.monotonic() - t0 < 3.0
    finally:
        for d in held:
            d(None)
        srv2.stop()
        srv2.join()


@needs_native
def test_fast_async_timeout_swept(native_server):
    held = []

    class Holder(Service):
        DESCRIPTOR = SVC

        def Echo(self, cntl, request, done):
            held.append(done)
            return None

    srv2 = Server(ServerOptions(native_dataplane=True))
    srv2.add_service(Holder())
    srv2.start("127.0.0.1:0")
    try:
        ch = _fast_channel(srv2.listen_endpoint(), timeout_ms=200)
        stub = Stub(ch, SVC)
        ev = threading.Event()
        seen = {}

        def done(cntl):
            seen["code"] = cntl.error_code
            ev.set()

        stub.Echo(echo_pb2.EchoRequest(message="x"), done=done)
        # the poller's coarse deadline sweep must fire the timeout
        assert ev.wait(5)
        assert seen["code"] == errors.ERPCTIMEDOUT
    finally:
        for d in held:
            d(None)
        srv2.stop()
        srv2.join()


@needs_native
def test_fast_elogoff_after_stop(native_server):
    ch = _fast_channel(native_server.listen_endpoint(), max_retry=0)
    stub = Stub(ch, SVC)
    stub.Echo(echo_pb2.EchoRequest(message="warm"))
    native_server.stop()
    with pytest.raises(RpcError) as ei:
        stub.Echo(echo_pb2.EchoRequest(message="rejected"))
    # logoff either rejects at admission or (if teardown already closed
    # the conn) surfaces as a socket failure
    assert ei.value.error_code in (errors.ELOGOFF, errors.EFAILEDSOCKET)


@needs_native
def test_fast_method_concurrency_limit():
    release = threading.Event()
    entered = threading.Event()

    class Slow(Service):
        DESCRIPTOR = SVC

        def Echo(self, cntl, request, done):
            entered.set()
            held_done.append(done)
            return None  # respond later

    held_done = []
    srv = Server(ServerOptions(native_dataplane=True))
    svc = Slow()
    srv.add_service(svc)
    svc.find_method("Echo").max_concurrency = 1
    srv.start("127.0.0.1:0")
    try:
        ch = _fast_channel(srv.listen_endpoint(), max_retry=0,
                           timeout_ms=3000)
        stub = Stub(ch, SVC)
        ev = threading.Event()
        first = {}

        def done1(cntl):
            first["code"] = cntl.error_code
            ev.set()

        stub.Echo(echo_pb2.EchoRequest(message="one"), done=done1)
        assert entered.wait(5)
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="two"))
        assert ei.value.error_code == errors.ELIMIT
        for d in held_done:
            d(echo_pb2.EchoResponse(message="late"))
        assert ev.wait(5)
        assert first["code"] == errors.OK
        release.set()
    finally:
        srv.stop()
        srv.join()


@needs_native
def test_fast_trace_propagation(native_server):
    # force sampling so the fast path carries trace ids natively
    from brpc_tpu import flags
    from brpc_tpu.metrics import collector as _collector
    from brpc_tpu.trace import span as _span

    _span.reset_for_test()
    coll = _collector.global_collector()
    old_rate = coll._fixed_rate
    coll._fixed_rate = 10 ** 9
    coll._deny_until = 0.0
    try:
        ch = _fast_channel(native_server.listen_endpoint())
        stub = Stub(ch, SVC)
        r = stub.Echo(echo_pb2.EchoRequest(message="traced"))
        assert r.message == "traced"
        time.sleep(0.2)  # server span lands via its own process... same proc
        spans = _span.recent_spans(50)
        kinds = {(s.kind, s.service) for s in spans}
        # client and server spans of the same trace must both exist
        client_spans = [s for s in spans if s.kind == _span.KIND_CLIENT
                        and s.method == "Echo"]
        server_spans = [s for s in spans if s.kind == _span.KIND_SERVER
                        and s.method == "Echo"]
        assert client_spans and server_spans, (kinds, spans)
        tids = {s.trace_id for s in client_spans}
        assert any(s.trace_id in tids for s in server_spans)
    finally:
        coll._fixed_rate = old_rate


@needs_native
def test_slow_path_call_on_fast_conn(native_server):
    # a full-Controller call (backup_request forces the slow path) on a
    # fast conn completes through the EV_RESPONSE reconstruct route
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=5000,
                                native_transport=True,
                                backup_request_ms=60000))
    ch.init(str(native_server.listen_endpoint()))
    stub = Stub(ch, SVC)
    r = stub.Echo(echo_pb2.EchoRequest(message="slowlane"))
    assert r.message == "slowlane"


@needs_native
def test_native_echo_admission_and_stats():
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(EchoImpl())
    srv.start("127.0.0.1:0")
    srv.register_native_echo("EchoService", "Echo")
    try:
        ch = _fast_channel(srv.listen_endpoint(), max_retry=0)
        stub = Stub(ch, SVC)
        for i in range(5):
            r = stub.Echo(echo_pb2.EchoRequest(message=f"n{i}"))
            assert r.message == f"n{i}"
        stats = srv.native_method_stats()
        assert stats, "native method stats missing"
        _, _, st = stats[0]
        assert st["requests"] >= 5
        assert st["errors"] == 0
        assert st["latency_max_us"] >= 0.0
        # graceful stop: native admission answers ELOGOFF
        srv.stop()
        with pytest.raises(RpcError) as ei:
            stub.Echo(echo_pb2.EchoRequest(message="x"))
        assert ei.value.error_code in (errors.ELOGOFF, errors.EFAILEDSOCKET)
        if ei.value.error_code == errors.ELOGOFF:
            st2 = srv.native_method_stats()[0][2]
            assert st2["errors"] >= 1
    finally:
        srv.stop()
        srv.join()


@needs_native
def test_fast_usercode_inline_server():
    srv = Server(ServerOptions(native_dataplane=True, usercode_inline=True))
    srv.add_service(EchoImpl())
    srv.start("127.0.0.1:0")
    try:
        ch = _fast_channel(srv.listen_endpoint())
        stub = Stub(ch, SVC)
        for i in range(20):
            assert stub.Echo(echo_pb2.EchoRequest(message=str(i))).message \
                == str(i)
    finally:
        srv.stop()
        srv.join()


@needs_native
def test_fast_zero_copy_tunnel_response():
    # tpu:// native tunnel: big responses arrive as zero-copy pool views
    # (EV_RESPONSE_ZC) and the credits must flow back (repeat calls would
    # wedge if ACKs leaked)
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(EchoImpl())
    srv.start("tpu://127.0.0.1:0/0")
    try:
        ch = _fast_channel(srv.listen_endpoint(), timeout_ms=20000)
        stub = Stub(ch, SVC)
        blob = bytes(range(256)) * 1024  # 256KB, content-checkable
        for _ in range(12):  # > block count pressure: credits must recycle
            cntl = Controller()
            cntl.request_attachment = blob
            r = stub.Echo(echo_pb2.EchoRequest(message="zc"),
                          controller=cntl)
            assert r.message == "zc"
            assert cntl.response_attachment == blob
    finally:
        srv.stop()
        srv.join()


@needs_native
def test_native_echo_zero_copy_tunnel():
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(EchoImpl())
    srv.start("tpu://127.0.0.1:0/0")
    srv.register_native_echo("EchoService", "Echo")
    try:
        ch = _fast_channel(srv.listen_endpoint(), timeout_ms=20000)
        stub = Stub(ch, SVC)
        blob = b"\x5a" * (1 << 20)
        for _ in range(6):
            cntl = Controller()
            cntl.request_attachment = blob
            stub.Echo(echo_pb2.EchoRequest(message="n"), controller=cntl)
            assert cntl.response_attachment == blob
        st = srv.native_method_stats()[0][2]
        assert st["requests"] >= 6
    finally:
        srv.stop()
        srv.join()


@needs_native
def test_zero_copy_rejections_return_credits():
    # admission-rejected bulk requests must still ACK the donated blocks;
    # a credit leak would wedge the tunnel after ~window/block_count
    # rejections (regression for the round-3 review finding)
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(EchoImpl())
    srv.start("tpu://127.0.0.1:0/0")
    srv.register_native_echo("EchoService", "Echo")
    ch = _fast_channel(srv.listen_endpoint(), timeout_ms=8000, max_retry=0)
    stub = Stub(ch, SVC)
    blob = b"\x11" * (1 << 20)
    cntl = Controller()
    cntl.request_attachment = blob
    stub.Echo(echo_pb2.EchoRequest(message="warm"), controller=cntl)
    srv.stop()  # native admission now answers ELOGOFF
    try:
        rejected = 0
        conn_dead = False
        for _ in range(80):  # 80MB of donated blocks >> the 16MB window
            c2 = Controller()
            c2.request_attachment = blob
            try:
                stub.Echo(echo_pb2.EchoRequest(message="x"), controller=c2)
            except RpcError as e:
                if e.error_code == errors.ERPCTIMEDOUT:
                    pytest.fail("tunnel wedged: rejection leaked its "
                                "donated blocks' credits")
                if e.error_code == errors.ELOGOFF:
                    rejected += 1
                else:
                    conn_dead = True  # teardown variance: fail-fast is fine
                    break
        # every outcome must be prompt: a long run of ELOGOFFs proves the
        # credits recycled; a fast conn failure proves nothing hung
        assert conn_dead or rejected >= 40, (rejected, conn_dead)
    finally:
        srv.stop()
        srv.join()


@needs_native
def test_fast_retry_after_server_restart():
    srv = Server(ServerOptions(native_dataplane=True))
    srv.add_service(EchoImpl())
    srv.start("127.0.0.1:0")
    ep = srv.listen_endpoint()
    ch = _fast_channel(ep, timeout_ms=2000)
    stub = Stub(ch, SVC)
    assert stub.Echo(echo_pb2.EchoRequest(message="a")).message == "a"
    srv.stop()
    srv.join()
    # server gone: calls fail fast (retry budget burns on dead conns)
    with pytest.raises(RpcError):
        stub.Echo(echo_pb2.EchoRequest(message="b"))


# ======================================================================
# Pure-Python small-message fastpath (no native engine required): the
# adaptive spin wakeup, run-to-completion dispatch, coalesced doorbells,
# and the priority lane. These pin the PR's latency-stack semantics.
# ======================================================================

from brpc_tpu import flags as _flags  # noqa: E402
from brpc_tpu.fiber import wakeup as _wakeup  # noqa: E402
from brpc_tpu.rpc import run_to_completion as _rtc  # noqa: E402


@pytest.fixture()
def rtc_reset():
    _rtc._reset_for_test()
    yield
    _rtc._reset_for_test()


# ------------------------------------------------------- adaptive spin
class TestAdaptiveSpin:
    def test_budget_grows_on_wins(self):
        s = _wakeup.AdaptiveSpin("t_grow", initial=8, floor=1, ceiling=64)
        assert s.spin(lambda: True)
        assert s.budget > 8
        for _ in range(20):
            s.spin(lambda: True)
        assert s.budget == 64  # clamped at the ceiling

    def test_budget_shrinks_to_floor_on_losses(self):
        s = _wakeup.AdaptiveSpin("t_shrink", initial=64, floor=2,
                                 ceiling=256)
        for _ in range(20):
            assert not s.spin(lambda: False)
        assert s.budget == 2  # halved down to the probe floor

    def test_win_inside_window_observed_mid_spin(self):
        s = _wakeup.AdaptiveSpin("t_mid", initial=32, floor=1, ceiling=64)
        calls = {"n": 0}

        def ready():
            calls["n"] += 1
            return calls["n"] >= 5  # wake arrives on the 5th probe

        assert s.spin(ready)
        assert s.budget > 32

    def test_stats_counters_move(self):
        before = _wakeup.stats()
        s = _wakeup.get_spin("t_stats", initial=4)
        s.spin(lambda: True)
        s.spin(lambda: False)
        after = _wakeup.stats()
        assert after["spin_wins"] >= before["spin_wins"] + 1
        assert after["spin_losses"] >= before["spin_losses"] + 1
        assert after["parks"] >= before["parks"] + 1
        assert "t_stats" in after["budgets"]


# -------------------------------------------------- run-to-completion
class TestRunToCompletion:
    def test_auto_classified_cheap_method_runs_inline(self, rtc_reset):
        srv = Server(ServerOptions())
        srv.add_service(EchoImpl())
        srv.start("127.0.0.1:0")
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=5000))
            ch.init(str(srv.listen_endpoint()))
            stub = Stub(ch, SVC)
            # MIN_SAMPLES queued observations feed the EMA, then the
            # method is classified cheap and later calls run inline
            for i in range(_rtc.MIN_SAMPLES + 12):
                r = stub.Echo(echo_pb2.EchoRequest(message=f"c{i}"))
                assert r.message == f"c{i}"
            st = _rtc.method_stats()["EchoService.Echo"]
            assert st["samples"] >= _rtc.MIN_SAMPLES
            assert st["hits"] > 0, st
            assert not st["demoted"], st
            assert 0 < st["ema_us"] < float(_flags.get("rtc_cheap_us")), st
        finally:
            srv.stop()
            srv.join()

    def test_slow_opted_in_handler_is_demoted(self, rtc_reset):
        budget_s = float(_flags.get("rtc_budget_us")) / 1e6

        class SlowEcho(Service):
            DESCRIPTOR = SVC

            @_rtc.inline_eligible
            def Echo(self, cntl, request, done):
                time.sleep(budget_s * 2)  # always overruns the budget
                return echo_pb2.EchoResponse(message=request.message)

        srv = Server(ServerOptions())
        srv.add_service(SlowEcho())
        srv.start("127.0.0.1:0")
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=10000))
            ch.init(str(srv.listen_endpoint()))
            stub = Stub(ch, SVC)
            for i in range(_rtc.DEMOTE_AFTER + 3):
                stub.Echo(echo_pb2.EchoRequest(message=f"s{i}"))
            st = _rtc.method_stats()["EchoService.Echo"]
            assert st["opted_in"], st
            # ran inline (opt-in skips the warmup), overran, got demoted
            assert st["hits"] >= _rtc.DEMOTE_AFTER, st
            assert st["demoted"], st
            assert st["demotions"] >= 1, st
            # demotion is sticky: later calls still answer correctly
            r = stub.Echo(echo_pb2.EchoRequest(message="after"))
            assert r.message == "after"
            assert _rtc.method_stats()["EchoService.Echo"]["hits"] \
                == st["hits"]
        finally:
            srv.stop()
            srv.join()

    def test_small_echo_identical_on_both_dispatch_paths(self, rtc_reset):
        """The run-to-completion lane must be semantically invisible:
        the same small echo answers identically with rtc on and off."""
        srv = Server(ServerOptions())
        srv.add_service(EchoImpl())
        srv.start("127.0.0.1:0")
        try:
            results = {}
            for enabled in (True, False):
                _flags.set_flag("rtc_enable", enabled)
                _rtc._reset_for_test()
                ch = Channel(ChannelOptions(protocol="trpc_std",
                                            timeout_ms=5000))
                ch.init(str(srv.listen_endpoint()))
                stub = Stub(ch, SVC)
                out = []
                for i in range(_rtc.MIN_SAMPLES + 4):
                    cntl = Controller()
                    cntl.request_attachment = b"att-%d" % i
                    r = stub.Echo(echo_pb2.EchoRequest(
                        message=f"d{i}", payload=b"\x7f" * 64),
                        controller=cntl)
                    out.append((r.message, bytes(r.payload),
                                bytes(cntl.response_attachment)))
                results[enabled] = out
            assert results[True] == results[False]
            # and the disabled run really stayed off the inline lane
            assert _rtc.method_stats().get(
                "EchoService.Echo", {}).get("hits", 0) == 0
        finally:
            _flags.set_flag("rtc_enable", True)
            srv.stop()
            srv.join()


# --------------------------------------- doorbells + credits (ledger)
class TestDoorbellCoalescing:
    def test_coalesced_doorbells_return_all_credits(self, rtc_reset):
        """BRPC_TPU_CHECK-armed run over the shm tunnel: banked doorbell
        responses and batched FT_ACKs must balance the credit window at
        teardown (a leaked credit wedges the tunnel; the ledger turns it
        into a hard failure)."""
        from brpc_tpu.analysis import runtime_check as rc
        from brpc_tpu.tpu import transport as T

        was_active = rc.ACTIVE
        rc.activate()
        srv = Server(ServerOptions())
        srv.add_service(EchoImpl())
        srv.start("tpu://127.0.0.1:0/0")
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=20000))
            ch.init(str(srv.listen_endpoint()))
            stub = Stub(ch, SVC)
            flushes0 = T.g_tunnel_doorbell_flushes.get_value()
            # small echoes: past MIN_SAMPLES the server answers on the
            # cut thread and its responses ride coalesced doorbells
            for i in range(_rtc.MIN_SAMPLES + 24):
                r = stub.Echo(echo_pb2.EchoRequest(message=f"db{i}"))
                assert r.message == f"db{i}"
            # bulk calls force pool borrows, so ACK credits must cycle
            blob = b"\x3c" * (256 << 10)
            for _ in range(4):
                cntl = Controller()
                cntl.request_attachment = blob
                stub.Echo(echo_pb2.EchoRequest(message="bulk"),
                          controller=cntl)
                assert cntl.response_attachment == blob
            assert T.g_tunnel_doorbell_flushes.get_value() > flushes0
            assert T.g_tunnel_doorbell_frames.get_value() >= \
                T.g_tunnel_doorbell_flushes.get_value()
        finally:
            srv.stop()
            srv.join()
            try:
                # every borrowed block returned, every credit released
                rc.ledger.assert_balanced(drain=T._sweep_deferred_pools)
            finally:
                if was_active:
                    rc.activate()
                else:
                    rc.deactivate()


# ------------------------------------------------------ priority lane
class TestPriorityLane:
    def test_small_calls_survive_concurrent_16mb_send(self, rtc_reset):
        """While a 16MB echo streams through the tunnel, small calls keep
        completing (the priority lane / coalesced doorbells bypass the
        bulk send) and the tunnel reports priority-lane traffic."""
        from brpc_tpu.tpu import transport as T

        srv = Server(ServerOptions())
        srv.add_service(EchoImpl())
        srv.start("tpu://127.0.0.1:0/0")
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=60000))
            ch.init(str(srv.listen_endpoint()))
            stub = Stub(ch, SVC)
            for i in range(_rtc.MIN_SAMPLES + 2):  # warm the rtc lane
                stub.Echo(echo_pb2.EchoRequest(message=f"w{i}"))

            pri0 = (T.g_tunnel_pri_tx_frames.get_value()
                    + T.g_tunnel_doorbell_frames.get_value())
            blob = b"\x99" * (16 << 20)
            bulk_err = []

            def bulk():
                try:
                    cntl = Controller()
                    cntl.request_attachment = blob
                    stub.Echo(echo_pb2.EchoRequest(message="bulk"),
                              controller=cntl)
                    assert cntl.response_attachment == blob
                except BaseException as e:  # surfaced after join
                    bulk_err.append(e)

            t = threading.Thread(target=bulk)
            t.start()
            lats = []
            deadline = time.monotonic() + 30
            # in-process loopback can finish the bulk echo quickly: keep
            # going until a few small calls have landed either way
            while ((t.is_alive() or len(lats) < 5)
                   and time.monotonic() < deadline):
                t0 = time.perf_counter()
                r = stub.Echo(echo_pb2.EchoRequest(message="tiny"))
                lats.append(time.perf_counter() - t0)
                assert r.message == "tiny"
            t.join(60)
            assert not t.is_alive(), "16MB echo wedged"
            assert not bulk_err, bulk_err
            assert lats, "no small call completed during the bulk send"
            lats.sort()
            # generous single-core bound: the lane exists so a small call
            # never waits out the whole 16MB transfer
            assert lats[len(lats) // 2] < 5.0, lats
            pri1 = (T.g_tunnel_pri_tx_frames.get_value()
                    + T.g_tunnel_doorbell_frames.get_value())
            assert pri1 > pri0, "no priority-lane/doorbell frame moved"
        finally:
            srv.stop()
            srv.join()
