"""Serving plane: paged KV cache, iteration-level scheduler, RPC surface.

Four layers, cheapest first:

* the KV block manager as a pure ledger — alloc/free/refcount/fork,
  watermark admission, the BRPC_TPU_CHECK-style audits catching a
  corrupted ledger;
* the scheduler against a stub model (no device programs, no compiles) —
  admission policy, static-vs-continuous refill, deadline expiry in the
  queue, and the chaos points (socket death mid-generation, forced KV
  exhaustion, decode stalls) proving every abort path returns all blocks;
* the real tiny transformer through the engine — greedy determinism,
  TTFT strictly inside full-generation latency, a short request
  overtaking a long one (the continuous-batching headline behavior);
* the RPC surface — Generate with and without streaming, TokenDelta
  frames matching the final response, and the committed rpc_dump corpus
  replayed against a fresh server with trace_diff gating the phase
  timelines (prefill_us/decode_us).
"""

import json
import os
import threading
import time
import types

import numpy as np
import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.rpc import errors
from brpc_tpu.serving import (
    EngineConfig,
    KVCacheConfig,
    LlmServingService,
    ModelConfig,
    PagedKVCache,
    ServingEngine,
    TinyTransformer,
)
from brpc_tpu.serving.kv_cache import KVCacheFull

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
CORPUS = os.path.join(REPO, "tests", "data", "serving_corpus")


def _small_kv(num_blocks=16, block_size=8, watermark=0.9, layers=1,
              kv_dim=8, check=True):
    kv = PagedKVCache(KVCacheConfig(block_size=block_size,
                                    num_blocks=num_blocks,
                                    watermark=watermark),
                      layers, kv_dim)
    kv._check = check  # audit every alloc/free like BRPC_TPU_CHECK=1
    return kv


# ---------------------------------------------------------------- KV ledger
class TestKVCache:
    def test_alloc_free_roundtrip(self):
        kv = _small_kv()
        table = kv.alloc_sequence(1, 20)  # 3 blocks at block_size 8
        assert len(table) == 3
        assert kv.used_blocks == 3 and kv.free_blocks == 13
        assert 0 not in table  # block 0 is the pad-scatter scratch block
        assert kv.free_sequence(1) == 3
        assert kv.used_blocks == 0
        kv.assert_idle("after roundtrip")

    def test_extend_grows_only_the_tail(self):
        kv = _small_kv()
        t0 = kv.alloc_sequence(7, 8)  # exactly one block
        t1 = kv.extend_sequence(7, 9)  # crosses into a second block
        assert t1[: len(t0)] == t0 and len(t1) == 2
        assert kv.extend_sequence(7, 16) == t1  # still fits, no growth
        kv.free_sequence(7)
        kv.assert_idle()

    def test_fork_shares_blocks_by_refcount(self):
        kv = _small_kv()
        src = kv.alloc_sequence(1, 24)
        dst = kv.fork_sequence(1, 2)
        assert dst == src
        assert kv.used_blocks == 3  # shared, not copied
        assert kv.free_sequence(1) == 0  # dst still holds every block
        assert kv.used_blocks == 3
        assert kv.free_sequence(2) == 3
        kv.assert_idle("after fork teardown")

    def test_watermark_keeps_decode_headroom(self):
        kv = _small_kv(num_blocks=8, watermark=0.5)  # admit limit: 4 blocks
        assert kv.can_admit(32)  # 4 blocks, exactly at the watermark
        assert not kv.can_admit(33)  # 5 blocks would eat decode headroom
        kv.alloc_sequence(1, 24)  # 3 used
        assert kv.can_admit(8) and not kv.can_admit(9)
        # but a RUNNING sequence may still grow into the slack above it
        kv.extend_sequence(1, 8 * 6)
        assert kv.used_blocks == 6
        kv.free_sequence(1)
        kv.assert_idle()

    def test_exhaustion_raises_kv_cache_full(self):
        kv = _small_kv(num_blocks=4, watermark=1.0)
        kv.alloc_sequence(1, 8 * 3)
        with pytest.raises(KVCacheFull):
            kv.alloc_sequence(2, 8 * 2)
        before = kv.snapshot()
        assert before["blocks_used"] == 3  # failed alloc took nothing
        kv.free_sequence(1)
        kv.assert_idle()

    def test_audit_catches_a_corrupted_ledger(self):
        kv = _small_kv()
        kv.alloc_sequence(1, 8)
        kv._ref[kv._tables[1][0]] += 1  # corrupt: ref without a table
        with pytest.raises(AssertionError, match="ledger violation"):
            kv.extend_sequence(1, 9)

    def test_assert_idle_names_the_leak(self):
        kv = _small_kv()
        kv.alloc_sequence(3, 8 * 2)
        with pytest.raises(AssertionError, match="leaked"):
            kv.assert_idle("leak probe")
        kv.free_sequence(3)
        kv.assert_idle()


# ------------------------------------------------------- scheduler (stubbed)
class _StubModel:
    """Pure-Python stand-in: the engine's scheduling is model-agnostic, so
    admission/abort paths are testable without compiling device programs."""

    def __init__(self, step_s=0.0):
        self.config = types.SimpleNamespace(max_context=4096)
        self.step_s = step_s
        self.prefills = 0

    def synth_prompt(self, n):
        return np.arange(1, n + 1, dtype=np.int32)

    def prefill(self, prompt, table):
        self.prefills += 1
        if self.step_s:
            time.sleep(self.step_s)
        return 1

    def decode_step(self, tokens, positions, tables):
        if self.step_s:
            time.sleep(self.step_s)
        return np.full(len(tables), 2, dtype=np.int32)


class _Cntl:
    """Just enough controller for the engine's getattr probes."""

    def __init__(self, deadline_mono=0.0):
        self.deadline_mono = deadline_mono
        self._srv_socket = types.SimpleNamespace(failed=False)
        self.code = 0
        self.text = ""

    def set_failed(self, code, text):
        self.code, self.text = code, text


def _stub_engine(step_s=0.0, start=True, **cfg):
    kv = _small_kv(num_blocks=cfg.pop("num_blocks", 32),
                   watermark=cfg.pop("watermark", 0.9))
    cfg.setdefault("idle_wait_s", 0.005)
    eng = ServingEngine(_StubModel(step_s), kv, EngineConfig(**cfg))
    if start:
        eng.start()
    return eng


def _submit_wait(engine, plen, max_new, cntl=None, timeout=30.0):
    ev = threading.Event()
    box = []

    def done(resp):
        box.append(resp)
        ev.set()

    code, _ = engine.submit(engine.model.synth_prompt(plen), max_new,
                            cntl=cntl, done=done)
    assert code == 0, errors.error_text(code)
    assert ev.wait(timeout), "generation never completed"
    return box[0]


class TestScheduling:
    def test_queue_cap_rejects_overcrowded(self):
        eng = _stub_engine(start=False, max_queue=2)
        eng.running = True  # accept submits without the step loop draining
        try:
            for _ in range(2):
                code, _ = eng.submit(eng.model.synth_prompt(4), 2)
                assert code == 0
            code, seq = eng.submit(eng.model.synth_prompt(4), 2)
            assert code == errors.EOVERCROWDED and seq is None
        finally:
            eng.running = False
            eng._abort_all_locked_out(errors.ELOGOFF, "test teardown")
            eng.kv.assert_idle("queue-cap teardown")

    def test_deadline_spent_rejected_at_admission(self):
        eng = _stub_engine(start=False)
        eng.running = True
        try:
            code, _ = eng.submit(eng.model.synth_prompt(4), 2,
                                 cntl=_Cntl(time.monotonic() - 0.1))
            assert code == errors.ERPCTIMEDOUT
        finally:
            eng.running = False

    def test_watermark_rejects_before_queueing(self):
        # 8 blocks * 0.5 watermark = 4-block admit limit; 5 blocks asked
        eng = _stub_engine(start=False, num_blocks=8, watermark=0.5)
        eng.running = True
        try:
            rejects0 = eng.kv.used_blocks
            code, _ = eng.submit(eng.model.synth_prompt(8 * 4 + 1), 2)
            assert code == errors.EOVERCROWDED
            assert eng.kv.used_blocks == rejects0  # nothing was allocated
        finally:
            eng.running = False

    def test_static_gang_drains_before_refill(self):
        eng = _stub_engine(start=False, scheduling="static", max_batch=4)
        eng.running = True
        for _ in range(3):
            assert eng.submit(eng.model.synth_prompt(4), 2)[0] == 0
        with eng._cv:
            gang = eng._admit_locked()
        assert len(gang) == 3
        assert eng.submit(eng.model.synth_prompt(4), 2)[0] == 0
        with eng._cv:
            assert eng._admit_locked() == []  # gang still running: no refill
        for seq in list(eng._running):
            eng._finish(seq, 0, "")
        eng._running = []
        with eng._cv:
            assert len(eng._admit_locked()) == 1  # drained: next gang
        eng.running = False
        eng._abort_all_locked_out(errors.ELOGOFF, "test teardown")
        eng.kv.assert_idle("static teardown")

    def test_continuous_refills_between_steps(self):
        eng = _stub_engine(start=False, scheduling="continuous", max_batch=4)
        eng.running = True
        assert eng.submit(eng.model.synth_prompt(4), 2)[0] == 0
        with eng._cv:
            assert len(eng._admit_locked()) == 1
        assert eng.submit(eng.model.synth_prompt(4), 2)[0] == 0
        with eng._cv:
            admitted = eng._admit_locked()  # running non-empty, still admits
        assert len(admitted) == 1
        eng.running = False
        eng._abort_all_locked_out(errors.ELOGOFF, "test teardown")
        eng.kv.assert_idle("continuous teardown")

    def test_expired_deadline_in_queue_finishes_timedout(self):
        eng = _stub_engine(start=False)
        eng.running = True
        cntl = _Cntl(time.monotonic() + 0.01)
        ev = threading.Event()
        code, _ = eng.submit(eng.model.synth_prompt(4), 2, cntl=cntl,
                             done=lambda r: ev.set())
        assert code == 0
        time.sleep(0.03)  # let the queued deadline expire
        with eng._cv:
            assert eng._admit_locked() == []
        assert ev.wait(1.0)
        assert cntl.code == errors.ERPCTIMEDOUT
        eng.running = False
        eng.kv.assert_idle("deadline teardown")

    def test_stop_aborts_in_flight_and_pool_is_whole(self):
        eng = _stub_engine(step_s=0.01)
        cntl = _Cntl()
        ev = threading.Event()
        code, seq = eng.submit(eng.model.synth_prompt(4), 1000, cntl=cntl,
                               done=lambda r: ev.set())
        assert code == 0
        deadline = time.monotonic() + 5.0
        while not seq.out_tokens and time.monotonic() < deadline:
            time.sleep(0.005)
        assert seq.out_tokens, "generation never started"
        eng.stop()
        assert ev.wait(5.0)
        assert cntl.code == errors.ELOGOFF
        eng.kv.assert_idle("stop teardown")


# ------------------------------------------------------------------- chaos
@pytest.fixture()
def fault_enabled():
    _flags.set_flag("fault_injection_enabled", True)
    yield
    fault.disarm_all()
    _flags.set_flag("fault_injection_enabled", False)


@pytest.mark.chaos
class TestServingChaos:
    def test_socket_death_mid_generation_frees_every_block(self):
        """The tunnel-kill contract: a connection that dies mid-generation
        aborts the sequence with a retriable EFAILEDSOCKET and every KV
        block returns to the pool."""
        eng = _stub_engine(step_s=0.005)
        try:
            cntl = _Cntl()
            ev = threading.Event()
            box = []

            def done(resp):
                box.append(resp)
                ev.set()

            code, seq = eng.submit(eng.model.synth_prompt(4), 1000,
                                   cntl=cntl, done=done)
            assert code == 0
            deadline = time.monotonic() + 5.0
            while len(seq.out_tokens) < 3 and time.monotonic() < deadline:
                time.sleep(0.005)
            assert len(seq.out_tokens) >= 3, "generation never got going"
            cntl._srv_socket.failed = True  # the tunnel dies here
            assert ev.wait(5.0), "abort never reached the done callback"
            assert box == [None]
            assert cntl.code == errors.EFAILEDSOCKET
            deadline = time.monotonic() + 5.0
            while eng.running_count and time.monotonic() < deadline:
                time.sleep(0.005)
        finally:
            eng.stop()
        eng.kv.assert_idle("post socket death")  # zero leaked blocks

    def test_kv_exhaust_fault_forces_overcrowded(self, fault_enabled):
        eng = _stub_engine()
        try:
            from brpc_tpu.serving.kv_cache import \
                g_serving_kv_admission_rejects
            before = g_serving_kv_admission_rejects.get_value()
            fault.arm("serving.kv.exhaust", mode="always", count=2)
            for _ in range(2):
                code, _ = eng.submit(eng.model.synth_prompt(4), 2)
                assert code == errors.EOVERCROWDED  # retriable reject
            assert g_serving_kv_admission_rejects.get_value() == before + 2
            # trigger exhausted: the same request is admitted again
            assert _submit_wait(eng, 4, 2) is not None
        finally:
            eng.stop()
        eng.kv.assert_idle("post exhaust fault")

    def test_decode_stall_fault_delays_the_step(self, fault_enabled):
        eng = _stub_engine()
        try:
            fault.arm("serving.decode.stall", mode="oneshot", delay_ms=80)
            t0 = time.monotonic()
            resp = _submit_wait(eng, 4, 2)
            assert resp is not None
            assert time.monotonic() - t0 >= 0.08
        finally:
            eng.stop()
        eng.kv.assert_idle("post stall fault")


# --------------------------------------------------------- real model lane
@pytest.fixture(scope="module")
def serving():
    """One small compiled engine for the whole module; warmup covers every
    (batch, context) jit bucket the tests below touch — twice, because
    donated pool outputs give each program a second signature."""
    cfg = ModelConfig(vocab=64, d_model=16, n_heads=2, n_layers=1,
                      max_context=256)
    kv = PagedKVCache(KVCacheConfig(block_size=8, num_blocks=64),
                      cfg.n_layers, cfg.kv_dim)
    kv._check = True  # every alloc/free audited throughout the module
    model = TinyTransformer(cfg, kv)
    eng = ServingEngine(model, kv, EngineConfig(max_batch=4,
                                                token_budget=128,
                                                idle_wait_s=0.005)).start()
    for _ in range(2):
        _submit_wait(eng, 16, 4, timeout=180.0)
        _submit_wait(eng, 16, 64, timeout=180.0)
    yield eng
    eng.stop()
    kv.assert_idle("module teardown")
    model.close()


class TestEngineRealModel:
    def test_greedy_generation_is_deterministic(self, serving):
        a = _submit_wait(serving, 16, 8)
        b = _submit_wait(serving, 16, 8)
        assert len(a.tokens) == 8
        assert list(a.tokens) == list(b.tokens)
        assert a.finish_reason == "length"

    def test_ttft_strictly_inside_full_latency(self, serving):
        t0 = time.monotonic()
        resp = _submit_wait(serving, 16, 32)
        wall_us = (time.monotonic() - t0) * 1e6
        assert len(resp.tokens) == 32
        assert 0 < resp.ttft_us < wall_us, (
            f"ttft {resp.ttft_us}us not inside full latency {wall_us:.0f}us")

    def test_short_request_overtakes_long(self, serving):
        """The continuous-batching headline: a 2-token request submitted
        AFTER a 64-token one completes first, because admission happens
        between decode steps instead of behind the running gang."""
        order = []
        evs = [threading.Event(), threading.Event()]

        def done_for(tag, ev):
            def done(resp):
                order.append(tag)
                ev.set()
            return done

        code, _ = serving.submit(serving.model.synth_prompt(16), 64,
                                 done=done_for("long", evs[0]))
        assert code == 0
        code, _ = serving.submit(serving.model.synth_prompt(16), 2,
                                 done=done_for("short", evs[1]))
        assert code == 0
        for ev in evs:
            assert ev.wait(120.0)
        assert order[0] == "short"

    def test_snapshot_reports_the_step_loop(self, serving):
        _submit_wait(serving, 16, 4)
        snap = serving.snapshot()
        assert snap["scheduling"] == "continuous"
        assert snap["steps"] > 0 and snap["tokens_generated"] > 0
        # nothing in flight: only radix-tree-held prefix chains remain
        assert snap["kv"]["blocks_used"] == snap["kv"]["blocks_cached"]
        assert snap["step_us_p50"] > 0


# -------------------------------------------------------------- RPC surface
@pytest.fixture(scope="module")
def served(serving):
    from brpc_tpu.proto import serving_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Server, Stub

    server = Server().add_service(LlmServingService(serving)) \
        .start("127.0.0.1:0")
    ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=60000))
    ch.init(str(server.listen_endpoint()))
    stub = Stub(ch, serving_pb2.DESCRIPTOR.services_by_name["LlmService"])
    yield stub
    server.stop()
    server.join(timeout=2)


class TestServingRpc:
    def test_generate_matches_engine_lane(self, serving, served):
        from brpc_tpu.proto import serving_pb2

        direct = _submit_wait(serving, 16, 8)
        resp = served.Generate(serving_pb2.GenerateRequest(
            prompt_len=16, max_new_tokens=8))
        assert list(resp.tokens) == list(direct.tokens)
        assert resp.prompt_len == 16 and resp.ttft_us > 0

    def test_missing_prompt_is_erequest(self, served):
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.rpc import Controller
        from brpc_tpu.rpc.channel import RpcError

        cntl = Controller()
        with pytest.raises(RpcError):
            served.Generate(serving_pb2.GenerateRequest(max_new_tokens=4),
                            controller=cntl)
        assert cntl.failed() and cntl.error_code == errors.EREQUEST

    def test_streamed_deltas_match_the_response(self, served):
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.rpc import Controller
        from brpc_tpu.rpc.stream import (StreamOptions, stream_close,
                                         stream_create)

        frames = []
        got_first = threading.Event()

        def on_received(sid, msgs):
            for m in msgs:
                d = serving_pb2.TokenDelta()
                d.ParseFromString(m)
                frames.append(d)
            got_first.set()

        sid = stream_create(StreamOptions(on_received=on_received))
        cntl = Controller()
        cntl.stream_id = sid
        cntl.timeout_ms = 60000
        resp = served.Generate(serving_pb2.GenerateRequest(
            prompt_len=16, max_new_tokens=8), controller=cntl)
        stream_close(sid)
        assert not cntl.failed(), cntl.error_text()
        assert got_first.wait(1.0), "no TokenDelta ever arrived"
        streamed = [t for d in frames for t in d.tokens]
        assert streamed == list(resp.tokens)
        assert frames[-1].done

    def test_stats_surface(self, serving, served):
        from brpc_tpu.proto import serving_pb2

        stats = served.Stats(serving_pb2.ServingStatsRequest())
        assert stats.kv_blocks_total == serving.kv.num_blocks
        assert stats.steps >= serving.steps - 1  # racy read, same ballpark


# ------------------------------------------------- corpus replay/diff gate
def test_serving_corpus_replays_and_phases_hold(tmp_path):
    """The committed rpc_dump corpus (tools/record_serving_corpus.py)
    replayed against a fresh serving stack: every recorded Generate
    succeeds, the replayed server spans carry the engine's
    prefill_us/decode_us phases, and tools/trace_diff finds no phase
    regression at p50 with a 50ms floor."""
    from brpc_tpu.metrics.collector import global_collector
    from brpc_tpu.rpc import Server
    from brpc_tpu.trace import span as _span
    from tools import record_serving_corpus as recorder
    from tools import rpc_replay, trace_diff

    dumps = [f for f in os.listdir(CORPUS) if f.endswith(".dump")]
    assert dumps, "committed corpus missing; run tools/record_serving_corpus"

    _flags.set_flag("rpcz_sample_ratio", "1.0")
    _flags.set_flag("collector_max_samples_per_second", "0")
    global_collector()._deny_until = 0.0
    engine = recorder.build_engine()
    try:
        recorder.warm_engine(engine)
        _span.reset_for_test()
        server = Server().add_service(LlmServingService(engine)) \
            .start("127.0.0.1:0")
        try:
            rc = rpc_replay.main([
                "--dump", CORPUS,
                "--server", str(server.listen_endpoint()),
                "--rate-mult", "2", "--timeout-ms", "30000",
                "--report-interval", "0"])
            assert rc == 0
            deadline = time.monotonic() + 5.0
            while (len([s for s in _span.recent_spans(200)
                        if s.kind == _span.KIND_SERVER])
                   < len(recorder.SCHEDULE)
                   and time.monotonic() < deadline):
                time.sleep(0.01)
        finally:
            server.stop()
            server.join(timeout=2)
        spans = [s for s in _span.recent_spans(200)
                 if s.kind == _span.KIND_SERVER]
        assert len(spans) >= len(recorder.SCHEDULE)
        with_phases = [s for s in spans
                       if "prefill_us" in s.phases and "decode_us" in s.phases]
        assert with_phases, "no replayed span carries the engine phases"
        replayed = tmp_path / "replayed.json"
        replayed.write_text(json.dumps(
            {"spans": [s.to_dict() for s in _span.recent_spans(200)]}))
        # p50 + 50ms floor: open-loop queueing noise must not flake the gate
        rc = trace_diff.main([CORPUS, str(replayed),
                              "--percentile", "50",
                              "--min-delta-us", "50000"])
        assert rc == 0
    finally:
        engine.stop()
        engine.kv.assert_idle("corpus gate teardown")
        engine.model.close()
        _flags.set_flag("rpcz_sample_ratio", "1.0")
        _flags.set_flag("collector_max_samples_per_second", "1000")
