"""Memcache binary client (against an in-test toy memcached) and nshead
client+server tests — the reference's legacy-protocol conformance pattern."""

import socket as pysocket
import struct
import threading

import pytest

from brpc_tpu.policy import memcache as mc
from brpc_tpu.policy.nshead import (
    NsheadMessage,
    NsheadService,
    nshead_method,
)
from brpc_tpu.rpc import Channel, ChannelOptions, Server, ServerOptions


# ------------------------------------------------------------ toy memcached
class ToyMemcached:
    """Minimal memcached speaking the binary protocol (test substrate —
    the reference tests against a real memcached; we craft the peer)."""

    def __init__(self):
        self.store = {}
        self.sock = pysocket.socket()
        self.sock.bind(("127.0.0.1", 0))
        self.sock.listen(8)
        self.port = self.sock.getsockname()[1]
        self._stop = False
        self.thread = threading.Thread(target=self._serve, daemon=True)
        self.thread.start()

    def _serve(self):
        while not self._stop:
            try:
                conn, _ = self.sock.accept()
            except OSError:
                return
            threading.Thread(target=self._conn, args=(conn,),
                             daemon=True).start()

    def _conn(self, conn):
        buf = b""
        try:
            while True:
                chunk = conn.recv(65536)
                if not chunk:
                    return
                buf += chunk
                out = b""
                while len(buf) >= 24:
                    (magic, op, keylen, extlen, _dt, _vb, bodylen, opaque,
                     cas) = struct.unpack_from(mc.HEADER_FMT, buf, 0)
                    if len(buf) < 24 + bodylen:
                        break
                    extras = buf[24:24 + extlen]
                    key = buf[24 + extlen:24 + extlen + keylen]
                    value = buf[24 + extlen + keylen:24 + bodylen]
                    buf = buf[24 + bodylen:]
                    out += self._handle(op, key, extras, value, opaque)
                if out:
                    conn.sendall(out)
        except OSError:
            pass
        finally:
            conn.close()

    def _resp(self, op, status, opaque, key=b"", extras=b"", value=b"",
              cas=0):
        body = len(extras) + len(key) + len(value)
        return struct.pack(mc.HEADER_FMT, 0x81, op, len(key), len(extras),
                           0, status, body, opaque, cas) + extras + key + value

    def _handle(self, op, key, extras, value, opaque):
        if op == mc.OP_SET:
            self.store[key] = (extras[:4], value)
            return self._resp(op, 0, opaque, cas=1)
        if op == mc.OP_ADD:
            if key in self.store:
                return self._resp(op, mc.STATUS_KEY_EXISTS, opaque,
                                  value=b"exists")
            self.store[key] = (extras[:4], value)
            return self._resp(op, 0, opaque, cas=1)
        if op == mc.OP_GET:
            if key not in self.store:
                return self._resp(op, mc.STATUS_KEY_NOT_FOUND, opaque,
                                  value=b"Not found")
            flags, v = self.store[key]
            return self._resp(op, 0, opaque, extras=flags, value=v, cas=1)
        if op == mc.OP_DELETE:
            ok = key in self.store
            self.store.pop(key, None)
            return self._resp(op, 0 if ok else mc.STATUS_KEY_NOT_FOUND,
                              opaque)
        if op == mc.OP_INCREMENT:
            delta, initial, _ = struct.unpack("!QQI", extras)
            cur = int(self.store.get(key, (b"", str(initial).encode()))[1])
            if key in self.store:
                cur += delta
            self.store[key] = (b"\x00" * 4, str(cur).encode())
            return self._resp(op, 0, opaque, value=struct.pack("!Q", cur))
        if op == mc.OP_VERSION:
            return self._resp(op, 0, opaque, value=b"1.6.0-toy")
        return self._resp(op, mc.STATUS_UNKNOWN_COMMAND, opaque,
                          value=b"unknown")

    def close(self):
        self._stop = True
        self.sock.close()


@pytest.fixture()
def toy_memcached():
    s = ToyMemcached()
    yield s
    s.close()


class TestMemcache:
    def test_set_get_delete_pipeline(self, toy_memcached):
        ch = Channel(ChannelOptions(protocol="memcache")).init(
            f"127.0.0.1:{toy_memcached.port}")
        req = mc.MemcacheRequest()
        req.set("k", "hello", flags=7).get("k").delete("k").get("k")
        resp = ch.call_method(mc.memcache_method(), req,
                              mc.MemcacheResponse())
        assert resp.result_size == 4
        r_set, r_get, r_del, r_get2 = [resp.pop() for _ in range(4)]
        assert r_set.ok and r_set.cas == 1
        assert r_get.ok and r_get.value == b"hello"
        assert struct.unpack("!I", r_get.extras[:4])[0] == 7
        assert r_del.ok
        assert r_get2.status == mc.STATUS_KEY_NOT_FOUND

    def test_incr_and_version(self, toy_memcached):
        ch = Channel(ChannelOptions(protocol="memcache")).init(
            f"127.0.0.1:{toy_memcached.port}")
        req = mc.MemcacheRequest().incr("ctr", 5, initial=10).incr("ctr", 5)
        req.version()
        resp = ch.call_method(mc.memcache_method(), req,
                              mc.MemcacheResponse())
        v1 = struct.unpack("!Q", resp.result(0).value)[0]
        v2 = struct.unpack("!Q", resp.result(1).value)[0]
        assert v2 == v1 + 5
        assert b"toy" in resp.result(2).value

    def test_concurrent_pipelines(self, toy_memcached):
        ch = Channel(ChannelOptions(protocol="memcache",
                                    timeout_ms=5000)).init(
            f"127.0.0.1:{toy_memcached.port}")
        bad = []

        def worker(i):
            for j in range(15):
                try:
                    req = mc.MemcacheRequest()
                    req.set(f"w{i}", f"{i}.{j}").get(f"w{i}")
                    resp = ch.call_method(mc.memcache_method(), req,
                                          mc.MemcacheResponse())
                    if resp.result(1).value != f"{i}.{j}".encode():
                        bad.append((i, j, resp.result(1).value))
                except Exception as e:
                    bad.append((i, j, repr(e)))
                    return

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not bad


# ------------------------------------------------------------------- nshead
class UpperNshead(NsheadService):
    def process(self, peer, request: NsheadMessage) -> NsheadMessage:
        return NsheadMessage(request.body.upper(), id=request.id,
                             log_id=request.log_id)


@pytest.fixture()
def nshead_server():
    server = Server(ServerOptions(
        nshead_service=UpperNshead())).start("127.0.0.1:0")
    yield server
    server.stop()
    server.join(timeout=2)


class TestNshead:
    def test_header_roundtrip(self):
        m = NsheadMessage(b"payload", id=3, version=1, log_id=99)
        raw = m.SerializeToString()
        assert len(raw) == 36 + 7
        m2 = NsheadMessage()
        m2.ParseFromString(raw)
        assert (m2.id, m2.version, m2.log_id) == (3, 1, 99)
        assert m2.body == b"payload"
        assert m2.provider == b"brpc-tpu"

    def test_client_server_echo(self, nshead_server):
        ch = Channel(ChannelOptions(protocol="nshead")).init(
            str(nshead_server.listen_endpoint()))
        resp = ch.call_method(nshead_method(),
                              NsheadMessage(b"hello nshead", log_id=5),
                              NsheadMessage())
        assert resp.body == b"HELLO NSHEAD"
        assert resp.log_id == 5

    def test_pipelined_order(self, nshead_server):
        ch = Channel(ChannelOptions(protocol="nshead",
                                    timeout_ms=5000)).init(
            str(nshead_server.listen_endpoint()))
        bad = []

        def worker(i):
            for j in range(15):
                try:
                    r = ch.call_method(nshead_method(),
                                       NsheadMessage(f"m{i}.{j}".encode()),
                                       NsheadMessage())
                    if r.body != f"M{i}.{j}".upper().encode():
                        bad.append((i, j, r.body))
                except Exception as e:
                    bad.append((i, j, repr(e)))
                    return

        ts = [threading.Thread(target=worker, args=(i,)) for i in range(6)]
        for t in ts:
            t.start()
        for t in ts:
            t.join()
        assert not bad
        assert nshead_server.connection_count() == 1
