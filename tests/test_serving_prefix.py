"""Radix prefix cache: copy-on-write KV reuse across the serving plane.

Four layers, cheapest first:

* the ledger's cache-hold surface — retain/release/adopt, copy-on-write
  block splits, the armed ``assert_writable`` range audit, and
  ``assert_idle`` naming lingering tree holds;
* the radix tree as a pure data structure over a ledger pool — commit
  (insert-or-share), block-aligned matching capped at a proper prefix,
  LRU eviction over refcount-1 chains ONLY, watermark trim, the
  admission-pressure release valve, and the kill-switch flag;
* prefix-hash routing — ``prefix_route_key`` semantics and the fleet
  contract that client-side :class:`GenerateRouter` and server-side
  :class:`ShardedPrefixCache` place the same prompt on the same shard;
* the real tiny transformer through the engine — the correctness
  oracle (forked generations bit-identical to cold-start on the
  committed corpus schedule), the ``/serving`` builtin's prefix
  section, the thrash watch rule, and the eviction-churn chaos lane
  proving zero leaked blocks under an armed ledger.
"""

import threading
import types

import pytest

from brpc_tpu import fault
from brpc_tpu import flags as _flags
from brpc_tpu.serving import (
    EngineConfig,
    KVCacheConfig,
    ModelConfig,
    PagedKVCache,
    PrefixCache,
    ServingEngine,
    ShardedKVCache,
    ShardedPrefixCache,
    TinyTransformer,
    build_prefix_cache,
    prefix_route_key,
)
from brpc_tpu.shard.plane import shard_for

# the committed replay corpus's schedule: synth prompts are arange(1, n+1),
# so every prompt shares its first block(s) with every longer one — the
# exact shared-system-prompt traffic the radix tree exists for
from tools.record_serving_corpus import SCHEDULE


def _kv(num_blocks=64, block_size=8, watermark=1.0, layers=1, kv_dim=8):
    kv = PagedKVCache(KVCacheConfig(block_size=block_size,
                                    num_blocks=num_blocks,
                                    watermark=watermark),
                      layers, kv_dim)
    kv._check = True  # audit every ledger mutation like BRPC_TPU_CHECK=1
    return kv


# ------------------------------------------------------ ledger cache holds
class TestLedgerCacheHolds:
    def test_retain_release_roundtrip(self):
        kv = _kv()
        t = kv.alloc_sequence(1, 16)  # 2 blocks
        kv.retain_block(t[0])
        assert kv.cache_held_blocks() == 1
        assert kv.block_ref(t[0]) == 2
        assert kv.free_sequence(1) == 1  # t[1] freed; t[0] cache-held
        assert kv.used_blocks == 1
        assert kv.release_block(t[0]) == 1  # last hold: block freed
        kv.assert_idle("after release")

    def test_release_without_hold_raises(self):
        kv = _kv()
        t = kv.alloc_sequence(1, 8)
        with pytest.raises(KeyError):
            kv.release_block(t[0])  # table-held, but no cache hold
        kv.free_sequence(1)
        kv.assert_idle()

    def test_assert_idle_names_lingering_cache_holds(self):
        kv = _kv()
        t = kv.alloc_sequence(1, 8)
        kv.retain_block(t[0])
        kv.free_sequence(1)
        with pytest.raises(AssertionError, match="prefix cache"):
            kv.assert_idle("cache hold probe")
        kv.release_block(t[0])
        kv.assert_idle()

    def test_adopt_shares_a_cached_chain(self):
        kv = _kv()
        t = kv.alloc_sequence(1, 24)  # 3 blocks
        for b in t:
            kv.retain_block(b)  # the tree pins the whole chain
        kv.free_sequence(1)
        assert kv.used_blocks == 3  # the chain outlives its sequence
        kv.adopt_sequence(2, t[:2], 16)  # fork: 2 blocks, zero copies
        assert list(kv.block_table(2)) == t[:2]
        assert kv.block_ref(t[0]) == 2 and kv.block_ref(t[2]) == 1
        ext = kv.extend_sequence(2, 17)  # grows a FRESH tail block
        assert ext[:2] == t[:2] and len(ext) == 3 and ext[2] != t[2]
        assert kv.free_sequence(2) == 1  # only the private tail frees
        for b in t:
            kv.release_block(b)
        kv.assert_idle("after adopt teardown")

    def test_cow_block_splits_shared_then_passes_through(self):
        kv = _kv()
        copies = []
        kv._cow_copy_fn = lambda dst, src: copies.append((dst, src))
        t = kv.alloc_sequence(1, 16)
        kv.fork_sequence(1, 2)  # both tables share both blocks
        new = kv.cow_block(2, 0)
        assert new != t[0] and copies == [(new, t[0])]
        assert kv.block_ref(t[0]) == 1 and kv.block_ref(new) == 1
        assert list(kv.block_table(2)) == [new, t[1]]
        assert kv.block_ref(t[1]) == 2  # index 1 untouched, still shared
        # sole owner now: passthrough, no second device copy
        assert kv.cow_block(2, 0) == new and len(copies) == 1
        kv.free_sequence(1)
        kv.free_sequence(2)
        kv.assert_idle("after cow teardown")

    def test_ensure_writable_maps_position_to_block(self):
        kv = _kv()
        t = kv.alloc_sequence(1, 24)
        kv.fork_sequence(1, 2)
        copies = []
        kv._cow_copy_fn = lambda dst, src: copies.append((dst, src))
        got = kv.ensure_writable(2, 8)  # position 8 -> block index 1
        assert copies == [(got, t[1])]
        kv.free_sequence(1)
        kv.free_sequence(2)
        kv.assert_idle()

    def test_assert_writable_catches_shared_write_ranges(self):
        kv = _kv()
        kv._cow_copy_fn = lambda dst, src: None
        t = kv.alloc_sequence(1, 16)
        kv.fork_sequence(1, 2)
        with pytest.raises(AssertionError, match="cow violation"):
            kv.assert_writable(t, 0, 16)
        kv.cow_block(2, 0)
        # block index 1 is still shared: writing there must still trip
        with pytest.raises(AssertionError, match="cow violation"):
            kv.assert_writable(kv.block_table(2), 8, 16)
        kv.assert_writable(kv.block_table(2), 0, 8)  # split block: fine
        kv.free_sequence(1)
        kv.free_sequence(2)
        kv.assert_idle()


# ------------------------------------------------------------- radix tree
def _commit_chain(kv, tree, seq_id, tokens):
    """The engine's completion path in miniature: alloc a sequence whose
    K/V is considered fully written, commit its full blocks into the
    tree, then free the sequence (tree holds survive)."""
    kv.alloc_sequence(seq_id, len(tokens))
    inserted = tree.commit(seq_id, tokens, len(tokens))
    kv.free_sequence(seq_id)
    return inserted


class TestPrefixRadixTree:
    def _tree(self, num_blocks=64, block_size=8):
        kv = _kv(num_blocks=num_blocks, block_size=block_size)
        return kv, PrefixCache(kv)

    def test_commit_then_match_is_block_aligned_and_proper(self):
        kv, tree = self._tree()
        toks = list(range(1, 21))  # 20 tokens: exactly 2 full blocks
        assert _commit_chain(kv, tree, 1, toks) == 2
        assert kv.used_blocks == 2  # the chain outlives its sequence
        assert tree.match_len(toks) == 16
        assert tree.match_len(toks[:17]) == 16
        # a 16-token prompt may only match 8: one suffix token must run
        assert tree.match_len(toks[:16]) == 8
        assert tree.match_len(list(range(100, 120))) == 0
        tree.clear()
        kv.assert_idle("after clear")

    def test_fork_adopts_the_chain_and_counts_hits(self):
        kv, tree = self._tree()
        toks = list(range(1, 25))  # 3 blocks
        _commit_chain(kv, tree, 1, toks)
        assert tree.fork(2, toks + [99]) == 24
        assert len(kv.block_table(2)) == 3  # the whole chain, no copies
        snap = tree.snapshot()
        assert snap["hit_seqs"] == 1 and snap["hit_blocks"] == 3
        assert snap["hit_tokens"] == 24 and snap["hit_ratio"] == 1.0
        assert tree.fork(3, [7] * 9) == 0  # miss: caller allocates cold
        assert tree.snapshot()["miss_seqs"] == 1
        kv.free_sequence(2)
        tree.clear()
        kv.assert_idle()

    def test_insert_or_share_keeps_the_trees_block(self):
        kv, tree = self._tree()
        toks = list(range(1, 17))
        _commit_chain(kv, tree, 1, toks)
        used = kv.used_blocks
        # a duplicate commit inserts nothing: the committer's blocks
        # free with its sequence, the tree keeps ITS copies
        kv.alloc_sequence(2, 16)
        assert tree.commit(2, toks, 16) == 0
        kv.free_sequence(2)
        assert kv.used_blocks == used
        tree.clear()
        kv.assert_idle()

    def test_divergent_prompts_share_the_common_prefix(self):
        kv, tree = self._tree()
        a = list(range(1, 17))
        b = a[:8] + [50 + i for i in range(8)]
        _commit_chain(kv, tree, 1, a)
        assert _commit_chain(kv, tree, 2, b) == 1  # first block shared
        assert kv.used_blocks == 3
        assert tree.match_len(a + [0]) == 16
        assert tree.match_len(b + [0]) == 16
        tree.clear()
        kv.assert_idle()

    def test_partial_last_block_never_commits(self):
        kv, tree = self._tree()
        toks = list(range(1, 21))  # 20 tokens but only 17 valid
        kv.alloc_sequence(1, 20)
        # valid_len 17: block 2 (tokens 16..19) is partially written
        assert tree.commit(1, toks, 17) == 2
        kv.free_sequence(1)
        assert kv.used_blocks == 2
        tree.clear()
        kv.assert_idle()

    def test_eviction_is_lru_over_sole_owner_leaves(self):
        kv, tree = self._tree()
        a, b, c = (list(range(s, s + 8)) for s in (1, 11, 21))
        for sid, toks in ((1, a), (2, b), (3, c)):
            _commit_chain(kv, tree, sid, toks)
        # touch a and c (fork + drop), leaving b least-recently used
        for sid, toks in ((4, a), (5, c)):
            assert tree.fork(sid, toks + [0]) == 8
            kv.free_sequence(sid)
        with tree._lock:
            assert tree._evict_locked(1) == 1
        assert tree.match_len(b + [0]) == 0  # b went first
        assert tree.match_len(a + [0]) == 8
        assert tree.match_len(c + [0]) == 8
        tree.clear()
        kv.assert_idle()

    def test_shared_chains_are_never_evicted(self):
        kv, tree = self._tree()
        a = list(range(1, 9))
        _commit_chain(kv, tree, 1, a + [0])
        assert tree.fork(2, a + [0]) == 8  # a live sequence shares it
        with tree._lock:
            assert tree._evict_locked(10) == 0  # refcount 2: untouchable
        kv.free_sequence(2)
        with tree._lock:
            assert tree._evict_locked(10) == 1  # sole owner again
        kv.assert_idle("after final evict")

    def test_evict_for_admission_frees_exactly_enough(self):
        kv, tree = self._tree(num_blocks=8)  # block 0 scratch: 7 usable
        chains = [list(range(10 * i + 1, 10 * i + 9)) for i in range(3)]
        for sid, toks in enumerate(chains, start=1):
            _commit_chain(kv, tree, sid, toks)
        assert kv.used_blocks == 3
        assert not kv.can_admit(48)  # 6 blocks > the 5 free
        assert tree.evict_for_admission(48) is True
        assert kv.used_blocks == 2  # gave back exactly one LRU chain
        assert kv.can_admit(48)
        # more than eviction can ever provide fails cleanly (and empties
        # nothing a live sequence would need)
        assert tree.evict_for_admission(9 * 8) is False
        tree.clear()
        kv.assert_idle()

    def test_commit_trims_back_under_the_watermark(self):
        kv, tree = self._tree(num_blocks=8)
        old = _flags.get("serving_prefix_evict_watermark")
        try:
            # 8-block pool, 0.25 watermark: at most 2 blocks may stay
            _flags.set_flag("serving_prefix_evict_watermark", "0.25")
            for sid in range(1, 5):
                toks = list(range(100 * sid, 100 * sid + 8))
                _commit_chain(kv, tree, sid, toks)
            assert kv.used_ratio() <= 0.25
            assert tree.snapshot()["evicted_blocks"] > 0
        finally:
            _flags.set_flag("serving_prefix_evict_watermark", str(old))
        tree.clear()
        kv.assert_idle()

    def test_kill_switch_flag_bypasses_the_tree(self):
        kv, tree = self._tree()
        toks = list(range(1, 17))
        old = _flags.get("serving_prefix_cache_enabled")
        try:
            _flags.set_flag("serving_prefix_cache_enabled", False)
            kv.alloc_sequence(1, 16)
            assert tree.commit(1, toks, 16) == 0
            kv.free_sequence(1)
            assert tree.fork(2, toks + [0]) == 0
            assert tree.snapshot()["enabled"] is False
            kv.assert_idle("disabled tree takes no holds")
        finally:
            _flags.set_flag("serving_prefix_cache_enabled", old)

    def test_evict_fault_point_is_registered(self):
        points = {p["point"] for p in fault.snapshot()}
        assert "serving.prefix.evict" in points


# --------------------------------------------------- prefix-hash routing
class TestPrefixRouting:
    def test_route_key_none_below_one_block_plus_suffix(self):
        assert prefix_route_key(list(range(16)), 16) is None
        assert prefix_route_key(list(range(17)), 16) is not None

    def test_route_key_depends_only_on_the_first_block(self):
        a = list(range(1, 40))
        b = a[:16] + [9] * 30
        assert prefix_route_key(a, 16) == prefix_route_key(b, 16)
        c = [2] + a[1:]
        assert prefix_route_key(c, 16) != prefix_route_key(a, 16)

    def test_client_and_server_place_the_same_shard(self):
        """The fleet contract: the client stub's GenerateRouter and the
        server's ShardedPrefixCache admission compute the SAME shard for
        a prompt, so same-prefix traffic lands where the chain lives."""
        from brpc_tpu.proto import serving_pb2
        from brpc_tpu.serving.router import (GenerateRouter,
                                             generate_route_key)

        kv = ShardedKVCache(KVCacheConfig(block_size=16, num_blocks=64),
                            1, 8)
        try:
            spc = ShardedPrefixCache(kv)
            router = GenerateRouter(kv.n_shards, block_size=16)
            placed = set()
            for seed in range(12):
                toks = [seed * 31 + i for i in range(20)]
                req = serving_pb2.GenerateRequest(prompt_tokens=toks)
                client = shard_for(router.route_key(req), kv.n_shards)
                assert client == spc.route_shard(toks)
                placed.add(client)
            assert placed == {0, 1}  # the hash actually spreads
            # short prompts fall back to whole-prompt routing
            short = serving_pb2.GenerateRequest(prompt_tokens=[1, 2, 3])
            assert router.route_key(short) == generate_route_key(short)
            assert spc.route_shard([1, 2, 3]) is None
        finally:
            kv.close()

    def test_sharded_fork_pins_the_sequence_to_the_chain_shard(self):
        kv = ShardedKVCache(KVCacheConfig(block_size=16, num_blocks=64),
                            1, 8)
        kv._check = True
        try:
            spc = ShardedPrefixCache(kv)
            toks = list(range(1, 33))  # 2 full blocks
            shard = spc.route_shard(toks + [0])
            assert shard is not None
            # build the chain where routing says it lives
            kv.alloc_sequence(101, 32, shard=shard)
            assert spc.commit(101, toks, 32) == 2
            kv.free_sequence(101)
            assert spc.match_len(toks + [0]) == 32
            assert spc.fork(202, toks + [0]) == 32
            # the fork pinned the sequence onto the chain's shard
            assert kv.block_table(202).shard == shard
            kv.free_sequence(202)
            assert spc.clear() == 2
            kv.assert_idle("sharded teardown")
        finally:
            kv.close()


# --------------------------------------------------------- engine wiring
class TestEngineWiring:
    def test_stub_models_get_no_prefix_cache(self):
        # no prefill_suffix on the model: the engine must not auto-build
        model = types.SimpleNamespace(
            config=types.SimpleNamespace(max_context=4096))
        eng = ServingEngine(model, _kv(), EngineConfig())
        assert eng.prefix is None

    def test_build_prefix_cache_dispatches_on_pool_type(self):
        assert isinstance(build_prefix_cache(_kv()), PrefixCache)
        skv = ShardedKVCache(KVCacheConfig(block_size=16, num_blocks=32),
                             1, 8)
        try:
            assert isinstance(build_prefix_cache(skv), ShardedPrefixCache)
        finally:
            skv.close()

    def test_thrash_watch_rule_installed_with_reloadable_bound(self):
        from brpc_tpu.metrics.watch import (KIND_RATE, global_watch,
                                            install_default_rules)

        install_default_rules()
        rules = {r.name: r for r in global_watch().rules()}
        rule = rules.get("serving_prefix_thrash")
        assert rule is not None, sorted(rules)
        assert rule.var == "g_serving_prefix_evicted_blocks"
        assert rule.kind == KIND_RATE
        assert rule.bound() == _flags.get("serving_prefix_thrash_rate")
        old = _flags.get("serving_prefix_thrash_rate")
        try:
            _flags.set_flag("serving_prefix_thrash_rate", "5")
            assert rule.bound() == 5.0
        finally:
            _flags.set_flag("serving_prefix_thrash_rate", str(old))


# ------------------------------------------------- real model: the oracle
MODEL_CFG = dict(vocab=256, d_model=32, n_heads=2, n_layers=2)


@pytest.fixture(scope="module")
def stack():
    """One compiled TinyTransformer + armed pool for the module; engines
    are per-run (the jit cache in the model is the expensive part)."""
    cfg = ModelConfig(**MODEL_CFG)
    kv = PagedKVCache(KVCacheConfig(block_size=16, num_blocks=256),
                      cfg.n_layers, cfg.kv_dim)
    kv._check = True  # armed ledger throughout
    model = TinyTransformer(cfg, kv)
    yield model, kv
    model.close()


def _run_schedule(model, kv, schedule, prefix_cache=None):
    """Drive one engine through the schedule; returns (token lists in
    submit order, final engine snapshot)."""
    engine = ServingEngine(model, kv, EngineConfig(
        max_batch=8, token_budget=512, idle_wait_s=0.002),
        prefix_cache=prefix_cache).start()
    try:
        evs, seqs = [], []
        for plen, max_new in schedule:
            ev = threading.Event()
            code, seq = engine.submit(model.synth_prompt(plen), max_new,
                                      done=lambda _r, ev=ev: ev.set())
            assert code == 0, f"submit rejected: {code}"
            evs.append(ev)
            seqs.append(seq)
        for ev in evs:
            assert ev.wait(300), "schedule run stalled"
        snap = engine.snapshot()
        return [list(s.out_tokens) for s in seqs], snap
    finally:
        engine.stop()


@pytest.fixture(scope="module")
def cold_reference(stack):
    """Cold-start outputs on the committed corpus schedule, from an
    engine with the prefix cache explicitly disabled."""
    model, kv = stack
    out, snap = _run_schedule(model, kv, SCHEDULE, prefix_cache=False)
    assert snap["prefix"] is None
    kv.assert_idle("cold reference teardown")
    return out


class TestForkOracle:
    def test_warm_outputs_bit_identical_to_cold(self, stack,
                                                cold_reference):
        """The acceptance oracle: generations that fork cached prefix
        chains are list-equal to cold-start on the committed corpus
        schedule — copy-on-write means a shared block is never mutated,
        so reuse cannot perturb a single logit."""
        model, kv = stack
        warm, snap = _run_schedule(model, kv, SCHEDULE * 2)
        assert warm == cold_reference * 2
        pfx = snap["prefix"]
        assert pfx["hit_seqs"] > 0 and pfx["hit_blocks"] > 0, pfx
        assert pfx["inserted_blocks"] > 0
        assert 0 < pfx["hit_ratio"] <= 1
        kv.assert_idle("oracle teardown")  # stop() cleared every hold

    def test_serving_builtin_reports_the_prefix_section(self, stack):
        import json as _json

        from brpc_tpu.builtin.services import serving_service

        model, kv = stack
        engine = ServingEngine(model, kv, EngineConfig(
            max_batch=8, token_budget=512, idle_wait_s=0.002)).start()
        try:
            evs = []
            for plen, max_new in SCHEDULE[:4]:
                ev = threading.Event()
                code, _ = engine.submit(model.synth_prompt(plen), max_new,
                                        done=lambda _r, ev=ev: ev.set())
                assert code == 0
                evs.append(ev)
            for ev in evs:
                assert ev.wait(300)
            status, _ctype, body = serving_service(
                None, types.SimpleNamespace(query={"format": "json"},
                                            path="/serving"))
            assert status == 200
            snap = _json.loads(body)["engines"][-1]
            assert snap["prefix"]["enabled"]
            assert snap["prefix"]["inserted_blocks"] > 0
            assert snap["kv"]["blocks_cached"] > 0
            status, _ctype, text = serving_service(
                None, types.SimpleNamespace(query={}, path="/serving"))
            assert status == 200
            assert "prefix: nodes=" in text and "hit_ratio=" in text
        finally:
            engine.stop()
        kv.assert_idle("builtin teardown")


# ------------------------------------------------------------------ chaos
@pytest.fixture()
def fault_enabled():
    _flags.set_flag("fault_injection_enabled", True)
    yield
    fault.disarm_all()
    _flags.set_flag("fault_injection_enabled", False)


@pytest.mark.chaos
class TestPrefixChaos:
    def test_eviction_churn_keeps_outputs_and_pool_whole(
            self, stack, cold_reference, fault_enabled):
        """Chaos: every admission force-evicts radix chains
        (serving.prefix.evict armed always) while the corpus schedule
        runs warm. Outputs stay bit-identical to cold-start, the armed
        ledger's per-mutation audits hold throughout, and after stop()
        the pool is whole — zero leaked blocks, zero lingering holds."""
        model, kv = stack
        fault.arm("serving.prefix.evict", mode="always", blocks=2)
        try:
            # two passes: the first populates the tree so the second's
            # admissions actually have chains to churn out from under
            churned, snap = _run_schedule(model, kv, SCHEDULE * 2)
        finally:
            fault.disarm_all()
        assert churned == cold_reference * 2
        assert snap["prefix"]["evicted_blocks"] > 0, snap["prefix"]
        kv.assert_idle("post eviction churn")
