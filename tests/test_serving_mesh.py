"""Mesh-sharded serving plane (brpc_tpu/serving/mesh_model.py, router.py,
ShardedKVCache): CPU-sim equivalence, the per-step dispatch invariant,
routing stability, and the sharded failure contract.

tests/conftest.py forces 8 virtual CPU devices, so the serving mesh here
is the REAL dp=2/sp=2/tp=2 split the multichip dryrun proves — not a
degenerate 1x1x1. Greedy decode is deterministic, so "sharded output ==
single-device output" is an exact list equality, not a tolerance check.
"""

import threading
import time
import types

import numpy as np
import pytest

from brpc_tpu.proto import serving_pb2
from brpc_tpu.rpc import Channel, ChannelOptions, Controller, RpcError, \
    Server, errors
from brpc_tpu.serving import (EngineConfig, KVCacheConfig, ModelConfig,
                              MeshTransformer, PagedKVCache, ServingEngine,
                              ShardedKVCache, ShardedLlmChannel,
                              TinyTransformer)
from brpc_tpu.rpc.combo_channels import SKIP
from brpc_tpu.serving.router import (GENERATE_MD, STATS_MD, GenerateRouter,
                                     StatsMerger, generate_route_key)
from brpc_tpu.serving.service import LlmServingService
from brpc_tpu.shard.plane import shard_for
from brpc_tpu.tpu.device_lane import DispatchCounter, step_dispatch

# the committed replay corpus's schedule (prompts synthesized from length
# alone, greedy argmax decode -> bit-replayable token streams)
from tools.record_serving_corpus import SCHEDULE

CFG = dict(vocab=256, d_model=32, n_heads=2, n_layers=2)


def _run_schedule(model, kv, schedule, scheduling="continuous"):
    """Drive one engine through the corpus schedule; returns each
    sequence's greedy token list in submit order."""
    engine = ServingEngine(model, kv, EngineConfig(
        max_batch=8, token_budget=512, scheduling=scheduling,
        idle_wait_s=0.002)).start()
    try:
        evs, seqs = [], []
        for plen, max_new in schedule:
            ev = threading.Event()
            code, seq = engine.submit(model.synth_prompt(plen), max_new,
                                      done=lambda _r, ev=ev: ev.set())
            assert code == 0, f"submit rejected: {code}"
            evs.append(ev)
            seqs.append(seq)
        for ev in evs:
            assert ev.wait(300), "schedule run stalled"
        return [list(s.out_tokens) for s in seqs]
    finally:
        engine.stop()


@pytest.fixture(scope="module")
def mesh_stack():
    """One MeshTransformer + armed ShardedKVCache shared by the module
    (the mesh jit cache is the expensive part; engines are per-test)."""
    cfg = ModelConfig(**CFG)
    kv = ShardedKVCache(KVCacheConfig(block_size=16, num_blocks=256),
                        cfg.n_layers, cfg.kv_dim)
    kv._check = True  # armed ledger: per-pool accounting + engine audit
    model = MeshTransformer(cfg, kv)
    yield cfg, model, kv
    model.close()


class TestMeshEquivalence:
    def test_mesh_is_dp2_sp2_tp2(self, mesh_stack):
        _, model, kv = mesh_stack
        assert kv.n_shards == 2
        assert dict(model.mesh.shape) == {"dp": 2, "sp": 2, "tp": 2}

    def test_corpus_schedule_tokens_identical_to_single_device(
            self, mesh_stack):
        """The acceptance gate: the sharded stack must produce the SAME
        greedy tokens as the single-device stack on the committed corpus
        schedule — bit-exact lowering, not approximately-equal serving."""
        cfg, model, kv = mesh_stack
        ref_kv = PagedKVCache(KVCacheConfig(block_size=16, num_blocks=256),
                              cfg.n_layers, cfg.kv_dim)
        ref_model = TinyTransformer(ModelConfig(**CFG), ref_kv)
        try:
            ref = _run_schedule(ref_model, ref_kv, SCHEDULE)
        finally:
            ref_model.close()
        got = _run_schedule(model, kv, SCHEDULE)
        assert got == ref
        kv.assert_idle()

    def test_dispatch_invariant_one_launch_one_sync_per_step(
            self, mesh_stack):
        """Every decode step costs exactly ONE fused program launch and
        ONE host materialization — the coalescing contract the whole PR
        rides, asserted from OUTSIDE the engine (the engine also asserts
        it internally per step because kv._check is armed)."""
        _, model, kv = mesh_stack
        orig = model.decode_step
        deltas = []

        def audited(tokens, positions, tables):
            before = step_dispatch.snapshot()
            out = orig(tokens, positions, tables)
            deltas.append(DispatchCounter.delta(
                before, step_dispatch.snapshot()))
            return out

        model.decode_step = audited
        try:
            _run_schedule(model, kv, SCHEDULE[:6])
        finally:
            model.decode_step = orig
        assert deltas, "no decode steps ran"
        assert all((launches, syncs) == (1, 1)
                   for launches, _ops, syncs in deltas), deltas
        kv.assert_idle()

    def test_serving_builtin_reports_per_shard_occupancy(self, mesh_stack):
        """/serving (text + ?format=json) must expose the per-device view:
        per-shard occupancy, the block-table shard map, and per-shard
        step latency."""
        import json as _json

        from brpc_tpu.builtin.services import serving_service

        _, model, kv = mesh_stack
        engine = ServingEngine(model, kv, EngineConfig(
            max_batch=8, token_budget=512, idle_wait_s=0.002)).start()
        try:
            evs = []
            for plen, max_new in SCHEDULE[:4]:
                ev = threading.Event()
                code, _ = engine.submit(model.synth_prompt(plen), max_new,
                                        done=lambda _r, ev=ev: ev.set())
                assert code == 0
                evs.append(ev)
            for ev in evs:
                assert ev.wait(300)
            status, _ctype, body = serving_service(
                None, types.SimpleNamespace(query={"format": "json"},
                                            path="/serving"))
            assert status == 200
            snap = _json.loads(body)["engines"][-1]
            assert snap["kv"]["n_shards"] == 2
            shards = snap["kv"]["shards"]
            assert [s["shard"] for s in shards] == [0, 1]
            assert all(s["blocks_total"] > 0 and s["devices"]
                       for s in shards)
            # every completed sequence freed its blocks again; what stays
            # used is exactly the prefix cache's committed chains
            assert all(s["blocks_used"] == s["blocks_cached"]
                       for s in shards)
            assert "shard_steps" in snap and snap["shard_steps"]
            status, _ctype, text = serving_service(
                None, types.SimpleNamespace(query={}, path="/serving"))
            assert status == 200
            assert "sharded: n_shards=2" in text
            assert "[shard 0]" in text and "[shard 1]" in text
        finally:
            engine.stop()
        kv.assert_idle()


class TestShardSkewWatchRule:
    def test_rule_installed_with_reloadable_bound(self):
        from brpc_tpu import flags as _flags
        from brpc_tpu.metrics.watch import (WatchRule, global_watch,
                                            install_default_rules)

        install_default_rules()
        rules = {r.name: r for r in global_watch().rules()}
        rule = rules.get("serving_shard_skew")
        assert rule is not None, sorted(rules)
        assert rule.var == "g_serving_kv_shard_skew"
        # the bound re-reads the flag every tick: /flags?setvalue=
        # retunes the live rule without re-installing it
        assert rule.bound() == _flags.get("serving_shard_skew_ratio")
        old = _flags.get("serving_shard_skew_ratio")
        try:
            _flags.set_flag("serving_shard_skew_ratio", "0.5")
            assert rule.bound() == 0.5
            assert "0.5" in rule.condition()
        finally:
            _flags.set_flag("serving_shard_skew_ratio", str(old))
        assert rule.bound() == old

    def test_value_fn_failure_falls_back_to_static_bound(self):
        from brpc_tpu.metrics.watch import KIND_THRESHOLD, WatchRule

        boom = WatchRule("t_boom", "v", KIND_THRESHOLD, ">", 0.25,
                         value_fn=lambda: (_ for _ in ()).throw(
                             RuntimeError("flag gone")))
        assert boom.bound() == 0.25

    def test_skew_gauge_tracks_unbalanced_pools(self):
        from brpc_tpu.serving.kv_cache import _fleet_skew

        kv = ShardedKVCache(KVCacheConfig(block_size=16, num_blocks=32),
                            1, 8)
        try:
            assert _fleet_skew() == 0.0  # idle fleet: balanced
            # pin blocks onto ONE shard: seq ids chosen so shard_of lands
            # on shard 0 every time
            sids = [s for s in range(1, 200) if kv.shard_of(s) == 0][:4]
            for s in sids:
                kv.alloc_sequence(s, 64)
            assert _fleet_skew() > 0.2
            for s in sids:
                kv.free_sequence(s)
            assert _fleet_skew() == 0.0
        finally:
            kv.close()


class TestRoutingStability:
    def test_versioned_cid_reuse_spreads_across_shards(self):
        """VersionedPool reuses slot 0 with only the high-bits version
        advancing, so real cids look like ``version << 32`` — exactly the
        pattern a truncating hash pins to shard 0. The splitmix64 spread
        must still balance them, and stay deterministic."""
        cids = [(v << 32) for v in range(1, 129)]
        shards = [shard_for(c, 2) for c in cids]
        assert set(shards) == {0, 1}
        share = sum(shards) / len(shards)
        assert 0.3 < share < 0.7, f"skewed spread: {share}"
        assert [shard_for(c, 2) for c in cids] == shards  # stable

    def test_block_table_routing_stable_under_cid_reuse(self):
        """Alloc/free cycles with VersionedPool-shaped seq ids: the block
        table's shard must equal shard_of(seq_id) every time, including
        when a reused id comes back — and nothing leaks."""
        kv = ShardedKVCache(KVCacheConfig(block_size=16, num_blocks=32),
                            1, 8)
        try:
            seen = set()
            for v in range(1, 41):
                cid = v << 32
                table = kv.alloc_sequence(cid, 20)
                assert table.shard == kv.shard_of(cid)
                assert kv.block_table(cid).shard == table.shard
                seen.add(table.shard)
                kv.free_sequence(cid)
                # the SAME cid re-allocated lands on the SAME shard
                again = kv.alloc_sequence(cid, 20)
                assert again.shard == table.shard
                kv.free_sequence(cid)
            assert seen == {0, 1}
            kv.assert_idle()
        finally:
            kv.close()


class TestGenerateRouter:
    def test_generate_maps_to_single_owner_partition(self):
        req = serving_pb2.GenerateRequest(prompt_tokens=[3, 1, 4, 1, 5])
        for n in (2, 4):
            router = GenerateRouter(n)
            decisions = [router.map(i, GENERATE_MD, req, None)
                         for i in range(n)]
            live = [i for i, d in enumerate(decisions) if d is not SKIP]
            assert live == [shard_for(generate_route_key(req), n)]

    def test_stats_fans_out_to_every_partition(self):
        router = GenerateRouter(4)
        req = serving_pb2.ServingStatsRequest()
        decisions = [router.map(i, STATS_MD, req, None) for i in range(4)]
        assert all(d is not SKIP for d in decisions)

    def test_route_key_deterministic_and_prompt_dependent(self):
        a = serving_pb2.GenerateRequest(prompt_tokens=[1, 2, 3])
        b = serving_pb2.GenerateRequest(prompt_tokens=[1, 2, 4])
        assert generate_route_key(a) == generate_route_key(a)
        assert generate_route_key(a) != generate_route_key(b)
        # synth-prompt requests route on prompt_len
        c = serving_pb2.GenerateRequest(prompt_len=16)
        d = serving_pb2.GenerateRequest(prompt_len=32)
        assert generate_route_key(c) != generate_route_key(d)

    def test_stats_merger_sums_shard_gauges(self):
        merger = StatsMerger()
        total = serving_pb2.ServingStats()
        for used in (3, 5):
            sub = serving_pb2.ServingStats(
                seqs_running=1, seqs_waiting=2, kv_blocks_total=128,
                kv_blocks_used=used, steps=10, tokens_generated=40)
            assert merger.merge(total, sub) == merger.MERGED
        assert total.kv_blocks_total == 256
        assert total.kv_blocks_used == 8
        assert total.seqs_running == 2 and total.tokens_generated == 80


class TestShardedGenerateChaos:
    def _fleet(self, n_layers=4):
        """n=2 shard-per-server fleet, each engine over its own ARMED
        paged pool (the deployment the router's i/n tags name)."""
        fleet = []
        for _ in range(2):
            cfg = ModelConfig(vocab=256, d_model=32, n_heads=2,
                              n_layers=n_layers)
            kv = PagedKVCache(KVCacheConfig(block_size=16, num_blocks=64),
                              cfg.n_layers, cfg.kv_dim)
            kv._check = True
            model = TinyTransformer(cfg, kv)
            engine = ServingEngine(model, kv, EngineConfig(
                max_batch=4, token_budget=256, idle_wait_s=0.002)).start()
            srv = Server().add_service(
                LlmServingService(engine)).start("127.0.0.1:0")
            fleet.append((srv, engine, model, kv))
        return fleet

    def test_shard_death_mid_generate_is_retriable_and_leak_free(self):
        """Chaos: the owning shard's server dies mid-Generate. The caller
        must see retriable EFAILEDSOCKET naming the shard (NOT the
        parallel-channel ETOOMANYFAILS verdict), and under the armed
        ledger every device-local block the doomed sequence held must
        come back — zero leaks."""
        fleet = self._fleet()
        try:
            url = (f"list://{fleet[0][0].listen_endpoint()} 0/2,"
                   f"{fleet[1][0].listen_endpoint()} 1/2")
            ch = ShardedLlmChannel(
                url, 2, options=ChannelOptions(protocol="trpc_std",
                                               timeout_ms=60000))
            req = serving_pb2.GenerateRequest(prompt_len=16,
                                              max_new_tokens=200)
            owner = ch.shard_of(req)
            # same route key as the chaos request (prompt_len routes), so
            # this warms the OWNER engine's jit buckets: the chaos run's
            # timing is then decode-bound, not compile-bound
            warm = ch.generate(serving_pb2.GenerateRequest(
                prompt_len=16, max_new_tokens=4))
            assert len(warm.tokens) == 4
            def kill(srv=fleet[owner][0]):
                # stop() alone is graceful (in-flight finishes); process
                # death is stop + zero-deadline join, which force-closes
                # the live connections under the request
                srv.stop()
                srv.join(timeout=0)

            killer = threading.Timer(0.05, kill)
            killer.start()
            try:
                with pytest.raises(RpcError) as ei:
                    ch.generate(req)
            finally:
                killer.cancel()
            assert ei.value.error_code == errors.EFAILEDSOCKET
            assert "retriable" in str(ei.value)
            assert f"shard {owner}/2" in str(ei.value)
            # the OTHER shard never saw either call (partitioned routing,
            # not fan-out)
            other_engine = fleet[1 - owner][1]
            assert other_engine.tokens_generated == 0
        finally:
            for srv, engine, model, kv in fleet:
                srv.stop()
                srv.join(timeout=2)
                engine.stop()
                # the armed ledger proves the doomed sequence's blocks
                # were returned: any leak raises here
                kv.assert_idle()
                model.close()

    def test_owner_shard_death_with_warm_prefix_leaks_nothing(self):
        """Chaos x prefix cache: the owning shard dies mid-Generate while
        the doomed sequence is FORKED from a committed radix chain. The
        abort must return only the sequence's own holds — the tree's
        refcounts stay consistent under the armed ledger (any drift
        raises inside the engine's per-step audit), and after stop()
        clears the tree the pool is bit-for-bit whole."""
        fleet = self._fleet(n_layers=2)
        try:
            url = (f"list://{fleet[0][0].listen_endpoint()} 0/2,"
                   f"{fleet[1][0].listen_endpoint()} 1/2")
            ch = ShardedLlmChannel(
                url, 2, options=ChannelOptions(protocol="trpc_std",
                                               timeout_ms=60000))
            # block_size=16: a 48-token prompt commits 3 full blocks, so
            # the repeat warm pass (and the doomed request) fork 2 of them
            req = serving_pb2.GenerateRequest(prompt_len=48,
                                              max_new_tokens=200)
            owner = ch.shard_of(req)
            owner_engine = fleet[owner][1]
            warms = [ch.generate(serving_pb2.GenerateRequest(
                prompt_len=48, max_new_tokens=4)) for _ in range(2)]
            # the warm hit is bit-identical to the cold pass
            assert list(warms[0].tokens) == list(warms[1].tokens)
            pfx = owner_engine.snapshot()["prefix"]
            assert pfx["hit_seqs"] >= 1 and pfx["blocks"] > 0, pfx

            def kill(srv=fleet[owner][0]):
                srv.stop()
                srv.join(timeout=0)

            killer = threading.Timer(0.05, kill)
            killer.start()
            try:
                with pytest.raises(RpcError) as ei:
                    ch.generate(req)
            finally:
                killer.cancel()
            assert ei.value.error_code == errors.EFAILEDSOCKET
            deadline = time.monotonic() + 5.0
            while owner_engine.running_count and time.monotonic() < deadline:
                time.sleep(0.005)
            # the doomed fork's private blocks came back; exactly the
            # tree-held committed chains stay pinned
            snap = owner_engine.kv.snapshot()
            assert snap["blocks_cached"] > 0
            assert snap["blocks_used"] == snap["blocks_cached"]
        finally:
            for srv, engine, model, kv in fleet:
                srv.stop()
                srv.join(timeout=2)
                engine.stop()  # clears the radix tree's holds
                kv.assert_idle()  # zero leaked blocks, zero cache holds
                model.close()

    def test_fleet_stats_merge_across_shards(self):
        fleet = self._fleet(n_layers=2)
        try:
            url = (f"list://{fleet[0][0].listen_endpoint()} 0/2,"
                   f"{fleet[1][0].listen_endpoint()} 1/2")
            ch = ShardedLlmChannel(
                url, 2, options=ChannelOptions(protocol="trpc_std",
                                               timeout_ms=60000))
            # land one generation on EACH shard (prompt_len routes; 16
            # and 32 hash to different shards for n=2 — asserted, not
            # assumed)
            lens = {ch.shard_of(serving_pb2.GenerateRequest(prompt_len=L)):
                    L for L in (16, 32, 48, 64)}
            assert set(lens) == {0, 1}
            for L in lens.values():
                r = ch.generate(serving_pb2.GenerateRequest(
                    prompt_len=L, max_new_tokens=4))
                assert len(r.tokens) == 4
            stats = ch.stats()
            assert stats.tokens_generated == 8
            # fleet totals: both pools' capacity summed
            assert stats.kv_blocks_total == 2 * 64
            # in-flight work drained; only prefix-cache chains stay used
            cached = sum(e.kv.snapshot()["blocks_cached"]
                         for _s, e, _m, _k in fleet)
            assert stats.kv_blocks_used == cached
        finally:
            for srv, engine, model, kv in fleet:
                srv.stop()
                srv.join(timeout=2)
                engine.stop()
                kv.assert_idle()
                model.close()
