"""Runtime invariant checker (BRPC_TPU_CHECK) — ledger + lock-order tests.

Unit level: the credit ledger catches overdraw/double-release/leaks and
the lock-order recorder catches opposite acquisition orders without
needing the schedules to actually collide. Integration level (the tier-1
chaos/streaming smoke from the ISSUE): a 16MB streaming echo and a
tunnel-kill recovery run with the ledger armed, and the credit window
balances at teardown."""

import threading
import time

import pytest

from brpc_tpu.analysis import runtime_check as rc
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    Service,
    Stub,
)

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoServiceImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        cntl.response_attachment = cntl.request_attachment
        return echo_pb2.EchoResponse(message=request.message,
                                     payload=request.payload)


@pytest.fixture()
def checker():
    """Arm the runtime checker for one test; always disarm after."""
    was_active = rc.ACTIVE
    rc.activate()
    try:
        yield rc
    finally:
        if was_active:
            # env-armed session (BRPC_TPU_CHECK=1): surface what this test
            # left behind instead of silently resetting it
            from brpc_tpu.tpu.transport import _sweep_deferred_pools
            rc.ledger.assert_balanced(drain=_sweep_deferred_pools)
            rc.activate()  # fresh state, stays armed
        else:
            rc.deactivate()


class _Obj:
    pass


# ------------------------------------------------------------- credit ledger
class TestCreditLedger:
    def test_balanced_window_passes(self, checker):
        win = _Obj()
        rc.ledger.track_window(win, 8, label="w", owner="t")
        rc.ledger.window_acquired(win, 5)
        rc.ledger.window_released(win, 5)
        rc.ledger.assert_balanced()

    def test_outstanding_credits_fail(self, checker):
        win = _Obj()
        rc.ledger.track_window(win, 8, label="w", owner="t")
        rc.ledger.window_acquired(win, 3)
        with pytest.raises(AssertionError, match="still holds 3"):
            rc.ledger.assert_balanced()
        rc.ledger.window_released(win, 3)

    def test_overdraw_recorded(self, checker):
        win = _Obj()
        rc.ledger.track_window(win, 4, label="w", owner="t")
        rc.ledger.window_acquired(win, 6)
        assert any("overdraw" in v for v in rc.ledger.violations)
        rc.ledger.reset()

    def test_double_release_recorded(self, checker):
        win = _Obj()
        rc.ledger.track_window(win, 4, label="w", owner="t")
        rc.ledger.window_acquired(win, 2)
        rc.ledger.window_released(win, 2)
        rc.ledger.window_released(win, 1)
        assert any("double-release" in v for v in rc.ledger.violations)
        rc.ledger.reset()

    def test_failure_close_excuses_in_flight_credits(self, checker):
        # a window torn down by tunnel death may carry credits the peer
        # will never ACK — close untracks without a verdict
        win = _Obj()
        rc.ledger.track_window(win, 8, label="w", owner="t")
        rc.ledger.window_acquired(win, 4)
        rc.ledger.window_closed(win)
        rc.ledger.assert_balanced()

    def test_graceful_teardown_demands_whole_window(self, checker):
        win = _Obj()
        rc.ledger.track_window(win, 8, label="w", owner="t")
        rc.ledger.window_acquired(win, 2)
        rc.ledger.window_teardown(win, wait=0.05)
        assert any("graceful teardown" in v for v in rc.ledger.violations)
        rc.ledger.reset()

    def test_borrow_leak_fails(self, checker):
        pool = _Obj()
        rc.ledger.track_pool(pool, label="p", owner="t")
        rc.ledger.export_added(pool)
        with pytest.raises(AssertionError, match="borrowed view"):
            rc.ledger.assert_balanced()
        rc.ledger.export_dropped(pool)
        rc.ledger.assert_balanced()

    def test_double_return_recorded(self, checker):
        pool = _Obj()
        rc.ledger.track_pool(pool, label="p", owner="t")
        rc.ledger.export_added(pool)
        rc.ledger.export_dropped(pool)
        rc.ledger.export_dropped(pool)
        assert any("double-return" in v for v in rc.ledger.violations)
        rc.ledger.reset()

    def test_untracked_objects_noop(self, checker):
        # created before activation (no token): every ledger call no-ops
        win = _Obj()
        rc.ledger.window_acquired(win, 99)
        rc.ledger.window_released(win, 99)
        rc.ledger.export_dropped(win)
        rc.ledger.assert_balanced()


# ----------------------------------------------------------------- lock order
class TestLockOrder:
    def test_opposite_orders_flagged_without_deadlock(self, checker):
        a = rc.tracked_lock("test.A")
        b = rc.tracked_lock("test.B")

        def ab():
            with a:
                with b:
                    pass

        def ba():
            with b:
                with a:
                    pass

        t1 = threading.Thread(target=ab)
        t1.start()
        t1.join()
        t2 = threading.Thread(target=ba)
        t2.start()
        t2.join()
        assert any("cycle" in v and "test.A" in v
                   for v in rc.lock_order.violations)
        rc.lock_order.reset()

    def test_consistent_order_clean(self, checker):
        a = rc.tracked_lock("test.C")
        b = rc.tracked_lock("test.D")
        for _ in range(3):
            with a:
                with b:
                    pass
        assert not rc.lock_order.violations

    def test_reentrant_lock_not_a_cycle(self, checker):
        lk = rc.tracked_lock("test.R", threading.RLock())
        with lk:
            with lk:
                pass
        assert not rc.lock_order.violations

    def test_inactive_returns_raw_lock(self):
        was = rc.ACTIVE
        rc.ACTIVE = False
        try:
            lk = rc.tracked_lock("raw")
            assert isinstance(lk, type(threading.Lock()))
        finally:
            rc.ACTIVE = was


# ----------------------------------------------------- tier-1 streaming smoke
@pytest.mark.chaos
class TestLedgerSmoke:
    """The ISSUE's acceptance smoke: streaming + chaos with the ledger
    armed, credits balancing at teardown."""

    def _wait_clean(self, timeout=5.0):
        """ACKs for the tail of a message may still be in flight; poll the
        ledger to quiescence before the hard assert."""
        from brpc_tpu.tpu.transport import _sweep_deferred_pools

        deadline = time.monotonic() + timeout
        while time.monotonic() < deadline:
            snap = rc.ledger.snapshot()
            if (not snap["violations"] and not snap["borrowed"]
                    and not any(snap["windows"].values())):
                break
            time.sleep(0.02)
        rc.ledger.assert_balanced(drain=_sweep_deferred_pools)

    def test_16mb_streaming_echo_balances(self, checker):
        server = Server(ServerOptions())
        server.add_service(EchoServiceImpl())
        server.start("tpu://127.0.0.1:0/0")
        try:
            channel = Channel(ChannelOptions(protocol="trpc_std",
                                             timeout_ms=60000))
            channel.init(str(server.listen_endpoint()))
            stub = Stub(channel, ECHO)
            payload = b"\x5a" * (16 * 1024 * 1024)
            r = stub.Echo(echo_pb2.EchoRequest(message="big",
                                               payload=payload))
            assert r.payload == payload
            self._wait_clean()
            assert not rc.lock_order.violations
        finally:
            server.stop()
            server.join()

    def test_tunnel_kill_recovery_balances(self, checker):
        from brpc_tpu import fault
        from brpc_tpu import flags as _flags

        _flags.set_flag("fault_injection_enabled", "true")
        server = Server(ServerOptions())
        server.add_service(EchoServiceImpl())
        server.start("tpu://127.0.0.1:0/0")
        try:
            channel = Channel(ChannelOptions(protocol="trpc_std",
                                             timeout_ms=60000))
            channel.init(str(server.listen_endpoint()))
            stub = Stub(channel, ECHO)
            assert stub.Echo(
                echo_pb2.EchoRequest(message="warm")).message == "warm"
            # kill the vsock mid-16MB streaming send: the dead epoch's
            # window untracks (its in-flight credits died with it), the
            # healed epoch's window must balance like any other
            fault.arm("tpu.tunnel.kill", after=8)
            payload = b"\xc7" * (16 * 1024 * 1024)
            r = stub.Echo(echo_pb2.EchoRequest(message="again",
                                               payload=payload))
            assert r.payload == payload
            self._wait_clean(timeout=8.0)
        finally:
            fault.disarm_all()
            _flags.set_flag("fault_injection_enabled", "false")
            server.stop()
            server.join()
