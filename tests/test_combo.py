"""Combo channel + admission/failure-policy tests (reference pattern:
brpc_channel_unittest.cpp:395-430 — N sub-channels to loopback servers)."""

import threading
import time

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Controller,
    MethodDescriptor,
    RpcError,
    Server,
    Service,
    Stub,
    errors,
)
from brpc_tpu.rpc.combo_channels import (
    CallMapper,
    ParallelChannel,
    PartitionChannel,
    ResponseMerger,
    SelectiveChannel,
    SKIP,
    SubCall,
)

ECHO_DESC = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]
ECHO_MD = MethodDescriptor("EchoService", "Echo",
                           echo_pb2.EchoRequest, echo_pb2.EchoResponse)


class NamedEcho(Service):
    DESCRIPTOR = ECHO_DESC

    def __init__(self, name, fail=False):
        super().__init__()
        self.name = name
        self.fail = fail
        self.hits = 0

    def Echo(self, cntl, request, done):
        self.hits += 1
        if self.fail:
            raise RuntimeError("injected")
        return echo_pb2.EchoResponse(message=self.name)


def start_servers(*impls):
    servers = [Server().add_service(i).start("127.0.0.1:0") for i in impls]
    return servers


def stop_servers(servers):
    for s in servers:
        s.stop()
        s.join(timeout=2)


class TestParallelChannel:
    def test_fanout_and_merge(self):
        impls = [NamedEcho("a"), NamedEcho("b"), NamedEcho("c")]
        servers = start_servers(*impls)
        try:
            pc = ParallelChannel()
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())))

            class ConcatMerger(ResponseMerger):
                def merge(self, response, sub):
                    response.message += sub.message
                    return 0

            pc2 = ParallelChannel()
            for s in servers:
                pc2.add_channel(Channel().init(str(s.listen_endpoint())),
                                response_merger=ConcatMerger())
            resp = pc2.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))
            assert sorted(resp.message) == ["a", "b", "c"]
            assert all(i.hits == 1 for i in impls)
        finally:
            stop_servers(servers)

    def test_fail_limit(self):
        impls = [NamedEcho("ok"), NamedEcho("bad", fail=True)]
        servers = start_servers(*impls)
        try:
            pc = ParallelChannel(fail_limit=1)
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())))
            with pytest.raises(RpcError) as ei:
                pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))
            assert ei.value.error_code == errors.ETOOMANYFAILS
        finally:
            stop_servers(servers)

    def test_partial_failure_tolerated_by_default(self):
        impls = [NamedEcho("ok"), NamedEcho("bad", fail=True)]
        servers = start_servers(*impls)
        try:
            pc = ParallelChannel()  # default: succeed unless ALL fail
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())))
            resp = pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))
            assert resp.message == "ok"
        finally:
            stop_servers(servers)

    def test_call_mapper_skip_and_rewrite(self):
        impls = [NamedEcho("a"), NamedEcho("b")]
        servers = start_servers(*impls)
        try:
            class OnlyFirst(CallMapper):
                def map(self, idx, method, request, response):
                    if idx != 0:
                        return SKIP
                    return SubCall(method,
                                   echo_pb2.EchoRequest(message="rewritten"),
                                   echo_pb2.EchoResponse())

            pc = ParallelChannel()
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())),
                               call_mapper=OnlyFirst())
            resp = pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))
            assert resp.message == "a"
            assert impls[0].hits == 1 and impls[1].hits == 0
        finally:
            stop_servers(servers)

    def test_async_done(self):
        impls = [NamedEcho("a")]
        servers = start_servers(*impls)
        try:
            pc = ParallelChannel()
            pc.add_channel(Channel().init(str(servers[0].listen_endpoint())))
            ev = threading.Event()
            out = []

            def on_done(cntl):
                out.append(cntl.failed())
                ev.set()

            pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"),
                           done=on_done)
            assert ev.wait(5)
            assert out == [False]
        finally:
            stop_servers(servers)


class SlowEcho(Service):
    DESCRIPTOR = ECHO_DESC

    def __init__(self, name, delay_s=1.0):
        super().__init__()
        self.name = name
        self.delay_s = delay_s
        self.hits = 0

    def Echo(self, cntl, request, done):
        self.hits += 1
        time.sleep(self.delay_s)
        return echo_pb2.EchoResponse(message=self.name)


class TestParallelChannelLimits:
    """Reference semantics regressions (parallel_channel.cpp:223-235,
    parallel_channel.h:161-174)."""

    def test_fail_limit_cancels_outstanding(self):
        # two instant failures + one slow success; fail_limit=2 must fail
        # the call immediately without waiting for the slow sub-call
        impls = [NamedEcho("bad1", fail=True), NamedEcho("bad2", fail=True),
                 SlowEcho("slow", delay_s=2.0)]
        servers = start_servers(*impls)
        try:
            pc = ParallelChannel(fail_limit=2)
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())))
            cntl = Controller()
            cntl.timeout_ms = 10_000
            start = time.monotonic()
            with pytest.raises(RpcError) as ei:
                pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"),
                               controller=cntl)
            elapsed = time.monotonic() - start
            assert ei.value.error_code == errors.ETOOMANYFAILS
            assert elapsed < 1.5, f"waited for canceled sub-call: {elapsed}"
        finally:
            stop_servers(servers)

    def test_success_limit_finishes_early(self):
        impls = [NamedEcho("fast"), SlowEcho("slow1", delay_s=2.0),
                 SlowEcho("slow2", delay_s=2.0)]
        servers = start_servers(*impls)
        try:
            pc = ParallelChannel(success_limit=1)
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())))
            cntl = Controller()
            cntl.timeout_ms = 10_000
            start = time.monotonic()
            resp = pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"),
                                  controller=cntl)
            elapsed = time.monotonic() - start
            assert resp.message == "fast"
            assert elapsed < 1.5, f"waited past success_limit: {elapsed}"
        finally:
            stop_servers(servers)

    def test_fail_limit_clamped_to_issued(self):
        # fail_limit > #channels must not turn an all-fail fan-out into a
        # silent empty success (reference clamps to ndone, .cpp:661-667)
        impls = [NamedEcho("b1", fail=True), NamedEcho("b2", fail=True)]
        servers = start_servers(*impls)
        try:
            pc = ParallelChannel(fail_limit=5)
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())))
            with pytest.raises(RpcError) as ei:
                pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))
            assert ei.value.error_code == errors.ETOOMANYFAILS
        finally:
            stop_servers(servers)

    def test_merger_fail_counts_against_fail_limit(self):
        impls = [NamedEcho("a"), NamedEcho("b")]
        servers = start_servers(*impls)
        try:
            class RejectAll(ResponseMerger):
                def merge(self, response, sub):
                    return ResponseMerger.FAIL

            pc = ParallelChannel()  # fail_limit = all
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())),
                               response_merger=RejectAll())
            with pytest.raises(RpcError) as ei:
                pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))
            assert ei.value.error_code == errors.ETOOMANYFAILS
        finally:
            stop_servers(servers)

    def test_merger_fail_all_fails_whole_call(self):
        impls = [NamedEcho("a"), NamedEcho("b"), NamedEcho("c")]
        servers = start_servers(*impls)
        try:
            class Poison(ResponseMerger):
                calls = 0

                def merge(self, response, sub):
                    Poison.calls += 1
                    if Poison.calls == 1:
                        return ResponseMerger.FAIL_ALL
                    return ResponseMerger.MERGED

            pc = ParallelChannel()  # default would tolerate one failure
            for s in servers:
                pc.add_channel(Channel().init(str(s.listen_endpoint())),
                               response_merger=Poison())
            with pytest.raises(RpcError) as ei:
                pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))
            assert ei.value.error_code == errors.ETOOMANYFAILS
        finally:
            stop_servers(servers)


class TestSelectiveChannel:
    def test_prefers_healthy_channel(self):
        impls = [NamedEcho("good")]
        servers = start_servers(*impls)
        try:
            sc = SelectiveChannel()
            dead = Channel(ChannelOptions(max_retry=0,
                                          connect_timeout_ms=200))
            dead.init("127.0.0.1:1")
            sc.add_channel(dead)
            sc.add_channel(Channel().init(str(servers[0].listen_endpoint())))
            for _ in range(4):
                resp = sc.call_method(ECHO_MD,
                                      echo_pb2.EchoRequest(message="x"))
                assert resp.message == "good"
            # dead channel parked after its failures: traffic converges
            assert impls[0].hits >= 4
        finally:
            stop_servers(servers)

    def test_all_dead_fails(self):
        sc = SelectiveChannel(max_retry=1)
        dead = Channel(ChannelOptions(max_retry=0, connect_timeout_ms=100))
        dead.init("127.0.0.1:1")
        sc.add_channel(dead)
        with pytest.raises(RpcError):
            sc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))

    def test_failed_attempt_does_not_leak_into_response(self):
        """A failed attempt that partially filled its response must not
        contaminate the caller's response object (VERDICT r1 weak #5;
        reference isolates sub-call responses)."""
        from brpc_tpu.rpc.channel import RpcError as _RpcError

        class GarbageThenFail:
            """Fake sub-channel: writes junk into the response, then fails."""

            def call_method(self, method, request, response=None,
                            controller=None, done=None):
                if response is not None:
                    response.message = "GARBAGE"
                cntl = controller or Controller()
                cntl.set_failed(errors.EINTERNAL, "injected partial fill")
                raise _RpcError(cntl)

        impls = [NamedEcho("good")]
        servers = start_servers(*impls)
        try:
            sc = SelectiveChannel()
            sc.add_channel(GarbageThenFail())
            sc.add_channel(Channel().init(str(servers[0].listen_endpoint())))
            caller_resp = echo_pb2.EchoResponse()
            out = sc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"),
                                 response=caller_resp)
            assert caller_resp.message == "good"
            assert out.message == "good"
        finally:
            stop_servers(servers)


class TestPartitionChannel:
    def test_partitioned_fanout(self):
        impls = [NamedEcho("p0"), NamedEcho("p1")]
        servers = start_servers(*impls)
        try:
            url = (f"list://{servers[0].listen_endpoint()} 0/2,"
                   f"{servers[1].listen_endpoint()} 1/2")

            class ConcatMerger(ResponseMerger):
                def merge(self, response, sub):
                    response.message += sub.message
                    return 0

            pc = PartitionChannel()
            pc.init(url, partition_count=2)
            # swap default mergers for concat to observe both partitions
            pc._subs = [(ch, m, ConcatMerger()) for ch, m, _ in pc._subs]
            resp = pc.call_method(ECHO_MD, echo_pb2.EchoRequest(message="x"))
            assert sorted(resp.message.split("p")[1:]) == ["0", "1"]
            assert impls[0].hits == 1 and impls[1].hits == 1
        finally:
            stop_servers(servers)

    def test_wrong_partition_count_dropped(self):
        from brpc_tpu.rpc.combo_channels import PartitionParser

        parser = PartitionParser()
        assert parser.parse("1/3") == (1, 3)
        assert parser.parse("junk") is None


class TestLimiters:
    def test_constant(self):
        from brpc_tpu.policy.limiters import ConstantLimiter

        lim = ConstantLimiter(2)
        assert lim.on_request() and lim.on_request()
        assert not lim.on_request()
        lim.on_response(100, 0)
        assert lim.on_request()

    def test_auto_grows_on_healthy_latency(self):
        from brpc_tpu.policy.limiters import AutoLimiter

        lim = AutoLimiter(initial=8, sample_window=16)
        for _ in range(200):
            if lim.on_request():
                lim.on_response(100.0, 0)
        assert lim.limit > 8  # stable latency -> limit grows

    def test_auto_shrinks_on_degraded_latency(self):
        from brpc_tpu.policy.limiters import AutoLimiter

        lim = AutoLimiter(initial=64, sample_window=16)
        for _ in range(32):  # establish a fast floor
            lim.on_request()
            lim.on_response(100.0, 0)
        for _ in range(200):  # latency collapses
            if lim.on_request():
                lim.on_response(10_000.0, 0)
        assert lim.limit < 64

    def test_timeout_limiter_rejects_when_backlogged(self):
        from brpc_tpu.policy.limiters import TimeoutLimiter

        lim = TimeoutLimiter(timeout_ms=1.0)
        lim._avg_latency_us = 10_000.0  # 10ms per request observed
        assert lim.on_request()  # queue empty: expected wait 0
        assert not lim.on_request()  # one queued x 10ms > 1ms budget

    def test_method_limiter_wireup(self):
        impl = NamedEcho("x")
        server = Server().add_service(impl).start("127.0.0.1:0")
        try:
            impl.find_method("Echo").set_limiter("constant:1")
            ch = Channel().init(str(server.listen_endpoint()))
            stub = Stub(ch, ECHO_DESC)
            assert stub.Echo(echo_pb2.EchoRequest(message="m")).message == "x"
        finally:
            server.stop()
            server.join(timeout=2)


class TestCircuitBreaker:
    def test_trips_on_error_burst_and_recovers(self):
        from brpc_tpu.rpc.circuit_breaker import CircuitBreaker

        cb = CircuitBreaker(min_samples=10, base_isolation_s=0.05)
        for _ in range(20):
            cb.on_call_end(1)  # all errors
        assert cb.isolated
        time.sleep(0.08)
        assert not cb.isolated  # isolation expired: half-open

    def test_healthy_traffic_never_trips(self):
        from brpc_tpu.rpc.circuit_breaker import CircuitBreaker

        cb = CircuitBreaker()
        for _ in range(1000):
            cb.on_call_end(0)
        assert not cb.isolated

    def test_repeat_offender_isolated_longer(self):
        from brpc_tpu.rpc.circuit_breaker import CircuitBreaker

        cb = CircuitBreaker(min_samples=5, base_isolation_s=0.02)
        for _ in range(10):
            cb.on_call_end(1)
        first = cb._isolated_until - time.monotonic()
        time.sleep(0.03)
        for _ in range(10):
            cb.on_call_end(1)
        second = cb._isolated_until - time.monotonic()
        assert second > first

    def test_cluster_recover_guard(self):
        from brpc_tpu.rpc.circuit_breaker import ClusterRecoverGuard

        g = ClusterRecoverGuard(threshold=0.5, interval_s=10)
        assert g.may_recover(1, 10)       # few isolated: free recovery
        assert g.may_recover(8, 10)       # mass isolation: first allowed
        assert not g.may_recover(8, 10)   # second rationed


class TestHealthCheck:
    def test_probe_revives_parked_node(self):
        from brpc_tpu.butil.endpoint import EndPoint
        from brpc_tpu.policy.load_balancers import RoundRobinLB, ServerNode
        from brpc_tpu.rpc.health_check import HealthChecker

        impl = NamedEcho("alive")
        server = Server().add_service(impl).start("127.0.0.1:0")
        try:
            ep = server.listen_endpoint()
            lb = RoundRobinLB()
            lb.reset_servers([ServerNode(ep)])
            # park it artificially
            st = lb._node_state(ep)
            st.fail_streak = 3
            st.down_until = time.monotonic() + 60
            checker = HealthChecker(lb, interval_s=0.05)
            deadline = time.time() + 5
            while st.is_down and time.time() < deadline:
                time.sleep(0.05)
            assert not st.is_down
            checker.stop()
        finally:
            server.stop()
            server.join(timeout=2)

    def test_tcp_probe_dead_endpoint(self):
        from brpc_tpu.butil.endpoint import EndPoint
        from brpc_tpu.rpc.health_check import tcp_probe

        assert tcp_probe(EndPoint.parse("127.0.0.1:1"), timeout=0.3) is False


class TestDynamicPartitionChannel:
    """Capacity migration between partition schemes (reference
    partition_channel.h:136, dynpart_load_balancer.cpp)."""

    def _make(self, servers):
        from brpc_tpu.rpc.combo_channels import DynamicPartitionChannel

        a, b = (str(s.listen_endpoint()) for s in servers)
        # two schemes live at once: 2-partition tier (1 server each) and a
        # 4-partition tier (the same two servers doubled up)
        url = (f"list://{a} 0/2,{b} 1/2,"
               f"{a} 0/4,{b} 1/4,{a} 2/4,{b} 3/4")

        class CountMerger(ResponseMerger):
            def merge(self, response, sub):
                response.message += "."
                return 0

        dpc = DynamicPartitionChannel()
        dpc.init(url, response_merger=CountMerger())
        return dpc

    def test_traffic_splits_by_capacity(self):
        impls = [NamedEcho("a"), NamedEcho("b")]
        servers = start_servers(*impls)
        try:
            dpc = self._make(servers)
            assert dpc.scheme_capacities() == {2: 2, 4: 4}
            fan_counts = set()
            for _ in range(60):
                resp = dpc.call_method(ECHO_MD,
                                       echo_pb2.EchoRequest(message="x"))
                fan_counts.add(len(resp.message))
            # both schemes must carry traffic (P[miss] <= (4/6)^60)
            assert fan_counts == {2, 4}, fan_counts
        finally:
            stop_servers(servers)

    def test_drain_finishes_migration(self, tmp_path):
        from brpc_tpu.policy.naming import parse_server_item
        from brpc_tpu.rpc.combo_channels import DynamicPartitionChannel

        impls = [NamedEcho("a"), NamedEcho("b")]
        servers = start_servers(*impls)
        try:
            a, b = (str(s.listen_endpoint()) for s in servers)
            both_tiers = (f"{a} 0/2\n{b} 1/2\n"
                          f"{a} 0/4\n{b} 1/4\n{a} 2/4\n{b} 3/4\n")
            ns_file = tmp_path / "cluster.lst"
            ns_file.write_text(both_tiers)

            class CountMerger(ResponseMerger):
                def merge(self, response, sub):
                    response.message += "."
                    return 0

            dpc = DynamicPartitionChannel()
            dpc.init(f"file://{ns_file}", response_merger=CountMerger())
            assert dpc.scheme_capacities() == {2: 2, 4: 4}
            # the old 2-partition tier drains: the naming FILE changes first
            # (so any periodic refresh agrees), then the update is pushed
            new_tier = f"{a} 0/4\n{b} 1/4\n{a} 2/4\n{b} 3/4\n"
            ns_file.write_text(new_tier)
            nodes = [parse_server_item(line)
                     for line in new_tier.splitlines()]
            dpc._listener().reset_servers(nodes)
            assert dpc.scheme_capacities() == {4: 4}
            for _ in range(10):
                resp = dpc.call_method(ECHO_MD,
                                       echo_pb2.EchoRequest(message="x"))
                assert len(resp.message) == 4  # always the 4-way fanout
        finally:
            stop_servers(servers)


class TestClusterRecover:
    def test_policy_sheds_proportionally(self):
        from brpc_tpu.policy.cluster_recover import (
            DefaultClusterRecoverPolicy)

        pol = DefaultClusterRecoverPolicy(min_working_instances=4,
                                          hold_seconds=60)
        assert not pol.do_reject(0)  # not recovering yet -> no shedding
        pol.start_recover()
        verdicts = [pol.do_reject(1) for _ in range(400)]
        frac = sum(verdicts) / len(verdicts)
        assert 0.55 < frac < 0.95, frac   # expect ~75% shed at 1/4 capacity
        assert pol.recovering
        # full capacity back -> recovery over, nothing shed
        assert not pol.do_reject(4)
        assert not pol.recovering
        assert not pol.do_reject(1)

    def test_policy_stops_after_hold(self):
        from brpc_tpu.policy.cluster_recover import (
            DefaultClusterRecoverPolicy)

        pol = DefaultClusterRecoverPolicy(min_working_instances=8,
                                          hold_seconds=0.1)
        pol.start_recover()
        pol.do_reject(2)
        time.sleep(0.15)
        pol.do_reject(2)          # usable stable for hold_seconds -> stop
        assert not pol.recovering

    def test_channel_integration(self):
        from brpc_tpu.policy.load_balancers import (ServerNode,
                                                    create_load_balancer)

        impl = NamedEcho("up")
        (server,) = start_servers(impl)
        try:
            lb = create_load_balancer(
                "rr:min_working_instances=2 hold_seconds=120")
            assert lb.recover_policy is not None
            ch = Channel(ChannelOptions(timeout_ms=2000, max_retry=0))
            ch.init_with_lb(lb)
            stub = Stub(ch, ECHO_DESC)
            # empty cluster: EHOSTDOWN and recovery armed
            with pytest.raises(RpcError):
                stub.Echo(echo_pb2.EchoRequest(message="x"))
            assert lb.recover_policy.recovering
            # half capacity back: some calls shed with EREJECT, some pass
            lb.reset_servers([ServerNode(server.listen_endpoint())])
            outcomes = set()
            for _ in range(200):
                try:
                    stub.Echo(echo_pb2.EchoRequest(message="x"))
                    outcomes.add("ok")
                except RpcError as e:
                    assert e.error_code == errors.EREJECT, e
                    outcomes.add("shed")
                if outcomes == {"ok", "shed"}:
                    break
            assert outcomes == {"ok", "shed"}
            # full capacity: recovery ends, everything flows
            lb.reset_servers([ServerNode(server.listen_endpoint()),
                              ServerNode(server.listen_endpoint(),
                                         tag="dup")])
            time.sleep(0.05)  # let the ~10ms usable_count cache expire
            for _ in range(5):
                stub.Echo(echo_pb2.EchoRequest(message="x"))
            assert not lb.recover_policy.recovering
        finally:
            stop_servers([server])


class TestCollectiveScheme:
    """VERDICT r3 #4: the ParallelChannel->collective mapping is a CODE
    path. Same ParallelChannel, same CollectiveScheme, two executions:
    (a) all-device sub-channels -> ONE shard_map program over the mesh,
    (b) forced RPC fallback -> one CollectiveService.Apply per sub-channel
    through the device-method lane + host merge. Results must agree."""

    def _make(self, n, merge):
        import numpy as np

        from brpc_tpu.rpc import Channel
        from brpc_tpu.rpc.combo_channels import (CollectiveScheme,
                                                 ParallelChannel)

        pc = ParallelChannel()
        for i in range(n):
            pc.add_channel(Channel().init(f"tpu://localhost/{i}"))
        scheme = CollectiveScheme(
            "test.affine", fn=lambda s: s * 2.0 + 1.0, merge=merge)
        return pc, scheme

    @pytest.mark.parametrize("merge", ["gather", "sum"])
    def test_collective_equals_rpc_fallback(self, merge):
        import numpy as np

        rng = np.random.default_rng(11)
        x = rng.normal(size=(16, 8)).astype(np.float32)
        pc, scheme = self._make(8, merge)
        mesh = pc.device_mesh(scheme.axis_name)
        assert mesh is not None and mesh.shape[scheme.axis_name] == 8
        out_coll = np.asarray(pc.call_tensor(x, scheme))
        out_rpc = np.asarray(pc._call_tensor_rpc(x, scheme))
        assert out_coll.shape == out_rpc.shape
        np.testing.assert_allclose(out_coll, out_rpc, rtol=1e-6, atol=1e-6)
        # and both match the direct computation
        if merge == "gather":
            np.testing.assert_allclose(out_coll, x * 2.0 + 1.0, rtol=1e-6)
        else:
            expect = sum(np.split(x * 2.0 + 1.0, 8, axis=0))
            np.testing.assert_allclose(out_coll, expect, rtol=1e-6)

    def test_mixed_subchannels_fall_back(self):
        # one TCP sub-channel spoils device detection (mesh is None, so
        # call_tensor would take the per-sub-channel RPC path)
        from brpc_tpu.rpc import Channel
        from brpc_tpu.rpc.combo_channels import (CollectiveScheme,
                                                 ParallelChannel)

        pc = ParallelChannel()
        pc.add_channel(Channel().init("tpu://localhost/0"))
        pc.add_channel(Channel().init("127.0.0.1:9"))
        scheme = CollectiveScheme("test.affine2", fn=lambda s: s - 3.0)
        assert pc.device_mesh(scheme.axis_name) is None

    def test_duplicate_ordinals_rejected(self):
        from brpc_tpu.rpc import Channel
        from brpc_tpu.rpc.combo_channels import (CollectiveScheme,
                                                 ParallelChannel)

        pc = ParallelChannel()
        pc.add_channel(Channel().init("tpu://localhost/0"))
        pc.add_channel(Channel().init("tpu://localhost/0"))
        scheme = CollectiveScheme("test.affine3", fn=lambda s: s)
        assert pc.device_mesh(scheme.axis_name) is None
