"""Unit tests for butil (pattern: reference test/iobuf_unittest.cpp,
test/endpoint_unittest.cpp, test/resource_pool_unittest.cpp)."""

import threading

import random

import pytest

from brpc_tpu.butil import (
    IOBuf,
    IOBufAppender,
    EndPoint,
    EndPointError,
    VersionedPool,
    DoublyBufferedData,
    crc32c,
    id_version,
)


class TestIOBuf:
    def test_append_and_size(self):
        buf = IOBuf()
        assert buf.empty()
        buf.append(b"hello")
        buf.append(b" world")
        assert len(buf) == 11
        assert buf.tobytes() == b"hello world"

    def test_cutn_zero_copy_split(self):
        buf = IOBuf(b"abcdefgh")
        head = buf.cutn(3)
        assert head.tobytes() == b"abc"
        assert buf.tobytes() == b"defgh"
        assert len(buf) == 5

    def test_cutn_across_blocks(self):
        buf = IOBuf()
        buf.append(b"aa")
        buf.append(b"bb")
        buf.append(b"cc")
        head = buf.cutn(3)
        assert head.tobytes() == b"aabb"[:3] + b""
        assert head.tobytes() == b"aab"
        assert buf.tobytes() == b"bcc"

    def test_cutn_more_than_size(self):
        buf = IOBuf(b"xy")
        head = buf.cutn(10)
        assert head.tobytes() == b"xy"
        assert buf.empty()

    def test_fetch_does_not_consume(self):
        buf = IOBuf()
        buf.append(b"ab")
        buf.append(b"cd")
        assert buf.fetch(3) == b"abc"
        assert len(buf) == 4

    def test_pop_front(self):
        buf = IOBuf(b"0123456789")
        buf.pop_front(4)
        assert buf.tobytes() == b"456789"

    def test_self_append_duplicates(self):
        a = IOBuf(b"ab")
        a.append(a)
        assert a.tobytes() == b"abab"

    def test_append_steals_iobuf(self):
        a = IOBuf(b"aa")
        b = IOBuf(b"bb")
        a.append(b)
        assert a.tobytes() == b"aabb"
        assert b.empty()

    def test_append_memoryview_no_copy(self):
        backing = bytearray(b"zzzz")
        buf = IOBuf()
        buf.append_user_data(memoryview(bytes(backing)))
        assert buf.tobytes() == b"zzzz"

    def test_cut_into_writer_partial(self):
        buf = IOBuf()
        buf.append(b"a" * 100)
        buf.append(b"b" * 100)
        sink = []

        def write_fn(mv):
            take = min(len(mv), 30)
            sink.append(bytes(mv[:take]))
            return take

        n = buf.cut_into_writer(write_fn)
        # first block: 30-byte short write stops the loop
        assert n == 30
        assert len(buf) == 170

    def test_appender_batches(self):
        app = IOBufAppender()
        for i in range(1000):
            app.append(b"x")
        buf = app.buf()
        assert len(buf) == 1000
        assert buf.block_count() < 10

    def test_readinto(self):
        buf = IOBuf()
        buf.append(b"abc")
        buf.append(b"def")
        out = bytearray(6)
        assert buf.readinto(out) == 6
        assert bytes(out) == b"abcdef"


class TestIOBufBlockOwnership:
    """append_user_data: a borrowed view's release callback fires exactly
    once, when the LAST reference over the block dies — the mechanism the
    tpu tunnel's zero-copy receive path hangs flow-control credits on."""

    @staticmethod
    def _borrowed(data=b"x" * 64):
        from brpc_tpu.butil.iobuf import supports_block_ownership

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        backing = bytearray(data)
        fired = []
        buf = IOBuf()
        assert buf.append_user_data(memoryview(backing),
                                    release=lambda: fired.append(1)) is True
        return buf, fired, backing

    def test_release_fires_on_clear(self):
        buf, fired, _ = self._borrowed()
        assert fired == []
        buf.clear()
        assert len(fired) == 1

    def test_release_fires_on_pop_front(self):
        buf, fired, _ = self._borrowed()
        buf.pop_front(10)
        assert fired == []          # tail of the block is still referenced
        buf.pop_front(len(buf))
        assert len(fired) == 1

    def test_fetch_does_not_release(self):
        buf, fired, backing = self._borrowed()
        assert buf.fetch(64) == bytes(backing)
        assert fired == []
        assert buf.tobytes() == bytes(backing)
        assert fired == []
        buf.clear()
        assert len(fired) == 1

    def test_cutn_splits_keep_block_alive(self):
        buf, fired, backing = self._borrowed()
        head = buf.cutn(20)
        mid = buf.cutn(20)
        assert fired == []
        buf.clear()                 # tail gone
        head.clear()
        assert fired == []          # mid still holds a slice
        assert mid.tobytes() == bytes(backing)[20:40]
        mid.clear()
        assert len(fired) == 1      # exactly once, at the LAST drop

    def test_appended_bytes_are_readable_in_place(self):
        buf, fired, backing = self._borrowed(b"hello borrowed world!")
        other = IOBuf()
        other.append(b"<")
        buf.cutn_into(len(buf), other)
        other.append(b">")
        assert other.tobytes() == b"<hello borrowed world!>"
        assert fired == []
        other.clear()
        assert len(fired) == 1

    def test_empty_view_releases_immediately(self):
        from brpc_tpu.butil.iobuf import supports_block_ownership

        if not supports_block_ownership():
            pytest.skip("no block-ownership exporter in this environment")
        fired = []
        buf = IOBuf()
        assert buf.append_user_data(memoryview(b""),
                                    release=lambda: fired.append(1)) is True
        assert len(buf) == 0
        assert len(fired) == 1

    def test_no_release_plain_append(self):
        buf = IOBuf()
        assert buf.append_user_data(memoryview(b"plain")) is True
        assert buf.tobytes() == b"plain"

    def test_has_owned_blocks(self):
        buf, fired, _ = self._borrowed()
        assert buf.has_owned_blocks()
        plain = IOBuf(b"abc")
        assert not plain.has_owned_blocks()
        # ownership travels with the refs through a cut
        head = buf.cutn(32)
        assert head.has_owned_blocks()
        buf.clear()
        head.clear()
        assert not buf.has_owned_blocks()


class TestEndPoint:
    def test_parse_ip(self):
        ep = EndPoint.parse("127.0.0.1:8787")
        assert ep.kind == "ip" and ep.host == "127.0.0.1" and ep.port == 8787
        assert str(ep) == "127.0.0.1:8787"

    def test_parse_hostname(self):
        ep = EndPoint.parse("localhost:80")
        assert ep.host == "localhost" and ep.port == 80

    def test_parse_unix(self):
        ep = EndPoint.parse("unix:/tmp/sock")
        assert ep.kind == "unix" and ep.path == "/tmp/sock"

    def test_parse_tpu(self):
        ep = EndPoint.parse("tpu://hostA:9000/3")
        assert ep.kind == "tpu"
        assert ep.host == "hostA" and ep.port == 9000 and ep.device_ordinal == 3
        assert str(ep) == "tpu://hostA:9000/3"

    def test_parse_tpu_default_ordinal(self):
        ep = EndPoint.parse("tpu://hostA")
        assert ep.device_ordinal == 0

    def test_parse_errors(self):
        with pytest.raises(EndPointError):
            EndPoint.parse("no-port-here")
        with pytest.raises(EndPointError):
            EndPoint.parse("tpu://h/xx")
        with pytest.raises(EndPointError):
            EndPoint.parse("tpu://")  # empty host
        with pytest.raises(EndPointError):
            EndPoint.parse("tpu://h:bad/1")  # malformed port, not host junk
        with pytest.raises(EndPointError):
            EndPoint.parse("1.2.3.4:99999")  # port out of range

    def test_parse_mesh_axis(self):
        ep = EndPoint.parse("tpu://mesh/tensor")
        assert ep.kind == "tpu" and ep.mesh_axis == "tensor"
        assert str(ep) == "tpu://mesh/tensor"

    def test_bare_mesh_host_is_device_endpoint(self):
        # "tpu://mesh" (no slash) means host named "mesh", NOT axis "0"
        ep = EndPoint.parse("tpu://mesh")
        assert ep.mesh_axis == "" and ep.device_ordinal == 0

    def test_hashable(self):
        a = EndPoint.parse("1.2.3.4:5")
        b = EndPoint.parse("1.2.3.4:5")
        assert a == b and hash(a) == hash(b)


class TestVersionedPool:
    def test_insert_address_remove(self):
        pool = VersionedPool()
        vid = pool.insert("obj")
        assert pool.address(vid) == "obj"
        assert pool.remove(vid) == "obj"
        assert pool.address(vid) is None

    def test_stale_id_after_reuse(self):
        pool = VersionedPool()
        vid1 = pool.insert("a")
        pool.remove(vid1)
        vid2 = pool.insert("b")
        # slot reused, version bumped: old id must not resolve
        assert pool.address(vid1) is None
        assert pool.address(vid2) == "b"
        assert id_version(vid2) == id_version(vid1) + 2

    def test_live_objects(self):
        pool = VersionedPool()
        ids = [pool.insert(i) for i in range(5)]
        pool.remove(ids[2])
        assert sorted(pool.live_objects()) == [0, 1, 3, 4]
        assert len(pool) == 4

    def test_concurrent_insert_remove(self):
        pool = VersionedPool()
        errors = []

        def worker():
            try:
                for _ in range(500):
                    vid = pool.insert(object())
                    assert pool.address(vid) is not None
                    assert pool.remove(vid) is not None
            except Exception as e:  # pragma: no cover
                errors.append(e)

        threads = [threading.Thread(target=worker) for _ in range(8)]
        for t in threads:
            t.start()
        for t in threads:
            t.join()
        assert not errors
        assert len(pool) == 0


class TestDoublyBuffered:
    def test_read_sees_modify(self):
        data = DoublyBufferedData(list)
        data.modify(lambda lst: lst.append("s1"))
        with data.read() as lst:
            assert lst == ["s1"]

    def test_both_buffers_converge(self):
        data = DoublyBufferedData(list)
        data.modify(lambda lst: lst.append(1))
        data.modify(lambda lst: lst.append(2))
        with data.read() as lst:
            assert lst == [1, 2]
        assert data._bufs[0] == data._bufs[1]

    def test_concurrent_readers_and_modifier(self):
        data = DoublyBufferedData(list)
        stop = threading.Event()
        errors = []

        def reader():
            while not stop.is_set():
                with data.read() as lst:
                    copy = list(lst)
                    # list must always be a prefix-consistent snapshot
                    if copy != sorted(copy):
                        errors.append(copy)

        threads = [threading.Thread(target=reader) for _ in range(4)]
        for t in threads:
            t.start()
        for i in range(100):
            data.modify(lambda lst, i=i: lst.append(i))
        stop.set()
        for t in threads:
            t.join()
        assert not errors


class TestCrc32c:
    def test_known_vector(self):
        # standard CRC32-C test vector
        assert crc32c(b"123456789") == 0xE3069283

    def test_empty(self):
        assert crc32c(b"") == 0

    def test_chaining_differs_by_input(self):
        assert crc32c(b"abc") != crc32c(b"abd")


class TestIOBufModel:
    def test_random_ops_match_bytes_model(self):
        """Model-based check: a long random sequence of append/cutn/
        pop_front/fetch/tobytes must agree with a plain bytes model
        (the RTMP fuzz campaign corrupted IOBuf once via negative n —
        this guards the whole op surface)."""
        rng = random.Random(0xB0F)
        buf = IOBuf()
        model = b""
        for step in range(3000):
            op = rng.randrange(6)
            if op in (0, 1):  # append (bytes or another IOBuf)
                data = bytes(rng.randrange(256)
                             for _ in range(rng.randrange(0, 64)))
                if op == 0:
                    buf.append(data)
                else:
                    other = IOBuf(data)
                    buf.append(other)
                    assert len(other) == 0  # refs stolen
                model += data
            elif op == 2 and model:  # cutn
                n = rng.randrange(0, len(model) + 1)
                cut = buf.cutn(n)
                assert cut.tobytes() == model[:n], f"step {step}"
                model = model[n:]
            elif op == 3 and model:  # pop_front
                n = rng.randrange(0, len(model) + 1)
                buf.pop_front(n)
                model = model[n:]
            elif op == 4:  # fetch (peek, non-consuming)
                n = rng.randrange(0, len(model) + 2)
                assert buf.fetch(n) == model[:n], f"step {step}"
            else:  # full compare
                assert len(buf) == len(model), f"step {step}"
                assert buf.tobytes() == model, f"step {step}"
        assert buf.tobytes() == model

    def test_negative_ops_rejected(self):
        buf = IOBuf(b"abc")
        with pytest.raises(ValueError):
            buf.cutn(-1)
        with pytest.raises(ValueError):
            buf.pop_front(-2)
        assert buf.tobytes() == b"abc"  # invariants intact after rejection


class TestVersionedPoolModel:
    def test_random_insert_remove_never_resolves_stale(self):
        """A removed id must NEVER resolve again, even after its slot is
        recycled (the reference's versioned SocketId contract,
        versioned_ref_with_id.h:54)."""
        rng = random.Random(0x5EED)
        pool = VersionedPool()
        live = {}    # id -> object
        dead = []    # ids that must stay dead
        for step in range(4000):
            if live and rng.random() < 0.45:
                vid = rng.choice(list(live))
                pool.remove(vid)
                del live[vid]
                dead.append(vid)
            else:
                obj = object()
                live[pool.insert(obj)] = obj
            if dead and rng.random() < 0.3:
                assert pool.address(rng.choice(dead)) is None, f"step {step}"
            if live and rng.random() < 0.3:
                vid = rng.choice(list(live))
                assert pool.address(vid) is live[vid], f"step {step}"
        for vid in dead[-200:]:
            assert pool.address(vid) is None
