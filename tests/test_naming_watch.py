"""Watch-style naming + app-level health check (VERDICT r1 #9; reference
policy/consul_naming_service.cpp long-poll, remote_file_naming_service.cpp,
details/health_check.cpp:34-107 app-level probe).

The consul test runs a FAKE consul agent on the framework's own HTTP
server: /v1/health/service/<name> implements real blocking queries
(index+wait), so the watch path is exercised end to end — membership
changes reach the load balancer the moment they happen, under live RPC
load, with no polling interval in the loop.
"""

import json
import threading
import time

import pytest

from brpc_tpu import builtin
from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import (
    Channel,
    ChannelOptions,
    Server,
    ServerOptions,
    Service,
    Stub,
)

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class NamedEcho(Service):
    DESCRIPTOR = ECHO

    def __init__(self, name):
        super().__init__()
        self.name = name

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=self.name)


class FakeConsul:
    """Blocking-query consul agent surface on a builtin HTTP path."""

    def __init__(self):
        self._lock = threading.Lock()
        self._index = 1
        self._members = []           # list of (address, port, tag)
        self._changed = threading.Condition(self._lock)

    def set_members(self, members) -> None:
        with self._lock:
            self._members = list(members)
            self._index += 1
            self._changed.notify_all()

    def handler(self, server, http):
        want_index = int(http.query.get("index", "0") or 0)
        wait_s = 5.0
        deadline = time.monotonic() + wait_s
        with self._lock:
            while self._index == want_index:
                left = deadline - time.monotonic()
                if left <= 0:
                    break
                self._changed.wait(left)
            body = json.dumps([
                {"Service": {"Address": a, "Port": p,
                             "Tags": [t] if t else []}}
                for a, p, t in self._members
            ]).encode()
            idx = self._index
        return 200, "application/json", body, {"X-Consul-Index": str(idx)}


@pytest.fixture()
def consul():
    fake = FakeConsul()
    agent = Server(ServerOptions())
    agent.add_service(NamedEcho("agent"))
    agent.start("127.0.0.1:0")
    builtin.register_builtin("v1", lambda server, http: fake.handler(server, http))
    yield fake, agent.listen_endpoint()
    with builtin._lock:
        builtin._services.pop("v1", None)
    agent.stop()
    agent.join()


class TestConsulWatch:
    def test_membership_change_under_load(self, consul):
        fake, agent_ep = consul
        impls = [NamedEcho("s1"), NamedEcho("s2")]
        servers = [Server().add_service(i).start("127.0.0.1:0")
                   for i in impls]
        try:
            eps = [s.listen_endpoint() for s in servers]
            fake.set_members([(eps[0].host, eps[0].port, "")])
            ch = Channel(ChannelOptions(timeout_ms=3000))
            ch.init(f"consul://{agent_ep.host}:{agent_ep.port}/echo", "rr")
            stub = Stub(ch, ECHO)
            assert stub.Echo(echo_pb2.EchoRequest(message="x")).message == "s1"

            # live membership change under load: responses flip to the new
            # instance well within the long-poll push latency (no 5s
            # polling interval in the path)
            seen = set()
            stop = threading.Event()
            errs = []

            def load():
                while not stop.is_set():
                    try:
                        seen.add(stub.Echo(
                            echo_pb2.EchoRequest(message="x")).message)
                    except Exception as e:  # pragma: no cover
                        errs.append(e)
                        return
                    time.sleep(0.005)

            t = threading.Thread(target=load)
            t.start()
            try:
                fake.set_members([(eps[1].host, eps[1].port, "")])
                deadline = time.monotonic() + 3.0
                while "s2" not in seen and time.monotonic() < deadline:
                    time.sleep(0.02)
            finally:
                stop.set()
                t.join()
            assert not errs, errs
            assert "s2" in seen, seen
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=2)


class TestRemoteFile:
    def test_remotefile_list(self, consul, tmp_path):
        _, agent_ep = consul
        impl = NamedEcho("rf")
        server = Server().add_service(impl).start("127.0.0.1:0")
        try:
            lst = f"{server.listen_endpoint()}\n".encode()
            builtin.register_builtin(
                "cluster.lst", lambda srv, http: (200, "text/plain", lst))
            ch = Channel(ChannelOptions(timeout_ms=3000))
            ch.init(f"remotefile://{agent_ep.host}:{agent_ep.port}"
                    f"/cluster.lst", "rr")
            stub = Stub(ch, ECHO)
            assert stub.Echo(echo_pb2.EchoRequest(message="x")).message \
                == "rf"
        finally:
            with builtin._lock:
                builtin._services.pop("cluster.lst", None)
            server.stop()
            server.join(timeout=2)


class TestAppLevelHealthCheck:
    def test_unhealthy_app_stays_parked(self):
        """TCP alive but app erroring: the app-level probe keeps the node
        parked; flipping the app healthy un-parks it."""
        from brpc_tpu.policy.load_balancers import (ServerNode,
                                                    create_load_balancer)
        from brpc_tpu.rpc import errors as _errors
        from brpc_tpu.rpc.health_check import HealthChecker, http_probe

        healthy = threading.Event()
        builtin.register_builtin(
            "apphealth",
            lambda srv, http: ((200, "text/plain", b"ok") if healthy.is_set()
                               else (503, "text/plain", b"warming")))
        server = Server().add_service(NamedEcho("h")).start("127.0.0.1:0")
        try:
            ep = server.listen_endpoint()
            lb = create_load_balancer("rr")
            lb.reset_servers([ServerNode(ep)])
            # park the node via failure feedback
            for _ in range(4):
                lb.feedback(ep, _errors.EFAILEDSOCKET, 1000.0)
            st = lb._node_state(ep)
            assert st.is_down
            checker = HealthChecker(lb, interval_s=0.05,
                                    probe=http_probe("/apphealth",
                                                     timeout=1.0))
            try:
                time.sleep(0.4)
                assert st.is_down  # TCP is up, app says 503 -> stays parked
                healthy.set()
                deadline = time.monotonic() + 3.0
                while st.is_down and time.monotonic() < deadline:
                    time.sleep(0.05)
                assert not st.is_down
            finally:
                checker.stop()
        finally:
            with builtin._lock:
                builtin._services.pop("apphealth", None)
            server.stop()
            server.join(timeout=2)
