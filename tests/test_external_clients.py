"""External-client conformance: a REAL curl (libcurl + nghttp2 + OpenSSL)
drives the server — HTTP/1.1, JSON RPC, prior-knowledge HTTP/2, TLS, and
TLS with ALPN-negotiated h2. This is the strongest interop evidence
available offline: the peer implementations are not ours."""

import shutil
import subprocess

import pytest

from brpc_tpu.proto import echo_pb2
from brpc_tpu.rpc import Server, ServerOptions, Service
from brpc_tpu.rpc.ssl_helper import ServerSslOptions

def _curl_features() -> str:
    if shutil.which("curl") is None:
        return ""
    try:
        return subprocess.run(["curl", "-V"], capture_output=True,
                              text=True, timeout=10).stdout
    except (OSError, subprocess.SubprocessError):
        return ""


_CURL = _curl_features()
pytestmark = pytest.mark.skipif(not _CURL, reason="curl not installed")

needs_h2 = pytest.mark.skipif("HTTP2" not in _CURL,
                              reason="curl built without nghttp2")

ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]


class EchoImpl(Service):
    DESCRIPTOR = ECHO

    def Echo(self, cntl, request, done):
        return echo_pb2.EchoResponse(message=request.message)


@pytest.fixture(scope="module")
def certpair(tmp_path_factory):
    d = tmp_path_factory.mktemp("curlcerts")
    cert, key = str(d / "c.pem"), str(d / "k.pem")
    try:
        subprocess.run(
            ["openssl", "req", "-x509", "-newkey", "rsa:2048", "-nodes",
             "-keyout", key, "-out", cert, "-days", "2",
             "-subj", "/CN=127.0.0.1"],
            check=True, capture_output=True, timeout=60)
    except (OSError, subprocess.SubprocessError) as e:
        pytest.skip(f"openssl unavailable: {e}")
    return cert, key


@pytest.fixture(scope="module")
def server(certpair):
    cert, key = certpair
    srv = Server(ServerOptions(ssl=ServerSslOptions(certfile=cert,
                                                    keyfile=key)))
    srv.add_service(EchoImpl())
    srv.start("127.0.0.1:0")
    yield srv
    srv.stop()
    srv.join(timeout=2)


def curl(*args, timeout=15):
    r = subprocess.run(["curl", "-s", "-m", "10", *args],
                       capture_output=True, text=True, timeout=timeout)
    assert r.returncode == 0, f"curl {args}: rc={r.returncode} {r.stderr}"
    return r.stdout


class TestCurlConformance:
    def test_http1_dashboard(self, server):
        base = str(server.listen_endpoint())
        assert curl(f"http://{base}/health").strip() == "OK"
        assert "EchoService" in curl(f"http://{base}/protobufs")

    def test_http1_json_rpc(self, server):
        base = str(server.listen_endpoint())
        out = curl("-X", "POST", "-H", "Content-Type: application/json",
                   "-d", '{"message":"from-curl"}',
                   f"http://{base}/EchoService/Echo")
        assert '"message": "from-curl"' in out

    @needs_h2
    def test_http2_prior_knowledge(self, server):
        """nghttp2 (a real h2 implementation) speaks to our h2 server."""
        base = str(server.listen_endpoint())
        head = curl("-i", "--http2-prior-knowledge", f"http://{base}/health")
        assert head.startswith("HTTP/2 200")
        assert "OK" in head

    def test_tls_http1(self, server):
        base = str(server.listen_endpoint())
        assert curl("-k", f"https://{base}/health").strip() == "OK"

    @needs_h2
    def test_tls_alpn_h2(self, server):
        """OpenSSL client handshake + ALPN selects h2; nghttp2 carries the
        request — the full TLS + h2 stack against foreign peers."""
        base = str(server.listen_endpoint())
        head = curl("-ik", "--http2", f"https://{base}/health")
        assert head.startswith("HTTP/2 200"), head.splitlines()[0]

    def test_keepalive_multiple_requests_one_connection(self, server):
        base = str(server.listen_endpoint())
        r = subprocess.run(
            ["curl", "-sv", "-m", "10", f"http://{base}/health",
             f"http://{base}/version"],
            capture_output=True, text=True, timeout=15)
        assert r.returncode == 0, r.stderr
        assert "OK" in r.stdout
        # curl -v announces connection reuse; without keep-alive it would
        # dial twice and this line would be absent
        assert "Re-using existing connection" in r.stderr, r.stderr[-400:]


needs_rtmp = pytest.mark.skipif("rtmp" not in _CURL.lower(),
                                reason="curl built without librtmp")


class TestLibrtmpConformance:
    @needs_rtmp
    def test_librtmp_plays_live_stream(self, tmp_path):
        """A REAL RTMP client (librtmp inside curl) handshakes, connects,
        plays, and pulls live frames from our server — none of the peer's
        protocol machinery is ours."""
        import threading
        import time

        from brpc_tpu.policy.rtmp import MSG_VIDEO, RtmpClient, RtmpService

        server = Server(ServerOptions(rtmp_service=RtmpService()))
        server.start("127.0.0.1:0")
        ep = server.listen_endpoint()
        pub = RtmpClient(ep.host, ep.port, app="live")
        stop = threading.Event()
        try:
            sid = pub.create_stream()
            pub.publish("cam", sid)
            pub.send_metadata(sid, "@setDataFrame",
                              {"width": 320.0, "height": 240.0})

            def pump():
                i = 0
                while not stop.is_set():
                    pub.send_frame(MSG_VIDEO, sid,
                                   b"\x17\x00" + bytes([i % 256]) * 500,
                                   timestamp=i * 33)
                    i += 1
                    time.sleep(0.02)

            threading.Thread(target=pump, daemon=True).start()
            out = tmp_path / "out.flv"
            r = subprocess.run(
                ["curl", "-s", "-m", "4", "-o", str(out),
                 f"rtmp://{ep.host}:{ep.port}/live/cam"],
                capture_output=True, text=True, timeout=20)
            # 28 = curl's own timeout: a LIVE stream never ends — success
            # here means the handshake/connect/play all worked and frames
            # flowed until the clock ran out
            assert r.returncode in (0, 28), r.stderr[-300:]
            assert out.exists() and out.stat().st_size > 10_000, \
                f"librtmp pulled only {out.stat().st_size if out.exists() else 0} bytes"
        finally:
            stop.set()
            pub.close()
            server.stop()
            server.join(timeout=2)
