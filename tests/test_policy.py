"""Load balancer + naming service tests (reference pattern:
test/brpc_load_balancer_unittest.cpp; cluster = channels to loopback
servers + file/list naming, brpc_channel_unittest.cpp:211)."""

import collections
import os
import time

import pytest

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.policy.load_balancers import (
    ConsistentHashingLB,
    LocalityAwareLB,
    RandomLB,
    RoundRobinLB,
    ServerNode,
    WeightedRoundRobinLB,
    create_load_balancer,
)
from brpc_tpu.policy.naming import (
    ListNamingService,
    FileNamingService,
    parse_server_item,
    start_naming_service,
)
from brpc_tpu.rpc import errors


def nodes(*specs):
    return [ServerNode(EndPoint.parse(s)) for s in specs]


class TestLoadBalancers:
    def test_rr_cycles(self):
        lb = RoundRobinLB()
        lb.reset_servers(nodes("127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"))
        picks = [str(lb.select_server()) for _ in range(6)]
        assert picks[:3] == picks[3:6]
        assert len(set(picks[:3])) == 3

    def test_random_member(self):
        lb = RandomLB()
        lb.reset_servers(nodes("127.0.0.1:1", "127.0.0.1:2"))
        for _ in range(20):
            assert str(lb.select_server()) in {"127.0.0.1:1", "127.0.0.1:2"}

    def test_empty_returns_none(self):
        assert RoundRobinLB().select_server() is None

    def test_wrr_respects_weights(self):
        lb = WeightedRoundRobinLB()
        lb.reset_servers([
            ServerNode(EndPoint.parse("127.0.0.1:1"), weight=3),
            ServerNode(EndPoint.parse("127.0.0.1:2"), weight=1),
        ])
        counts = collections.Counter(
            str(lb.select_server()) for _ in range(40))
        assert counts["127.0.0.1:1"] == 30
        assert counts["127.0.0.1:2"] == 10

    def test_la_prefers_fast(self):
        lb = LocalityAwareLB()
        lb.reset_servers(nodes("127.0.0.1:1", "127.0.0.1:2"))
        fast, slow = EndPoint.parse("127.0.0.1:1"), EndPoint.parse("127.0.0.1:2")
        for _ in range(50):
            lb.feedback(fast, errors.OK, 100)
            lb.feedback(slow, errors.OK, 10_000)
        counts = collections.Counter(str(lb.select_server())
                                     for _ in range(500))
        assert counts["127.0.0.1:1"] > counts["127.0.0.1:2"] * 5

    def test_failure_parks_node(self):
        lb = RoundRobinLB()
        lb.reset_servers(nodes("127.0.0.1:1", "127.0.0.1:2"))
        bad = EndPoint.parse("127.0.0.1:2")
        for _ in range(3):
            lb.feedback(bad, errors.EFAILEDSOCKET, 0)
        picks = {str(lb.select_server()) for _ in range(10)}
        assert picks == {"127.0.0.1:1"}

    def test_c_hash_sticky_and_minimal_move(self):
        lb = ConsistentHashingLB()
        lb.reset_servers(nodes(*[f"127.0.0.1:{p}" for p in range(1, 6)]))

        class C:
            def __init__(self, code):
                self.log_id = code

        before = {code: str(lb.select_server(C(code))) for code in range(200)}
        # same key -> same server, always
        for code in range(200):
            assert str(lb.select_server(C(code))) == before[code]
        # removing one server moves only its keys
        lb.reset_servers(nodes(*[f"127.0.0.1:{p}" for p in range(1, 5)]))
        moved = sum(
            1 for code in range(200)
            if str(lb.select_server(C(code))) != before[code])
        assert moved < 100  # ~1/5 expected, never a full reshuffle

    def test_registry(self):
        assert create_load_balancer("rr").name == "rr"
        with pytest.raises(ValueError):
            create_load_balancer("nope")


class TestNaming:
    def test_parse_item(self):
        n = parse_server_item("10.0.0.1:80 w=5 zoneA")
        assert n.weight == 5 and n.tag == "zoneA"
        assert str(n.endpoint) == "10.0.0.1:80"

    def test_list_ns(self):
        ns = ListNamingService("127.0.0.1:1,127.0.0.1:2 w=2")
        servers = ns.get_servers()
        assert len(servers) == 2 and servers[1].weight == 2

    def test_file_ns(self, tmp_path):
        f = tmp_path / "servers"
        f.write_text("127.0.0.1:1\n# comment\n127.0.0.1:2 w=3\n\n")
        ns = FileNamingService(str(f))
        servers = ns.get_servers()
        assert len(servers) == 2 and servers[1].weight == 3

    def test_tpu_ns(self):
        from brpc_tpu.policy.naming import TpuNamingService

        servers = TpuNamingService("localhost").get_servers()
        assert len(servers) == 8  # the virtual pod
        assert all(s.endpoint.is_tpu() for s in servers)

    def test_watcher_pushes_updates(self, tmp_path):
        f = tmp_path / "servers"
        f.write_text("127.0.0.1:1\n")
        lb = RoundRobinLB()
        thread = start_naming_service(f"file://{f}", lb, interval_s=0.1)
        try:
            assert lb.server_count() == 1
            f.write_text("127.0.0.1:1\n127.0.0.1:2\n")
            deadline = time.time() + 5
            while lb.server_count() != 2 and time.time() < deadline:
                time.sleep(0.05)
            assert lb.server_count() == 2
        finally:
            thread.stop()

    def test_unknown_scheme(self):
        with pytest.raises(ValueError):
            start_naming_service("zk://x", RoundRobinLB())


class TestChannelWithLB:
    def test_rr_over_two_loopback_servers(self):
        """The reference's multi-node simulation: N real servers on loopback
        behind a list:// naming service (brpc_channel_unittest.cpp:211)."""
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, Server, Service, Stub

        class Impl(Service):
            DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

            def __init__(self, name):
                super().__init__()
                self.name = name
                self.hits = 0

            def Echo(self, cntl, request, done):
                self.hits += 1
                return echo_pb2.EchoResponse(message=self.name)

        impls = [Impl("s1"), Impl("s2")]
        servers = [Server().add_service(i).start("127.0.0.1:0")
                   for i in impls]
        try:
            url = "list://" + ",".join(
                str(s.listen_endpoint()) for s in servers)
            ch = Channel().init(url, "rr")
            stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
            got = {stub.Echo(echo_pb2.EchoRequest(message="x")).message
                   for _ in range(10)}
            assert got == {"s1", "s2"}
            assert impls[0].hits == 5 and impls[1].hits == 5
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=2)

    def test_failover_to_healthy_server(self):
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, ChannelOptions, Server, Service, Stub

        class Impl(Service):
            DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

            def Echo(self, cntl, request, done):
                return echo_pb2.EchoResponse(message="alive")

        server = Server().add_service(Impl()).start("127.0.0.1:0")
        try:
            # one dead endpoint + one live one
            url = f"list://127.0.0.1:1,{server.listen_endpoint()}"
            ch = Channel(ChannelOptions(max_retry=3,
                                        connect_timeout_ms=200)).init(url, "rr")
            stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
            for _ in range(4):
                assert stub.Echo(
                    echo_pb2.EchoRequest(message="x")).message == "alive"
        finally:
            server.stop()
            server.join(timeout=2)
