"""Load balancer + naming service tests (reference pattern:
test/brpc_load_balancer_unittest.cpp; cluster = channels to loopback
servers + file/list naming, brpc_channel_unittest.cpp:211)."""

import collections
import os
import time

import pytest

from brpc_tpu.butil.endpoint import EndPoint
from brpc_tpu.policy.load_balancers import (
    ConsistentHashingLB,
    LocalityAwareLB,
    RandomLB,
    RoundRobinLB,
    ServerNode,
    WeightedRoundRobinLB,
    create_load_balancer,
)
from brpc_tpu.policy.naming import (
    ListNamingService,
    FileNamingService,
    parse_server_item,
    start_naming_service,
)
from brpc_tpu.rpc import errors


def nodes(*specs):
    return [ServerNode(EndPoint.parse(s)) for s in specs]


class TestLoadBalancers:
    def test_rr_cycles(self):
        lb = RoundRobinLB()
        lb.reset_servers(nodes("127.0.0.1:1", "127.0.0.1:2", "127.0.0.1:3"))
        picks = [str(lb.select_server()) for _ in range(6)]
        assert picks[:3] == picks[3:6]
        assert len(set(picks[:3])) == 3

    def test_random_member(self):
        lb = RandomLB()
        lb.reset_servers(nodes("127.0.0.1:1", "127.0.0.1:2"))
        for _ in range(20):
            assert str(lb.select_server()) in {"127.0.0.1:1", "127.0.0.1:2"}

    def test_empty_returns_none(self):
        assert RoundRobinLB().select_server() is None

    def test_wrr_respects_weights(self):
        lb = WeightedRoundRobinLB()
        lb.reset_servers([
            ServerNode(EndPoint.parse("127.0.0.1:1"), weight=3),
            ServerNode(EndPoint.parse("127.0.0.1:2"), weight=1),
        ])
        counts = collections.Counter(
            str(lb.select_server()) for _ in range(40))
        assert counts["127.0.0.1:1"] == 30
        assert counts["127.0.0.1:2"] == 10

    def test_la_prefers_fast(self):
        lb = LocalityAwareLB()
        lb.reset_servers(nodes("127.0.0.1:1", "127.0.0.1:2"))
        fast, slow = EndPoint.parse("127.0.0.1:1"), EndPoint.parse("127.0.0.1:2")
        for _ in range(50):
            lb.feedback(fast, errors.OK, 100)
            lb.feedback(slow, errors.OK, 10_000)
        counts = collections.Counter(str(lb.select_server())
                                     for _ in range(500))
        assert counts["127.0.0.1:1"] > counts["127.0.0.1:2"] * 5

    def test_failure_parks_node(self):
        lb = RoundRobinLB()
        lb.reset_servers(nodes("127.0.0.1:1", "127.0.0.1:2"))
        bad = EndPoint.parse("127.0.0.1:2")
        for _ in range(3):
            lb.feedback(bad, errors.EFAILEDSOCKET, 0)
        picks = {str(lb.select_server()) for _ in range(10)}
        assert picks == {"127.0.0.1:1"}

    def test_c_hash_sticky_and_minimal_move(self):
        lb = ConsistentHashingLB()
        lb.reset_servers(nodes(*[f"127.0.0.1:{p}" for p in range(1, 6)]))

        class C:
            def __init__(self, code):
                self.log_id = code

        before = {code: str(lb.select_server(C(code))) for code in range(200)}
        # same key -> same server, always
        for code in range(200):
            assert str(lb.select_server(C(code))) == before[code]
        # removing one server moves only its keys
        lb.reset_servers(nodes(*[f"127.0.0.1:{p}" for p in range(1, 5)]))
        moved = sum(
            1 for code in range(200)
            if str(lb.select_server(C(code))) != before[code])
        assert moved < 100  # ~1/5 expected, never a full reshuffle

    def test_registry(self):
        assert create_load_balancer("rr").name == "rr"
        with pytest.raises(ValueError):
            create_load_balancer("nope")


class TestNaming:
    def test_parse_item(self):
        n = parse_server_item("10.0.0.1:80 w=5 zoneA")
        assert n.weight == 5 and n.tag == "zoneA"
        assert str(n.endpoint) == "10.0.0.1:80"

    def test_list_ns(self):
        ns = ListNamingService("127.0.0.1:1,127.0.0.1:2 w=2")
        servers = ns.get_servers()
        assert len(servers) == 2 and servers[1].weight == 2

    def test_file_ns(self, tmp_path):
        f = tmp_path / "servers"
        f.write_text("127.0.0.1:1\n# comment\n127.0.0.1:2 w=3\n\n")
        ns = FileNamingService(str(f))
        servers = ns.get_servers()
        assert len(servers) == 2 and servers[1].weight == 3

    def test_tpu_ns(self):
        from brpc_tpu.policy.naming import TpuNamingService

        servers = TpuNamingService("localhost").get_servers()
        assert len(servers) == 8  # the virtual pod
        assert all(s.endpoint.is_tpu() for s in servers)

    def test_watcher_pushes_updates(self, tmp_path):
        f = tmp_path / "servers"
        f.write_text("127.0.0.1:1\n")
        lb = RoundRobinLB()
        thread = start_naming_service(f"file://{f}", lb, interval_s=0.1)
        try:
            assert lb.server_count() == 1
            f.write_text("127.0.0.1:1\n127.0.0.1:2\n")
            deadline = time.time() + 5
            while lb.server_count() != 2 and time.time() < deadline:
                time.sleep(0.05)
            assert lb.server_count() == 2
        finally:
            thread.stop()

    def test_unknown_scheme(self):
        with pytest.raises(ValueError):
            start_naming_service("zk://x", RoundRobinLB())


class TestChannelWithLB:
    def test_rr_over_two_loopback_servers(self):
        """The reference's multi-node simulation: N real servers on loopback
        behind a list:// naming service (brpc_channel_unittest.cpp:211)."""
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, Server, Service, Stub

        class Impl(Service):
            DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

            def __init__(self, name):
                super().__init__()
                self.name = name
                self.hits = 0

            def Echo(self, cntl, request, done):
                self.hits += 1
                return echo_pb2.EchoResponse(message=self.name)

        impls = [Impl("s1"), Impl("s2")]
        servers = [Server().add_service(i).start("127.0.0.1:0")
                   for i in impls]
        try:
            url = "list://" + ",".join(
                str(s.listen_endpoint()) for s in servers)
            ch = Channel().init(url, "rr")
            stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
            got = {stub.Echo(echo_pb2.EchoRequest(message="x")).message
                   for _ in range(10)}
            assert got == {"s1", "s2"}
            assert impls[0].hits == 5 and impls[1].hits == 5
        finally:
            for s in servers:
                s.stop()
                s.join(timeout=2)

    def test_failover_to_healthy_server(self):
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, ChannelOptions, Server, Service, Stub

        class Impl(Service):
            DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

            def Echo(self, cntl, request, done):
                return echo_pb2.EchoResponse(message="alive")

        server = Server().add_service(Impl()).start("127.0.0.1:0")
        try:
            # one dead endpoint + one live one
            url = f"list://127.0.0.1:1,{server.listen_endpoint()}"
            ch = Channel(ChannelOptions(max_retry=3,
                                        connect_timeout_ms=200)).init(url, "rr")
            stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
            for _ in range(4):
                assert stub.Echo(
                    echo_pb2.EchoRequest(message="x")).message == "alive"
        finally:
            server.stop()
            server.join(timeout=2)


class TestLaFidelity:
    """VERDICT r2 #7: la must demonstrably SHIFT traffic away from a
    degraded replica, and punish in-flight load before feedback lands."""

    def test_la_shifts_from_slow_server(self):
        import time

        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import (Channel, ChannelOptions, Server, Service,
                                  Stub)

        counts = {"fast": 0, "slow": 0}

        def impl(tag, sleep_s):
            class Impl(Service):
                DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name[
                    "EchoService"]

                def Echo(self, cntl, request, done):
                    counts[tag] += 1
                    if sleep_s:
                        time.sleep(sleep_s)
                    return echo_pb2.EchoResponse(message=tag)

            return Impl()

        fast = Server().add_service(impl("fast", 0.0)).start("127.0.0.1:0")
        slow = Server().add_service(impl("slow", 0.02)).start("127.0.0.1:0")
        try:
            url = (f"list://{fast.listen_endpoint()},"
                   f"{slow.listen_endpoint()}")
            ch = Channel(ChannelOptions(timeout_ms=5000)).init(url, "la")
            stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name[
                "EchoService"])
            for _ in range(150):
                stub.Echo(echo_pb2.EchoRequest(message="x"))
            total = counts["fast"] + counts["slow"]
            assert total == 150
            # 20ms vs ~0.3ms EWMA: the slow replica's share must collapse
            assert counts["slow"] < total * 0.35, counts
        finally:
            fast.stop()
            fast.join(timeout=2)
            slow.stop()
            slow.join(timeout=2)

    def test_la_punishes_inflight_before_feedback(self):
        from brpc_tpu.policy.load_balancers import (LocalityAwareLB,
                                                    ServerNode)

        lb = LocalityAwareLB()
        a = EndPoint.from_ip_port("10.0.0.1", 1)
        b = EndPoint.from_ip_port("10.0.0.2", 2)
        lb.reset_servers([ServerNode(a), ServerNode(b)])
        # equal latency history; node A holds 15 unanswered calls
        lb._node_state(a).inflight = 15
        picks = {a: 0, b: 0}
        for _ in range(400):
            ep = lb.select_server()
            picks[ep] += 1
            lb._node_state(ep).inflight -= 1  # undo select's charge
        # ~16:1 punishment: A should receive well under a quarter
        assert picks[a] < 100, picks
        # feedback repays the charge and the split recovers
        st = lb._node_state(a)
        st.inflight = 0
        picks = {a: 0, b: 0}
        for _ in range(400):
            ep = lb.select_server()
            picks[ep] += 1
            lb._node_state(ep).inflight -= 1
        assert 120 < picks[a] < 280, picks


class TestAutoLimiterFidelity:
    """VERDICT r2 #7: the gradient limiter must CONVERGE DOWN against an
    overload curve (latency inflating above the observed floor)."""

    def test_limit_shrinks_under_latency_inflation(self):
        from brpc_tpu.policy.limiters import AutoLimiter

        lim = AutoLimiter(initial=256, min_limit=4, sample_window=16)
        # healthy phase establishes the latency floor
        for _ in range(4 * 16):
            assert lim.on_request()
            lim.on_response(1_000.0, 0)
        healthy_limit = lim.limit
        # overload: latency 12x the floor, windows keep landing
        for _ in range(12 * 16):
            if lim.on_request():
                lim.on_response(12_000.0, 0)
        assert lim.limit < healthy_limit * 0.5, (healthy_limit, lim.limit)
        assert lim.limit >= lim.min_limit
        # recovery: latency returns to the floor, the limit grows back
        shrunk = lim.limit
        for _ in range(12 * 16):
            if lim.on_request():
                lim.on_response(1_100.0, 0)
        assert lim.limit > shrunk, (shrunk, lim.limit)

    def test_limiter_sheds_real_overload(self):
        import threading
        import time

        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import (Channel, ChannelOptions, Server, Service,
                                  Stub)
        from brpc_tpu.rpc.channel import RpcError

        conc = {"n": 0, "max": 0}
        lock = threading.Lock()

        class Impl(Service):
            DESCRIPTOR = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

            def Echo(self, cntl, request, done):
                with lock:
                    conc["n"] += 1
                    conc["max"] = max(conc["max"], conc["n"])
                    n = conc["n"]
                time.sleep(0.002 * n)  # latency grows with concurrency
                with lock:
                    conc["n"] -= 1
                return echo_pb2.EchoResponse(message="ok")

        svc = Impl()
        server = Server().add_service(svc).start("127.0.0.1:0")
        svc.find_method("Echo").set_limiter("auto")
        entry = svc.find_method("Echo")
        entry.limiter._limit = 64.0  # start far above healthy
        entry.limiter._sample_window = 16
        try:
            ch = Channel(ChannelOptions(timeout_ms=10000, max_retry=0)).init(
                str(server.listen_endpoint()))
            stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name[
                "EchoService"])
            # healthy phase (sequential): the limiter learns its latency
            # floor before the storm inflates it
            for _ in range(40):
                stub.Echo(echo_pb2.EchoRequest(message="warm"))
            rejected = [0]

            def worker():
                for _ in range(25):
                    try:
                        stub.Echo(echo_pb2.EchoRequest(message="x"))
                    except RpcError as e:
                        if e.error_code == errors.ELIMIT:
                            rejected[0] += 1

            ts = [threading.Thread(target=worker) for _ in range(12)]
            for t in ts:
                t.start()
            for t in ts:
                t.join()
            # the limit must have converged well below the inflated start
            assert entry.limiter.limit < 48, entry.limiter.limit
        finally:
            server.stop()
            server.join(timeout=2)
