"""North-star benchmark: echo bandwidth through the TpuSocket datapath.

The reference's headline (BASELINE.md): multi-connection echo plateaus at
~2.3 GB/s through the kernel's loopback; rdma_performance measures the same
echo over the HCA. Our transport's steady state keeps payloads device-
resident (the design goal — no NIC, no kernel socket, no host bounce), so
the headline measures the on-device echo: payload DMA'd client-buffer ->
server-buffer -> back, as pallas copy kernels the compiler cannot elide
(brpc_tpu/tpu/bench_kernels.py). Payload 16 MB (past VMEM, genuinely HBM).

Also drives the FULL host RPC stack (Channel -> call-id -> TpuSocket ->
device -> response) and reports it to stderr — on this environment the
host<->device hop crosses a network tunnel with ~150 ms fixed D2H cost, so
it is diagnostics, not the headline.

Prints ONE JSON line: {"metric", "value", "unit", "vs_baseline"}.
vs_baseline = multiple of the reference's 2.3 GB/s plateau.
"""

from __future__ import annotations

import json
import sys
import time

PAYLOAD_BYTES = 64 << 20  # 64 MB device-resident echo payload (past VMEM)
ROUNDS_LO, ROUNDS_HI = 16, 1024
REPS = 3
BASELINE_GBPS = 2.3       # reference docs/cn/benchmark.md:104 plateau
HOST_PAYLOAD = 1 << 20    # full-stack (tunnel) echo payload
HOST_ITERS = 5


def bench_device_echo() -> float:
    """Marginal-cost measurement: time the echo loop at two round counts
    and take the slope. On this environment every host<->device sync
    crosses a network tunnel with a large fixed cost; the slope isolates
    the actual per-round device time. Sync is a dependent scalar fetch —
    block_until_ready is not reliable through the relay."""
    import jax
    import jax.numpy as jnp

    from brpc_tpu.tpu.bench_kernels import echo_loop_probe

    interpret = jax.default_backend() != "tpu"
    x = jnp.ones((PAYLOAD_BYTES // 4 // 2048, 2048), dtype=jnp.int32)
    times = {}
    for rounds in (ROUNDS_LO, ROUNDS_HI):
        v = float(echo_loop_probe(x, rounds=rounds, interpret=interpret))
        assert v == 2.0, v  # payload integrity after the round trips
        best = float("inf")
        for _ in range(REPS):
            t0 = time.perf_counter()
            float(echo_loop_probe(x, rounds=rounds, interpret=interpret))
            best = min(best, time.perf_counter() - t0)
        times[rounds] = best
    marginal = (times[ROUNDS_HI] - times[ROUNDS_LO]) / (ROUNDS_HI - ROUNDS_LO)
    # bytes echoed per round trip: payload there + payload back
    return (2 * PAYLOAD_BYTES) / marginal / 1e9


def bench_host_stack() -> None:
    """Full RPC stack through the tunnel — stderr diagnostics."""
    try:
        from brpc_tpu.proto import echo_pb2
        from brpc_tpu.rpc import Channel, ChannelOptions, Stub
        import jax

        dev = jax.devices()[0]
        ch = Channel(ChannelOptions(timeout_ms=120_000)).init(
            f"tpu://localhost/{dev.id}")
        stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
        payload = b"\xab" * HOST_PAYLOAD
        lat = []
        for _ in range(HOST_ITERS):
            t0 = time.perf_counter()
            resp = stub.Echo(echo_pb2.EchoRequest(message="b",
                                                  payload=payload))
            lat.append(time.perf_counter() - t0)
            assert len(resp.payload) == HOST_PAYLOAD
        lat.sort()
        gbps = 2 * HOST_PAYLOAD / lat[len(lat) // 2] / 1e9
        print(f"# host-stack 1MB echo through tunnel: p50="
              f"{lat[len(lat)//2]*1e3:.1f}ms ({gbps:.3f} GB/s) — "
              f"tunnel D2H fixed cost dominates", file=sys.stderr)
    except Exception as e:  # diagnostics must never sink the bench
        print(f"# host-stack bench skipped: {e}", file=sys.stderr)


def main() -> None:
    import jax

    gbps = bench_device_echo()
    dev = jax.devices()[0]
    print(f"# device={dev.platform}:{dev.id} payload={PAYLOAD_BYTES>>20}MB "
          f"rounds={ROUNDS_LO}->{ROUNDS_HI} (marginal)", file=sys.stderr)
    bench_host_stack()
    print(json.dumps({
        "metric": "echo_64mb_device_datapath_bandwidth",
        "value": round(gbps, 3),
        "unit": "GB/s",
        "vs_baseline": round(gbps / BASELINE_GBPS, 3),
    }))


if __name__ == "__main__":
    main()
