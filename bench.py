"""North-star benchmarks through the FRAMEWORK's own datapath.

What the reference measures (BASELINE.md):
  - multi_threaded_echo_c++: N client threads hammering an echo server,
    QPS + latency percentiles (client.cpp prints once per second).
  - rdma_performance: 64B-16MB payload sweep over the transport,
    bandwidth + p99 (client.cpp:254-266).

This bench does the same against OUR stack, client and server in separate
processes (no shared GIL):
  1. multi_threaded_echo: loopback TCP, trpc_std protocol, 16B payload ->
     QPS, p50/p99.
  2. payload sweep 64B-16MB over the cross-process tpu:// transport —
     bytes staged through the shared-memory registered block pool
     (brpc_tpu/tpu/transport.py, the RdmaEndpoint analog).
  3. device-datapath probe (Pallas HBM echo) — stderr diagnostic for the
     on-chip ceiling; NOT the headline.

Headline (the ONE JSON line): 1MB echo bandwidth through the full
Channel -> tpu:// transport -> Server stack, vs the reference's 2.3 GB/s
loopback plateau (/root/reference/docs/cn/benchmark.md:104).

Env knobs: BENCH_QUICK=1 shortens every phase (CI smoke); BENCH_SKIP_DEVICE=1
skips the jax probe; BENCH_PHASES=shm,qps,native,hybrid,batch,serving,spec,
qos,device runs only the named phases (default: all) — e.g. BENCH_PHASES=shm
is the CPU-only tier-1 smoke lane, whose headline is then the Python tpu://
sweep; batch is the adaptive-batching vs per-request dispatch comparison
(also CPU-only); spec is the speculative-decoding draft+verify A/B; qos is
the multi-tenant overload A/B (protected p99 + shed rate).
"""

from __future__ import annotations

import json
import os
import subprocess
import sys
import threading
import time

REPO = os.path.dirname(os.path.abspath(__file__))
QUICK = os.environ.get("BENCH_QUICK") == "1"
PHASES = {p.strip() for p in os.environ.get("BENCH_PHASES", "").split(",")
          if p.strip()}


def _phase_enabled(name: str) -> bool:
    return not PHASES or name in PHASES
BASELINE_GBPS = 2.3       # reference docs/cn/benchmark.md:104 plateau
HEADLINE_SIZE = 1 << 20
# small-message baseline: the 64B row of the r03 Python tpu:// sweep
# (pre fastpath-stack; BENCH_r03.json) — the qps the latency work is
# measured against
BASELINE_64B_QPS = 1692.0
# isolated per-RPC device dispatch rate on the tunneled chip (BENCH_r05);
# the coalesced per-step dispatch path is measured against this
BASELINE_DEVICE_OPS = 7222.0

# (payload bytes, threads, calls per thread)
SWEEP = [
    (64,        8, 60 if QUICK else 600),
    (4096,      8, 60 if QUICK else 600),
    (65536,     4, 40 if QUICK else 400),
    (1 << 20,   4, 20 if QUICK else 150),
    (16 << 20,  2, 3 if QUICK else 12),
]
QPS_THREADS = 8
QPS_SECONDS = 1.0 if QUICK else 4.0


def _host_port(endpoint: str):
    """'proto://host:port/ordinal' or 'host:port' -> (host, port_int)."""
    hp = endpoint.split("//")[-1].split("/")[0]
    host, port = hp.rsplit(":", 1)
    return host, int(port)


def _percentile(sorted_lat, p):
    if not sorted_lat:
        return 0.0
    return sorted_lat[min(len(sorted_lat) - 1, int(p * len(sorted_lat)))]


class _BenchServer:
    """Child echo server; LISTEN line gives the bound endpoint."""

    def __init__(self, listen: str, *extra_args: str):
        self.proc = subprocess.Popen(
            [sys.executable, os.path.join(REPO, "tools", "bench_server.py"),
             "--listen", listen, *extra_args],
            stdin=subprocess.PIPE, stdout=subprocess.PIPE, cwd=REPO,
            text=True)
        line = self.proc.stdout.readline().strip()
        if not line.startswith("LISTEN "):
            raise RuntimeError(f"bench server failed to start: {line!r}")
        self.endpoint = line.split(" ", 1)[1]

    def close(self):
        try:
            self.proc.stdin.close()
        except Exception:
            pass
        try:
            self.proc.wait(timeout=10)
        except subprocess.TimeoutExpired:
            self.proc.kill()
            self.proc.wait()


def _run_calls(stub, echo_pb2, payload: bytes, threads: int, calls: int):
    """threads x calls sync echoes; returns (wall_s, sorted latencies s)."""
    lat_per_thread = [[] for _ in range(threads)]
    failures = []
    barrier = threading.Barrier(threads + 1)

    def worker(idx):
        req = echo_pb2.EchoRequest(message="b", payload=payload)
        lats = lat_per_thread[idx]
        barrier.wait()
        try:
            for _ in range(calls):
                t0 = time.perf_counter()
                resp = stub.Echo(req)
                lats.append(time.perf_counter() - t0)
                assert len(resp.payload) == len(payload)
        except BaseException as e:
            failures.append(e)

    ts = [threading.Thread(target=worker, args=(i,)) for i in range(threads)]
    for t in ts:
        t.start()
    barrier.wait()
    t0 = time.perf_counter()
    for t in ts:
        t.join()
    wall = time.perf_counter() - t0
    if failures:  # a partial run must fail the bench, not skew the headline
        raise RuntimeError(f"{len(failures)}/{threads} bench workers "
                           f"failed; first: {failures[0]!r}") from failures[0]
    lats = sorted(x for l in lat_per_thread for x in l)
    return wall, lats


def bench_multi_threaded_echo():
    """Reference multi_threaded_echo_c++: QPS + p50/p99, small payload."""
    from brpc_tpu.proto import echo_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Stub

    srv = _BenchServer("127.0.0.1:0")
    try:
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=10000))
        ch.init(srv.endpoint)
        stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
        payload = b"x" * 16
        # warmup (connection + codepaths)
        _run_calls(stub, echo_pb2, payload, QPS_THREADS, 20)
        calls = max(50, int(QPS_SECONDS * 400))  # per thread
        wall, lats = _run_calls(stub, echo_pb2, payload, QPS_THREADS, calls)
        qps = len(lats) / wall
        print(f"# multi_threaded_echo: threads={QPS_THREADS} "
              f"qps={qps:,.0f} p50={_percentile(lats,0.5)*1e6:.0f}us "
              f"p99={_percentile(lats,0.99)*1e6:.0f}us "
              f"p999={_percentile(lats,0.999)*1e6:.0f}us", file=sys.stderr)
        return qps
    finally:
        srv.close()


def bench_tpu_sweep():
    """rdma_performance analog: payload sweep over the tpu:// transport.

    Returns (1MB aggregate GB/s — the headline, 64B sweep qps — the
    small-message summary metric)."""
    from brpc_tpu.proto import echo_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Stub
    from brpc_tpu.tpu.transport import (g_tunnel_ack_credits,
                                        g_tunnel_ack_frames,
                                        g_tunnel_borrowed_bytes,
                                        g_tunnel_copied_bytes)

    srv = _BenchServer("tpu://127.0.0.1:0/0")
    headline = 0.0
    zc0 = (g_tunnel_borrowed_bytes.get_value(),
           g_tunnel_copied_bytes.get_value(),
           g_tunnel_ack_frames.get_value(), g_tunnel_ack_credits.get_value())
    try:
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=60000))
        ch.init(srv.endpoint)
        stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
        _run_calls(stub, echo_pb2, b"w" * 1024, 2, 10)  # warmup
        # TRUE transport latency first: depth-1 ping-pong (the r3 sweep's
        # "p50 3.6ms" was closed-loop queueing of 8 sync threads behind a
        # throughput ceiling, not the wire — the reference also reports
        # latency from unloaded clients)
        for size in (64, 4096):
            wall, lats = _run_calls(stub, echo_pb2, b"\xab" * size, 1,
                                    60 if QUICK else 300)
            print(f"# tpu:// ping-pong {size}B depth-1: "
                  f"p50={_percentile(lats,0.5)*1e3:.2f}ms "
                  f"p99={_percentile(lats,0.99)*1e3:.2f}ms",
                  file=sys.stderr)
        print("# tpu:// sweep (shm block-pool transport, both-ways bytes; "
              "p50 at depth>1 includes closed-loop queueing):",
              file=sys.stderr)
        # warm the largest size once: the first bulk call pays the block
        # pool's page faults, which at 3 QUICK calls would dominate p50
        _run_calls(stub, echo_pb2, b"\xab" * max(s for s, _, _ in SWEEP),
                   1, 1)
        by_size = {}
        qps_by_size = {}
        bulk_copied = bulk_borrowed = 0
        for size, threads, calls in SWEEP:
            payload = b"\xab" * size
            b0 = (g_tunnel_borrowed_bytes.get_value(),
                  g_tunnel_copied_bytes.get_value())
            wall, lats = _run_calls(stub, echo_pb2, payload, threads, calls)
            gbps = 2 * size * len(lats) / wall / 1e9
            by_size[size] = gbps
            qps_by_size[size] = len(lats) / wall
            if size == 16 << 20:
                bulk_borrowed = g_tunnel_borrowed_bytes.get_value() - b0[0]
                bulk_copied = g_tunnel_copied_bytes.get_value() - b0[1]
            print(f"#   {size:>9}B x{threads}thr x{calls}: "
                  f"{gbps:7.3f} GB/s  qps={len(lats)/wall:9,.0f}  "
                  f"p50={_percentile(lats,0.5)*1e3:7.2f}ms "
                  f"p99={_percentile(lats,0.99)*1e3:7.2f}ms", file=sys.stderr)
            if size == HEADLINE_SIZE:
                headline = gbps
        # regression guard for the 16MB entry (the ROADMAP "collapses to
        # ~0.1 GB/s" item): bulk messages must stay inside the window's
        # zero-copy borrow budget (DEFAULT_BLOCK_COUNT, tpu/transport.py).
        # The budget overflowing shows up as copy-and-ACK fallback bytes —
        # a deterministic signal, unlike the QUICK sweep's 3-call timings.
        if (16 << 20) in by_size and HEADLINE_SIZE in by_size:
            bulk_total = bulk_borrowed + bulk_copied
            copied_frac = bulk_copied / bulk_total if bulk_total else 0.0
            bulk_ratio = by_size[16 << 20] / max(by_size[HEADLINE_SIZE],
                                                 1e-9)
            print(f"# tpu:// sweep 16MB entry: {bulk_ratio:.2f}x the 1MB "
                  f"rate, {copied_frac:.0%} of bulk bytes copied "
                  f"(borrow-budget regression when > 10%)", file=sys.stderr)
            from brpc_tpu.butil.iobuf import supports_block_ownership

            if supports_block_ownership() and bulk_total \
                    and copied_frac > 0.10:
                raise RuntimeError(
                    f"16MB sweep entry regressed: {copied_frac:.0%} of "
                    f"bulk bytes fell back to copy-and-ACK — messages no "
                    f"longer fit the tpu:// borrow budget")
        borrowed = g_tunnel_borrowed_bytes.get_value() - zc0[0]
        copied = g_tunnel_copied_bytes.get_value() - zc0[1]
        frames = g_tunnel_ack_frames.get_value() - zc0[2]
        credits = g_tunnel_ack_credits.get_value() - zc0[3]
        total = borrowed + copied
        print(f"# tpu:// zero-copy receive (this process = client side): "
              f"borrowed={borrowed:,}B copied={copied:,}B "
              f"({borrowed / total:.0%} borrowed)" if total else
              "# tpu:// zero-copy receive: no block-segment traffic",
              file=sys.stderr)
        if frames:
            print(f"# tpu:// ack batching: {credits:,} credits in "
                  f"{frames:,} FT_ACK frames "
                  f"({credits / frames:.1f} credits/frame)", file=sys.stderr)
        # streaming-parse guard: the window shrank 320 -> 64 blocks on the
        # strength of mid-message credit return keeping the in-flight
        # borrow footprint at a frame's worth, not a message's worth. Peak
        # borrowed-outstanding at (or past) the window means claiming
        # stopped happening mid-body and the shrunken window is now the
        # bottleneck again.
        from brpc_tpu.butil.iobuf import supports_block_ownership
        from brpc_tpu.tpu.transport import (DEFAULT_BLOCK_COUNT,
                                            borrowed_peak_blocks)

        peak = borrowed_peak_blocks()
        print(f"# tpu:// borrowed peak: {peak} blocks "
              f"(window {DEFAULT_BLOCK_COUNT})", file=sys.stderr)
        if supports_block_ownership() and total \
                and peak >= DEFAULT_BLOCK_COUNT:
            raise RuntimeError(
                f"peak borrowed-outstanding ({peak} blocks) reached the "
                f"{DEFAULT_BLOCK_COUNT}-block window — bodies are no "
                f"longer being claimed mid-message")
        return headline, qps_by_size.get(64, 0.0)
    finally:
        srv.close()


def measure_series_overhead() -> float:
    """Cost of one series-ring sweep over this process's exposed vars
    (metrics/series.py), as a percentage of the 1s tick budget the
    sampler daemon grants it. Measured on a private registry so the
    probe never perturbs the live rings."""
    from brpc_tpu.metrics.series import SeriesRegistry

    reg = SeriesRegistry()
    for _ in range(50):
        reg.tick()
    avg_s = reg.total_tick_s / max(reg.ticks, 1)
    return avg_s * 100.0


def bench_batch_lane():
    """Adaptive batching (brpc_tpu/batch/) head to head with per-request
    dispatch: the same jitted MLP behind BatchBench.Infer (one B=1 jit call
    per RPC) and BatchBench.InferBatched (concurrent RPCs coalesced into
    one padded jit call). Pipelined async client, pure-Python server —
    the win is per-item device-dispatch + interpreter cost amortized
    across the batch. Returns the batched/per-request QPS ratio."""
    import numpy as np

    from brpc_tpu.policy.http_protocol import http_fetch
    from brpc_tpu.proto import echo_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions
    from brpc_tpu.rpc.channel import MethodDescriptor

    srv = _BenchServer("127.0.0.1:0", "--batch")
    try:
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=30000,
                                    done_inline=True))
        ch.init(srv.endpoint)
        rng = np.random.default_rng(7)
        req = echo_pb2.EchoRequest(
            message="b",
            payload=rng.standard_normal(256, dtype=np.float32).tobytes())

        def run(method, depth, total):
            md = MethodDescriptor("BatchBench", method,
                                  echo_pb2.EchoRequest,
                                  echo_pb2.EchoResponse)
            done_ev = threading.Event()
            state = {"issued": 0, "completed": 0, "errors": 0}
            lats = []

            def make_done(t0):
                def done(cntl):
                    lats.append(time.perf_counter() - t0)
                    if cntl.failed():
                        state["errors"] += 1
                    state["completed"] += 1
                    if state["issued"] < total:
                        state["issued"] += 1
                        ch.call_method(md, req,
                                       done=make_done(time.perf_counter()))
                    elif state["completed"] >= total:
                        done_ev.set()
                return done

            t_start = time.perf_counter()
            for _ in range(min(depth, total)):
                state["issued"] += 1
                ch.call_method(md, req, done=make_done(time.perf_counter()))
            if not done_ev.wait(180):
                raise RuntimeError(f"batch bench stalled ({method}): "
                                   f"{state['completed']}/{total}")
            if state["errors"]:
                raise RuntimeError(
                    f"{state['errors']} {method} calls failed")
            wall = time.perf_counter() - t_start
            lats.sort()
            return len(lats) / wall, lats

        run("Infer", 4, 30)          # warmup: connection + codepaths
        run("InferBatched", 8, 60)
        total_pr = 150 if QUICK else 600
        total_b = 600 if QUICK else 4000
        qps_pr, lat_pr = run("Infer", 16, total_pr)
        qps_b, lat_b = run("InferBatched", 32, total_b)
        ratio = qps_b / max(qps_pr, 1e-9)
        print(f"# batch lane (jitted MLP 256x32L, pipelined py client): "
              f"per-request qps={qps_pr:,.0f} "
              f"p50={_percentile(lat_pr,0.5)*1e3:.2f}ms | batched "
              f"qps={qps_b:,.0f} p50={_percentile(lat_b,0.5)*1e3:.2f}ms | "
              f"batched/per-request = {ratio:.2f}x "
              f"({'OK' if ratio >= 2.0 else 'BELOW'} 2x floor)",
              file=sys.stderr)
        # the observability half of the acceptance: the coalescing must be
        # visible through /vars on the serving process
        hostport = f"{_host_port(srv.endpoint)[0]}:" \
                   f"{_host_port(srv.endpoint)[1]}"
        for var in ("g_batch_size", "g_batch_queue_delay_us"):
            body = http_fetch(hostport, "GET", f"/vars/{var}",
                              timeout=10).body.decode().strip()
            print(f"# batch lane /vars: {body}", file=sys.stderr)
        return ratio
    finally:
        srv.close()


def _serving_engine_qps(scheduling: str, n_requests: int,
                        sharded: bool = False):
    """In-process half of the serving lane: one engine, one mixed-length
    workload (mostly short 4-token generations with a long 64-token one
    every 4th request — each static gang carries exactly one straggler;
    all submitted up front); returns (requests/sec, tokens/sec). Static
    gang scheduling drains a whole batch before admitting the next, so
    every short request waits out the longest gang member; continuous
    batching refills freed slots between decode steps
    (brpc_tpu/serving/engine.py). Identical model/engine configs, so the
    ratio isolates the scheduler. ``sharded=True`` runs the mesh stack
    (MeshTransformer + ShardedKVCache over the dp/sp/tp serving mesh) —
    on one device the mesh degenerates to 1x1x1, so the lane works under
    any XLA_FLAGS device count."""
    from brpc_tpu.serving import (EngineConfig, KVCacheConfig, ModelConfig,
                                  PagedKVCache, ServingEngine,
                                  TinyTransformer)

    cfg = ModelConfig(vocab=256, d_model=32, n_heads=2, n_layers=2)
    if sharded:
        from brpc_tpu.serving import MeshTransformer, ShardedKVCache

        kv = ShardedKVCache(KVCacheConfig(block_size=16, num_blocks=256),
                            cfg.n_layers, cfg.kv_dim)
        model = MeshTransformer(cfg, kv)
    else:
        kv = PagedKVCache(KVCacheConfig(block_size=16, num_blocks=256),
                          cfg.n_layers, cfg.kv_dim)
        model = TinyTransformer(cfg, kv)
    # prefix_cache=False: this A/B isolates the SCHEDULER — cached-prefix
    # reuse would shrink exactly the prefill work the static gang stalls
    # behind (the prefix cache gets its own hit-TTFT lane below)
    engine = ServingEngine(model, kv, EngineConfig(
        max_batch=4, token_budget=256, scheduling=scheduling,
        idle_wait_s=0.005), prefix_cache=False).start()
    tokens = sum(64 if i % 4 == 3 else 4 for i in range(n_requests))

    def run(n):
        evs = []
        t0 = time.perf_counter()
        for i in range(n):
            ev = threading.Event()
            code, _ = engine.submit(model.synth_prompt(16),
                                    64 if i % 4 == 3 else 4,
                                    done=lambda _r, ev=ev: ev.set())
            if code != 0:
                raise RuntimeError(f"serving submit rejected: {code}")
            evs.append(ev)
        for ev in evs:
            if not ev.wait(300):
                raise RuntimeError(f"serving A/B stalled ({scheduling})")
        wall = time.perf_counter() - t0
        return n / wall, tokens / wall

    try:
        # two warmup rounds of the EXACT timed workload: the queue-depth
        # profile decides which (batch, context) buckets the decode hits,
        # so a smaller warmup misses combos (e.g. full batch at long
        # context) and their compiles would land in the timed run; the
        # second round covers the donated-pool second jit signature
        run(n_requests)
        run(n_requests)
        return run(n_requests)
    finally:
        engine.stop()
        model.close()


def _device_op_rate() -> tuple:
    """Coalesced per-step device dispatch rate, measured in-process on
    the sim lane: one small HBM-resident buffer, transient copies queued
    through DeviceStore.copy_coalesced (the per-step batch API the
    serving engine rides) so the dispatcher thread fuses them into O(1)
    compiled programs instead of per-op ~7ms command latencies. Returns
    (op_rate, ops). Hardware counterpart: tests_hw/bench.py drives the
    same path over the Copy RPC's nbytes=-k rider against the real chip
    and holds the 14.5k op/s floor (BENCH_r05 isolated-dispatch
    baseline: 7.2k op/s)."""
    from brpc_tpu.tpu.device_lane import (DispatchCounter, global_store,
                                          step_dispatch)

    store = global_store()
    handle, _ = store.put(b"\x00" * 1024)
    try:
        store.copy_coalesced(handle, 64)  # warmup: dispatcher + jit cache
        store.fence()
        total_ops = 2048 if QUICK else 16384
        batch = 256  # one "step" worth of device ops per Python dispatch
        before = step_dispatch.snapshot()
        t0 = time.perf_counter()
        for _ in range(total_ops // batch):
            store.copy_coalesced(handle, batch)
        store.fence()
        wall = time.perf_counter() - t0
        _, ops, _ = DispatchCounter.delta(before, step_dispatch.snapshot())
        return ops / wall, ops
    finally:
        store.free(handle)


def _bench_prefix_ttft():
    """Prefix-cache hit-TTFT A/B: two identical engines — one with the
    radix cache disabled (cold reference), one with it on (warm) — driven
    with a shared-prefix corpus (same synth prompt, one distinct tail
    token per request, the system-prompt traffic shape). After the warm
    engine's first request commits the shared chain, every later request
    forks it and prefills ONE suffix token — hit TTFT collapses from
    O(prompt) reference-attention prefill to one decode-shaped launch.
    Returns (hit_ttft_ms, cold_ttft_ms, hit_ratio)."""
    from brpc_tpu.serving import (EngineConfig, KVCacheConfig, ModelConfig,
                                  PagedKVCache, ServingEngine,
                                  TinyTransformer)

    plen = 256 if QUICK else 512
    reqs = 4 if QUICK else 8
    cfg = ModelConfig(vocab=256, d_model=32, n_heads=2, n_layers=2,
                      max_context=4 * plen)
    ecfg = dict(max_batch=4, token_budget=4 * plen, idle_wait_s=0.002)

    def build(prefix_cache):
        kv = PagedKVCache(KVCacheConfig(block_size=16,
                                        num_blocks=2 * (4 * plen) // 16),
                          cfg.n_layers, cfg.kv_dim)
        model = TinyTransformer(cfg, kv)
        return ServingEngine(model, kv, EngineConfig(**ecfg),
                             prefix_cache=prefix_cache).start()

    base = None  # shared-prefix corpus: common first blocks, unique tail

    def prompt(i):
        p = base.copy()
        p[-1] = 1 + (7 * i + 3) % (cfg.vocab - 1)
        return p

    def one(engine, i):
        ev = threading.Event()
        box = {}
        code, _ = engine.submit(prompt(i), 4,
                                done=lambda r, ev=ev: (box.update(r=r),
                                                       ev.set()))
        if code != 0:
            raise RuntimeError(f"prefix bench submit rejected: {code}")
        if not ev.wait(300):
            raise RuntimeError("prefix bench stalled")
        return box["r"].ttft_us / 1000.0

    cold = build(prefix_cache=False)
    warm = build(prefix_cache=None)
    base = cold.model.synth_prompt(plen + 1)
    try:
        # warmup: compile every bucket both lanes touch (cold prefill,
        # warm suffix decode-shape), twice for the donated-pool second
        # jit signature; the warm engine's warmup also PRIMES the tree —
        # the first commit is the corpus the timed hits fork
        for _ in range(2):
            for i in range(reqs):
                one(cold, i)
                one(warm, i)
        cold_ms = _percentile(sorted(one(cold, i) for i in range(reqs)), 0.5)
        hit_ms = _percentile(sorted(one(warm, i) for i in range(reqs)), 0.5)
        snap = warm.snapshot()["prefix"]
        hit_ratio = snap["hit_ratio"]
    finally:
        warm.stop()
        cold.stop()
        warm.model.close()
        cold.model.close()
    return hit_ms, cold_ms, hit_ratio


def _bench_disagg_interference():
    """Disaggregated prefill/decode interference A/B: the same 3:1 mixed
    corpus (three short decode-heavy requests, then one long prefill)
    through (a) ONE co-located engine, where every long prefill launch
    stalls the decode steps sharing its loop, and (b) a prefill engine
    that hands each just-prefilled sequence to a separate decode engine
    over the tpu:// record lane (KVMigrator -> loopback LlmService ->
    adopt). The decode engine then runs NOTHING but (1,1) decode steps,
    so its inter-token jitter (p99-p50 of per-engine ITL samples) must
    come in below the co-located engine's — that spread IS the
    interference the disaggregation removes. Returns
    (coloc_jitter_ms, disagg_jitter_ms, coloc_ttft_ms, disagg_ttft_ms,
    migrator_snapshot)."""
    import numpy as np

    from brpc_tpu.rpc.server import Server
    from brpc_tpu.serving import (EngineConfig, KVCacheConfig, ModelConfig,
                                  PagedKVCache, ServingEngine,
                                  TinyTransformer)
    from brpc_tpu.serving.migration import KVMigrator
    from brpc_tpu.serving.service import LlmServingService

    n = 16 if QUICK else 32
    corpus = [(160, 4) if i % 4 == 3 else (16, 24) for i in range(n)]
    cfg = ModelConfig(vocab=256, d_model=32, n_heads=2, n_layers=2,
                      max_context=256)

    def build(role):
        kv = PagedKVCache(KVCacheConfig(block_size=16, num_blocks=256),
                          cfg.n_layers, cfg.kv_dim)
        model = TinyTransformer(cfg, kv)
        # prefix_cache=False: this A/B isolates scheduling interference —
        # cached-prefix reuse would shrink exactly the prefill launches
        # the co-located decode steps stall behind
        return ServingEngine(model, kv, EngineConfig(
            max_batch=4, token_budget=256, idle_wait_s=0.002, role=role),
            prefix_cache=False).start()

    def submit(eng, plen, max_new, resume=0):
        ev = threading.Event()
        box = {}
        prompt = (np.zeros(0, dtype=np.int32) if resume
                  else eng.model.synth_prompt(plen))
        code, _ = eng.submit(
            prompt, 0 if resume else max_new,
            done=lambda r, box=box, ev=ev: (box.update(r=r), ev.set()),
            resume_seq_id=resume)
        if code != 0:
            raise RuntimeError(f"disagg bench submit rejected: {code}")
        return ev, box

    def run_coloc(eng):
        pend = [submit(eng, p, m) for p, m in corpus]
        for ev, _ in pend:
            if not ev.wait(300):
                raise RuntimeError("disagg bench: co-located run stalled")

    def run_disagg(pre, dec):
        stage1 = [submit(pre, p, m) for p, m in corpus]
        for ev, box in stage1:
            if not ev.wait(300):
                raise RuntimeError("disagg bench: prefill stage stalled")
            r = box["r"]
            if r is None or r.finish_reason != "handoff":
                raise RuntimeError(
                    f"disagg bench: expected handoff, got "
                    f"{getattr(r, 'finish_reason', None)!r}")
        stage2 = [submit(dec, 0, 0, resume=box["r"].seq_id)
                  for _, box in stage1]
        for ev, _ in stage2:
            if not ev.wait(300):
                raise RuntimeError("disagg bench: decode stage stalled")

    def jitter_ms(samples):
        s = sorted(samples)
        if not s:
            return 0.0
        return (_percentile(s, 0.99) - _percentile(s, 0.5)) / 1e3

    def ttft_ms(samples):
        s = sorted(samples)
        return (_percentile(s, 0.5) / 1e3) if s else 0.0

    def warm_buckets(eng):
        # deterministically compile every (batch, context) decode bucket
        # the timed reps can hit — a mid-run jit trace (hundreds of ms)
        # would otherwise masquerade as scheduling jitter in a p99 drawn
        # from a few hundred samples
        for group in ([(160, 4)] * 4, [(16, 4)] * 4, [(160, 4)],
                      [(16, 4)]):
            pend = [submit(eng, p, m) for p, m in group]
            for ev, _ in pend:
                if not ev.wait(300):
                    raise RuntimeError(
                        "disagg bench: bucket warmup stalled")

    REPS = 3  # min-of-reps: p99 from ~300 samples is one GC pause from
    #           flipping the A/B, so each mode keeps its best draw

    coloc = build("both")
    try:
        # warmup covers every (batch, context) bucket the timed run hits,
        # twice for the donated-pool second jit signature
        run_coloc(coloc)
        run_coloc(coloc)
        warm_buckets(coloc)
        coloc_j = coloc_t = float("inf")
        for _ in range(REPS):
            coloc.itl_samples.clear()
            coloc.ttft_samples.clear()
            run_coloc(coloc)
            coloc_j = min(coloc_j, jitter_ms(coloc.itl_samples))
            coloc_t = min(coloc_t, ttft_ms(coloc.ttft_samples))
    finally:
        coloc.stop()
        coloc.model.close()

    dec = build("decode")
    srv = Server().add_service(LlmServingService(dec)).start("127.0.0.1:0")
    pre = build("prefill")
    pre.set_migrator(KVMigrator(f"{srv.listen_endpoint()}"))
    try:
        run_disagg(pre, dec)
        run_disagg(pre, dec)
        warm_buckets(dec)
        dis_j = dis_t = float("inf")
        for _ in range(REPS):
            pre.ttft_samples.clear()
            dec.itl_samples.clear()
            run_disagg(pre, dec)
            dis_j = min(dis_j, jitter_ms(dec.itl_samples))
            dis_t = min(dis_t, ttft_ms(pre.ttft_samples))
        mig = pre.migrator.snapshot()
    finally:
        pre.stop()
        srv.stop()
        srv.join(timeout=2)
        dec.stop()
        pre.model.close()
        dec.model.close()
    return coloc_j, dis_j, coloc_t, dis_t, mig


def bench_serving_lane():
    """Serving plane (brpc_tpu/serving/): streamed generations over the
    RPC path against a pre-warmed child server — aggregate tokens/sec and
    TTFT percentiles measured at stream-frame arrival — then the
    in-process continuous-vs-static scheduling A/B on mixed-length
    traffic over the SHARDED mesh stack, the prefix-cache hit-TTFT A/B,
    the disaggregated prefill/decode interference A/B, plus the coalesced
    device dispatch-rate probe. Emits the ten serving JSON metric
    lines."""
    from brpc_tpu.proto import serving_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Controller, Stub
    from brpc_tpu.rpc.stream import (StreamOptions, stream_close,
                                     stream_create)

    threads = 4 if QUICK else 8
    calls = 3 if QUICK else 8
    srv = _BenchServer("127.0.0.1:0", "--serving")
    try:
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=120000))
        ch.init(srv.endpoint)
        stub = Stub(ch,
                    serving_pb2.DESCRIPTOR.services_by_name["LlmService"])

        def generate(prompt_len, max_new):
            t_first = [0.0]

            def on_received(sid, msgs):
                if not t_first[0]:
                    t_first[0] = time.perf_counter()

            sid = stream_create(StreamOptions(on_received=on_received))
            cntl = Controller()
            cntl.stream_id = sid
            cntl.timeout_ms = 120000
            t0 = time.perf_counter()
            resp = stub.Generate(
                serving_pb2.GenerateRequest(prompt_len=prompt_len,
                                            max_new_tokens=max_new),
                controller=cntl)
            total = time.perf_counter() - t0
            stream_close(sid)
            if cntl.failed():
                raise RuntimeError(f"Generate failed: {cntl.error_text()}")
            ttft = (t_first[0] - t0) if t_first[0] else total
            return len(resp.tokens), ttft

        generate(16, 2)  # warmup: connection + client codepaths
        tok_count = [0] * threads
        ttfts = [[] for _ in range(threads)]
        failures = []
        barrier = threading.Barrier(threads + 1)

        def worker(idx):
            barrier.wait()
            try:
                for c in range(calls):
                    n, ttft = generate(16 + 16 * (idx % 2),
                                       4 if (idx + c) % 2 else 24)
                    tok_count[idx] += n
                    ttfts[idx].append(ttft)
            except BaseException as e:
                failures.append(e)

        ts = [threading.Thread(target=worker, args=(i,))
              for i in range(threads)]
        for t in ts:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        if failures:
            raise RuntimeError(f"serving bench worker failed: "
                               f"{failures[0]!r}") from failures[0]
        tps = sum(tok_count) / wall
        lat = sorted(x for l in ttfts for x in l)
    finally:
        srv.close()

    # the scheduling A/B runs on the SHARDED stack (mesh prefill/decode +
    # per-device KV pools): the 1.5x continuous-vs-static floor must hold
    # with sharding on, or the mesh lowering broke iteration-level refill
    n_ab = 16 if QUICK else 32
    cont_qps, cont_tps = _serving_engine_qps("continuous", n_ab,
                                             sharded=True)
    stat_qps, _ = _serving_engine_qps("static", n_ab, sharded=True)
    ratio = cont_qps / max(stat_qps, 1e-9)
    hit_ms, cold_ms, hit_ratio = _bench_prefix_ttft()
    pfx_ratio = hit_ms / max(cold_ms, 1e-9)
    coloc_j, dis_j, coloc_t, dis_t, mig = _bench_disagg_interference()
    op_rate, n_ops = _device_op_rate()
    import jax as _jax
    n_dev = len(_jax.devices())
    p50 = _percentile(lat, 0.5) * 1e3
    p99 = _percentile(lat, 0.99) * 1e3
    print(f"# serving lane: {threads}x{calls} streamed generations "
          f"tokens/s={tps:,.0f} ttft p50={p50:.1f}ms p99={p99:.1f}ms | "
          f"sharded A/B ({n_dev} dev) {n_ab} mixed-length reqs: "
          f"continuous={cont_qps:.1f} req/s "
          f"static={stat_qps:.1f} req/s ratio={ratio:.2f}x "
          f"({'OK' if ratio >= 1.5 else 'BELOW'} 1.5x floor) | "
          f"coalesced device dispatch: {n_ops} ops at {op_rate:,.0f} op/s "
          f"(isolated-dispatch baseline {BASELINE_DEVICE_OPS:,.0f})",
          file=sys.stderr)
    print(f"# serving prefix: shared-prefix hit ttft={hit_ms:.2f}ms "
          f"cold={cold_ms:.2f}ms ratio={pfx_ratio:.3f} "
          f"({'OK' if pfx_ratio <= 0.5 else 'ABOVE'} 0.5x ceiling) "
          f"hit_ratio={hit_ratio:.2f}", file=sys.stderr)
    print(f"# serving disagg: 3:1 mixed corpus decode jitter "
          f"coloc={coloc_j:.3f}ms disagg={dis_j:.3f}ms "
          f"({'OK' if dis_j < coloc_j else 'ABOVE'} interference floor) "
          f"ttft coloc={coloc_t:.2f}ms disagg={dis_t:.2f}ms | "
          f"migrated seqs={mig['seqs']} blocks={mig['blocks']} "
          f"at {mig['gbps']:.3f} GB/s", file=sys.stderr)
    print(json.dumps({
        "metric": "serving_tokens_per_sec",
        "value": round(tps, 1),
        "unit": "tokens/s",
    }))
    print(json.dumps({
        "metric": "serving_ttft_ms",
        "value": round(p50, 2),
        "unit": "ms",
        "p99": round(p99, 2),
    }))
    print(json.dumps({
        "metric": "serving_continuous_vs_static",
        "value": round(ratio, 3),
        "unit": "x",
        "continuous_qps": round(cont_qps, 1),
        "static_qps": round(stat_qps, 1),
    }))
    print(json.dumps({
        "metric": "serving_sharded_tokens_per_s",
        "value": round(cont_tps, 1),
        "unit": "tokens/s",
        "devices": n_dev,
    }))
    print(json.dumps({
        "metric": "serving_prefix_hit_ttft_ms",
        "value": round(hit_ms, 3),
        "unit": "ms",
        "cold_ms": round(cold_ms, 3),
        "ratio": round(pfx_ratio, 4),
    }))
    print(json.dumps({
        "metric": "serving_prefix_hit_ratio",
        "value": round(hit_ratio, 4),
        "unit": "ratio",
    }))
    print(json.dumps({
        "metric": "serving_disagg_decode_jitter",
        "value": round(dis_j, 4),
        "unit": "ms",
        "coloc_ms": round(coloc_j, 4),
    }))
    print(json.dumps({
        "metric": "serving_disagg_ttft_ms",
        "value": round(dis_t, 3),
        "unit": "ms",
        "coloc_ms": round(coloc_t, 3),
    }))
    print(json.dumps({
        "metric": "serving_migrate_gbps",
        "value": round(mig["gbps"], 4),
        "unit": "GB/s",
        "seqs": mig["seqs"],
        "blocks": mig["blocks"],
    }))
    print(json.dumps({
        "metric": "device_op_rate",
        "value": round(op_rate, 1),
        "unit": "op/s",
        "ops": n_ops,
        "vs_baseline": BASELINE_DEVICE_OPS,
    }))
    return ratio


def bench_spec_lane():
    """Speculative decoding A/B: two identical engines — one plain
    (spec_k=0), one running the prompt-lookup draft + one fused verify
    lane (spec_k=4) — driven with the same repetition-heavy corpus the
    committed spec replay corpus records (templated motif prompts whose
    greedy continuations the n-gram matcher predicts). Greedy acceptance
    makes the lanes bit-identical (raised on here, gated exactly in
    tests/test_serving_spec.py), so the only delta is steps: the spec
    lane commits up to k+1 tokens per fused launch. Emits tokens/s for
    both lanes (1.3x floor), the run's accept rate, and the per-user
    decode latency (request wall minus TTFT over tokens after the first
    — the per-token latency one client observes)."""
    import numpy as np

    from brpc_tpu.serving import (EngineConfig, KVCacheConfig, ModelConfig,
                                  PagedKVCache, ServingEngine,
                                  TinyTransformer)
    from tools.record_serving_corpus_spec import SCHEDULE, SPEC_K, spec_prompt

    # no QUICK trim — doubled instead: the 8-request schedule is only
    # ~256 decode tokens, and a pass that short puts OS-scheduler noise
    # on the same scale as the A/B delta; 16 requests keep a pass in the
    # hundreds of milliseconds, and the longer generations amortize the
    # prefill share out of the tokens/s ratio
    sched = SCHEDULE * 2
    n_tokens = sum(mn for _, mn, _ in sched)
    cfg = ModelConfig(vocab=256, d_model=32, n_heads=2, n_layers=2)

    def build(spec_k):
        kv = PagedKVCache(KVCacheConfig(block_size=16, num_blocks=256),
                          cfg.n_layers, cfg.kv_dim)
        model = TinyTransformer(cfg, kv)
        # prefix_cache off: repeated warmups of the same motif prompts
        # would otherwise fold prefill into the A/B, which is about the
        # decode loop only. max_batch=1: speculation's win is fewer
        # LAUNCHES per committed token, so the A/B runs where launch
        # overhead dominates — a verify over k+1 rows costs ~one decode
        # dispatch but commits up to k+1 tokens; at large batch the CPU
        # sim's row compute scales linearly and hides exactly the
        # dispatch overhead a real accelerator step is bound by (the
        # batched-throughput story is the serving phase's A/B)
        return ServingEngine(model, kv, EngineConfig(
            max_batch=1, token_budget=512, idle_wait_s=0.002,
            spec_k=spec_k), prefix_cache=False).start()

    def run(engine, itls=None):
        """One open-loop pass over the schedule; returns (wall_s, outputs)
        and appends per-request mean decode ITL seconds to ``itls``."""
        pend = []
        t0 = time.perf_counter()
        for plen, max_new, motif in sched:
            ev = threading.Event()
            box = {}
            code, _ = engine.submit(
                np.asarray(spec_prompt(plen, motif), dtype=np.int32),
                max_new,
                done=lambda r, box=box, ev=ev: (box.update(r=r,
                                                           t=time.perf_counter()),
                                                ev.set()))
            if code != 0:
                raise RuntimeError(f"spec bench submit rejected: {code}")
            pend.append((ev, box))
        outs = []
        for ev, box in pend:
            if not ev.wait(300):
                raise RuntimeError("spec bench stalled")
            r = box["r"]
            outs.append(list(r.tokens))
            if itls is not None and len(r.tokens) > 1:
                decode_s = (box["t"] - t0) - r.ttft_us / 1e6
                itls.append(max(0.0, decode_s) / (len(r.tokens) - 1))
        return time.perf_counter() - t0, outs

    REPS = 5  # best-of: one GC pause must not flip the A/B
    base = build(0)
    sp = build(SPEC_K)
    try:
        for _ in range(2):  # compile every bucket (2nd donated signature)
            run(base)
            run(sp)
        base_wall, base_itl = float("inf"), []
        sp_wall, sp_itl = float("inf"), []
        base_outs = sp_outs = None
        for _ in range(REPS):
            w, base_outs = run(base, base_itl)
            base_wall = min(base_wall, w)
            w, sp_outs = run(sp, sp_itl)
            sp_wall = min(sp_wall, w)
        if sp_outs != base_outs:
            raise RuntimeError(
                "speculative lane diverged from baseline: greedy "
                "acceptance must be bit-identical")
        st = sp.spec_stats.snapshot()
    finally:
        sp.stop()
        base.stop()
        sp.model.close()
        base.model.close()
    tps = n_tokens / sp_wall
    base_tps = n_tokens / base_wall
    ratio = tps / max(base_tps, 1e-9)
    itl_ms = 1e3 * sorted(sp_itl)[len(sp_itl) // 2] if sp_itl else 0.0
    base_itl_ms = 1e3 * sorted(base_itl)[len(base_itl) // 2] \
        if base_itl else 0.0
    print(f"# serving spec: {len(sched)} reqs ({n_tokens} tokens) "
          f"draft+verify k={SPEC_K}: spec={tps:,.0f} tok/s "
          f"baseline={base_tps:,.0f} tok/s ratio={ratio:.2f}x "
          f"({'OK' if ratio >= 1.3 else 'BELOW'} 1.3x floor) | "
          f"accept_rate={st['accept_rate']:.2f} "
          f"(drafted={st['drafted']} accepted={st['accepted']} "
          f"bonus={st['bonus']}) | per-user decode itl p50 "
          f"spec={itl_ms:.2f}ms baseline={base_itl_ms:.2f}ms",
          file=sys.stderr)
    print(json.dumps({
        "metric": "serving_spec_tokens_per_s",
        "value": round(tps, 1),
        "unit": "tokens/s",
        "baseline": round(base_tps, 1),
        "ratio": round(ratio, 3),
    }))
    print(json.dumps({
        "metric": "serving_spec_accept_rate",
        "value": st["accept_rate"],
        "unit": "ratio",
        "drafted": st["drafted"],
        "accepted": st["accepted"],
        "bonus": st["bonus"],
    }))
    print(json.dumps({
        "metric": "serving_spec_itl_ms",
        "value": round(itl_ms, 3),
        "unit": "ms",
        "baseline_ms": round(base_itl_ms, 3),
    }))
    return ratio


def bench_qos_lane():
    """Multi-tenant QoS A/B under a best-effort flood: two engines see
    the same offered load — a ``batch`` tenant (priority 0) dumping a
    saturating wave, then a ``prod`` tenant (priority 1, weight 4)
    submitting its steady work. The QoS engine meters admission by
    weighted fair share and sheds batch past its queue cap
    (EOVERCROWDED, retriable); the control engine is the plain FIFO
    path, where prod queues behind the entire flood. Emits the
    protected tenant's p99 (vs its unloaded p99 and the FIFO engine's
    flooded p99) and the shed rate — the overload-survival headline
    tests/test_bench_quick.py floor-gates."""
    import numpy as np

    from brpc_tpu.serving import (EngineConfig, KVCacheConfig, ModelConfig,
                                  PagedKVCache, QosConfig, ServingEngine,
                                  TinyTransformer)

    cfg = ModelConfig(vocab=256, d_model=32, n_heads=2, n_layers=2)
    FLOOD, PROD_REQS = 32, 8
    PLEN, MAX_NEW = 16, 8
    qos_cfg = QosConfig(tenants={"prod": 4.0, "batch": 1.0},
                        queue_cap=12, protected_priority=1)

    def build(qos):
        kv = PagedKVCache(KVCacheConfig(block_size=16, num_blocks=256),
                          cfg.n_layers, cfg.kv_dim)
        model = TinyTransformer(cfg, kv)
        # max_batch=2 + a tight budget keeps the flood saturating for
        # many steps — the regime where admission ORDER is the outcome
        return ServingEngine(model, kv, EngineConfig(
            max_batch=2, token_budget=64, max_queue=256,
            idle_wait_s=0.002, qos=qos), prefix_cache=False).start()

    def submit(engine, tenant, priority, lats, sheds, pend):
        t0 = time.perf_counter()
        ev = threading.Event()
        code, _ = engine.submit(
            engine.model.synth_prompt(PLEN), MAX_NEW,
            tenant_id=tenant, priority=priority,
            done=lambda r, ev=ev, t0=t0: (
                lats.append(time.perf_counter() - t0), ev.set()))
        if code != 0:
            sheds.append(code)
        else:
            pend.append(ev)

    def drain(pend):
        for ev in pend:
            if not ev.wait(300):
                raise RuntimeError("qos bench stalled")

    def flood_run(engine):
        """The overload wave: batch floods, then prod submits its work.
        Returns (prod_p99_s, batch_shed, batch_sent)."""
        prod_lats, batch_lats = [], []
        prod_shed, batch_shed = [], []
        pend = []
        for _ in range(FLOOD):
            submit(engine, "batch", 0, batch_lats, batch_shed, pend)
        for _ in range(PROD_REQS):
            submit(engine, "prod", 1, prod_lats, prod_shed, pend)
        drain(pend)
        if prod_shed:
            raise RuntimeError("protected tenant was shed")
        return (sorted(prod_lats)[max(0, int(len(prod_lats) * 0.99) - 1)],
                len(batch_shed), FLOOD)

    qos_eng = build(qos_cfg)
    fifo = build(None)
    try:
        # compile both buckets on both engines (2nd donated signature)
        for eng in (qos_eng, fifo):
            for _ in range(2):
                lats, sheds, pend = [], [], []
                submit(eng, "prod", 1, lats, sheds, pend)
                drain(pend)
        # unloaded: the protected tenant alone, sequentially
        unloaded = []
        for _ in range(PROD_REQS):
            lats, sheds, pend = [], [], []
            submit(qos_eng, "prod", 1, lats, sheds, pend)
            drain(pend)
            unloaded.extend(lats)
        unloaded_p99 = sorted(unloaded)[max(0,
                                            int(len(unloaded) * 0.99) - 1)]
        qos_p99, shed, sent = flood_run(qos_eng)
        fifo_p99, fifo_shed, _ = flood_run(fifo)
    finally:
        qos_eng.stop()
        fifo.stop()
        qos_eng.model.close()
        fifo.model.close()
    ratio = qos_p99 / max(unloaded_p99, 1e-9)
    vs_fifo = fifo_p99 / max(qos_p99, 1e-9)
    shed_rate = shed / sent
    print(f"# serving qos: flood={FLOOD} batch + {PROD_REQS} prod: "
          f"protected p99 {qos_p99 * 1e3:.1f}ms "
          f"(unloaded {unloaded_p99 * 1e3:.1f}ms, {ratio:.1f}x; "
          f"fifo {fifo_p99 * 1e3:.1f}ms, qos {vs_fifo:.1f}x better) | "
          f"batch shed {shed}/{sent} ({shed_rate:.0%}) "
          f"fifo shed {fifo_shed}", file=sys.stderr)
    print(json.dumps({
        "metric": "serving_qos_protected_p99_ms",
        "value": round(qos_p99 * 1e3, 3),
        "unit": "ms",
        "unloaded_ms": round(unloaded_p99 * 1e3, 3),
        "ratio_vs_unloaded": round(ratio, 3),
        "fifo_ms": round(fifo_p99 * 1e3, 3),
        "fifo_ratio": round(vs_fifo, 3),
    }))
    print(json.dumps({
        "metric": "serving_qos_shed_rate",
        "value": round(shed_rate, 3),
        "unit": "ratio",
        "shed": shed,
        "sent": sent,
        "fifo_shed": fifo_shed,
    }))
    return vs_fifo


def bench_native_lane():
    """The framework's native lane end to end: C++ bench client (the analog
    of the reference's C++ client binaries) against the C++ engine serving
    a registered native echo. QPS phase + payload sweep; returns the 1MB
    bandwidth (headline when available)."""
    from brpc_tpu.rpc.native_transport import (bench_echo_native,
                                               dataplane_available)

    if not dataplane_available():
        print("# native lane skipped: engine unavailable", file=sys.stderr)
        return None
    srv = _BenchServer("127.0.0.1:0", "--native", "--native_echo")
    headline = None
    try:
        host, port = srv.endpoint.rsplit(":", 1)
        port = int(port)
        dur = 400 if QUICK else 2000
        r = bench_echo_native(host, port, conns=16, depth=8, payload=16,
                              duration_ms=dur)
        print(f"# native lane multi_conn_echo: conns=16 depth=8 "
              f"qps={r['qps']:,.0f} p50={r['p50_us']:.0f}us "
              f"p99={r['p99_us']:.0f}us p999={r['p999_us']:.0f}us",
              file=sys.stderr)
        r = bench_echo_native(host, port, conns=1, depth=1, payload=16,
                              duration_ms=dur)
        print(f"# native lane ping_pong: qps={r['qps']:,.0f} "
              f"p50={r['p50_us']:.0f}us p99={r['p99_us']:.0f}us",
              file=sys.stderr)
        # all-C++ grpc: client h2 framing + server h2 + native echo — the
        # reference's http2_rpc_protocol.cpp lane, engine-resident
        r = bench_echo_native(host, port, conns=8, depth=32, payload=16,
                              duration_ms=dur, grpc=True)
        print(f"# native lane grpc/h2 (C++ client + C++ echo): 8x32 "
              f"qps={r['qps']:,.0f} p50={r['p50_us']:.0f}us",
              file=sys.stderr)
        print("# native lane sweep (C++ client, C++ echo service):",
              file=sys.stderr)
        for size, conns, depth in [(64, 8, 4), (4096, 8, 4), (65536, 8, 4),
                                   (1 << 20, 4, 4), (16 << 20, 2, 4)]:
            r = bench_echo_native(host, port, conns=conns, depth=depth,
                                  payload=size, duration_ms=dur)
            print(f"#   {size:>9}B x{conns}conns x{depth}deep: "
                  f"{r['gbps']:7.3f} GB/s  qps={r['qps']:9,.0f}  "
                  f"p50={r['p50_us']/1e3:8.2f}ms "
                  f"p99={r['p99_us']/1e3:8.2f}ms", file=sys.stderr)
            if size == HEADLINE_SIZE:
                headline = r["gbps"]
        return headline
    finally:
        srv.close()


def bench_native_tpu_lane():
    """The graft's native lane: TPUC shm tunnel (RDMA-endpoint analog)
    with both endpoints in the C++ engine — the rdma_performance analog
    with no kernel socket in the payload path."""
    from brpc_tpu.rpc.native_transport import (bench_echo_native,
                                               dataplane_available)

    if not dataplane_available():
        return None
    srv = _BenchServer("tpu://127.0.0.1:0/0", "--native", "--native_echo")
    headline = None
    try:
        host_port = srv.endpoint.split("//", 1)[1].rsplit("/", 1)[0]
        host, port = host_port.rsplit(":", 1)
        port = int(port)
        dur = 400 if QUICK else 2000
        print("# native tpu:// tunnel sweep (shm block pools, C++ both "
              "ends):", file=sys.stderr)
        # configs picked for a single shared core: extra conns only add
        # self-contention; pipeline depth does the overlapping (the
        # negotiated window lets 16MB messages pipeline too)
        for size, conns, depth in [(4096, 4, 4), (65536, 1, 4),
                                   (1 << 20, 1, 2), (16 << 20, 1, 2)]:
            r = bench_echo_native(host, port, conns=conns, depth=depth,
                                  payload=size, duration_ms=dur, tpu=True)
            print(f"#   {size:>9}B x{conns}conns x{depth}deep: "
                  f"{r['gbps']:7.3f} GB/s  qps={r['qps']:9,.0f}  "
                  f"p50={r['p50_us']/1e3:8.2f}ms "
                  f"p99={r['p99_us']/1e3:8.2f}ms", file=sys.stderr)
            if size == HEADLINE_SIZE:
                headline = r["gbps"]
        return headline
    finally:
        srv.close()


def _run_pipelined(stub, echo_pb2, payload: bytes, depth: int, total: int):
    """Async pipelined echoes (done callbacks re-issue): the client poller
    drives completions, no per-call thread wake — the shape the reference's
    own QPS benchmarks use (pipelined clients, depth > 1)."""
    done_ev = threading.Event()
    state = {"issued": 0, "completed": 0, "errors": 0}
    lats = []
    req = echo_pb2.EchoRequest(message="b", payload=payload)

    def make_done(t0):
        def done(cntl):
            lats.append(time.perf_counter() - t0)
            if cntl.failed():
                state["errors"] += 1
            state["completed"] += 1
            if state["issued"] < total:
                state["issued"] += 1
                stub.Echo(req, done=make_done(time.perf_counter()))
            elif state["completed"] >= total:
                done_ev.set()
        return done

    t_start = time.perf_counter()
    for _ in range(depth):
        state["issued"] += 1
        stub.Echo(req, done=make_done(time.perf_counter()))
    if not done_ev.wait(120):
        raise RuntimeError(
            f"pipelined bench stalled: {state['completed']}/{total}")
    wall = time.perf_counter() - t_start
    if state["errors"]:
        raise RuntimeError(f"{state['errors']} pipelined calls failed")
    lats.sort()
    return wall, lats


def bench_hybrid_native():
    """Python client/service code over the native engine (the hybrid lane
    most users run): sync-thread QPS, pipelined QPS, 1MB attachment echo."""
    from brpc_tpu.proto import echo_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Controller, Stub
    from brpc_tpu.rpc.native_transport import dataplane_available

    if not dataplane_available():
        return
    srv = _BenchServer("127.0.0.1:0", "--native", "--inline")
    try:
        # service capacity under a C++ load generator — the reference's own
        # methodology (its bench clients are C++, example/multi_threaded_
        # echo_c++/client.cpp); the service is FULL-POLICY Python user code
        from brpc_tpu.rpc.native_transport import bench_echo_native

        host, port = _host_port(srv.endpoint)
        dur = 1500 if QUICK else 4000
        r1 = bench_echo_native(host, port, conns=8, depth=1,
                               payload=16, duration_ms=dur)
        r2 = bench_echo_native(host, port, conns=8, depth=32,
                               payload=16, duration_ms=dur)
        print(f"# hybrid service capacity (C++ load, py full-policy "
              f"service): sync-8 qps={r1['qps']:,.0f} "
              f"p50={r1['p50_us']:.0f}us | pipelined 8x32 "
              f"qps={r2['qps']:,.0f} p50={r2['p50_us']:.0f}us",
              file=sys.stderr)
        # grpc over the native h2 data plane (VERDICT r4 #5): the SAME
        # listener, the SAME Python service — requests arrive as h2
        # frames, the engine does HPACK + framing + flow control, the
        # service sees the same EV_REQUEST fast path. Target: >= 0.5x the
        # std-protocol fast-path QPS.
        g1 = bench_echo_native(host, port, conns=8, depth=1,
                               payload=16, duration_ms=dur, grpc=True)
        g2 = bench_echo_native(host, port, conns=8, depth=32,
                               payload=16, duration_ms=dur, grpc=True)
        print(f"# grpc/h2 NATIVE data plane (same py service): sync-8 "
              f"qps={g1['qps']:,.0f} p50={g1['p50_us']:.0f}us | "
              f"pipelined 8x32 qps={g2['qps']:,.0f} | grpc/std = "
              f"{g1['qps']/max(r1['qps'],1):.0%} sync, "
              f"{g2['qps']/max(r2['qps'],1):.0%} pipelined",
              file=sys.stderr)
        # NULL-SERVICE CONTROL (VERDICT r4 #2a): same C++ load generator,
        # same poll loop, but the Python body is a raw body echo with the
        # policy machinery OFF — the process-pair interpreter-crossing
        # ceiling on this 1-core box. full-policy/control is the
        # framework's own share.
        srv0 = _BenchServer("127.0.0.1:0", "--native", "--null")
        try:
            h0, p0 = _host_port(srv0.endpoint)
            c1 = bench_echo_native(h0, p0, conns=8, depth=1,
                                   payload=16, duration_ms=dur)
            c2 = bench_echo_native(h0, p0, conns=8, depth=32,
                                   payload=16, duration_ms=dur)
            print(f"# NULL-SERVICE CONTROL (py body = raw echo, policy "
                  f"off): sync-8 qps={c1['qps']:,.0f} "
                  f"p50={c1['p50_us']:.0f}us | pipelined 8x32 "
                  f"qps={c2['qps']:,.0f} | full-policy/control = "
                  f"{r1['qps']/max(c1['qps'],1):.0%} sync, "
                  f"{r2['qps']/max(c2['qps'],1):.0%} pipelined",
                  file=sys.stderr)
        finally:
            srv0.close()
        # VERDICT r4 #2b lever, on the record: subinterpreter dispatch
        # cost on this box (nproc=1 -> any dispatch is pure loss)
        import subprocess as _sp

        try:
            out = _sp.run([sys.executable,
                           os.path.join(REPO, "tools",
                                        "subinterp_probe.py")],
                          capture_output=True, text=True, timeout=120)
            for line in out.stdout.splitlines():
                if line.startswith("#"):
                    print(line, file=sys.stderr)
        except _sp.SubprocessError as e:
            print(f"# subinterp probe failed: {type(e).__name__}",
                  file=sys.stderr)
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=30000,
                                    native_transport=True))
        ch.init(srv.endpoint)
        stub = Stub(ch, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
        _run_calls(stub, echo_pb2, b"w" * 16, 4, 25)  # warmup
        calls = 40 if QUICK else 400
        wall, lats = _run_calls(stub, echo_pb2, b"x" * 16, QPS_THREADS, calls)
        print(f"# hybrid lane (py client+service, native engine; one core "
              f"carries BOTH processes + engines): "
              f"qps={len(lats)/wall:,.0f} "
              f"p50={_percentile(lats,0.5)*1e6:.0f}us "
              f"p99={_percentile(lats,0.99)*1e6:.0f}us", file=sys.stderr)
        # pipelined async client against the same full-policy Python service
        chp = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=30000,
                                     native_transport=True,
                                     done_inline=True))
        chp.init(srv.endpoint)
        stubp = Stub(chp, echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
        _run_pipelined(stubp, echo_pb2, b"w" * 16, 8, 200)  # warmup
        total = 2000 if QUICK else 40000
        wall, lats = _run_pipelined(stubp, echo_pb2, b"x" * 16, 32, total)
        print(f"# hybrid lane pipelined (depth=32, done_inline, "
              f"usercode_inline): qps={len(lats)/wall:,.0f} "
              f"p50={_percentile(lats,0.5)*1e6:.0f}us "
              f"p99={_percentile(lats,0.99)*1e6:.0f}us", file=sys.stderr)
        # 1MB attachment echo, single thread (GIL makes threads moot here)
        att = b"\xab" * (1 << 20)
        lats = []
        n = 8 if QUICK else 60
        for _ in range(n):
            cntl = Controller()
            cntl.request_attachment = att
            t0 = time.perf_counter()
            stub.Echo(echo_pb2.EchoRequest(message="b"), controller=cntl)
            lats.append(time.perf_counter() - t0)
            assert len(cntl.response_attachment) == len(att)
        lats.sort()
        gbps = 2 * len(att) / lats[len(lats) // 2] / 1e9
        print(f"# hybrid lane 1MB attachment echo: p50="
              f"{lats[len(lats)//2]*1e3:.2f}ms ({gbps:.3f} GB/s)",
              file=sys.stderr)
        # connection types at 1MB x 4 threads (reference: pooled conns are
        # how single-peer bulk throughput scales, channel.h:90-95)
        def _att_echo_threads(ctype):
            chx = Channel(ChannelOptions(protocol="trpc_std",
                                         timeout_ms=30000,
                                         native_transport=True,
                                         connection_type=ctype))
            chx.init(srv.endpoint)
            stubx = Stub(chx, echo_pb2.DESCRIPTOR.services_by_name[
                "EchoService"])
            per = 4 if QUICK else 20
            errs = []
            barrier = threading.Barrier(5)

            def worker():
                barrier.wait()
                try:
                    for _ in range(per):
                        c = Controller()
                        c.request_attachment = att
                        stubx.Echo(echo_pb2.EchoRequest(message="p"),
                                   controller=c)
                        assert len(c.response_attachment) == len(att)
                except BaseException as e:
                    errs.append(e)

            ts = [threading.Thread(target=worker) for _ in range(4)]
            for t in ts:
                t.start()
            barrier.wait()
            t0 = time.perf_counter()
            for t in ts:
                t.join()
            if errs:
                raise errs[0]
            wall = time.perf_counter() - t0
            return 2 * len(att) * 4 * per / wall / 1e9

        g_single = _att_echo_threads("single")
        g_pooled = _att_echo_threads("pooled")
        print(f"# hybrid 1MBx4thr: single={g_single:.3f} GB/s  "
              f"pooled={g_pooled:.3f} GB/s  (single-core floor: ~1ms/call "
              f"of kernel loopback copies timeshares the same CPU "
              f"regardless of conn count — the reference's 3x multi-conn "
              f"scaling is a multi-core phenomenon; docs/round4-notes.md)",
              file=sys.stderr)
    finally:
        srv.close()


def bench_device_lane():
    """Device-resident RPC data plane (tpu/device_lane.py): the control
    plane rides the shm tunnel, payload bytes live in HBM and move
    on-device (docs/round3-notes.md — on this environment host<->HBM is
    tunnel-capped at ~0.65 GB/s, so the honest ICI-analog keeps data
    device-side). The serving CHILD owns the chip; this process never
    imports jax here."""
    from brpc_tpu.proto import device_lane_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Controller, Stub
    from brpc_tpu.rpc.native_transport import dataplane_available

    if not dataplane_available():
        return None
    srv = _BenchServer("tpu://127.0.0.1:0/0", "--native", "--device")
    try:
        dsvc = device_lane_pb2.DESCRIPTOR.services_by_name[
            "DeviceDataService"]
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=120000,
                                    native_transport=True,
                                    done_inline=True))
        ch.init(srv.endpoint)
        stub = Stub(ch, dsvc)
        # host->HBM staging through the full RPC stack (VERDICT r3 #5),
        # measured BEFORE any Get: a single device->host fetch through
        # this environment's ~5 MB/s down-wire degrades the server's PJRT
        # stream to ~0.22 GB/s for the rest of the session (measured;
        # docs/round4-notes.md). The relay also warms per transfer shape
        # over its first ~16 transfers (0.08 -> 0.65 GB/s), so warm
        # first like the kernels warm their first compile.
        put_mb = 1
        puts = 4 if QUICK else 16
        warm_puts = 4 if QUICK else 16  # the per-shape warm curve length
        payload = b"\xab" * (put_mb << 20)
        for _ in range(warm_puts):
            cw = Controller()
            cw.request_attachment = payload
            stub.Put(device_lane_pb2.DeviceHandle(), controller=cw)
        t0 = time.perf_counter()
        for _ in range(puts):
            c = Controller()
            c.request_attachment = payload
            stub.Put(device_lane_pb2.DeviceHandle(), controller=c)
        put_gbps = puts * put_mb / 1024 / (time.perf_counter() - t0)
        # correctness probe AFTER the bandwidth phase: content survives
        # HBM residency and comes back intact through Get
        blob = bytes(range(256)) * 256  # 64KB
        cntl = Controller()
        cntl.request_attachment = blob
        small = stub.Put(device_lane_pb2.DeviceHandle(), controller=cntl)
        h2 = stub.Copy(
            device_lane_pb2.DeviceHandle(handle=small.handle)).handle
        cg = Controller()
        stub.Get(device_lane_pb2.DeviceHandle(handle=h2), controller=cg)
        assert cg.response_attachment == blob, "device roundtrip corrupt"
        # on-device data plane: Pump RPCs run the Pallas echo loop over an
        # 8MB HBM-resident array; each returns a DEPENDENT checksum so the
        # passes verifiably executed (block_until_ready lies on the axon
        # relay — docs/round3-notes.md)
        copy_mb = 8
        c = Controller()
        c.request_attachment = b"\xcd" * (copy_mb << 20)
        src = stub.Put(device_lane_pb2.DeviceHandle(), controller=c).handle
        # warmup compiles the pallas loop for this shape
        warm = stub.Pump(device_lane_pb2.PumpRequest(handle=src, rounds=1))
        rounds = 128 if QUICK else 1024
        n_pumps = 4 if QUICK else 8
        moved = 0
        t0 = time.perf_counter()
        for _ in range(n_pumps):
            r = stub.Pump(device_lane_pb2.PumpRequest(handle=src,
                                                      rounds=rounds))
            assert r.checksum == warm.checksum  # same data, same scalar
            moved += r.moved_bytes
        wall = time.perf_counter() - t0
        hbm_gbps = moved / wall / 1e9
        # op-rate probe: async-dispatch Copy RPC round trips (the rate the
        # control plane can drive device ops; completion is async)
        n_copies = 64 if QUICK else 256
        req = device_lane_pb2.DeviceHandle(handle=src, nbytes=-1)
        done_ev = threading.Event()
        state = {"issued": 0, "done": 0}

        def done(cntl2):
            state["done"] += 1
            if state["issued"] < n_copies:
                state["issued"] += 1
                stub.Copy(req, done=done)
            elif state["done"] >= n_copies:
                done_ev.set()

        t0 = time.perf_counter()
        for _ in range(16):
            state["issued"] += 1
            stub.Copy(req, done=done)
        if not done_ev.wait(180):
            raise RuntimeError(f"device copy bench stalled: {state}")
        copy_rate = n_copies / (time.perf_counter() - t0)
        stub.Stats(device_lane_pb2.DeviceStatsRequest(fence=True))
        print(f"# device lane (RPC control plane over shm tunnel, data in "
              f"HBM):", file=sys.stderr)
        print(f"#   host->HBM Put {put_mb}MB x{puts} (warmed): "
              f"{put_gbps:6.3f} GB/s "
              f"(env ceiling ~0.65; docs/round3-notes.md)", file=sys.stderr)
        print(f"#   NOTE: Get (HBM->host) is excluded by design — this "
              f"environment's device->host wire measures ~5 MB/s "
              f"(docs/round3-notes.md); device-resident payloads are "
              f"consumed ON-DEVICE (Copy/Pump), not fetched.",
              file=sys.stderr)
        print(f"#   on-device Pump {copy_mb}MB x{rounds}rounds x{n_pumps}: "
              f"{hbm_gbps:8.1f} GB/s HBM moved (checksum-verified)",
              file=sys.stderr)
        print(f"#   Copy op-rate (async dispatch): {copy_rate:,.0f} "
              f"device-op RPC/s", file=sys.stderr)
        # streaming into HBM (VERDICT r4 #6, tpu/device_stream.py): the
        # stream's DATA frames carry 16-byte handle records; each record
        # is consumed as a 1024-round on-device pump; the credit window
        # counts HBM bytes. Completion = the stream's own cumulative-
        # consumed feedback reaching the produced total (the flow-control
        # protocol IS the completion signal).
        from brpc_tpu.rpc.stream import get_stream, stream_close
        from brpc_tpu.tpu.device_stream import (open_device_stream,
                                                send_handle)

        n_recs = 2 if QUICK else 8
        sid = open_device_stream(
            srv.endpoint, window_bytes=4 * (copy_mb << 20),
            channel_options=ChannelOptions(protocol="trpc_std",
                                           timeout_ms=120000,
                                           native_transport=True))
        blk = copy_mb << 20
        t0 = time.perf_counter()
        for _ in range(n_recs):
            rc = send_handle(sid, src, blk, timeout=120)
            assert rc == 0, f"send_handle rc={rc}"
        target = n_recs * blk
        st = get_stream(sid)
        deadline = time.time() + 300
        while st._remote_consumed < target and time.time() < deadline:
            time.sleep(0.005)
        wall = time.perf_counter() - t0
        stream_close(sid)
        assert st._remote_consumed >= target, "stream credits never returned"
        stream_gbps = n_recs * (2.0 * blk * 1024) / wall / 1e9
        print(f"#   STREAM->HBM {copy_mb}MB-block records x{n_recs} "
              f"(1024-round pump per record, credit window in HBM "
              f"bytes): {stream_gbps:8.1f} GB/s HBM moved "
              f"({stream_gbps/max(hbm_gbps,1e-9)*100:.0f}% of the Pump "
              f"lane)", file=sys.stderr)
        return hbm_gbps
    finally:
        srv.close()


def bench_device_probe():
    """On-chip HBM echo ceiling (Pallas copy loop) — stderr diagnostic.
    Marginal-cost slope isolates per-round device time from the tunnel's
    fixed host<->device sync cost on this environment."""
    import jax
    import jax.numpy as jnp  # noqa: F401

    from brpc_tpu.tpu.bench_kernels import echo_loop_probe

    payload = 64 << 20
    interpret = jax.default_backend() != "tpu"
    x = jnp.ones((payload // 4 // 2048, 2048), dtype=jnp.int32)
    times = {}
    for rounds in (16, 1024):
        v = float(echo_loop_probe(x, rounds=rounds, interpret=interpret))
        assert v == 2.0, v
        best = float("inf")
        for _ in range(3):
            t0 = time.perf_counter()
            float(echo_loop_probe(x, rounds=rounds, interpret=interpret))
            best = min(best, time.perf_counter() - t0)
        times[rounds] = best
    marginal = (times[1024] - times[16]) / (1024 - 16)
    gbps = (2 * payload) / marginal / 1e9
    dev = jax.devices()[0]
    print(f"# device datapath ceiling ({dev.platform}:{dev.id}, 64MB HBM "
          f"echo): {gbps:.1f} GB/s", file=sys.stderr)


def _task_cpu_s(native_tid: int) -> float:
    """One thread's OS CPU seconds (utime+stime) from /proc; 0.0 when the
    thread is gone or the platform has no /proc."""
    try:
        with open(f"/proc/self/task/{native_tid}/stat") as f:
            parts = f.read().rsplit(")", 1)[1].split()
        return (int(parts[11]) + int(parts[12])) / os.sysconf("SC_CLK_TCK")
    except (OSError, ValueError, IndexError):
        return 0.0


def bench_profile():
    """``bench.py --profile``: the echo lane under the whole-process
    sampler. Server and client live in THIS process (one sampler sees
    both sides of the GIL), a ProfileSession wraps the measured loop, and
    the output is (a) the folded-stack artifact (BENCH_PROFILE_OUT, for
    tools/flame_view.py + tools/prof_diff.py) and (b) the per-call CPU
    budget table: each thread's OS-measured CPU (time.thread_time for the
    client workers, /proc task stats for the framework threads)
    distributed over span phases in proportion to that thread's
    cpu-classified samples, then checked against time.process_time() —
    the check fails if thread tracking loses part of the process."""
    from brpc_tpu.profiling.sampler import ProfileSession
    from brpc_tpu.proto import echo_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Server, Service, Stub

    ECHO = echo_pb2.DESCRIPTOR.services_by_name["EchoService"]

    class EchoImpl(Service):
        DESCRIPTOR = ECHO

        def Echo(self, cntl, request, done):
            return echo_pb2.EchoResponse(message=request.message,
                                         payload=request.payload)

    out_path = os.environ.get(
        "BENCH_PROFILE_OUT", os.path.join(REPO, "BENCH_PROFILE.folded"))
    hz = 200.0
    threads = 4
    calls = 300 if QUICK else 2500
    server = Server().add_service(EchoImpl()).start("tpu://127.0.0.1:0/0")
    try:
        ch = Channel(ChannelOptions(protocol="trpc_std", timeout_ms=30000))
        ch.init(str(server.listen_endpoint()))
        stub = Stub(ch, ECHO)
        payload = b"\xab" * 4096
        _run_calls(stub, echo_pb2, payload, threads, 30)  # warmup

        # like _run_calls, but each worker reports its own thread CPU
        # (the workers are gone from /proc by the time the session stops)
        lat_per_thread = [[] for _ in range(threads)]
        worker_cpu = {}  # thread ident -> thread_time seconds
        failures = []
        barrier = threading.Barrier(threads + 1)

        def worker(idx):
            req = echo_pb2.EchoRequest(message="b", payload=payload)
            lats = lat_per_thread[idx]
            barrier.wait()
            try:
                for _ in range(calls):
                    t0 = time.perf_counter()
                    resp = stub.Echo(req)
                    lats.append(time.perf_counter() - t0)
                    assert len(resp.payload) == len(payload)
            except BaseException as e:
                failures.append(e)
            finally:
                worker_cpu[threading.get_ident()] = time.thread_time()

        ts = [threading.Thread(target=worker, args=(i,),
                               name=f"bench-profile-{i}")
              for i in range(threads)]
        cpu_base = {t.native_id: _task_cpu_s(t.native_id)
                    for t in threading.enumerate() if t.native_id}
        sess = ProfileSession(hz=hz, budget=False,
                              track_threads=True).start()
        proc0 = time.process_time()
        for t in ts:
            t.start()
        barrier.wait()
        t0 = time.perf_counter()
        for t in ts:
            t.join()
        wall = time.perf_counter() - t0
        proc_cpu_s = time.process_time() - proc0
        prof = sess.stop()
        if failures:
            raise RuntimeError(f"{len(failures)}/{threads} profile workers "
                               f"failed; first: {failures[0]!r}")
        lats = sorted(x for l in lat_per_thread for x in l)
    finally:
        server.stop()
        server.join(timeout=2)

    n = threads * calls
    measured_us = proc_cpu_s / n * 1e6
    # per-thread OS CPU, distributed over phases by that thread's own
    # cpu-classified sample mix (all samples when a thread never showed a
    # cpu-classified leaf)
    phase_cpu_s = {}
    covered_cpu_s = 0.0
    for tid, phases in prof.thread_counts.items():
        if tid in worker_cpu:
            cpu = worker_cpu[tid]
        else:
            ntid = prof.thread_native.get(tid, 0)
            cpu = _task_cpu_s(ntid) - cpu_base.get(ntid, 0.0) \
                if ntid else 0.0
        if cpu <= 0:
            continue
        covered_cpu_s += cpu
        weights = {ph: c for ph, (w, c) in phases.items() if c}
        if not weights:
            weights = {ph: w for ph, (w, c) in phases.items()}
        wsum = sum(weights.values())
        for ph, wgt in weights.items():
            phase_cpu_s[ph] = phase_cpu_s.get(ph, 0.0) + cpu * wgt / wsum

    print(f"# profile lane (in-process tpu:// echo, 4KB, whole-process "
          f"sampler @{hz:.0f}hz): calls={n} wall={wall:.2f}s "
          f"qps={n / wall:,.0f} p50={_percentile(lats, 0.5) * 1e6:.0f}us",
          file=sys.stderr)
    print("# per-call CPU budget by phase (per-thread OS CPU distributed "
          "by sample mix):", file=sys.stderr)
    attributed_us = 0.0
    for phase, cpu_s in sorted(phase_cpu_s.items(), key=lambda kv: -kv[1]):
        us = cpu_s / n * 1e6
        attributed_us += us
        label = phase if phase != "-" else "- (unmarked: client+framework)"
        print(f"#   {label:<34} {us:8.1f} us/call", file=sys.stderr)
    ratio = attributed_us / max(measured_us, 1e-9)
    print(f"# profile budget: attributed={attributed_us:.1f} us/call  "
          f"measured(process_time)={measured_us:.1f} us/call  "
          f"ratio={ratio:.2f}", file=sys.stderr)
    print(f"# profile sampler overhead: "
          f"{100.0 * prof.sample_time_s / max(wall, 1e-9):.3f}% of wall "
          f"({prof.ticks} ticks, {prof.overruns} overruns)",
          file=sys.stderr)
    lines = prof.folded_lines()
    with open(out_path, "w", encoding="utf-8") as fh:
        fh.write("\n".join(lines) + "\n")
    print(f"# profile artifact: {out_path} ({len(lines)} stacks, "
          f"{prof.samples} samples)", file=sys.stderr)
    print(json.dumps({
        "metric": "profile_attributed_cpu_ratio",
        "value": round(ratio, 3),
        "unit": "attributed/measured",
        "artifact": out_path,
    }))


def bench_shard_sweep(spec: str) -> None:
    """``bench.py --workers 0,1,2``: the 64B tpu:// echo QPS per shard
    worker count. Emits one ``echo_64b_qps_w<N>`` JSON line per N plus
    ``shard_scaling_efficiency`` = QPS(maxN) / (maxN x QPS(1)) when the
    sweep includes both 1 and a larger N (BENCH_r06). On a 1-core box the
    efficiency is expected << 1 (the workers time-slice one core); the
    metric is bench-gated, not asserted."""
    from brpc_tpu.proto import echo_pb2
    from brpc_tpu.rpc import Channel, ChannelOptions, Stub

    ns = [int(x) for x in spec.split(",") if x.strip() != ""]
    qps_by_n = {}
    for n in ns:
        extra = ("--shard-workers", str(n)) if n > 0 else ()
        srv = _BenchServer("tpu://127.0.0.1:0/0", *extra)
        try:
            ch = Channel(ChannelOptions(protocol="trpc_std",
                                        timeout_ms=60000))
            ch.init(srv.endpoint)
            stub = Stub(ch,
                        echo_pb2.DESCRIPTOR.services_by_name["EchoService"])
            _run_calls(stub, echo_pb2, b"w" * 64, 2, 20)  # warmup
            wall, lats = _run_calls(stub, echo_pb2, b"\xab" * 64,
                                    QPS_THREADS, 60 if QUICK else 600)
            qps = len(lats) / wall
            qps_by_n[n] = qps
            print(f"# shard sweep workers={n}: qps={qps:9,.0f} "
                  f"p50={_percentile(lats, 0.5)*1e3:.2f}ms "
                  f"p99={_percentile(lats, 0.99)*1e3:.2f}ms",
                  file=sys.stderr)
        finally:
            srv.close()
    for n, qps in qps_by_n.items():
        print(json.dumps({
            "metric": f"echo_64b_qps_w{n}",
            "value": round(qps, 1),
            "unit": "qps",
            "vs_baseline": round(qps / BASELINE_64B_QPS, 3),
        }))
    top = max((n for n in qps_by_n if n > 0), default=0)
    if top > 1 and 1 in qps_by_n and qps_by_n[1] > 0:
        eff = qps_by_n[top] / (top * qps_by_n[1])
        print(json.dumps({
            "metric": "shard_scaling_efficiency",
            "value": round(eff, 3),
            "unit": "ratio",
            "workers": top,
        }))


def main() -> None:
    if "--profile" in sys.argv[1:]:
        bench_profile()
        return
    if "--workers" in sys.argv[1:]:
        i = sys.argv.index("--workers")
        spec = sys.argv[i + 1] if i + 1 < len(sys.argv) else "0,1,2"
        bench_shard_sweep(spec)
        return
    if _phase_enabled("qps"):
        bench_multi_threaded_echo()
    native_1mb = tpu_1mb = None
    if _phase_enabled("native"):
        native_1mb = bench_native_lane()
        tpu_1mb = bench_native_tpu_lane()
    if native_1mb is not None and tpu_1mb is not None:
        native_1mb = max(native_1mb, tpu_1mb)
    if _phase_enabled("hybrid"):
        bench_hybrid_native()
    if _phase_enabled("batch"):
        bench_batch_lane()
    if _phase_enabled("serving"):
        bench_serving_lane()
    if _phase_enabled("spec"):
        bench_spec_lane()
    if _phase_enabled("qos"):
        bench_qos_lane()
    py_1mb = py_64b_qps = series_pct = None
    if _phase_enabled("shm"):
        py_1mb, py_64b_qps = bench_tpu_sweep()
        series_pct = measure_series_overhead()
        print(f"# vars series sampler overhead: {series_pct:.4f}% of the "
              f"1s tick budget (one ring sweep over this process's "
              f"exposed vars)", file=sys.stderr)
    if os.environ.get("BENCH_SKIP_DEVICE") != "1" and \
            _phase_enabled("device"):
        try:
            bench_device_lane()
        except Exception as e:  # diagnostics must never sink the bench
            print(f"# device lane skipped: {e}", file=sys.stderr)
        if not QUICK:
            try:
                # kernel numbers on the chip (flash/rmsnorm/train-step
                # MFU) — subprocess owns the chip (tests_hw's bench half)
                r = subprocess.run(
                    [sys.executable, os.path.join(REPO, "tools",
                                                  "kernel_bench.py")],
                    capture_output=True, text=True, timeout=560)
                for line in r.stdout.splitlines():
                    if line.startswith("#"):
                        print(line, file=sys.stderr)
                if r.returncode != 0:
                    tail = (r.stderr or "").strip().splitlines()[-3:]
                    print(f"# kernel bench FAILED rc={r.returncode}: "
                          f"{' | '.join(tail)}", file=sys.stderr)
            except Exception as e:
                print(f"# kernel bench skipped: {e}", file=sys.stderr)
    if os.environ.get("BENCH_SKIP_DEVICE") != "1" and not QUICK \
            and _phase_enabled("device"):
        try:
            bench_device_probe()
        except Exception as e:  # diagnostics must never sink the bench
            print(f"# device probe skipped: {e}", file=sys.stderr)
    # headline: the framework's fastest supported lane (native when built,
    # like the reference's C++ stack; Python tpu:// sweep otherwise);
    # omitted when neither lane ran (e.g. BENCH_PHASES=batch|serving)
    headline = native_1mb if native_1mb is not None else py_1mb
    if headline is not None:
        print(json.dumps({
            "metric": "echo_1mb_framework_bandwidth",
            "value": round(headline, 3),
            "unit": "GB/s",
            "vs_baseline": round(headline / BASELINE_GBPS, 3),
        }))
    # small-message summary line: the Python tpu:// sweep's 64B row (the
    # fastpath stack's target metric; vs_baseline is against BENCH_r03)
    if py_64b_qps:
        print(json.dumps({
            "metric": "echo_64b_qps",
            "value": round(py_64b_qps, 1),
            "unit": "qps",
            "vs_baseline": round(py_64b_qps / BASELINE_64B_QPS, 3),
        }))
    if series_pct is not None:
        print(json.dumps({
            "metric": "vars_series_overhead_pct",
            "value": round(series_pct, 4),
            "unit": "%",
        }))


if __name__ == "__main__":
    main()
