"""flags — runtime-reloadable configuration flags (gflags equivalent).

Rebuild of the reference's flag system: ~180 ``DEFINE_*`` gflags across
src/brpc, with **reloadable** flags registered through a validator
(``reloadable_flags.h:43-60``) that can be PUT at runtime via the
``/flags/<name>?setvalue=`` builtin service (``builtin/flags_service.cpp``),
and every flag surfaced as a metrics variable (``bvar/gflag.cpp``).

Design notes (not a port): a Flag is a typed cell with an optional
validator; ``set_from_string`` parses + validates + swaps atomically under
the registry lock. Modules read flags with ``flags.get(name)`` or by holding
the Flag object — reads are a single attribute load, no lock (Python object
assignment is atomic), matching the reference's relaxed-read semantics.
"""

from __future__ import annotations

import threading
from typing import Any, Callable, Dict, List, Optional

_BOOL_TRUE = {"true", "1", "yes", "on", "t", "y"}
_BOOL_FALSE = {"false", "0", "no", "off", "f", "n"}


class FlagError(Exception):
    pass


class Flag:
    """One typed, named configuration cell."""

    __slots__ = ("name", "value", "default", "type", "help",
                 "validator", "reloadable")

    def __init__(self, name: str, default: Any, help: str = "",
                 validator: Optional[Callable[[Any], bool]] = None,
                 reloadable: bool = False, type_: Optional[type] = None):
        self.name = name
        self.default = default
        self.value = default
        self.type = type_ or type(default)
        self.help = help
        self.validator = validator
        self.reloadable = reloadable or validator is not None

    # ------------------------------------------------------------------ parse
    def parse(self, text: str) -> Any:
        if self.type is bool:
            low = text.strip().lower()
            if low in _BOOL_TRUE:
                return True
            if low in _BOOL_FALSE:
                return False
            raise FlagError(f"{self.name}: not a bool: {text!r}")
        try:
            if self.type is int:
                return int(text, 0)
            if self.type is float:
                return float(text)
            if self.type is str:
                return text
        except ValueError as e:
            raise FlagError(f"{self.name}: {e}") from None
        raise FlagError(f"{self.name}: unsupported flag type {self.type}")

    def set(self, value: Any) -> None:
        """Validate + swap. Raises FlagError if rejected."""
        if self.type is not type(value):
            # allow int->float promotion only
            if self.type is float and isinstance(value, int):
                value = float(value)
            else:
                raise FlagError(
                    f"{self.name}: expected {self.type.__name__}, "
                    f"got {type(value).__name__}")
        if self.validator is not None and not self.validator(value):
            raise FlagError(f"{self.name}: value {value!r} rejected by validator")
        self.value = value

    def set_from_string(self, text: str) -> None:
        self.set(self.parse(text))


_registry: Dict[str, Flag] = {}
_lock = threading.Lock()


def define(name: str, default: Any, help: str = "",
           validator: Optional[Callable[[Any], bool]] = None,
           reloadable: bool = False) -> Flag:
    """DEFINE_* equivalent. A validator makes the flag reloadable (the
    reference's RegisterFlagValidatorOrDie contract)."""
    with _lock:
        if name in _registry:
            raise FlagError(f"flag {name!r} already defined")
        f = Flag(name, default, help, validator, reloadable)
        _registry[name] = f
        return f


def get(name: str) -> Any:
    f = _registry.get(name)
    if f is None:
        raise FlagError(f"unknown flag {name!r}")
    return f.value


def set_flag(name: str, text_or_value) -> None:
    """Runtime update — the /flags/<name>?setvalue= path. Only reloadable
    flags may change after startup."""
    with _lock:
        f = _registry.get(name)
        if f is None:
            raise FlagError(f"unknown flag {name!r}")
        if not f.reloadable:
            raise FlagError(f"flag {name!r} is not reloadable")
        if isinstance(text_or_value, str) and f.type is not str:
            f.set_from_string(text_or_value)
        else:
            f.set(text_or_value)


def find(name: str) -> Optional[Flag]:
    return _registry.get(name)


def list_flags() -> List[Flag]:
    with _lock:
        return sorted(_registry.values(), key=lambda f: f.name)


def reset_for_test() -> None:
    with _lock:
        _registry.clear()


# ---------------------------------------------------------------- core flags
# (defined here so every subsystem shares one registry; subsystems may also
# define their own at import)
def _positive(v) -> bool:
    return v > 0


def _non_negative(v) -> bool:
    return v >= 0


health_check_interval_s = define(
    "health_check_interval_s", 3.0,
    "seconds between re-probes of a failed server", validator=_positive)
circuit_breaker_enabled = define(
    "circuit_breaker_enabled", True,
    "isolate error-rate outlier nodes", reloadable=True)
max_body_size = define(
    "max_body_size", 1 << 31,
    "largest accepted wire message", validator=_positive)
idle_timeout_s = define(
    "idle_timeout_s", -1.0,
    "close connections idle longer than this (<=0 disables)",
    reloadable=True)
log_error_text = define(
    "log_error_text", False,
    "log every failed RPC's error text", reloadable=True)
rpcz_sample_ratio = define(
    "rpcz_sample_ratio", 1.0,
    "fraction of RPCs recorded by rpcz", validator=lambda v: 0 <= v <= 1)
rpc_dump_ratio = define(
    "rpc_dump_ratio", 0.0,
    "fraction of requests sampled to dump files",
    validator=lambda v: 0 <= v <= 1)
rpc_dump_max_per_sec = define(
    "rpc_dump_max_per_sec", 0,
    "hard cap on dump records written per second, enforced by a "
    "monotonic-clock token bucket after the ratio draw (0 = no cap "
    "beyond the shared collector budget)", validator=_non_negative)
span_export_path = define(
    "span_export_path", "",
    "append every finished span to this file as one OTLP-shaped JSON "
    "line (trace/export.py); empty disables export", reloadable=True)
event_dispatcher_num = define(
    "event_dispatcher_num", 2,
    "number of IO event loops sockets are spread across "
    "(reference event_dispatcher.cpp:32)", validator=_positive)
inline_cut_max_bytes = define(
    "inline_cut_max_bytes", 128 * 1024,
    "read bursts beyond this are parsed on a fiber worker instead of the "
    "event loop (reference ProcessEvent handoff, socket.cpp:2256)",
    validator=_positive)
stream_body_min_bytes = define(
    "stream_body_min_bytes", 256 * 1024,
    "message bodies at least this large are consumed incrementally through "
    "a pending-body cursor once their header is cracked, so transport "
    "flow-control credits return mid-message", reloadable=True,
    validator=_positive)
tpu_tunnel_auto_heal = define(
    "tpu_tunnel_auto_heal", True,
    "re-establish a failed tpu:// tunnel in the background (fresh HELLO "
    "handshake under a new window generation) instead of waiting for the "
    "next caller to re-dial", reloadable=True)
tpu_reconnect_backoff_ms = define(
    "tpu_reconnect_backoff_ms", 50,
    "initial delay between tpu:// re-handshake attempts; doubles per "
    "failure up to tpu_reconnect_backoff_max_ms", validator=_positive)
tpu_reconnect_backoff_max_ms = define(
    "tpu_reconnect_backoff_max_ms", 2000,
    "ceiling for the tpu:// reconnect exponential backoff",
    validator=_positive)
tpu_reconnect_window_s = define(
    "tpu_reconnect_window_s", 10.0,
    "total time budget a background tunnel heal keeps retrying before "
    "giving up (the next RPC or health probe re-dials on demand)",
    validator=_positive)
tpu_doorbell_coalesce_us = define(
    "tpu_doorbell_coalesce_us", 200,
    "coalesce FT_ACK credit returns and small response frames produced "
    "inside one poll-batch round into a single ctrl-socket doorbell, "
    "bounded by this many microseconds of added hold latency "
    "(0 = legacy per-message writes)", validator=_non_negative)
rtc_enable = define(
    "rtc_enable", True,
    "run-to-completion dispatch: execute cheap, small-payload methods "
    "directly on the cut-loop thread instead of the queue->worker hop",
    reloadable=True)
rtc_budget_us = define(
    "rtc_budget_us", 2000,
    "a run-to-completion handler exceeding this wall budget demotes its "
    "method back to queued dispatch (sticky)", validator=_positive)
rtc_cheap_us = define(
    "rtc_cheap_us", 1000,
    "auto-classify a method as inline-eligible once its observed "
    "execution-time EMA sits below this", validator=_positive)
rtc_max_body = define(
    "rtc_max_body", 16 * 1024,
    "only messages with bodies at most this large (and no attachment) "
    "ride the run-to-completion path", validator=_positive)
tpu_shard_workers = define(
    "tpu_shard_workers", 0,
    "spread the Python service lane over this many worker OS processes "
    "(cid-sharded dispatch plane); 0 disables sharding entirely — the "
    "in-process dispatch path is untouched", validator=_non_negative)
tpu_shard_rebalance_pct = define(
    "tpu_shard_rebalance_pct", 60,
    "reclaim lease credits from a sibling worker only when its idle "
    "share exceeds this percent of a fair per-worker split (lower = "
    "eager rebalancing, higher = less reclaim churn)",
    validator=lambda v: 0 < v <= 100)
tpu_shard_respawn_backoff_ms = define(
    "tpu_shard_respawn_backoff_ms", 50,
    "base backoff before respawning a dead shard worker (multiplied by "
    "the slot's respawn count)", validator=_positive)
tpu_shard_respawn_max = define(
    "tpu_shard_respawn_max", 3,
    "stop respawning a worker slot after this many deaths; its cids "
    "then route to in-process fallback", validator=_non_negative)
tpu_shard_ring_mb = define(
    "tpu_shard_ring_mb", 4,
    "size in MiB of each parent<->worker shm doorbell ring",
    validator=_positive)
tpu_shard_forward_max = define(
    "tpu_shard_forward_max", 128 * 1024,
    "requests larger than this stay on the in-process dispatch path "
    "(forwarding copies the frame through the shm ring once)",
    validator=_positive)
shard_vars_interval_s = define(
    "shard_vars_interval_s", 1.0,
    "seconds between W_VARS windowed var snapshots a shard worker ships "
    "to the parent for fleet-wide /vars aggregation", validator=_positive)
serving_shard_skew_ratio = define(
    "serving_shard_skew_ratio", 0.25,
    "serving_shard_skew watch rule fires when any KV shard's occupancy "
    "exceeds its fleet mean by more than this ratio (reloadable: the "
    "rule reads the flag at every tick)",
    validator=lambda v: 0.0 < v <= 1.0)
serving_prefix_cache_enabled = define(
    "serving_prefix_cache_enabled", True,
    "radix prefix cache over the paged KV pools: admission forks the "
    "longest cached block-aligned prefix chain (refcount++, zero "
    "copies) and prefills only the suffix; completion commits full "
    "blocks back into the tree (reloadable: the engine reads the flag "
    "per admission)", validator=lambda v: v in (True, False, 0, 1))
serving_prefix_evict_watermark = define(
    "serving_prefix_evict_watermark", 0.80,
    "prefix-cache trim target: tree commits evict LRU refcount-1 "
    "chains until pool occupancy is back under this ratio, keeping the "
    "slack up to the admission watermark as decode headroom "
    "(reloadable: read at every trim)",
    validator=lambda v: 0.0 < float(v) <= 1.0)
serving_prefix_thrash_rate = define(
    "serving_prefix_thrash_rate", 20.0,
    "serving_prefix_thrash watch rule fires when prefix-cache eviction "
    "sustains above this many blocks/s — the tree is churning instead "
    "of caching (reloadable: the rule reads the flag at every tick)",
    validator=_positive)
serving_migrate_window_mb = define(
    "serving_migrate_window_mb", 64,
    "credit window (MiB of staged HBM bytes) for the KV-migration "
    "record stream: the prefill shard stalls exactly when the decode "
    "shard holds this many unconsumed migrated-block bytes",
    validator=_positive)
serving_migrate_timeout_ms = define(
    "serving_migrate_timeout_ms", 30000,
    "per-sequence migration deadline: MigrateCommit gives up (and the "
    "source retains the chain, falling back to local decode) if the "
    "destination has not adopted every block within this bound",
    validator=_positive)
serving_spec_accept_rate_min = define(
    "serving_spec_accept_rate_min", 0.2,
    "serving_spec_collapse watch rule fires when the speculative-decode "
    "accept rate (accepted/drafted over recent steps) sustains below "
    "this bound — drafts are being rejected wholesale and the verify "
    "rows are wasted compute (reloadable: the rule reads the flag at "
    "every tick)", validator=lambda v: 0.0 < float(v) <= 1.0)
serving_migrate_backlog_max = define(
    "serving_migrate_backlog_max", 8.0,
    "serving_migrate_backlog watch rule fires when more than this many "
    "KV migrations are in flight at once — prefill shards are shipping "
    "chains faster than decode shards adopt them (reloadable: the rule "
    "reads the flag at every tick)", validator=_positive)
serving_qos_starvation_ms = define(
    "serving_qos_starvation_ms", 2000.0,
    "serving_qos_starvation watch rule fires when the oldest queued "
    "request across the QoS tenant lanes has waited more than this many "
    "milliseconds — fair-share weights (or the limiter ceiling) are "
    "starving a lane (reloadable: the rule reads the flag at every "
    "tick)", validator=_positive)
