"""native — the C++ core, built on first import and loaded via ctypes.

The reference is native C++ throughout (SURVEY §2); our compute path is
JAX/XLA, but the runtime hot paths (checksums, rand, wire-frame scanning —
and, growing over time, the transport loop) are C++ here too. The build is
a single ``g++ -O3 -shared`` invocation cached next to the source; when no
toolchain is available every caller falls back to the pure-Python
implementation transparently.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "core.cpp")

_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None


def _build_flags():
    flags = ["-O3", "-shared", "-fPIC", "-std=c++17"]
    import platform

    if platform.machine() in ("x86_64", "AMD64"):
        flags.append("-msse4.2")
    return flags


def _so_path() -> str:
    with open(_SRC, "rb") as f:
        digest = hashlib.sha256(f.read()).hexdigest()[:16]
    return os.path.join(_DIR, f"_core_{digest}.so")


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native core; None on failure."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        so = _so_path()
        if not os.path.exists(so):
            tmp = so + ".tmp"
            cmd = ["g++", *_build_flags(), _SRC, "-o", tmp]
            try:
                subprocess.run(cmd, check=True, capture_output=True,
                               timeout=120)
                os.replace(tmp, so)
            except (OSError, subprocess.SubprocessError) as e:
                _build_error = f"{type(e).__name__}: {e}"
                return None
        try:
            lib = ctypes.CDLL(so)
        except OSError as e:
            _build_error = str(e)
            return None
        lib.tn_crc32c.restype = ctypes.c_uint32
        lib.tn_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint32]
        lib.tn_fast_rand.restype = ctypes.c_uint64
        lib.tn_fast_rand.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
        lib.tn_fast_rand_less_than.restype = ctypes.c_uint64
        lib.tn_fast_rand_less_than.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
        lib.tn_frame_scan.restype = ctypes.c_int
        lib.tn_frame_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.tn_abi_version.restype = ctypes.c_int
        if lib.tn_abi_version() != 1:
            _build_error = "abi mismatch"
            return None
        _lib = lib
        return _lib


def build_error() -> Optional[str]:
    return _build_error


# ------------------------------------------------------------- installation
def install() -> bool:
    """Point the Python fallbacks at the native implementations.
    Returns True when the native core is active."""
    lib = load()
    if lib is None:
        return False
    from brpc_tpu.butil import misc

    def native_crc32c(data, value: int = 0) -> int:
        b = bytes(data)
        return lib.tn_crc32c(b, len(b), value)

    misc._native_crc32c = native_crc32c

    # entropy-seeded, like the Python fallback (identical sequences across
    # a fleet would synchronize "random" LB picks and jitter)
    state = ctypes.c_uint64(
        int.from_bytes(os.urandom(8), "little") | 1)

    def native_fast_rand() -> int:
        return lib.tn_fast_rand(ctypes.byref(state))

    def native_fast_rand_less_than(n: int) -> int:
        return lib.tn_fast_rand_less_than(ctypes.byref(state), n) if n > 0 else 0

    misc._native_fast_rand = native_fast_rand
    misc._native_fast_rand_less_than = native_fast_rand_less_than
    return True


class FrameScanner:
    """Batched TRPC/TSTR frame-boundary scanner over a contiguous buffer."""

    def __init__(self, max_frames: int = 128):
        self._lib = load()
        self.max_frames = max_frames
        self._offsets = (ctypes.c_uint64 * (3 * max_frames))()
        self._consumed = ctypes.c_uint64()

    @property
    def available(self) -> bool:
        return self._lib is not None

    def scan(self, data: bytes, max_body: int):
        """Returns (frames, consumed, bad) where frames is a list of
        (start, meta_size, body_size) for each COMPLETE frame."""
        n = self._lib.tn_frame_scan(
            data, len(data), max_body, self._offsets, self.max_frames,
            ctypes.byref(self._consumed))
        bad = n < 0
        frames = [(self._offsets[i * 3], self._offsets[i * 3 + 1],
                   self._offsets[i * 3 + 2]) for i in range(max(n, 0))]
        return frames, self._consumed.value, bad
