"""native — the C++ core, built on first import and loaded via ctypes.

The reference is native C++ throughout (SURVEY §2); our compute path is
JAX/XLA, but the runtime hot paths (checksums, rand, wire-frame scanning —
and, growing over time, the transport loop) are C++ here too. The build is
a single ``g++ -O3 -shared`` invocation cached next to the source; when no
toolchain is available every caller falls back to the pure-Python
implementation transparently.
"""

from __future__ import annotations

import ctypes
import hashlib
import os
import subprocess
import threading
from typing import Optional

_DIR = os.path.dirname(os.path.abspath(__file__))
_SRC = os.path.join(_DIR, "core.cpp")
_DP_SRC = os.path.join(_DIR, "dataplane.cpp")

_lib = None
_lib_lock = threading.Lock()
_build_error: Optional[str] = None
_dp_lib = None
_dp_lock = threading.Lock()
_dp_build_error: Optional[str] = None


def _build_flags():
    flags = ["-O3", "-shared", "-fPIC", "-std=c++17"]
    import platform

    if platform.machine() in ("x86_64", "AMD64"):
        flags.append("-msse4.2")
    return flags


def _build_so(src: str, stem: str, extra_flags=(), headers=()) -> str:
    """Compile src to a digest-named .so next to it; raises on failure.
    ``headers``: local #includes folded into the cache digest."""
    sha = hashlib.sha256()
    for path in (src, *headers):
        with open(path, "rb") as f:
            sha.update(f.read())
    digest = sha.hexdigest()[:16]
    so = os.path.join(_DIR, f"_{stem}_{digest}.so")
    if not os.path.exists(so):
        tmp = f"{so}.{os.getpid()}.tmp"
        cmd = ["g++", *_build_flags(), *extra_flags, src, "-o", tmp]
        subprocess.run(cmd, check=True, capture_output=True, timeout=180)
        os.replace(tmp, so)
    return so


def load() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the native core; None on failure."""
    global _lib, _build_error
    with _lib_lock:
        if _lib is not None:
            return _lib
        if _build_error is not None:
            return None
        try:
            so = _build_so(_SRC, "core")
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.SubprocessError) as e:
            _build_error = f"{type(e).__name__}: {e}"
            return None
        lib.tn_crc32c.restype = ctypes.c_uint32
        lib.tn_crc32c.argtypes = [ctypes.c_char_p, ctypes.c_uint64,
                                  ctypes.c_uint32]
        lib.tn_fast_rand.restype = ctypes.c_uint64
        lib.tn_fast_rand.argtypes = [ctypes.POINTER(ctypes.c_uint64)]
        lib.tn_fast_rand_less_than.restype = ctypes.c_uint64
        lib.tn_fast_rand_less_than.argtypes = [
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_uint64]
        lib.tn_frame_scan.restype = ctypes.c_int
        lib.tn_frame_scan.argtypes = [
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_uint64), ctypes.c_int,
            ctypes.POINTER(ctypes.c_uint64)]
        lib.tn_abi_version.restype = ctypes.c_int
        if lib.tn_abi_version() != 1:
            _build_error = "abi mismatch"
            return None
        _lib = lib
        return _lib


def build_error() -> Optional[str]:
    return _build_error


# ---------------------------------------------------------------- dataplane
class DpEventStruct(ctypes.Structure):
    """Mirror of DpEvent in dataplane.cpp."""

    _fields_ = [
        ("kind", ctypes.c_int32),
        ("tag", ctypes.c_int32),
        ("conn_id", ctypes.c_uint64),
        ("aux", ctypes.c_int64),
        ("base", ctypes.c_void_p),
        ("meta", ctypes.c_void_p),
        ("meta_len", ctypes.c_uint64),
        ("body", ctypes.c_void_p),
        ("body_len", ctypes.c_uint64),
    ]


def load_dataplane() -> Optional[ctypes.CDLL]:
    """Build (if needed) and load the dataplane engine; None on failure."""
    global _dp_lib, _dp_build_error
    with _dp_lock:
        if _dp_lib is not None:
            return _dp_lib
        if _dp_build_error is not None:
            return None
        try:
            so = _build_so(_DP_SRC, "dataplane", ("-pthread",),
                           headers=(os.path.join(_DIR, "hpack_tables.h"),))
            lib = ctypes.CDLL(so)
        except (OSError, subprocess.SubprocessError) as e:
            _dp_build_error = f"{type(e).__name__}: {e}"
            return None
        ev_p = ctypes.POINTER(DpEventStruct)
        lib.dp_abi_version.restype = ctypes.c_int
        lib.dp_rt_create.restype = ctypes.c_void_p
        lib.dp_rt_create.argtypes = [ctypes.c_int, ctypes.c_uint64]
        lib.dp_rt_shutdown.argtypes = [ctypes.c_void_p]
        lib.dp_listen.restype = ctypes.c_int
        lib.dp_listen.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.c_int]
        lib.dp_listener_close.restype = ctypes.c_int
        lib.dp_listener_close.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dp_listen_port.restype = ctypes.c_int
        lib.dp_listen_port.argtypes = [ctypes.c_void_p, ctypes.c_int]
        lib.dp_register_echo.restype = ctypes.c_int
        lib.dp_register_echo.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.c_char_p, ctypes.c_char_p]
        lib.dp_unregister_listener_echoes.restype = ctypes.c_int
        lib.dp_unregister_listener_echoes.argtypes = [ctypes.c_void_p,
                                                      ctypes.c_int]
        lib.dp_connect.restype = ctypes.c_uint64
        lib.dp_connect.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                   ctypes.c_int, ctypes.c_int,
                                   ctypes.POINTER(ctypes.c_int)]
        lib.dp_connect_tpu.restype = ctypes.c_uint64
        lib.dp_connect_tpu.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_int, ctypes.c_int,
                                       ctypes.c_int,
                                       ctypes.POINTER(ctypes.c_int)]
        lib.dp_connect_tpu2.restype = ctypes.c_uint64
        lib.dp_connect_tpu2.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_int, ctypes.c_int,
                                        ctypes.c_int, ctypes.c_uint32,
                                        ctypes.c_uint32,
                                        ctypes.POINTER(ctypes.c_int)]
        lib.dp_connect_grpc.restype = ctypes.c_uint64
        lib.dp_connect_grpc.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                        ctypes.c_int, ctypes.c_int,
                                        ctypes.POINTER(ctypes.c_int)]
        lib.dp_listener_set_tpu.restype = ctypes.c_int
        lib.dp_listener_set_tpu.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                            ctypes.c_int]
        lib.dp_send.restype = ctypes.c_int
        lib.dp_send.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                ctypes.c_char_p, ctypes.c_uint64]
        lib.dp_sendv.restype = ctypes.c_int
        lib.dp_sendv.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                 ctypes.POINTER(ctypes.c_char_p),
                                 ctypes.POINTER(ctypes.c_uint64),
                                 ctypes.c_int]
        lib.dp_poll.restype = ctypes.c_int
        lib.dp_poll.argtypes = [ctypes.c_void_p, ev_p, ctypes.c_int,
                                ctypes.c_int]
        lib.dp_poll_packed.restype = ctypes.c_int
        lib.dp_poll_packed.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                       ctypes.c_uint64, ctypes.c_int,
                                       ctypes.c_int]
        lib.dp_free.argtypes = [ctypes.c_void_p]
        lib.dp_conn_close.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.dp_conn_stats.restype = ctypes.c_int
        lib.dp_conn_stats.argtypes = [ctypes.c_void_p, ctypes.c_uint64] + \
            [ctypes.POINTER(ctypes.c_uint64)] * 4
        lib.dp_bench_echo.restype = ctypes.c_int
        lib.dp_bench_echo.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
        ] + [ctypes.POINTER(ctypes.c_double)] * 5
        lib.dp_bench_echo2.restype = ctypes.c_int
        lib.dp_bench_echo2.argtypes = [
            ctypes.c_char_p, ctypes.c_int, ctypes.c_int, ctypes.c_int,
            ctypes.c_int, ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p,
            ctypes.c_char_p,
        ] + [ctypes.POINTER(ctypes.c_double)] * 5
        # fast path (abi 2): engine-side meta parse/pack for Python RPCs
        lib.dp_listener_set_fastpath.restype = ctypes.c_int
        lib.dp_listener_set_fastpath.argtypes = [ctypes.c_void_p,
                                                 ctypes.c_int, ctypes.c_int]
        lib.dp_conn_set_fastpath.restype = ctypes.c_int
        lib.dp_conn_set_fastpath.argtypes = [ctypes.c_void_p,
                                             ctypes.c_uint64, ctypes.c_int]
        lib.dp_respond.restype = ctypes.c_int
        lib.dp_respond.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_int, ctypes.c_int]
        lib.dp_call.restype = ctypes.c_int
        lib.dp_call.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int64, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_int]
        lib.dp_flush_all.restype = ctypes.c_int
        lib.dp_flush_all.argtypes = [ctypes.c_void_p]
        lib.dp_tpu_ack.restype = ctypes.c_int
        lib.dp_tpu_ack.argtypes = [ctypes.c_void_p, ctypes.c_uint64,
                                   ctypes.c_char_p, ctypes.c_uint64]
        lib.dp_svc_set_limit.restype = ctypes.c_int
        lib.dp_svc_set_limit.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                         ctypes.c_char_p, ctypes.c_char_p,
                                         ctypes.c_int]
        lib.dp_listener_set_logoff.restype = ctypes.c_int
        lib.dp_listener_set_logoff.argtypes = [ctypes.c_void_p, ctypes.c_int,
                                               ctypes.c_int]
        lib.dp_svc_stats.restype = ctypes.c_int
        lib.dp_svc_stats.argtypes = [
            ctypes.c_void_p, ctypes.c_int, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_int32)]
        # abi 3: engine-parked sync calls (dp_call_sync) — the caller
        # blocks in C with the GIL released; the parse thread completes it
        lib.dp_call_sync.restype = ctypes.c_int
        lib.dp_call_sync.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_uint64, ctypes.c_int64, ctypes.c_int64,
            ctypes.c_int64, ctypes.c_int32, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.POINTER(ctypes.c_int32), ctypes.POINTER(ctypes.c_uint64),
            ctypes.POINTER(ctypes.c_uint64), ctypes.POINTER(ctypes.c_void_p),
            ctypes.POINTER(ctypes.c_void_p), ctypes.POINTER(ctypes.c_uint64),
            ctypes.c_char_p, ctypes.POINTER(ctypes.c_uint64)]
        lib.dp_call2.restype = ctypes.c_int
        lib.dp_call2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.dp_respond2.restype = ctypes.c_int
        lib.dp_respond2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64]
        lib.dp_call_sync2.restype = ctypes.c_int
        lib.dp_call_sync2.argtypes = [
            ctypes.c_void_p, ctypes.c_char_p, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_char_p, ctypes.c_uint64,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64]
        lib.dp_sync_complete_py.restype = ctypes.c_int
        lib.dp_sync_complete_py.argtypes = [
            ctypes.c_void_p, ctypes.c_uint64, ctypes.c_int32,
            ctypes.c_char_p, ctypes.c_uint64, ctypes.c_char_p,
            ctypes.c_uint64, ctypes.c_uint64, ctypes.c_uint64]
        if lib.dp_abi_version() != 3:
            _dp_build_error = "dataplane abi mismatch"
            return None
        _dp_lib = lib
        return _dp_lib


def dataplane_build_error() -> Optional[str]:
    return _dp_build_error


# ------------------------------------------------------------- installation
def install() -> bool:
    """Point the Python fallbacks at the native implementations.
    Returns True when the native core is active."""
    lib = load()
    if lib is None:
        return False
    from brpc_tpu.butil import misc

    def native_crc32c(data, value: int = 0) -> int:
        b = bytes(data)
        return lib.tn_crc32c(b, len(b), value)

    misc._native_crc32c = native_crc32c

    # entropy-seeded, like the Python fallback (identical sequences across
    # a fleet would synchronize "random" LB picks and jitter)
    state = ctypes.c_uint64(
        int.from_bytes(os.urandom(8), "little") | 1)

    def native_fast_rand() -> int:
        return lib.tn_fast_rand(ctypes.byref(state))

    def native_fast_rand_less_than(n: int) -> int:
        return lib.tn_fast_rand_less_than(ctypes.byref(state), n) if n > 0 else 0

    misc._native_fast_rand = native_fast_rand
    misc._native_fast_rand_less_than = native_fast_rand_less_than
    return True


class FrameScanner:
    """Batched TRPC/TSTR frame-boundary scanner over a contiguous buffer."""

    def __init__(self, max_frames: int = 128):
        self._lib = load()
        self.max_frames = max_frames
        self._offsets = (ctypes.c_uint64 * (3 * max_frames))()
        self._consumed = ctypes.c_uint64()

    @property
    def available(self) -> bool:
        return self._lib is not None

    def scan(self, data: bytes, max_body: int):
        """Returns (frames, consumed, bad) where frames is a list of
        (start, meta_size, body_size) for each COMPLETE frame."""
        n = self._lib.tn_frame_scan(
            data, len(data), max_body, self._offsets, self.max_frames,
            ctypes.byref(self._consumed))
        bad = n < 0
        frames = [(self._offsets[i * 3], self._offsets[i * 3 + 1],
                   self._offsets[i * 3 + 2]) for i in range(max(n, 0))]
        return frames, self._consumed.value, bad
